"""Shard: the unit of storage + indexing.

Reference: adapters/repos/db/shard.go (ShardLike :77, struct :185) — owns an
lsmkv Store (objects bucket + docid mappings), one vector index per named
vector, and the inverted index. Write path parity: shard_write_put.go
(putObjectLSM -> updateInvertedIndexLSM -> VectorIndex.Add); read path:
shard_read.go (ObjectVectorSearch / ObjectSearch).
"""

from __future__ import annotations

import logging
import os
import threading

import numpy as np

from weaviate_tpu.engine.flat import FlatIndex
from weaviate_tpu.runtime import tracing
from weaviate_tpu.schema.config import CollectionConfig, VectorConfig
from weaviate_tpu.storage.kv import KVStore
from weaviate_tpu.storage.objects import StorageObject

logger = logging.getLogger(__name__)


class ShardReadOnlyError(RuntimeError):
    """Write refused: shard status is READONLY
    (PUT /v1/schema/{class}/shards/{shard})."""


class StagedExpiredError(RuntimeError):
    """2PC commit refused: the staged entry outlived the staged-entry
    TTL (WEAVIATE_TPU_STAGED_TTL_S). The coordinator treats this like
    any other per-replica commit failure — abort + anti-entropy."""

# bucket names (reference: helpers/helpers.go:22-25)
BUCKET_OBJECTS = "objects"
BUCKET_DOCID = "docid"  # uuid -> doc_id  (adapters/repos/db/docid)
BUCKET_META = "meta"  # counters, checkpoints (indexcounter/)


def _make_vector_index(vc: VectorConfig, dim: int, mesh=None):
    cfg = vc.index
    if cfg.index_type == "noop":
        return None
    import jax.numpy as jnp

    common = dict(
        dim=dim,
        metric=cfg.metric,
        capacity=8192,
        chunk_size=8192,
    )
    if cfg.index_type == "flat" and cfg.quantization:
        return FlatIndex(
            quantization=cfg.quantization,
            pq_segments=cfg.pq_segments,
            pq_centroids=cfg.pq_centroids,
            rescore_limit=cfg.rescore_limit,
            prefix_bits=cfg.prefix_bits,
            mesh=mesh,
            epoch_rows=cfg.epoch_rows,
            **common,
        )
    if cfg.index_type == "flat":
        return FlatIndex(
            mesh=mesh,
            dtype=jnp.bfloat16 if cfg.storage_dtype == "bfloat16" else jnp.float32,
            epoch_rows=cfg.epoch_rows,
            **common,
        )
    if cfg.index_type == "ivf":
        from weaviate_tpu.engine.ivf import IVFIndex

        if cfg.quantization == "bq":
            # no bq form for IVF lists — honor the compression request on
            # the flat scan (documented fallback, not a silent drop)
            return FlatIndex(quantization="bq", mesh=mesh,
                             rescore_limit=cfg.rescore_limit,
                             prefix_bits=cfg.prefix_bits, **common)
        # mesh forwarded so the single-replica guard fires loudly instead of
        # silently landing a sharded corpus on one device
        return IVFIndex(nlist=cfg.ivf_nlist, nprobe=cfg.ivf_nprobe,
                        mesh=mesh,
                        quantization=cfg.quantization,
                        pq_segments=cfg.pq_segments,
                        pq_centroids=cfg.pq_centroids,
                        dtype=jnp.bfloat16 if cfg.storage_dtype == "bfloat16"
                        else jnp.float32,
                        **common)
    if cfg.index_type == "hnsw":
        # reference-parity graph index (engine/hnsw.py). A pq-quantized
        # hnsw keeps its GRAPH (runtime ADC compression is applied once
        # enough data exists — compress.go:38); bq has no ADC form for
        # graph hops, so bq configs run the quantized flat scan instead.
        if cfg.quantization == "bq":
            return FlatIndex(quantization="bq", mesh=mesh,
                             rescore_limit=cfg.rescore_limit,
                             prefix_bits=cfg.prefix_bits, **common)
        from weaviate_tpu.engine.hnsw import HNSWIndex

        return HNSWIndex(
            dim=dim, metric=cfg.metric,
            max_connections=cfg.max_connections,
            ef_construction=cfg.ef_construction, ef=cfg.ef,
        )
    if cfg.index_type == "dynamic":
        # the ANN regime on TPU is IVF (SURVEY §7 step 5), entered via the
        # dynamic flat→ANN upgrade so small corpora stay exact
        from weaviate_tpu.engine.dynamic import DynamicIndex

        if cfg.quantization:
            # quantized flat scan is already the fast path; stays flat
            # (DynamicIndex refuses to upgrade a quantized impl)
            return DynamicIndex(
                threshold=cfg.flat_to_ann_threshold,
                quantization=cfg.quantization,
                pq_segments=cfg.pq_segments,
                pq_centroids=cfg.pq_centroids,
                rescore_limit=cfg.rescore_limit,
                prefix_bits=cfg.prefix_bits,
                mesh=mesh,
                **common,
            )
        return DynamicIndex(
            threshold=cfg.flat_to_ann_threshold, mesh=mesh,
            nlist=cfg.ivf_nlist, nprobe=cfg.ivf_nprobe,
            dtype=jnp.bfloat16 if cfg.storage_dtype == "bfloat16" else jnp.float32,
            **common,
        )
    raise ValueError(f"unknown index type {cfg.index_type}")


class Shard:
    def __init__(self, data_dir: str, collection: CollectionConfig, name: str,
                 mesh=None, memwatch=None, async_indexing: bool | None = None,
                 sync_wal: bool | None = None):
        self.name = name
        self.memwatch = memwatch
        # PERSISTENCE_WAL_SYNC (reference: commit logger fsync
        # discipline): fsync every acked write's WAL frame. Parsed by
        # config._flag itself so the two can never disagree.
        if sync_wal is None:
            from weaviate_tpu.config import _flag

            sync_wal = _flag(os.environ, "PERSISTENCE_WAL_SYNC")
        self.sync_wal = sync_wal
        # ASYNC_INDEXING (reference env gate, repo.go/index_queue.go):
        # imports enqueue vectors; a background worker drains into the
        # vector index. Off by default — searches stay read-your-writes.
        # Same accepted values as config._flag so the two never disagree.
        if async_indexing is None:
            async_indexing = os.environ.get(
                "ASYNC_INDEXING", "").lower() in ("true", "1", "on",
                                                  "enabled")
        self.async_indexing = async_indexing
        self._index_queues: dict[str, "IndexQueue"] = {}
        # server-side dynamic batching: concurrent single-query searches
        # coalesce into one device dispatch (continuous batching — see
        # runtime/query_batcher.py). QUERY_DYNAMIC_BATCHING=false opts out.
        self.dynamic_batching = os.environ.get(
            "QUERY_DYNAMIC_BATCHING", "true").lower() in (
                "true", "1", "on", "enabled")
        # zero-sync serving pipeline (ISSUE 7): batched dispatches return
        # device-resident handles and drain D2H on a transfer thread
        # while the next batch dispatches. QUERY_ASYNC_PIPELINE=false
        # opts back into worker-synchronous fetches.
        self.async_pipeline = os.environ.get(
            "QUERY_ASYNC_PIPELINE", "true").lower() in (
                "true", "1", "on", "enabled")
        self._query_batchers: dict[str, "QueryBatcher"] = {}
        # hybridplane (ISSUE 18): device-resident BM25 + fusion rides the
        # dense dispatch when the index supports it. Kill switch keeps
        # hybrid on the host reference path; the candidate budget bounds
        # the packed sparse operand (over-budget queries fall back).
        self.device_hybrid = os.environ.get(
            "WEAVIATE_TPU_DEVICE_HYBRID", "true").lower() in (
                "true", "1", "on", "enabled")
        try:
            self.hybrid_max_candidates = int(os.environ.get(
                "WEAVIATE_TPU_HYBRID_MAX_CANDIDATES", "4096"))
        except ValueError:
            self.hybrid_max_candidates = 4096
        # READONLY shard status (reference: PUT /v1/schema/{c}/shards/{s}
        # — schema_shards handlers flip writes off per shard); persisted
        # below once the meta bucket is open so restarts keep the freeze
        self.read_only = False
        self.collection_name = collection.name
        self.config = collection
        # exact-case directory: two collections differing only in case are
        # distinct and must not share (or cross-delete) storage
        self.dir = os.path.join(data_dir, collection.name, name)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.RLock()
        self.store = KVStore(self.dir, sync_wal=self.sync_wal)
        self.objects = self.store.bucket(BUCKET_OBJECTS, "replace")
        self.docid = self.store.bucket(BUCKET_DOCID, "replace")
        self.meta = self.store.bucket(BUCKET_META, "replace")
        # deletion tombstones (uuid -> mtime ms) so anti-entropy can tell
        # "deleted here" from "never seen" and not resurrect deletes
        self.tombstones = self.store.bucket("tombstones", "replace")
        # staged 2PC batches: request id -> ("put", [objs]) | ("delete", uuid).
        # In-memory ON PURPOSE — that is what makes the abort-unreachable
        # path crash-safe: a replica that dies between prepare and
        # commit restarts with nothing staged (an implicit abort), and
        # the write converges through anti-entropy if it committed
        # elsewhere. Live orphans (coordinator died / stayed partitioned)
        # expire after ``staged_ttl_s``: gc drops them, and commit_staged
        # REFUSES them even before gc ran, so a straggler commit racing a
        # partition heal can never land a stale write late.
        self._staged: dict[str, tuple] = {}
        self.staged_ttl_s = float(os.environ.get(
            "WEAVIATE_TPU_STAGED_TTL_S", str(self.STAGED_TTL_S)))
        self._staged_expired = 0
        # epoch-migration routing overrides (uuid -> destination shard),
        # durable in the meta bucket; the in-memory count makes the
        # common case (no migrations) a zero-cost check on reads/puts
        self._migrated_count = sum(
            1 for k in self.meta.keys() if k.startswith(b"migrated:"))
        # memory-pressure rescue hook (db/collection.py wires this to
        # epoch compaction + migration): called once when admission
        # would 507, then admission re-checks before actually rejecting
        self.memory_rescue = None
        # optional per-shard HBM quota (WEAVIATE_TPU_SHARD_HBM_LIMIT_
        # BYTES): the placement-level watermark epoch migration exists
        # for — moving the coldest sealed epoch to a sibling genuinely
        # relieves THIS shard's ledger footprint, where the device-
        # global budget only compaction can relieve locally
        try:
            self.shard_hbm_limit = int(os.environ.get(
                "WEAVIATE_TPU_SHARD_HBM_LIMIT_BYTES", "0") or 0)
        except ValueError:
            self.shard_hbm_limit = 0
        self._counter = self.meta.get(b"doc_counter") or 0
        self.read_only = bool(self.meta.get(b"read_only") or False)
        self.mesh = mesh
        # named vector indexes, built lazily at first insert (dim inference)
        self.vector_indexes: dict[str, FlatIndex] = {}
        from weaviate_tpu.text.inverted import InvertedIndex

        # persistent inverted index: postings/filterables write through the
        # shard's own LSM store and are read on demand — NOT rebuilt from
        # objects at open (reference: inverted/ lsmkv buckets)
        self._inverted = InvertedIndex(collection, store=self.store)
        # doc_id -> uuid, rebuilt at startup; the object-resolution hot path
        # after a vector search (reference: docid bucket, adapters/repos/db/docid)
        self._doc_to_uuid: dict[int, str] = {}
        self._restore_vector_indexes()

    # -- startup -------------------------------------------------------------

    def _restore_vector_indexes(self):
        """Rebuild HBM state from the durable object store (reference:
        hnsw/startup.go:57 replays the commit log; we replay the objects
        bucket — the vectors ARE the log)."""
        batch: dict[str, tuple[list[int], list[np.ndarray]]] = {}
        # one-time migration: a shard written before the inverted index was
        # persistent has objects but empty inv_* buckets — rebuild postings
        # from objects once so pre-upgrade data stays searchable
        migrate_inverted = self._inverted.doc_count == 0
        migrated = 0
        migrate_chunk: list[StorageObject] = []
        for key, raw in self.objects.iter_items():
            obj = StorageObject.from_bytes(raw)
            self._doc_to_uuid[obj.doc_id] = obj.uuid
            if migrate_inverted:
                migrate_chunk.append(obj)
                if len(migrate_chunk) >= 2000:  # batched WAL frames
                    self._inverted.index_objects(migrate_chunk)
                    migrated += len(migrate_chunk)
                    migrate_chunk = []
            for vec_name, vec in obj.vectors.items():
                ids, vecs = batch.setdefault(vec_name, ([], []))
                ids.append(obj.doc_id)
                vecs.append(vec)
        if migrate_chunk:
            self._inverted.index_objects(migrate_chunk)
            migrated += len(migrate_chunk)
        self._inverted.reconcile_doc_count(len(self._doc_to_uuid))
        if migrated:
            import logging

            logging.getLogger(__name__).info(
                "shard %s: migrated %d objects into the persistent "
                "inverted index", self.name, migrated)
        for vec_name, (ids, vecs) in batch.items():
            # tolerate poisoned rows (dim drift from old bugs/corruption)
            # instead of refusing to start — reference analog:
            # hnsw/corrupt_commit_logs_fixer.go skips bad log entries
            dim = len(vecs[0])
            keep = [j for j, v in enumerate(vecs) if len(v) == dim]
            if len(keep) != len(vecs):
                import logging

                logging.getLogger(__name__).warning(
                    "shard %s: skipping %d vectors with mismatched dims for %r",
                    self.name, len(vecs) - len(keep), vec_name,
                )
            idx = self._ensure_vector_index(vec_name, dim)
            if idx is not None and keep:
                idx.add_batch(
                    np.asarray([ids[j] for j in keep]),
                    np.stack([vecs[j] for j in keep]),
                )
                # configs that ask for quantization on a graph/ivf index
                # compress at runtime (compress.go:38) — re-apply after the
                # rebuild so a restart doesn't silently lose compression
                self._maybe_compress(vec_name, idx)

    def _ensure_vector_index(self, vec_name: str, dim: int):
        if vec_name in self.vector_indexes:
            return self.vector_indexes[vec_name]
        vc = self.config.vector_config(vec_name)
        if vc is None:
            vc = VectorConfig(name=vec_name)
        # HBM-ledger owner scope: every device array the index (and its
        # stores) allocates — now or on a later grow — is attributed to
        # this (collection, shard, tenant)
        from weaviate_tpu.runtime import hbm_ledger

        with hbm_ledger.owner(self.collection_name, self.name,
                              tenant=self._tenant_label()):
            idx = _make_vector_index(vc, dim, mesh=self.mesh)
        self.vector_indexes[vec_name] = idx
        self._register_drift_canary(vec_name)
        return idx

    def _register_drift_canary(self, vec_name: str) -> None:
        """Hand this vector space to driftwatch as a canary target. The
        callbacks resolve ``self.vector_indexes[vec_name]`` per call so
        they survive compress()/DynamicIndex upgrades swapping stores
        under the same key, and the probe search routes through
        ``_query_batcher`` — the REAL serving dispatch (coalescing,
        faultline point, kernelscope attribution), not a side channel."""
        from weaviate_tpu.runtime import driftwatch

        def _idx():
            return self.vector_indexes.get(vec_name)

        def corpus_fn():
            idx = _idx()
            id_map = getattr(idx, "_id_to_slot", None)
            if not id_map:
                return None
            doc_ids = sorted(int(d) for d in id_map)
            objs = self.objects_by_doc_ids(doc_ids)
            ids, vecs = [], []
            for d, obj in zip(doc_ids, objs):
                v = None if obj is None else obj.vectors.get(vec_name)
                if v is not None:
                    ids.append(d)
                    vecs.append(np.asarray(v, dtype=np.float32))
            if not ids:
                return None
            return np.asarray(ids, dtype=np.int64), np.stack(vecs)

        def epoch_token_fn():
            idx = _idx()
            if idx is None:
                return None
            es = getattr(idx, "epoch_store", None)
            if es is not None:
                return (tuple((e["epoch"], e["rows"], e["live"])
                              for e in es.epoch_stats()), len(idx))
            return (len(idx),)

        def pairwise_fn(qs, vecs):
            idx = _idx()
            metric = getattr(idx, "metric", "l2-squared")
            return Shard._host_pairwise(qs, vecs, metric)

        def search_fn(queries, k):
            idx = _idx()
            if idx is None or getattr(idx, "search_by_vector_batch",
                                      None) is None:
                return None
            b = self._query_batcher(vec_name, idx)
            out = []
            for q in np.asarray(queries, dtype=np.float32):
                ids, _ = b.search(q, k, None)
                ids = np.asarray(ids)
                out.append(ids[ids >= 0].astype(np.int64))
            return out

        driftwatch.register_canary(
            f"{self.collection_name}/{self.name}/{vec_name or '-'}",
            collection=self.collection_name, shard=self.name,
            search_fn=search_fn, corpus_fn=corpus_fn,
            epoch_token_fn=epoch_token_fn, pairwise_fn=pairwise_fn)

    def _tenant_label(self) -> str:
        """Tenants ARE shards in this layout (reference: partitioned
        shards keyed by tenant name) — the ledger's tenant label is the
        shard name iff multi-tenancy is on."""
        return self.name if self.config.multi_tenancy.enabled else ""

    def _maybe_compress(self, vec_name: str, idx) -> None:
        vc = self.config.vector_config(vec_name)
        if (vc is None or not vc.index.quantization
                or getattr(idx, "compressed", True)
                or not hasattr(idx, "compress")
                # trainability floor — the SAME gate the config-update
                # path has, so a restart can never silently drop
                # compression a live update applied
                or len(idx) < (vc.index.pq_centroids or 16)):
            return
        try:
            idx.compress(quantization=vc.index.quantization,
                         pq_segments=vc.index.pq_segments,
                         pq_centroids=vc.index.pq_centroids,
                         rescore_limit=vc.index.rescore_limit,
                         prefix_bits=vc.index.prefix_bits)
        except (RuntimeError, ValueError) as e:
            import logging

            logging.getLogger(__name__).warning(
                "shard %s/%s: deferring runtime compression: %s",
                self.name, vec_name, e)

    # -- write path ----------------------------------------------------------

    def _next_doc_id(self) -> int:
        with self._lock:
            doc_id = self._counter
            self._counter += 1
            self.meta.put(b"doc_counter", self._counter)
            return doc_id

    def put_object(self, obj: StorageObject) -> int:
        """Insert or update (reference: shard_write_put.go:218 putObjectLSM).

        Updates keep the uuid but get a fresh doc id, tombstoning the old
        one in the vector indexes (reference does the same doc-id bump)."""
        return self.put_object_batch([obj])[0]

    def _expected_dim(self, vec_name: str) -> int | None:
        idx = self.vector_indexes.get(vec_name)
        if idx is not None:
            return idx.dim
        vc = self.config.vector_config(vec_name)
        if vc is not None and vc.dim:
            return vc.dim
        return None

    def _validate_vectors(self, objs: list[StorageObject]) -> None:
        """Reject dim mismatches BEFORE any mutation — a failed index add
        after the object landed in the store would poison restart replay."""
        first_dims: dict[str, int] = {}
        for obj in objs:
            for vec_name, vec in obj.vectors.items():
                dim = self._expected_dim(vec_name) or first_dims.get(vec_name)
                if dim is None:
                    first_dims[vec_name] = len(vec)
                elif len(vec) != dim:
                    raise ValueError(
                        f"vector dim {len(vec)} != expected dim {dim} "
                        f"for vector {vec_name!r} (object {obj.uuid})"
                    )

    def put_object_batch(self, objs: list[StorageObject]) -> list[int]:
        """Reference: shard_write_batch_objects.go:33."""
        # dedupe by uuid (last wins): a duplicate in one batch would queue
        # the first occurrence's vector for an already-deleted doc id,
        # leaving a ghost row in the index
        if len({o.uuid for o in objs}) != len(objs):
            last = {o.uuid: i for i, o in enumerate(objs)}
            objs = [objs[i] for i in sorted(last.values())]
        doc_ids: list[int] = []
        gate = self.memwatch is not None or self.shard_hbm_limit
        if gate:
            # optimistic rescue pass, OUTSIDE the shard lock so the
            # hook (epoch compaction, then migrating the coldest sealed
            # epoch to a sibling — db/collection.py) can touch sibling
            # shards without a lock cycle. The AUTHORITATIVE admission
            # check re-runs under the lock below, serialized with the
            # adds, so N concurrent importers can't all pass against
            # the same stale usage. Read-only shards skip the rescue —
            # they refuse with ShardReadOnlyError, not 507.
            nbytes = sum(int(np.asarray(v).nbytes)
                         for o in objs for v in o.vectors.values())
            if not self.read_only:
                try:
                    self._admit_device_bytes(nbytes)
                except MemoryError:
                    if self.memory_rescue is None:
                        raise
                    try:
                        self.memory_rescue()
                    except Exception:  # noqa: BLE001 — best-effort; the
                        logger.exception(  # typed 507 below is the answer
                            "shard %s/%s: memory-pressure rescue failed",
                            self.collection_name, self.name)
        with self._lock:
            if self.read_only:
                raise ShardReadOnlyError(
                    f"shard {self.name!r} is read-only (status READONLY)")
            self._validate_vectors(objs)
            if gate:
                # refuse BEFORE mutating anything (reference memwatch
                # CheckAlloc semantics): vectors land in device HBM
                self._admit_device_bytes(nbytes)
            vec_batches: dict[str, tuple[list[int], list[np.ndarray]]] = {}
            # doc ids for the whole batch come from one counter bump (one
            # meta write instead of len(objs))
            first_id = self._counter
            self._counter += len(objs)
            self.meta.put(b"doc_counter", self._counter)
            docid_puts: list[tuple[bytes, object]] = []
            object_puts: list[tuple[bytes, object]] = []
            uuid_keys = [o.uuid.encode() for o in objs]
            old_raws = self.docid.get_many(uuid_keys)
            # flagship import shape (exactly one unnamed vector per
            # object): all storobj value frames come out of ONE native
            # call; props are msgpacked here so the bytes match the
            # Python encoder exactly. Any other shape — or a uuid the
            # fast parser rejects — keeps the per-object Python codec.
            frames = None
            from weaviate_tpu import native

            single_vec = (objs and native.available() and all(
                len(o.vectors) == 1 and "" in o.vectors for o in objs))
            if single_vec:
                import msgpack

                vec_block = np.stack([
                    np.asarray(o.vectors[""], dtype=np.float32)
                    for o in objs])
                n_objs = len(objs)
                frames = native.storobj_encode_batch(
                    uuid_keys,
                    [msgpack.packb(o.properties, use_bin_type=True)
                     for o in objs],
                    vec_block,
                    np.arange(first_id, first_id + n_objs, dtype=np.int64),
                    np.fromiter((o.creation_time_ms for o in objs),
                                np.int64, n_objs),
                    np.fromiter((o.last_update_time_ms for o in objs),
                                np.int64, n_objs))
            # update path: every replaced doc's teardown runs BATCHED —
            # the per-object form paid one device dispatch per tombstone
            # (flat.delete -> store.delete) and one inverted pass each,
            # which made re-imports ~5x slower than fresh inserts
            updates = [(int(old_raw), obj.uuid)
                       for obj, old_raw in zip(objs, old_raws)
                       if old_raw is not None]
            if updates:
                self._delete_docs_batch(updates)
            for i, obj in enumerate(objs):
                obj.doc_id = first_id + i
                docid_puts.append((uuid_keys[i], obj.doc_id))
                self._doc_to_uuid[obj.doc_id] = obj.uuid
                object_puts.append((
                    uuid_keys[i],
                    frames[i] if frames is not None else obj.to_bytes()))
                if frames is None:
                    for vec_name, vec in obj.vectors.items():
                        ids, vecs = vec_batches.setdefault(
                            vec_name, ([], []))
                        ids.append(obj.doc_id)
                        vecs.append(np.asarray(vec, dtype=np.float32))
                doc_ids.append(obj.doc_id)
            if frames is not None:
                vec_batches[""] = (doc_ids, vec_block)
            # ordering invariant: inverted postings land BEFORE the objects
            # bucket. A crash in between leaves ghost postings (doc ids the
            # object replay never resurrects — filters mask them out and
            # result resolution drops them), never missing postings for a
            # visible object. The objects-bucket WAL is the commit point.
            self._inverted.index_objects(objs)
            # clear any prior delete markers in one frame
            self.tombstones.delete_many(k for k, _ in docid_puts)
            self.docid.put_many(docid_puts)
            self.objects.put_many(object_puts)
            for vec_name, (ids, vecs) in vec_batches.items():
                idx = self._ensure_vector_index(vec_name, len(vecs[0]))
                if idx is None:
                    continue
                # fast path hands a prebuilt [n, d] block; list -> stack
                block = vecs if isinstance(vecs, np.ndarray) \
                    else np.stack(vecs)
                if self.async_indexing:
                    self._index_queue(vec_name, idx).push(
                        np.asarray(ids), block)
                else:
                    idx.add_batch(np.asarray(ids), block)
                    self._maybe_compress(vec_name, idx)
        return doc_ids

    def _batched_search(self, vec_name: str, idx, query: np.ndarray, k: int,
                        allow_list):
        """Dynamic-batched single-query search: concurrent callers share
        one device dispatch (VERDICT r1 item 6). Falls back to the direct
        path for index types without a batch entry point."""
        if getattr(idx, "search_by_vector_batch", None) is None:
            return idx.search_by_vector(query, k, allow_list=allow_list)
        b = self._query_batcher(vec_name, idx)
        ids, dists = b.search(query, k, allow_list)
        live = ids >= 0
        return (np.asarray(ids)[live].astype(np.int64),
                np.asarray(dists)[live].astype(np.float32))

    def _query_batcher(self, vec_name: str, idx):
        """The shard's per-vector-space QueryBatcher, built lazily (shared
        by the dense path and the hybridplane's fused dispatch)."""
        batch_fn = idx.search_by_vector_batch
        b = self._query_batchers.get(vec_name)
        if b is None:
            from weaviate_tpu.runtime.query_batcher import QueryBatcher

            # filtered requests coalesce (bitmask-batched) when the index
            # supports per-query allow lists; the capacity hook powers
            # the batcher's selectivity cutover and reports 0 (= never
            # solo) unless the CURRENT store has a solo gathered path
            # (single-device DeviceVectorStore) — elsewhere a solo
            # dispatch is a full masked scan, strictly worse than riding
            # the batch. Resolved per call: compress()/upgrade() swap
            # idx.store after the batcher exists.
            def _gathered_capacity(i=idx) -> int:
                s = getattr(i, "store", None)
                es = getattr(i, "epoch_store", None)
                if es is not None:
                    # single-epoch passthrough keeps the solo gathered
                    # cutover (the epoch IS a DeviceVectorStore); a
                    # multi-epoch stack has no host-remap solo path, so
                    # selective filters ride the batched bitmask there
                    if (es.mesh is None and not es.quantization
                            and es.epoch_count == 1):
                        return es.capacity
                    return 0
                if (s is None or getattr(s, "mesh", None) is not None
                        or not hasattr(s, "_dispatch_gathered")):
                    return 0
                return s.capacity

            # zero-sync pipeline: resolved through getattr PER CALL so a
            # compress()/DynamicIndex.upgrade() swapping the impl under
            # the cached batcher degrades to the sync path (None) instead
            # of pinning a stale bound method
            def _async_batch(queries, k2, allow=None, i=idx):
                fn = getattr(i, "search_by_vector_batch_async", None)
                return None if fn is None else fn(queries, k2, allow)

            # fused sparse+dense drain (ISSUE 18): hybrid rows ride the
            # same coalescing window as plain vector queries; resolved
            # per call for the same impl-swap reason as _async_batch
            def _hybrid_batch(queries, k2, allows=None, sparses=None,
                              i=idx):
                fn = getattr(i, "hybrid_batch_async", None)
                return None if fn is None else fn(queries, k2, allows,
                                                  sparses)

            b = self._query_batchers.setdefault(
                vec_name,
                QueryBatcher(
                    batch_fn,
                    # callable: DynamicIndex upgrades / compress() can
                    # change the capability under the cached batcher
                    supports_filter_batching=lambda i=idx: bool(
                        getattr(i, "supports_batched_filters", False)),
                    capacity_fn=_gathered_capacity,
                    pad_pow2=bool(getattr(idx, "compiled_batch_shapes",
                                          True)),
                    async_batch_fn=(_async_batch if self.async_pipeline
                                    else None),
                    hybrid_batch_fn=_hybrid_batch,
                    owner={"collection": self.collection_name,
                           "shard": self.name,
                           "tenant": self._tenant_label()},
                    # kernelscope variant label: residency EWMAs key on
                    # (index kind, b bucket, k bucket) compiled variants
                    kind=str(getattr(idx, "index_type", "index")),
                ))
        return b

    def _index_queue(self, vec_name: str, idx):
        q = self._index_queues.get(vec_name)
        if q is None:
            from weaviate_tpu.runtime.index_queue import IndexQueue

            q = IndexQueue(idx)
            self._index_queues[vec_name] = q
        return q

    def _delete_doc(self, doc_id: int, uuid: str, old=None):
        for q in self._index_queues.values():
            q.delete(doc_id)  # drop any queued insert for this doc
        for idx in self.vector_indexes.values():
            if idx is not None:
                idx.delete(doc_id)
        if old is None:
            old = self.get_object(uuid)
        if old is not None:
            self._inverted.unindex_object(old)
        self._doc_to_uuid.pop(doc_id, None)

    def _delete_docs_batch(self, pairs: list[tuple[int, str]]) -> None:
        """Batched twin of ``_delete_doc`` for the update path: one
        vector-index delete (one device tombstone scatter), one batched
        object fetch, one inverted unindex pass."""
        doc_ids = [d for d, _u in pairs]
        for q in self._index_queues.values():
            for d in doc_ids:
                q.delete(d)
        for idx in self.vector_indexes.values():
            if idx is not None:
                idx.delete(*doc_ids)
        raws = self.objects.get_many([u.encode() for _d, u in pairs])
        olds = [StorageObject.from_bytes(r) for r in raws if r is not None]
        if olds:
            self._inverted.unindex_objects(olds)
        for d in doc_ids:
            self._doc_to_uuid.pop(d, None)

    def delete_object(self, uuid: str, tombstone_ms: int | None = None) -> bool:
        import time as _time

        with self._lock:
            if self.read_only:
                raise ShardReadOnlyError(
                    f"shard {self.name!r} is read-only (status READONLY)")
            raw = self.docid.get(uuid.encode())
            if raw is None:
                return False
            # same ordering invariant as the put path: the object/docid
            # deletes commit FIRST, the inverted unindex follows — a crash
            # in between leaves benign ghost postings, never a visible
            # object invisible to filters/BM25
            old = self.get_object(uuid)
            self.docid.delete(uuid.encode())
            self.objects.delete(uuid.encode())
            self.tombstones.put(uuid.encode(),
                                tombstone_ms or int(_time.time() * 1000))
            self._delete_doc(int(raw), uuid, old=old)
            return True

    # -- read path -----------------------------------------------------------

    def get_object(self, uuid: str) -> StorageObject | None:
        raw = self.objects.get(uuid.encode())
        if raw is None:
            return None
        return StorageObject.from_bytes(raw)

    def exists(self, uuid: str) -> bool:
        return self.docid.get(uuid.encode()) is not None

    def object_count(self) -> int:
        # exact and O(1): maintained by put/delete/restore (len(self.docid)
        # would re-scan every segment per key)
        return len(self._doc_to_uuid)

    def object_by_doc_id(self, doc_id: int) -> StorageObject | None:
        uuid = self._doc_to_uuid.get(int(doc_id))
        return None if uuid is None else self.get_object(uuid)

    def objects_by_doc_ids(self, doc_ids) -> list[StorageObject | None]:
        """Batched doc-id -> object resolution: ONE ``kv.get_many``
        layer snapshot for the whole id list instead of a point lookup
        (lock + sealed-list copy) per doc — the native data plane's
        reply-building feed (warm pass + cache-miss fill) reads through
        here, so property fetch on the hot path is one LSM batch per
        reply batch."""
        uuids = [self._doc_to_uuid.get(int(d)) for d in doc_ids]
        keys = [u.encode() for u in uuids if u is not None]
        if not keys:
            return [None] * len(uuids)
        raws = iter(self.objects.get_many(keys))
        out: list[StorageObject | None] = []
        for u in uuids:
            if u is None:
                out.append(None)
                continue
            raw = next(raws)
            out.append(None if raw is None
                       else StorageObject.from_bytes(raw))
        return out

    def vector_search(self, query: np.ndarray, k: int, vec_name: str = "",
                      allow_list: np.ndarray | None = None):
        """(doc_ids, dists) for the shard-local search (reference:
        shard_read.go ObjectVectorSearch). With async indexing on, queued
        (not-yet-indexed) vectors are brute-forced and merged so the path
        stays read-your-writes (reference: index queue search over the
        unindexed tail)."""
        idx = self.vector_indexes.get(vec_name)
        if idx is None:
            return np.empty(0, np.int64), np.empty(0, np.float32)
        with tracing.span("shard.vector_search", shard=self.name, k=k,
                          filtered=allow_list is not None):
            return self._vector_search_traced(idx, query, k, vec_name,
                                              allow_list)

    def _vector_search_traced(self, idx, query, k, vec_name, allow_list):
        # snapshot BEFORE the index search: every queued vector is either
        # in the snapshot or already drained into the index by the time
        # the index search runs — the union misses nothing (the reverse
        # order races a drain finishing between the two reads)
        queued = self._queued_candidates(vec_name, query, allow_list)
        if self.dynamic_batching and query.ndim == 1:
            ids, dists = self._batched_search(vec_name, idx, query, k,
                                              allow_list)
        else:
            ids, dists = idx.search_by_vector(query, k, allow_list=allow_list)
        if queued is None:
            return ids, dists
        q_ids, q_dists = queued
        cat_ids = np.concatenate([np.asarray(ids, np.int64), q_ids])
        cat_d = np.concatenate([np.asarray(dists, np.float32), q_dists])
        order = np.argsort(cat_d, kind="stable")
        # dedup (a drain may have landed an in-flight vector in the index
        # between the index search and the snapshot), best distance first
        seen: set = set()
        out_ids, out_d = [], []
        for j in order:
            did = int(cat_ids[j])
            if did in seen:
                continue
            seen.add(did)
            out_ids.append(did)
            out_d.append(float(cat_d[j]))
            if len(out_ids) == k:
                break
        return (np.asarray(out_ids, np.int64),
                np.asarray(out_d, np.float32))

    def vector_search_batch(self, queries: np.ndarray, k: int,
                            vec_name: str = ""):
        """Batched twin of vector_search for the native data plane's
        coalesced dispatch (csrc/dataplane.cpp): one index batch search,
        queued (not-yet-indexed) vectors brute-forced against the whole
        query block and merged per row. No filters — filtered queries
        take the fallback path. Returns (ids [B, k], dists [B, k],
        counts [B]); dead rows are -1-padded."""
        idx = self.vector_indexes.get(vec_name)
        b = len(queries)
        if idx is None:
            return (np.full((b, k), -1, np.int64),
                    np.full((b, k), np.inf, np.float32),
                    np.zeros(b, np.int64))
        queue = self._index_queues.get(vec_name)
        pending = queue.snapshot() if queue is not None else []
        ids, dists = idx.search_by_vector_batch(queries, k)
        return self._finish_batch_results(ids, dists, pending, queries,
                                          idx.metric, k)

    def vector_search_batch_async(self, queries: np.ndarray, k: int,
                                  vec_name: str = ""):
        """Dispatch-only twin of ``vector_search_batch`` for the native
        data plane's pipelined loop (ISSUE 7): returns a
        ``DeviceResultHandle`` resolving to the same (ids, dists,
        counts), or ``None`` when the index has no async path — the
        plane then falls back to the synchronous call. The queued-tail
        snapshot is taken BEFORE the index dispatch (same ordering
        invariant as ``_vector_search_traced``) and merged in the
        handle's host finish step."""
        idx = self.vector_indexes.get(vec_name)
        if idx is None:
            return None
        fn = getattr(idx, "search_by_vector_batch_async", None)
        if fn is None:
            return None
        queue = self._index_queues.get(vec_name)
        pending = queue.snapshot() if queue is not None else []
        handle = fn(queries, k)
        if handle is None:
            return None
        queries = np.asarray(queries, np.float32)

        def _finish(res, _pending=pending, _queries=queries, _k=k,
                    _metric=idx.metric):
            ids, dists = res
            return self._finish_batch_results(ids, dists, _pending,
                                              _queries, _metric, _k)

        return handle.map(_finish)

    def _finish_batch_results(self, ids, dists, pending, queries,
                              metric: str, k: int):
        """Host half shared by the sync and pipelined batch paths:
        merge the queued (not-yet-indexed) tail, count live rows."""
        b = len(queries)
        ids = np.asarray(ids, np.int64)
        dists = np.asarray(dists, np.float32)
        if pending:
            q_ids = np.asarray([d for d, _ in pending], np.int64)
            q_vecs = np.stack([v for _, v in pending]).astype(np.float32)
            qd = self._host_pairwise(np.asarray(queries, np.float32),
                                     q_vecs, metric)  # [B, nq]
            cat_ids = np.concatenate(
                [ids, np.broadcast_to(q_ids, (b, len(q_ids)))], axis=1)
            cat_d = np.concatenate([dists, qd.astype(np.float32)], axis=1)
            order = np.argsort(cat_d, axis=1, kind="stable")
            out_i = np.full((b, k), -1, np.int64)
            out_d = np.full((b, k), np.inf, np.float32)
            for r in range(b):
                seen: set = set()
                n = 0
                for j in order[r]:
                    did = int(cat_ids[r, j])
                    if did < 0 or did in seen:
                        continue
                    seen.add(did)
                    out_i[r, n] = did
                    out_d[r, n] = cat_d[r, j]
                    n += 1
                    if n == k:
                        break
            ids, dists = out_i, out_d
        counts = (ids >= 0).sum(axis=1).astype(np.int64)
        return ids, dists, counts

    @staticmethod
    def _host_pairwise(qs: np.ndarray, vecs: np.ndarray,
                       metric: str) -> np.ndarray:
        """[B, n] host-BLAS distances (queued-tail scoring; see the
        numpy-not-jit note in _queued_candidates)."""
        if metric in ("cosine", "cosine-dot"):
            def unit(a):
                n = np.linalg.norm(a, axis=-1, keepdims=True)
                return a / np.where(n > 1e-30, n, 1.0)

            return 1.0 - unit(qs) @ unit(vecs).T
        if metric == "dot":
            return -(qs @ vecs.T)
        if metric == "hamming":
            return (qs[:, None, :] != vecs[None, :, :]).sum(-1).astype(
                np.float32)
        if metric == "manhattan":
            return np.abs(qs[:, None, :] - vecs[None, :, :]).sum(-1)
        sq = (qs ** 2).sum(-1)[:, None] + (vecs ** 2).sum(-1)[None, :]
        return sq - 2.0 * (qs @ vecs.T)

    def _queued_candidates(self, vec_name: str, query: np.ndarray,
                           allow_list: np.ndarray | None):
        queue = self._index_queues.get(vec_name)
        if queue is None:
            return None
        pending = queue.snapshot()
        if not pending:
            return None
        ids = np.asarray([d for d, _ in pending], dtype=np.int64)
        vecs = np.stack([v for _, v in pending]).astype(np.float32)
        if allow_list is not None:
            allow = np.asarray(allow_list)
            if allow.dtype == np.bool_:
                keep = (ids < len(allow)) & allow[
                    np.clip(ids, 0, len(allow) - 1)]
            else:
                keep = np.isin(ids, allow.astype(np.int64))
            ids, vecs = ids[keep], vecs[keep]
            if not len(ids):
                return None
        metric = getattr(self.vector_indexes.get(vec_name), "metric",
                         "l2-squared")
        # plain numpy: the pending set's length changes every drain tick,
        # and a jitted path would recompile per distinct length (the
        # device store pads to buckets for exactly this reason) — the
        # queue is small, host BLAS is plenty
        q = np.asarray(query, np.float32)
        d = self._host_pairwise(q[None, :], vecs, metric)[0]
        return ids, d.astype(np.float32)

    def bm25_search(self, query: str, k: int = 10,
                    properties: list[str] | None = None,
                    allow_mask: np.ndarray | None = None):
        """(doc_ids, scores) keyword search (reference: shard ObjectSearch →
        inverted.BM25Searcher). ``allow_mask`` accepts either form the
        vector path does: bool mask or doc-id array."""
        with tracing.span("shard.bm25_search", shard=self.name, k=k,
                          filtered=allow_mask is not None):
            return self._inverted.bm25_search(query, k, properties,
                                              self._norm_allow(allow_mask))

    def _norm_allow(self, allow_mask):
        """Allow-list normalization shared by the keyword and hybrid
        paths: bool mask passes through, doc-id arrays densify over this
        shard's doc-id space."""
        if allow_mask is None:
            return None
        allow_mask = np.asarray(allow_mask)
        if allow_mask.dtype != np.bool_:
            ids = allow_mask.astype(np.int64)
            allow_mask = np.zeros(self.doc_id_space, dtype=bool)
            allow_mask[ids[ids < len(allow_mask)]] = True
        return allow_mask

    # -- hybrid dataplane (ISSUE 18) ------------------------------------------

    def _hybrid_index(self, vec_name: str):
        """The vector index for ``vec_name`` iff it can run the fused
        device hybrid program (and the kill switch is off)."""
        if not self.device_hybrid:
            return None
        idx = self.vector_indexes.get(vec_name)
        if idx is None or not getattr(idx, "supports_device_hybrid",
                                      False):
            return None
        return idx

    def _hybrid_operand(self, idx, query: str, k: int, alpha: float,
                        fusion: str, properties, allow_mask):
        """Plan one hybrid query's sparse leg for device scoring:
        ``bm25_pack`` picks the candidate universe + per-segment
        operands, doc ids translate to store slots. None = this query
        can't ride the device path (no candidates, budget blown, or a
        candidate isn't resident in the vector index)."""
        from weaviate_tpu.ops.bm25 import SparseOperand, fusion_kind

        pack = self._inverted.bm25_pack(
            query, properties, allow_mask,
            max_candidates=self.hybrid_max_candidates)
        if pack is None:
            return None
        slots = idx.slots_for_doc_ids(pack["doc_ids"])
        if len(slots) == 0 or (slots < 0).any():
            # a candidate missing from the vector index would silently
            # vanish from the sparse leg — host fallback keeps recall
            return None
        return SparseOperand(
            pack["doc_ids"], slots, pack["seg_tf"], pack["seg_len"],
            pack["seg_term"], pack["seg_boost"], pack["seg_avg"],
            pack["idf"], pack["k1"], pack["b"], pack["one_minus_b"],
            float(alpha), fusion_kind(fusion),
            max(k * 10, 100),  # host reference over-fetch (collection.py)
            pack["stats"])

    def hybrid_search(self, query: str, vector, k: int = 10,
                      alpha: float = 0.75, fusion: str = "rankedFusion",
                      properties: list[str] | None = None,
                      vec_name: str = "",
                      allow_mask: np.ndarray | None = None):
        """Fused device hybrid (ISSUE 18): ONE batched device program
        runs the dense scan, BM25F-scores the packed sparse candidates,
        and merges the legs (RRF / relative-score) — no host scoring, no
        second dispatch. Single queries coalesce with concurrent vector
        and hybrid traffic through the shard's QueryBatcher. Returns
        (doc_ids, fused_scores) or None when the device path can't serve
        this query — callers then run the host reference path
        (text/hybrid.py)."""
        idx = self._hybrid_index(vec_name)
        if idx is None or vector is None:
            return None
        queue = self._index_queues.get(vec_name)
        if queue is not None and queue.snapshot():
            # queued (not-yet-indexed) vectors are invisible to the
            # device dense leg; the host path brute-forces that tail
            return None
        allow_mask = self._norm_allow(allow_mask)
        with tracing.span("shard.hybrid_search", shard=self.name, k=k,
                          filtered=allow_mask is not None):
            op = self._hybrid_operand(idx, query, k, alpha, fusion,
                                      properties, allow_mask)
            if op is None:
                return None
            from weaviate_tpu.runtime.query_batcher import \
                DeviceHybridUnavailable

            q = np.asarray(vector, np.float32)
            try:
                if self.dynamic_batching and q.ndim == 1:
                    b = self._query_batcher(vec_name, idx)
                    ids, dists = b.search(q, k, allow_mask, sparse=op)
                else:
                    h = idx.hybrid_batch_async(
                        np.atleast_2d(q), k,
                        [allow_mask] if allow_mask is not None else None,
                        [op])
                    if h is None:
                        return None
                    ids, dists = h.result()
                    ids, dists = ids[0], dists[0]
            except DeviceHybridUnavailable:
                return None
            ids = np.asarray(ids)[:k]
            dists = np.asarray(dists)[:k]
            live = ids >= 0
            # hybrid rows carry NEGATED fused scores on the distance
            # plane; flip back for the caller
            return (ids[live].astype(np.int64),
                    (-dists[live]).astype(np.float32))

    def hybrid_search_async(self, query: str, vector, k: int = 10,
                            alpha: float = 0.75,
                            fusion: str = "rankedFusion",
                            properties: list[str] | None = None,
                            vec_name: str = "",
                            allow_mask: np.ndarray | None = None):
        """Dispatch-only twin of ``hybrid_search``: returns a
        ``DeviceResultHandle`` resolving to the same (doc_ids,
        fused_scores), with the D2H draining on the TransferPipeline
        while the caller dispatches more work. None = host fallback
        (same conditions as the sync path)."""
        idx = self._hybrid_index(vec_name)
        if idx is None or vector is None:
            return None
        queue = self._index_queues.get(vec_name)
        if queue is not None and queue.snapshot():
            return None
        allow_mask = self._norm_allow(allow_mask)
        op = self._hybrid_operand(idx, query, k, alpha, fusion,
                                  properties, allow_mask)
        if op is None:
            return None
        q = np.atleast_2d(np.asarray(vector, np.float32))
        h = idx.hybrid_batch_async(
            q, k, [allow_mask] if allow_mask is not None else None, [op])
        if h is None:
            return None

        def _finish(res, _k=k):
            ids, dists = res
            ids = np.asarray(ids)[0][:_k]
            dists = np.asarray(dists)[0][:_k]
            live = ids >= 0
            return (ids[live].astype(np.int64),
                    (-dists[live]).astype(np.float32))

        return h.map(_finish)

    @property
    def doc_id_space(self) -> int:
        """Upper bound (exclusive) on doc ids ever assigned — the size of
        AllowList masks."""
        return self._counter

    def allow_mask(self, where) -> np.ndarray | None:
        """Filter tree → bool mask over this shard's doc-id space
        (reference: inverted.Searcher → helpers.AllowList)."""
        if where is None:
            return None
        from weaviate_tpu.filters import compute_allow_mask

        with tracing.span("shard.allow_mask", shard=self.name):
            with self._lock:
                return compute_allow_mask(where, self._inverted,
                                          self.doc_id_space)

    def set_read_only(self, value: bool) -> None:
        """Persisted so a restart keeps the freeze (reference persists
        shard status)."""
        with self._lock:
            self.read_only = bool(value)
            self.meta.put(b"read_only", bool(value))

    # -- epoch migration (db/collection.py orchestrates; see
    #    ARCHITECTURE.md "Epoch store") ---------------------------------------

    def _admit_device_bytes(self, nbytes: int) -> None:
        """Both admission gates, typed 507 on either: the device-global
        watermark (memwatch; compaction relieves it) and the per-shard
        quota (ledger bytes vs ``shard_hbm_limit``; epoch MIGRATION
        relieves it — the bytes move to a sibling's ledger scope)."""
        what = f"import {self.collection_name}/{self.name}"
        if self.memwatch is not None:
            self.memwatch.check_device_alloc(nbytes, what=what)
        if self.shard_hbm_limit and self.over_shard_limit(nbytes):
            from weaviate_tpu.runtime.hbm_ledger import ledger
            from weaviate_tpu.runtime.memwatch import \
                InsufficientMemoryError

            used = ledger.shard_bytes(self.collection_name, self.name)
            high = (self.memwatch.high_watermark
                    if self.memwatch is not None else 0.9)
            raise InsufficientMemoryError(
                f"device allocation of {nbytes} bytes ({what}) would "
                f"exceed {high:.0%} of shard HBM quota "
                f"{self.shard_hbm_limit} (ledger usage {used})",
                projected=used + int(nbytes),
                budget=self.shard_hbm_limit, source="ledger")

    def over_shard_limit(self, extra: int = 0) -> bool:
        """Is this shard's ledger footprint (+``extra``) past its quota
        watermark? The epoch policy migrates when this trips."""
        if not self.shard_hbm_limit:
            return False
        from weaviate_tpu.runtime.hbm_ledger import ledger

        high = (self.memwatch.high_watermark
                if self.memwatch is not None else 0.9)
        used = ledger.shard_bytes(self.collection_name, self.name)
        return used + int(extra) > self.shard_hbm_limit * high

    def migrated_to(self, uuid: str) -> str | None:
        """Destination shard of a migrated object, or None. The durable
        marker keeps uuid ring routing correct after an epoch moved its
        objects to a sibling; the in-memory count keeps this a no-op
        when no migration ever happened."""
        if self._migrated_count <= 0:
            return None
        v = self.meta.get(b"migrated:" + uuid.encode())
        if v is None:
            return None
        return v.decode() if isinstance(v, (bytes, bytearray)) else str(v)

    def clear_migrated(self, uuid: str) -> None:
        """Drop a routing override (the object was re-put or deleted at
        its ring home)."""
        with self._lock:
            if self.meta.get(b"migrated:" + uuid.encode()) is not None:
                self.meta.delete(b"migrated:" + uuid.encode())
                self._migrated_count = max(0, self._migrated_count - 1)

    def mark_migrating(self, uuids: list[str], dst_name: str) -> None:
        """Durably record the routing markers (one WAL frame) BEFORE
        the destination ingest: a kill anywhere after this point leaves
        every copy findable — GETs prefer the ring copy and follow the
        marker only on a miss, deletes/re-puts clean BOTH sides through
        the marker, search dedups by uuid. A marker pointing at a copy
        that never landed (kill before ingest) is harmless for the same
        reasons."""
        with self._lock:
            keys = [b"migrated:" + u.encode() for u in uuids]
            fresh = sum(1 for k in keys if self.meta.get(k) is None)
            self.meta.put_many([(k, dst_name) for k in keys])
            self._migrated_count += fresh  # re-marking an interrupted
            # move must not inflate the fast-path counter

    def migrate_out(self, uuids: list[str], dst_name: str) -> int:
        """Source-side cutover AFTER the destination acked the ingest
        (markers were written by ``mark_migrating`` before it): remove
        the objects — batched index tombstones, inverted unindex,
        docid/objects deletes. Crash ordering: a kill before this point
        leaves a double-present object (never a lost one, and the
        pre-ingest markers mean deletes reach both copies); after it,
        reads route through the markers to the destination."""
        with self._lock:
            keys = [u.encode() for u in uuids]
            pairs = []
            for u, k in zip(uuids, keys):
                raw = self.docid.get(k)
                if raw is not None:
                    pairs.append((int(raw), u))
            if pairs:
                self._delete_docs_batch(pairs)
            self.docid.delete_many(keys)
            self.objects.delete_many(keys)
            return len(pairs)

    def epoch_maintenance(self) -> bool:
        """Run the epoch policy for every epoch-backed index on this
        shard: seal overfull actives, drop empty sealed epochs, fold
        tombstone-heavy ones (reclaims HBM through the ledger
        finalizers). Indexes exposing their own ``maintain`` hook (IVF
        delta fold / drift retrain, dynamic's deferred upgrade) get the
        same tick. Returns True when work was done (cyclemanager
        backoff signal)."""
        did = False
        for idx in self.vector_indexes.values():
            es = getattr(idx, "epoch_store", None)
            if es is not None:
                did = es.maintain() or did
            idx_maintain = getattr(idx, "maintain", None)
            if idx_maintain is not None:
                idx_maintain()
        return did

    # -- replication support -------------------------------------------------

    STAGED_TTL_S = 120.0

    def stage(self, request_id: str, task: tuple) -> None:
        """2PC prepare: hold a write until commit/abort
        (reference: replica store staging before commit). A READONLY
        shard votes NO here — failing at prepare keeps all replicas
        consistent instead of silently diverging at commit."""
        import time as _time

        with self._lock:
            if self.read_only:
                raise ShardReadOnlyError(
                    f"shard {self.name!r} is read-only (status READONLY)")
            self._staged[request_id] = (_time.monotonic(), task)

    def gc_staged(self) -> int:
        """Drop staged batches whose coordinator never came back (crash
        between prepare and commit/abort) — anti-entropy re-delivers the
        write if it committed elsewhere. Every expiry is counted
        (``weaviate_tpu_replication_staged_expired_total``): an orphaned
        prepare must neither leak nor commit, and the counter is how a
        chaos run proves the TTL path actually fired."""
        import time as _time

        cutoff = _time.monotonic() - self.staged_ttl_s
        with self._lock:
            stale = [rid for rid, (t, _task) in self._staged.items()
                     if t < cutoff]
            for rid in stale:
                del self._staged[rid]
            self._staged_expired += len(stale)
        if stale:
            self._count_staged_expired(len(stale))
        return len(stale)

    def _count_staged_expired(self, n: int) -> None:
        try:
            from weaviate_tpu.runtime.metrics import (
                replication_staged_expired)

            replication_staged_expired.labels(
                self.collection_name, self.name).inc(n)
        except Exception:  # pragma: no cover — registry unavailable
            pass

    def commit_staged(self, request_id: str):
        """2PC commit. An entry past its TTL is REFUSED, not applied:
        without this, a commit that sat in flight across a partition
        (or a coordinator straggler thread racing the heal) could land
        a stale write long after the rest of the replica set aborted —
        the expiry has to be deterministic at the commit boundary, not
        dependent on whether the gc cycle happened to run first."""
        import time as _time

        with self._lock:
            entry = self._staged.pop(request_id, None)
            if entry is not None \
                    and _time.monotonic() - entry[0] > self.staged_ttl_s:
                self._staged_expired += 1
                self._count_staged_expired(1)
                raise StagedExpiredError(
                    f"replication request {request_id!r} staged "
                    f"{_time.monotonic() - entry[0]:.1f}s ago, past the "
                    f"{self.staged_ttl_s:.0f}s TTL — refused (late "
                    "commit after partition heal)")
        if entry is None:
            raise KeyError(f"unknown replication request {request_id!r}")
        _t, task = entry
        kind = task[0]
        if kind == "put":
            return self.put_object_batch(task[1])
        if kind == "delete":
            return self.delete_object(task[1], tombstone_ms=task[2])
        raise ValueError(f"unknown staged task kind {kind!r}")

    def staged_status(self) -> dict:
        """Introspection for the chaos checker's leak invariant: live
        staged entries (gc'd first so the answer is TTL-deterministic)
        and the total this shard ever expired."""
        self.gc_staged()
        with self._lock:
            return {"staged": len(self._staged),
                    "expired_total": self._staged_expired}

    def abort_staged(self, request_id: str) -> None:
        with self._lock:
            self._staged.pop(request_id, None)

    def object_digest(self, uuid: str) -> dict | None:
        """Replica-comparable digest (reference: Finder digest reads,
        repairer.go). None = never seen here."""
        raw = self.objects.get(uuid.encode())
        if raw is not None:
            obj = StorageObject.from_bytes(raw)
            return {"uuid": uuid, "mtime": obj.last_update_time_ms,
                    "deleted": False, "hash": obj.content_hash()}
        ts = self.tombstones.get(uuid.encode())
        if ts is not None:
            return {"uuid": uuid, "mtime": int(ts), "deleted": True,
                    "hash": b""}
        return None

    def iter_digests(self):
        with self._lock:
            uuids = list(self._doc_to_uuid.values())
            tombs = [(k.decode(), int(v)) for k, v in
                     ((k, self.tombstones.get(k)) for k in
                      self.tombstones.keys()) if v is not None]
        for uuid in uuids:
            d = self.object_digest(uuid)
            if d is not None and not d["deleted"]:
                yield d
        for uuid, ts in tombs:
            yield {"uuid": uuid, "mtime": ts, "deleted": True, "hash": b""}

    def build_hashtree(self, depth: int = 8):
        """Merkle tree over all digests (reference: shard hashtree kept
        by the hashbeater; we rebuild per beat — object counts per shard
        make this cheap relative to the network round-trips saved)."""
        from weaviate_tpu.replication.hashtree import MerkleTree

        tree = MerkleTree(depth)
        for d in self.iter_digests():
            tree.insert(d["uuid"], d["mtime"], d["deleted"], d["hash"])
        return tree

    def bucket_digests(self, depth: int, buckets: list[int]) -> list[dict]:
        """Digest entries falling into the given hashtree leaf buckets."""
        from weaviate_tpu.replication.hashtree import MerkleTree

        want = set(buckets)
        return [d for d in self.iter_digests()
                if MerkleTree.bucket_of(d["uuid"], depth) in want]

    def apply_sync(self, raw_objects: list[bytes],
                   deletes: list[dict]) -> int:
        """Apply newer peer state (anti-entropy propagation). Winner per
        uuid decided by digest_rank (mtime, tombstone-beats-object,
        content-hash tie-break)."""
        from weaviate_tpu.replication.hashtree import digest_rank

        applied = 0
        with self._lock:
            for raw in raw_objects:
                obj = StorageObject.from_bytes(raw)
                if self.migrated_to(obj.uuid):
                    # the durable cutover moved this uuid to its marker
                    # destination: re-applying a peer's (stale) copy here
                    # would resurrect the moved-away object at its old
                    # ring home — double-present to search, and the next
                    # hashbeat would propagate the zombie back out.
                    # Anti-entropy must respect the marker like reads do.
                    logger.debug("apply_sync: skipping %s — migrated to "
                                 "%s", obj.uuid, self.migrated_to(obj.uuid))
                    continue
                mine = self.object_digest(obj.uuid)
                incoming = {"mtime": obj.last_update_time_ms,
                            "deleted": False, "hash": obj.content_hash()}
                if mine is not None and digest_rank(mine) >= digest_rank(incoming):
                    continue
                obj.doc_id = 0  # re-assigned locally
                self.put_object_batch([obj])
                applied += 1
            for d in deletes:
                mine = self.object_digest(d["uuid"])
                incoming = {"mtime": d["mtime"], "deleted": True, "hash": b""}
                if mine is None:
                    # never saw it: record the tombstone so our tree converges
                    self.tombstones.put(d["uuid"].encode(), d["mtime"])
                    applied += 1
                    continue
                if digest_rank(mine) >= digest_rank(incoming):
                    continue
                if mine["deleted"]:
                    self.tombstones.put(d["uuid"].encode(), d["mtime"])
                else:
                    self.delete_object(d["uuid"], tombstone_ms=d["mtime"])
                applied += 1
        return applied

    # -- maintenance ---------------------------------------------------------

    def flush(self):
        for name, q in self._index_queues.items():
            if not q.wait_idle(timeout=30.0):
                logger.warning(
                    "shard %s/%s: index queue %r still has %d queued "
                    "vectors after 30s — flush() returns with the vector "
                    "index lagging the object store",
                    self.collection_name, self.name, name, q.size())
        for b in (self.objects, self.docid, self.meta):
            b.flush()

    def maintenance(self, compact_above: int = 4) -> bool:
        """One background cycle: flush dirty memtables, compact segment
        stacks past the threshold (reference: store_cyclecallbacks.go).
        Returns True when work was done (cyclemanager backoff signal)."""
        from weaviate_tpu.runtime.metrics import (
            lsm_segment_count, vector_index_compressed,
            vector_index_hbm_bytes, vector_index_tombstones)

        did = False
        if self.gc_staged():
            did = True
        for b in self.store.buckets():
            # sealed-memtable flush + threshold compaction, all off the
            # write path (reference: store_cyclecallbacks.go)
            if b.maintain(compact_above=compact_above):
                did = True
            lsm_segment_count.labels(f"{self.collection_name}/{self.name}/{b.name}"
                                     ).set(b.segment_count)
        for vec_name, idx in self.vector_indexes.items():
            if idx is None:
                continue
            labels = (self.collection_name, self.name, vec_name or "default")
            store = getattr(idx, "store", None)
            live = len(idx)
            total = getattr(store, "count", live) if store is not None                 else getattr(idx, "_count", live)
            vector_index_tombstones.labels(*labels).set(max(total - live, 0))
            vector_index_compressed.labels(*labels).set(
                1 if getattr(idx, "compressed", False) else 0)
            hbm = 0
            stores = ([ep.store for ep in store.epochs]
                      if getattr(idx, "epoch_store", None) is not None
                      else [store])
            for st in stores:
                for arr_name in ("vectors", "valid", "sq_norms", "codes",
                                 "rescore_rows", "list_vecs", "list_codes",
                                 "list_valid", "list_slots", "list_norms"):
                    arr = getattr(st, arr_name, None)
                    if arr is not None and hasattr(arr, "nbytes"):
                        hbm += int(arr.nbytes)
            vector_index_hbm_bytes.labels(*labels).set(hbm)
        return did

    def close(self):
        from weaviate_tpu.runtime import driftwatch

        driftwatch.unregister_canaries(
            f"{self.collection_name}/{self.name}/")
        for q in self._index_queues.values():
            q.stop()
        for b in self._query_batchers.values():
            b.stop()
        self.store.close()
