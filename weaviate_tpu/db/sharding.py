"""Sharding state: object -> physical shard routing.

Reference: usecases/sharding/state.go — physical shards with virtual-shard
ring, object routed by murmur3 of the UUID (state.go:167-176); multi-tenant
collections use one shard per tenant (state.go:293).

This implementation keeps the same contract (stable uuid -> shard mapping,
fixed shard count at creation, tenant = shard name) with xxhash64 as the
ring hash — we don't need wire compatibility with the reference, only
stability and dispersion.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import xxhash


def _hash64(s: str) -> int:
    return xxhash.xxh64_intdigest(s)


@dataclass
class ShardingState:
    shard_names: list[str] = field(default_factory=list)
    partitioning_enabled: bool = False  # multi-tenancy
    # node placement: shard name -> list of node names (replication)
    placement: dict[str, list[str]] = field(default_factory=dict)
    # tenant activity status (reference: HOT/COLD tenant offload,
    # models.TenantActivityStatus); absent = HOT
    tenant_status: dict[str, str] = field(default_factory=dict)

    @classmethod
    def create(cls, shard_count: int, nodes: list[str] | None = None,
               replication_factor: int = 1) -> "ShardingState":
        names = [f"shard-{i}" for i in range(shard_count)]
        nodes = nodes or ["node-0"]
        placement = {}
        for i, name in enumerate(names):
            placement[name] = [
                nodes[(i + r) % len(nodes)] for r in range(min(replication_factor,
                                                               len(nodes)))
            ]
        return cls(shard_names=names, placement=placement)

    @classmethod
    def create_partitioned(cls) -> "ShardingState":
        """Multi-tenant: shards appear per tenant."""
        return cls(shard_names=[], partitioning_enabled=True)

    def shard_for(self, uuid: str, tenant: str | None = None) -> str:
        if self.partitioning_enabled:
            if not tenant:
                raise ValueError("multi-tenant collection requires a tenant")
            return tenant
        if not self.shard_names:
            raise ValueError("sharding state has no shards")
        return self.shard_names[_hash64(uuid) % len(self.shard_names)]

    def add_tenant(self, tenant: str, nodes: list[str] | None = None,
                   replication_factor: int = 1):
        if not self.partitioning_enabled:
            raise ValueError("not a multi-tenant collection")
        if tenant not in self.shard_names:
            self.shard_names.append(tenant)
            nodes = nodes or ["node-0"]
            start = _hash64(tenant) % len(nodes)
            self.placement[tenant] = [
                nodes[(start + r) % len(nodes)]
                for r in range(min(replication_factor, len(nodes)))
            ]

    def remove_tenant(self, tenant: str):
        if tenant in self.shard_names:
            self.shard_names.remove(tenant)
            self.placement.pop(tenant, None)
            self.tenant_status.pop(tenant, None)

    def nodes_for(self, shard: str) -> list[str]:
        return self.placement.get(shard, ["node-0"])

    def status_of(self, tenant: str) -> str:
        return self.tenant_status.get(tenant, "HOT")

    def to_dict(self) -> dict:
        return {
            "shard_names": list(self.shard_names),
            "partitioning_enabled": self.partitioning_enabled,
            "placement": {k: list(v) for k, v in self.placement.items()},
            "tenant_status": dict(self.tenant_status),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ShardingState":
        return cls(
            shard_names=list(d.get("shard_names", [])),
            partitioning_enabled=d.get("partitioning_enabled", False),
            placement={k: list(v) for k, v in d.get("placement", {}).items()},
            tenant_status=dict(d.get("tenant_status", {})),
        )
