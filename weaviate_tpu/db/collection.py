"""Collection: shard routing + scatter-gather queries.

Reference: adapters/repos/db/index.go (Index struct :156) — putObject routes
by sharding state (:637), objectVectorSearch scatter-gathers across shards
and merges by distance (:1541-1663). Multi-tenant collections address one
shard per tenant.
"""

from __future__ import annotations

import functools
import logging
import os
import threading
import time
import uuid as uuid_mod
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from weaviate_tpu.db.shard import Shard
from weaviate_tpu.db.sharding import ShardingState
from weaviate_tpu.runtime import degrade
from weaviate_tpu.runtime import metrics as monitoring
from weaviate_tpu.runtime import tracing
from weaviate_tpu.schema.config import CollectionConfig
from weaviate_tpu.storage.objects import StorageObject

logger = logging.getLogger(__name__)


class SearchResult:
    __slots__ = ("uuid", "distance", "score", "object", "shard",
                 "rerank_score")

    def __init__(self, uuid, distance=None, score=None, object=None, shard=None):
        self.uuid = uuid
        self.distance = distance
        self.score = score
        self.object = object
        self.shard = shard
        self.rerank_score = None  # set by the reranker module path

    def __repr__(self):
        return f"SearchResult({self.uuid}, dist={self.distance}, score={self.score})"


def _remote_result(item: dict, shard_name: str) -> "SearchResult":
    raw = item.get("object")
    return SearchResult(
        uuid=item["uuid"], distance=item.get("distance"),
        score=item.get("score"), shard=shard_name,
        object=StorageObject.from_bytes(raw) if raw else None)


def _timed(query_type: str):
    """Record query latency per collection (reference: monitoring
    query-duration metric vecs, usecases/monitoring/prometheus.go) and
    log queries slower than the configured threshold (parsed once in
    runtime/tracing.py — one source for QUERY_SLOW_LOG_*)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            t0 = time.perf_counter()
            with monitoring.query_duration.labels(self.config.name,
                                                  query_type).time(), \
                    tracing.span(f"query.{query_type}",
                                 collection=self.config.name):
                out = fn(self, *args, **kwargs)
            threshold = tracing.get_slow_threshold()
            # inside a trace the ROOT logs slow queries with the full
            # span breakdown — logging here too would double-report
            if threshold > 0 and not tracing.is_active():
                took = time.perf_counter() - t0
                if took >= threshold:
                    import logging

                    logging.getLogger("weaviate_tpu.slow_query").warning(
                        "slow %s query on %s: %.3fs (threshold %.3fs)",
                        query_type, self.config.name, took, threshold)
            return out

        return wrapper

    return deco


class Collection:
    def __init__(self, data_dir: str, config: CollectionConfig,
                 sharding_state: ShardingState | None = None, mesh=None,
                 local_node: str = "node-0", on_sharding_change=None,
                 memwatch=None, remote=None, nodes_provider=None,
                 async_indexing: bool | None = None,
                 sync_wal: bool | None = None,
                 node_hbm_provider=None):
        config.validate()
        self.config = config
        self.data_dir = data_dir
        self.mesh = mesh
        self.local_node = local_node
        self.memwatch = memwatch
        self.async_indexing = async_indexing  # None = shard reads the env
        self.sync_wal = sync_wal  # None = shard reads PERSISTENCE_WAL_SYNC
        # cross-node data plane (reference: Index holds a
        # sharding.RemoteIndexClient for non-local shards, index.go:1607)
        self.remote = remote
        self._nodes_provider = nodes_provider or (lambda: [local_node])
        # node -> HBM ledger bytes (gossiped meta in a cluster); feeds
        # ledger-driven placement + the cross-node epoch migration
        # target choice. None = only the local ledger is known.
        self._node_hbm_provider = node_hbm_provider
        # cluster hook fn(collection_name, [tenant]) routing auto tenant
        # creation through Raft; None = apply locally (single node)
        self._auto_tenant_hook = None
        # FROZEN-tier offload target (a backup backend); set by Database
        self.offload_backend = None
        self._lock = threading.RLock()
        # reentrancy guard for the epoch memory-pressure rescue (a
        # migration's target-side ingest runs admission too)
        self._rescue_tls = threading.local()
        # at most ONE epoch migration in flight per collection: the
        # mover holds the SOURCE shard's lock across ingest + cutover
        # (so concurrent writes to the moving uuids can't be lost), and
        # serializing migrations means only one thread ever nests two
        # shard locks — no ABBA ordering can arise. RLock: a rescue
        # fired from a migration's own target-side admission re-enters.
        self._migrate_lock = threading.RLock()
        # Sharded per-uuid write locks for read-modify-write flows
        # (reference appends, PATCH) — the RMW must be atomic per object but
        # must not hold the collection-wide lock across a replicated put,
        # where one slow replica's 2PC RPC would block every unrelated
        # request (reference analog: vector/common/sharded_locks.go).
        self._uuid_locks = [threading.RLock() for _ in range(64)]
        if sharding_state is None:
            if config.multi_tenancy.enabled:
                sharding_state = ShardingState.create_partitioned()
            else:
                # ledger-driven placement (ROADMAP item 2): round-robin
                # starts at the node with the most HBM headroom, so a
                # new collection's shards land on light nodes first
                sharding_state = ShardingState.create(
                    config.sharding.desired_count,
                    nodes=self._placement_nodes(),
                    replication_factor=config.replication.factor,
                )
        self.sharding = sharding_state
        # persistence hook: auto-created tenants must reach the schema store
        # or they vanish from sharding state on restart
        self._on_sharding_change = on_sharding_change or (lambda col: None)
        self.shards: dict[str, Shard] = {}
        for name in self.sharding.shard_names:
            if self.local_node in self.sharding.nodes_for(name) and \
                    self.sharding.status_of(name) not in ("COLD", "FROZEN"):
                self._load_shard(name)  # COLD/FROZEN tenants stay unloaded
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix=f"{config.name}-search")
        # hot/cold tenant tracking (reference: entities/tenantactivity +
        # rest/tenantactivity/handler.go): tenant -> last access stamps
        self.tenant_activity: dict[str, dict] = {}

    def uuid_lock(self, uuid: str) -> threading.RLock:
        """Lock guarding read-modify-write of one object (sharded by uuid
        hash; collisions just serialize two unrelated RMWs, never deadlock
        since callers take at most one)."""
        return self._uuid_locks[hash(uuid) % len(self._uuid_locks)]

    def _record_tenant(self, tenant: str | None, kind: str) -> None:
        if not tenant or not self.config.multi_tenancy.enabled:
            return
        now = time.time()
        with self._lock:  # REST reads snapshot under the same lock
            entry = self.tenant_activity.setdefault(
                tenant, {"reads": 0, "writes": 0, "lastRead": None,
                         "lastWrite": None})
            if kind == "read":
                entry["reads"] += 1
                entry["lastRead"] = now
            else:
                entry["writes"] += 1
                entry["lastWrite"] = now

    def tenant_activity_snapshot(self) -> dict:
        with self._lock:
            return {t: dict(v) for t, v in self.tenant_activity.items()}

    def apply_runtime_config(self) -> None:
        """Propagate runtime-mutable config (reference: UpdateUserConfig →
        hnsw/config_update.go) into LIVE shard objects, which copied
        config values at construction: BM25 k1/b and per-index search
        knobs (ef / nprobe / rescore / upgrade threshold)."""
        with self._lock:
            shards = list(self.shards.values())
        for shard in shards:
            inv = shard._inverted
            inv.k1 = self.config.inverted.bm25_k1
            inv.b = self.config.inverted.bm25_b
            for vec_name, idx in shard.vector_indexes.items():
                vc = self.config.vector_config(vec_name)
                if idx is None or vc is None:
                    continue
                for attr, value in (
                    ("ef", vc.index.ef),
                    ("rescore_limit", vc.index.rescore_limit),
                    ("nprobe", vc.index.ivf_nprobe),
                    ("threshold", vc.index.flat_to_ann_threshold),
                ):
                    # 0 is meaningful (= auto); only skip absent values
                    if hasattr(idx, attr) and value is not None:
                        setattr(idx, attr, value)
                if vc.index.quantization and not idx.compressed and \
                        hasattr(idx, "compress"):
                    # runtime compression enable (compress.go:38): train
                    # on live contents and swap to the compressed path.
                    # Too little data to train yet is not an error — the
                    # config sticks and a later update/restart retries
                    # (the reference also defers until enough objects).
                    try:
                        idx.compress(
                            quantization=vc.index.quantization,
                            pq_segments=vc.index.pq_segments,
                            pq_centroids=vc.index.pq_centroids,
                        )
                    except (RuntimeError, ValueError) as e:
                        import logging

                        logging.getLogger(__name__).warning(
                            "collection %s/%s: deferring runtime "
                            "compression: %s", self.config.name,
                            vec_name, e)

    # -- shard management ----------------------------------------------------

    def _load_shard(self, name: str) -> Shard:
        # check-then-insert under the lock: two concurrent writers must not
        # construct two Shard objects (two WALs, two doc counters) for the
        # same on-disk shard
        with self._lock:
            if name not in self.shards:
                shard = Shard(
                    self.data_dir, self.config, name, mesh=self.mesh,
                    memwatch=self.memwatch,
                    async_indexing=self.async_indexing,
                    sync_wal=self.sync_wal)
                # admission rescue: compact tombstone-heavy epochs,
                # then migrate the coldest sealed epoch to a sibling
                # with headroom, BEFORE a 507 latches (epoch policy)
                shard.memory_rescue = (
                    lambda s=shard: self._rescue_shard(s))
                self.shards[name] = shard
            return self.shards[name]

    def _require_active(self, tenant: str) -> None:
        """COLD/FROZEN tenants reject access unless auto-activation is on
        (reference: tenant activityStatus + autoTenantActivation)."""
        status = self.sharding.status_of(tenant)
        if status in ("COLD", "FROZEN"):
            if self.config.multi_tenancy.auto_tenant_activation:
                self.set_tenant_status(tenant, "HOT")
            else:
                raise ValueError(
                    f"tenant {tenant!r} is not active (activityStatus "
                    f"{status}); activate it or enable "
                    "autoTenantActivation")

    def _check_tenant(self, tenant: str | None, kind: str = "read") -> None:
        if self.config.multi_tenancy.enabled:
            if not tenant:
                raise ValueError("multi-tenant collection requires a tenant")
            if tenant not in self.sharding.shard_names:
                raise KeyError(f"tenant {tenant!r} does not exist")
            self._require_active(tenant)
            self._record_tenant(tenant, kind)

    def _ensure_tenant_shard(self, tenant: str | None) -> None:
        if not self.config.multi_tenancy.enabled:
            return
        with self._lock:
            if tenant in self.sharding.shard_names:
                self._require_active(tenant)
                self._record_tenant(tenant, "write")
                return
            if not self.config.multi_tenancy.auto_tenant_creation:
                raise KeyError(f"tenant {tenant!r} does not exist")
            hook = self._auto_tenant_hook
            if hook is None:
                self.sharding.add_tenant(
                    tenant, nodes=self._nodes_provider(),
                    replication_factor=self.config.replication.factor)
                self._on_sharding_change(self)
                self._record_tenant(tenant, "write")
                return
        # cluster mode: tenant creation must go through Raft so every node
        # applies the same placement — a local-only mutation would diverge
        # from the replica that has to accept the write. Called OUTSIDE the
        # collection lock: the FSM apply (another thread on followers)
        # needs that lock to install the tenant.
        hook(self.config.name, [tenant])
        if tenant not in self.sharding.shard_names:
            raise RuntimeError(f"auto tenant creation for {tenant!r} did "
                               "not converge")
        self._record_tenant(tenant, "write")

    def _reported_hbm(self) -> dict:
        """The hbm provider's reading (gossiped ``hbmBytes`` meta in a
        cluster), {} when no provider is wired or it fails — stale
        gossip must never fail collection creation or migration."""
        if self._node_hbm_provider is None:
            return {}
        try:
            return {str(k): int(v) for k, v in
                    dict(self._node_hbm_provider()).items()}
        except Exception:  # noqa: BLE001
            return {}

    def _node_hbm_bytes(self, reported: dict | None = None) -> dict:
        """node -> known HBM ledger bytes. The local node always reads
        its own ledger (authoritative); other nodes come from the
        provider reading (pass ``reported`` to reuse one already
        fetched), defaulting to 0 — an unknown node is assumed empty,
        which keeps single-node behavior identical to the
        pre-placement code."""
        from weaviate_tpu.runtime.hbm_ledger import ledger

        out = dict(reported) if reported is not None \
            else self._reported_hbm()
        out[self.local_node] = ledger.total_bytes()
        return out

    def _placement_nodes(self) -> list[str]:
        """Candidate nodes ordered by HBM headroom (lightest ledger
        first; sort is stable so equally-loaded nodes keep the
        provider's order). ShardingState.create round-robins shards
        from index 0, so the lightest node receives the first shard(s)
        of every new collection.

        Ranking engages only when at least one PEER (a node other than
        this one) has actually reported through the hbm provider
        (gossip in a cluster): with no peer information, the provider's
        order stands — the gossip view always contains this node's own
        reading, and comparing the local live ledger against
        unreported-as-zero peers would spuriously demote the local node
        on every non-empty process."""
        nodes = list(self._nodes_provider())
        reported = self._reported_hbm()
        if not any(n != self.local_node for n in reported):
            return nodes
        hbm = self._node_hbm_bytes(reported)
        return sorted(nodes, key=lambda n: hbm.get(n, 0))

    def _require_remote(self, shard_name: str):
        if self.remote is None:
            raise RuntimeError(
                f"shard {shard_name!r} is placed on "
                f"{self.sharding.nodes_for(shard_name)} but node "
                f"{self.local_node!r} has no remote client configured")
        return self.remote

    def _is_local(self, shard_name: str) -> bool:
        return self.local_node in self.sharding.nodes_for(shard_name)

    def _read_node(self, shard_name: str) -> str:
        """Preferred replica for a read: local if we own it, else the
        first placed node (reference: Finder picks the local/first
        replica for direct reads)."""
        if self._is_local(shard_name):
            return self.local_node
        return self.sharding.nodes_for(shard_name)[0]

    def _remote_search_degraded(self, shard_name: str, **kwargs):
        """Remote-shard scatter leg with replica failover and graceful
        degradation: try each placed replica in read-preference order
        (the per-peer circuit breaker makes a known-dead node cost ~0
        deadline budget); when every replica is unreachable, return
        ``None`` — the shard contributes NOTHING, the query still
        answers, and an explicit ``missing_shard`` marker rides the
        response (surfaced by the REST edge + the degraded counter)
        instead of the whole-query failure a single dead replica used
        to cause."""
        from weaviate_tpu.cluster.transport import RpcError

        remote = self._require_remote(shard_name)
        nodes = [n for n in self.sharding.nodes_for(shard_name)
                 if n != self.local_node]
        last: Exception | None = None
        for i, node in enumerate(nodes):
            try:
                items = remote.search_shard(node, self.config.name,
                                            shard_name, **kwargs)
            except RpcError as e:
                last = e
                # NOT a degraded marker: if a later replica serves, the
                # answer is complete — failover is an implementation
                # detail, and marking it partial would make clients
                # distrust full results
                import logging

                logging.getLogger(__name__).warning(
                    "replica %s failed for %s/%s, failing over: %s",
                    node, self.config.name, shard_name, e)
                continue
            return items
        degrade.report("missing_shard", collection=self.config.name,
                       shard=shard_name,
                       detail=str(last) if last is not None
                       else "no reachable replica")
        return None

    def _target_shard_names(self, tenant: str | None,
                            kind: str = "read") -> list[str]:
        if self.config.multi_tenancy.enabled:
            if not tenant:
                raise ValueError("multi-tenant collection requires a tenant")
            if tenant not in self.sharding.shard_names:
                raise KeyError(f"tenant {tenant!r} does not exist")
            self._require_active(tenant)
            self._record_tenant(tenant, kind)
            return [tenant]
        return list(self.sharding.shard_names)

    def _target_shards(self, tenant: str | None) -> list[Shard]:
        """LOCAL shards addressed by a query (all shards on a single
        node; the locally-placed subset in a cluster)."""
        return [self._load_shard(n) for n in self._target_shard_names(tenant)
                if self._is_local(n)]

    # -- tenants -------------------------------------------------------------

    def add_tenant(self, tenant: str, nodes: list[str] | None = None):
        with self._lock:
            self.sharding.add_tenant(
                tenant, nodes=nodes or self._nodes_provider(),
                replication_factor=self.config.replication.factor)
            if self._is_local(tenant):
                self._load_shard(tenant)
            self._on_sharding_change(self)

    def remove_tenant(self, tenant: str):
        with self._lock:
            shard = self.shards.pop(tenant, None)
            if shard is not None:
                shard.close()
            self.sharding.remove_tenant(tenant)

    def tenants(self) -> list[str]:
        return list(self.sharding.shard_names) if self.config.multi_tenancy.enabled else []

    def set_tenant_status(self, tenant: str, status: str) -> None:
        """HOT/COLD/FROZEN tenant offload (reference: PUT tenants with
        activityStatus; COLD unloads the shard from memory/HBM, files
        stay on disk; FROZEN ships the files to the offload backend and
        removes them locally — entities/tenantactivity + offload
        modules; HOT loads it back)."""
        status = status.upper()
        if status not in ("HOT", "COLD", "FROZEN"):
            raise ValueError(
                "tenant activityStatus must be HOT, COLD or FROZEN")
        if tenant not in self.sharding.shard_names:
            raise KeyError(f"tenant {tenant!r} does not exist")
        with self._lock:
            prev = self.sharding.status_of(tenant)
            if status == prev:
                return
            # the side effect runs BEFORE the status commits: a failed
            # freeze/thaw (no offload backend, backend error) leaves the
            # tenant in its previous, working state instead of wedged
            if prev == "FROZEN" and status in ("HOT", "COLD"):
                self._unfreeze_tenant(tenant)
            if status == "FROZEN":
                self._freeze_tenant(tenant)
            elif status == "COLD":
                shard = self.shards.pop(tenant, None)
                if shard is not None:
                    shard.close()
            elif self._is_local(tenant):
                self._load_shard(tenant)
            self.sharding.tenant_status[tenant] = status
            self._on_sharding_change(self)

    def _offload_backend(self):
        backend = self.offload_backend
        if backend is None:
            raise RuntimeError(
                "FROZEN tenants need an offload backend: configure a "
                "backup module and OFFLOAD_BACKEND (reference: offload-s3 "
                "module + tenant activityStatus FROZEN)")
        return backend

    def _offload_id(self, tenant: str) -> str:
        return f"tenant-offload--{self.config.name}--{tenant}"

    def _freeze_tenant(self, tenant: str) -> None:
        """Stream the tenant's shard files to the offload backend, then
        delete them locally (reference: FROZEN tier — local resources are
        released entirely; files live in cloud storage)."""
        import json as _json
        import shutil as _shutil

        from weaviate_tpu.backup.cluster import put_file_compressed
        from weaviate_tpu.modules.backup_backends import walk_files

        backend = self._offload_backend()
        shard = self.shards.pop(tenant, None)
        if shard is not None:
            shard.flush()
            shard.close()
        sh_dir = os.path.join(self.data_dir, self.config.name, tenant)
        oid = self._offload_id(tenant)
        backend.initialize(oid)
        # an empty tenant still gets a manifest — thawing must always find
        # one (a manifest-less freeze would wedge the tenant FROZEN)
        stored = [put_file_compressed(backend, oid, rel,
                                      os.path.join(sh_dir, rel))
                  for rel in (walk_files(sh_dir)
                              if os.path.isdir(sh_dir) else [])]
        backend.put(oid, "manifest.json",
                    _json.dumps({"files": stored}).encode())
        _shutil.rmtree(sh_dir, ignore_errors=True)

    def _unfreeze_tenant(self, tenant: str) -> None:
        import json as _json

        from weaviate_tpu.backup.cluster import (get_file_decompressed,
                                                 logical_name)

        backend = self._offload_backend()
        oid = self._offload_id(tenant)
        try:
            manifest = _json.loads(backend.get(oid, "manifest.json"))
        except KeyError:
            # tenant frozen by a pre-manifest version or never offloaded
            # data — nothing to pull back
            manifest = {"files": []}
        sh_dir = os.path.abspath(
            os.path.join(self.data_dir, self.config.name, tenant))
        for stored in manifest.get("files", []):
            dst = os.path.abspath(
                os.path.join(sh_dir, logical_name(stored)))
            if not dst.startswith(sh_dir + os.sep):
                raise ValueError(
                    f"offload manifest path {stored!r} escapes the shard")
            get_file_decompressed(backend, oid, stored, dst)

    # -- object CRUD ---------------------------------------------------------

    def _write_to_shard(self, shard_name: str, objs: list[StorageObject],
                        consistency: str = "QUORUM") -> None:
        """Write a batch to the shard's replicas. Replicated shards take
        the 2PC coordinator (reference: replica.Replicator, replicator.go:57);
        single-replica shards write directly (index.go:922)."""
        nodes = self.sharding.nodes_for(shard_name)
        if len(nodes) > 1:
            from weaviate_tpu.replication import Replicator

            Replicator(self).put_objects(shard_name, objs, consistency)
            return
        node = nodes[0]
        if node == self.local_node:
            shard = self._load_shard(shard_name)
            shard.put_object_batch(objs)
            # clean any migrated sibling copy AFTER the fresh write
            # landed: a 507/crash before the write must leave the old
            # copy intact (double-present is deduped; lost is lost)
            self._unmigrate(shard, objs)
        else:
            self._require_remote(shard_name).put_objects(
                node, self.config.name, shard_name,
                [o.to_bytes() for o in objs])

    def put_object(self, properties: dict, vector=None, vectors: dict | None = None,
                   uuid: str | None = None, tenant: str | None = None,
                   consistency: str = "QUORUM", creation_time_ms: int = 0) -> str:
        """``creation_time_ms``: carried through on updates so a re-put keeps
        the original creation stamp (reference merge semantics)."""
        uuid = uuid or str(uuid_mod.uuid4())
        obj = StorageObject(uuid=uuid, properties=properties,
                            creation_time_ms=creation_time_ms)
        if creation_time_ms:
            # an update keeps its creation stamp but is "touched" now
            obj.last_update_time_ms = int(time.time() * 1000)
        if vector is not None:
            obj.vector = np.asarray(vector, dtype=np.float32)
        for name, vec in (vectors or {}).items():
            obj.vectors[name] = np.asarray(vec, dtype=np.float32)
        if self.config.multi_tenancy.enabled:
            self._ensure_tenant_shard(tenant)
        shard_name = self.sharding.shard_for(uuid, tenant)
        self._write_to_shard(shard_name, [obj], consistency)
        monitoring.objects_total.labels(self.config.name, "put").inc()
        return uuid

    def batch_put(self, objects: list[dict], tenant: str | None = None,
                  consistency: str = "QUORUM") -> list[dict]:
        """Batch import; per-object error reporting, not transactional
        (reference: usecases/objects/batch_add.go)."""
        results = []
        by_shard: dict[str, list[StorageObject]] = {}
        metas: dict[str, list[int]] = {}
        for i, spec in enumerate(objects):
            try:
                uid = spec.get("uuid") or str(uuid_mod.uuid4())
                obj = StorageObject(uuid=uid,
                                    properties=spec.get("properties", {}))
                if spec.get("vector") is not None:
                    obj.vector = np.asarray(spec["vector"], dtype=np.float32)
                for name, vec in (spec.get("vectors") or {}).items():
                    obj.vectors[name] = np.asarray(vec, dtype=np.float32)
                shard_name = self.sharding.shard_for(uid, tenant)
                by_shard.setdefault(shard_name, []).append(obj)
                metas.setdefault(shard_name, []).append(i)
                results.append({"uuid": uid, "status": "SUCCESS"})
            except Exception as e:  # per-object failure, keep going
                results.append({"uuid": spec.get("uuid"), "status": "FAILED",
                                "error": str(e)})
        for shard_name, objs in by_shard.items():
            try:
                if self.config.multi_tenancy.enabled:
                    self._ensure_tenant_shard(shard_name)
                self._write_to_shard(shard_name, objs, consistency)
                monitoring.objects_total.labels(self.config.name, "put"
                                                ).inc(len(objs))
            except MemoryError:
                # admission rejection (memwatch watermark) must surface
                # as the typed 507 at the API layer, not dissolve into
                # per-object FAILED entries under an HTTP 200 — bulk
                # import is the path capacity gating exists for
                raise
            except Exception as e:
                for i in metas[shard_name]:
                    results[i] = {"uuid": results[i]["uuid"], "status": "FAILED",
                                  "error": str(e)}
        return results

    def get_object(self, uuid: str, tenant: str | None = None,
                   consistency: str | None = None) -> StorageObject | None:
        """``consistency``: None = direct read from the preferred replica;
        a level (ONE/QUORUM/ALL) = digest-compared read with read repair
        (reference: Finder.Pull, coordinator.go:178)."""
        self._check_tenant(tenant)
        name = self.sharding.shard_for(uuid, tenant)
        if consistency is not None and len(self.sharding.nodes_for(name)) > 1:
            from weaviate_tpu.replication import Finder

            return Finder(self).get_object(uuid, name, consistency)
        if self._is_local(name):
            shard = self._load_shard(name)
            obj = shard.get_object(uuid)
            if obj is None:
                # epoch migration moved this object to a sibling: the
                # durable marker keeps ring routing correct (the
                # sibling may live on another NODE after a cross-node
                # epoch move)
                dst = shard.migrated_to(uuid)
                if dst and dst != name:
                    if self._is_local(dst):
                        return self._load_shard(dst).get_object(uuid)
                    if self.remote is not None:
                        raw = self._require_remote(dst).get_object(
                            self._read_node(dst), self.config.name,
                            dst, uuid)
                        if raw is not None:
                            return StorageObject.from_bytes(raw)
            return obj
        raw = self._require_remote(name).get_object(
            self._read_node(name), self.config.name, name, uuid)
        return None if raw is None else StorageObject.from_bytes(raw)

    def delete_object(self, uuid: str, tenant: str | None = None,
                      consistency: str = "QUORUM") -> bool:
        self._check_tenant(tenant, kind="write")  # deletes are writes
        name = self.sharding.shard_for(uuid, tenant)
        nodes = self.sharding.nodes_for(name)
        if len(nodes) > 1:
            from weaviate_tpu.replication import Replicator

            ok = Replicator(self).delete(name, uuid, consistency)
        elif nodes[0] == self.local_node:
            shard = self._load_shard(name)
            ok = shard.delete_object(uuid)
            # a migrated copy (or the transient double-present crash
            # window) lives at the marker's destination — delete it too
            # so exactly zero copies remain, and drop the marker
            # (cross-node moves route the delete over the shard RPC)
            dst = shard.migrated_to(uuid)
            if dst and dst != name:
                if self._is_local(dst):
                    ok = self._load_shard(dst).delete_object(uuid) or ok
                elif self.remote is not None:
                    ok = self._require_remote(dst).delete_object(
                        self._read_node(dst), self.config.name, dst,
                        uuid) or ok
            if dst:
                shard.clear_migrated(uuid)
        else:
            ok = self._require_remote(name).delete_object(
                nodes[0], self.config.name, name, uuid)
        if ok:
            monitoring.objects_total.labels(self.config.name, "delete").inc()
        return ok

    def batch_delete(self, where, tenant: str | None = None,
                     dry_run: bool = False, verbose: bool = False,
                     consistency: str = "QUORUM",
                     max_matches: int = 10_000) -> dict:
        """Delete all objects matching a filter (reference: batch_delete —
        REST DELETE /v1/batch/objects and gRPC BatchDelete; match set capped
        at QUERY_MAXIMUM_RESULTS like the reference's dryRun/match cap).
        Returns {"matches", "successful", "failed", "objects": [...]}, where
        ``objects`` is populated per-uuid only when ``verbose``."""
        names = self._target_shard_names(tenant, kind="write")
        where_dict = where.to_dict() if where is not None else None
        uuids: list[str] = []
        for name in names:
            if len(uuids) >= max_matches:
                break
            if self._is_local(name):
                shard = self._load_shard(name)
                mask = shard.allow_mask(where) if where is not None else None
                with shard._lock:
                    items = list(shard._doc_to_uuid.items())
                for doc_id, uid in items:
                    if mask is not None and (doc_id >= len(mask)
                                             or not mask[doc_id]):
                        continue
                    uuids.append(uid)
                    if len(uuids) >= max_matches:
                        break
            else:
                raws = self._require_remote(name).list_objects(
                    self._read_node(name), self.config.name, name,
                    limit=max_matches - len(uuids), where=where_dict)
                uuids.extend(StorageObject.from_bytes(r).uuid for r in raws)
        result = {"matches": len(uuids), "successful": 0, "failed": 0,
                  "objects": []}
        for uid in uuids:
            if dry_run:
                ok, err = True, None
            else:
                try:
                    ok = self.delete_object(uid, tenant, consistency)
                    err = None if ok else "not found"
                except Exception as e:  # per-object errors, not transactional
                    ok, err = False, str(e)
            result["successful" if ok else "failed"] += 1
            if verbose:
                entry = {"id": uid, "successful": ok}
                if err:
                    entry["error"] = err
                result["objects"].append(entry)
        return result

    def object_count(self, tenant: str | None = None) -> int:
        """One replica per shard counts (replicas would double-count)."""
        if self.config.multi_tenancy.enabled and not tenant:
            return 0
        total = 0
        for name in self._target_shard_names(tenant):
            if self._is_local(name):
                total += self._load_shard(name).object_count()
            elif self.remote is not None:
                total += self.remote.overview(self._read_node(name),
                                              self.config.name,
                                              name)["object_count"]
        return total

    def iter_objects(self, tenant: str | None = None):
        for shard in self._target_shards(tenant):
            for key, raw in shard.objects.iter_items():
                yield StorageObject.from_bytes(raw)

    def fetch_objects(self, limit: int = 25, offset: int = 0,
                      sort: list[dict] | None = None, where=None,
                      tenant: str | None = None,
                      after: str | None = None) -> list[StorageObject]:
        """List objects with optional filter/sort/cursor (reference:
        /v1/objects listing; sorter/objects_sorter.go; cursor via ?after=
        which requires uuid order — sort and after are mutually exclusive,
        as in the reference API)."""
        from weaviate_tpu.query.sorter import sort_objects

        if after is not None and sort:
            raise ValueError("'after' cursor cannot be combined with sort")
        names = self._target_shard_names(tenant)
        where_dict = where.to_dict() if where is not None else None
        if sort:
            # property sort needs the values: materialize candidates
            objs: list[StorageObject] = []
            for name in names:
                if self._is_local(name):
                    shard = self._load_shard(name)
                    mask = shard.allow_mask(where) if where is not None else None
                    for _key, raw in shard.objects.iter_items():
                        obj = StorageObject.from_bytes(raw)
                        if mask is not None and (obj.doc_id >= len(mask)
                                                 or not mask[obj.doc_id]):
                            continue
                        objs.append(obj)
                else:
                    raws = self._require_remote(name).list_objects(
                        self._read_node(name), self.config.name, name,
                        where=where_dict)
                    objs.extend(StorageObject.from_bytes(r) for r in raws)
            return sort_objects(objs, sort)[offset: offset + limit]
        # uuid-ordered page: select uuids from the in-RAM docid map (or a
        # remote page), only deserialize what is actually returned
        candidates: list[tuple[str, object]] = []  # (uuid, shard name | obj)
        for name in names:
            if self._is_local(name):
                shard = self._load_shard(name)
                mask = shard.allow_mask(where) if where is not None else None
                with shard._lock:  # snapshot: writers mutate _doc_to_uuid
                    items = list(shard._doc_to_uuid.items())
                for doc_id, uid in items:
                    if mask is not None and (doc_id >= len(mask)
                                             or not mask[doc_id]):
                        continue
                    if after is not None and uid <= after:
                        continue
                    candidates.append((uid, name))
            else:
                # each remote shard over-fetches its own first offset+limit
                # matching objects; the merge below trims to the page
                raws = self._require_remote(name).list_objects(
                    self._read_node(name), self.config.name, name,
                    limit=offset + limit, after=after, where=where_dict)
                for raw in raws:
                    obj = StorageObject.from_bytes(raw)
                    candidates.append((obj.uuid, obj))
        candidates.sort(key=lambda t: t[0])
        page = candidates[offset: offset + limit]
        out = []
        for uid, src in page:
            obj = src if isinstance(src, StorageObject) else \
                self._load_shard(src).get_object(uid)
            if obj is not None:
                out.append(obj)
        return out

    # -- aggregation ---------------------------------------------------------

    @_timed("aggregate")
    def aggregate(self, properties: list[str] | None = None,
                  group_by: str | None = None, where=None,
                  tenant: str | None = None,
                  requested: dict[str, list[str]] | None = None,
                  near_vector=None, near_vec_name: str = "",
                  near_max_distance: float | None = None,
                  object_limit: int | None = None,
                  top_occurrences_limit: int = 5) -> dict:
        """Scatter-gather aggregation (reference: aggregator/aggregator.go →
        per-shard fold, shard_combiner.go merge). With ``near_vector`` +
        ``object_limit``, aggregates over the top-k of a vector search
        instead of the whole (filtered) corpus (aggregator/hybrid.go)."""
        from weaviate_tpu.query.aggregator import (
            aggregate_objects,
            combine_partials,
            finalize_aggregation,
        )

        if near_vector is not None:
            k = object_limit or 100
            hits = self.near_vector(near_vector, k=k, tenant=tenant,
                                    vec_name=near_vec_name,
                                    include_objects=True, where=where,
                                    max_distance=near_max_distance)
            partials = [aggregate_objects((r.object for r in hits if r.object),
                                          properties, group_by)]
        else:
            def one(name: str):
                if not self._is_local(name):
                    return self._require_remote(name).aggregate(
                        self._read_node(name), self.config.name, name,
                        properties, group_by,
                        where.to_dict() if where is not None else None)
                shard = self._load_shard(name)
                mask = shard.allow_mask(where) if where is not None else None

                def objs():
                    for _key, raw in shard.objects.iter_items():
                        obj = StorageObject.from_bytes(raw)
                        if mask is not None and (obj.doc_id >= len(mask)
                                                 or not mask[obj.doc_id]):
                            continue
                        yield obj

                return aggregate_objects(objs(), properties, group_by)

            names = self._target_shard_names(tenant)
            partials = [one(names[0])] if len(names) == 1 else \
                list(self._pool.map(tracing.propagate(one), names))
        return finalize_aggregation(combine_partials(partials), requested,
                                    top_occurrences_limit)

    # -- search --------------------------------------------------------------

    def _attach_objects(self, results: list[SearchResult]) -> None:
        """Fill in .object for results that don't carry one yet — local
        lookup, or ONE batched remote get per non-local shard (not one
        RPC per result)."""
        missing: dict[str, list[SearchResult]] = {}
        for r in results:
            if r.object is None:
                missing.setdefault(r.shard, []).append(r)
        if not missing:
            return
        with tracing.span("objects.fetch",
                          n=sum(len(rs) for rs in missing.values()),
                          shards=len(missing)):
            for name, rs in missing.items():
                if self._is_local(name):
                    shard = self._load_shard(name)
                    for r in rs:
                        r.object = shard.get_object(r.uuid)
                else:
                    from weaviate_tpu.cluster.transport import RpcError

                    try:
                        raws = self._require_remote(name).get_objects(
                            self._read_node(name), self.config.name, name,
                            [r.uuid for r in rs])
                    except RpcError as e:
                        # the replica died between search and property
                        # fetch: serve the ids/distances we have with a
                        # degraded marker rather than failing the query
                        degrade.report("objects_unavailable",
                                       collection=self.config.name,
                                       shard=name, detail=str(e))
                        continue
                    for r, raw in zip(rs, raws):
                        r.object = StorageObject.from_bytes(raw) \
                            if raw else None

    # -- epoch migration (ROADMAP item 3: ledger-driven epoch placement) ------

    def _unmigrate(self, shard, objs) -> None:
        """A re-put at an object's ring home supersedes its migrated
        copy: delete the sibling's copy and drop the routing marker —
        called AFTER the fresh write landed (a failed or crashed re-put
        must never have destroyed the only copy first; the transient
        double-present window is deduped by uuid in the merge, and GETs
        prefer the ring copy). Zero-cost when the shard never migrated
        anything."""
        if shard._migrated_count <= 0:
            return
        for obj in objs:
            dst = shard.migrated_to(obj.uuid)
            if dst and dst != shard.name:
                if self._is_local(dst):
                    self._load_shard(dst).delete_object(obj.uuid)
                elif self.remote is not None:
                    self._require_remote(dst).delete_object(
                        self._read_node(dst), self.config.name, dst,
                        obj.uuid)
            if dst:
                shard.clear_migrated(obj.uuid)

    def _sibling_with_headroom(self, src_name: str) -> str | None:
        """The local sibling shard with the most HBM headroom (smallest
        ledger footprint) — the migration target. None when this
        collection has no other local shard."""
        from weaviate_tpu.runtime.hbm_ledger import ledger

        def over_quota(name: str) -> bool:
            # quota check from ALREADY-LOADED shards only: a cold shard
            # holds no device arrays (its ledger bytes are ~0), and
            # constructing N-1 Shard objects mid-rescue — fresh device
            # stores, bucket opens — is exactly wrong under pressure
            sh = self.shards.get(name)
            return sh is not None and sh.over_shard_limit()

        best, best_bytes = None, None
        for name in self.sharding.shard_names:
            if name == src_name or not self._is_local(name):
                continue
            if over_quota(name):
                continue  # no headroom there either
            b = ledger.shard_bytes(self.config.name, name)
            if best_bytes is None or b < best_bytes:
                best, best_bytes = name, b
        if best is None:
            return None
        if over_quota(src_name):
            # quota pressure: any under-quota sibling IS headroom
            return best
        src_bytes = ledger.shard_bytes(self.config.name, src_name)
        # "headroom exists" = the sibling is meaningfully lighter than
        # the source; migrating between two equally-full shards would
        # just bounce the epoch back on the next cycle
        return best if best_bytes < src_bytes else None

    def _remote_sibling_with_headroom(self, src_name: str) -> str | None:
        """The cross-NODE half of epoch migration (ROADMAP item 3's
        leftover, riding item 2's placement machinery): a sibling shard
        placed on another node, chosen by that node's gossiped HBM
        ledger bytes. Only nodes whose reported footprint is BELOW this
        node's qualify — a local move cannot relieve device-global
        pressure (two shards of one process share the chips), but
        shipping the epoch to a genuinely lighter node does. Nodes with
        no gossiped ledger reading are skipped: never ship an epoch
        blind."""
        if self.remote is None:
            return None
        hbm = self._node_hbm_bytes()
        local_bytes = hbm.get(self.local_node, 0)
        best, best_bytes = None, None
        for name in self.sharding.shard_names:
            if name == src_name or self._is_local(name):
                continue
            node = self.sharding.nodes_for(name)[0]
            b = hbm.get(node)
            if b is None or b >= local_bytes:
                continue
            if best_bytes is None or b < best_bytes:
                best, best_bytes = name, b
        return best

    def migrate_epoch(self, src_name: str, vec_name: str = "",
                      dst_name: str | None = None) -> int:
        """Migrate the coldest sealed epoch of ``src_name``'s
        epoch-backed index to a sibling shard with headroom: serialize
        the epoch's objects from the source LSM, durable ingest on the
        target (``Shard.put_object_batch`` — vectors land in the
        target's device epochs), then the atomic source-side cutover
        (``Shard.migrate_out``: durable routing markers + slot→doc-id
        table rows dropped under the index lock) and the epoch's HBM
        released (``drop_epoch``). Crash ordering keeps every object
        served EXACTLY once: before the cutover markers the ring copy
        answers; after them the marker routes reads to the target; the
        transient double-present window is deduped by uuid in the
        scatter-gather merge. Returns objects moved (0 = nothing to
        do). Single-replica, non-tenant collections only — a replicated
        shard's epochs rebalance through the replication story, not
        this local move."""
        if (self.config.replication.factor > 1
                or self.config.multi_tenancy.enabled
                or not self._is_local(src_name)):
            return 0
        src = self._load_shard(src_name)
        moved_total = 0
        with self._migrate_lock:
            for name, idx in list(src.vector_indexes.items()):
                if vec_name and name != vec_name:
                    continue
                es = getattr(idx, "epoch_store", None)
                if es is None:
                    continue
                eid = es.coldest_sealed()
                if eid is None:
                    continue
                dst = dst_name or self._sibling_with_headroom(src_name)
                if dst is None and dst_name is None:
                    # no LOCAL headroom: the cross-node half — ship the
                    # epoch to a sibling shard on a lighter node,
                    # behind the same durable-marker cutover
                    dst = self._remote_sibling_with_headroom(src_name)
                if dst is None or dst == src_name:
                    return moved_total
                if self._is_local(dst):
                    moved_total += self._migrate_one(src, idx, es, eid,
                                                     dst)
                else:
                    moved_total += self._migrate_one_remote(
                        src, idx, es, eid, dst)
        return moved_total

    def _migrate_one(self, src, idx, es, eid: int, dst: str) -> int:
        """Move one epoch. Caller holds ``_migrate_lock``. The SOURCE
        shard's lock is held across serialize -> target ingest ->
        cutover so a concurrent put/delete of a moving uuid cannot land
        in the un-synchronized window (it would be erased by the
        cutover, or resurrected from the target's pre-write copy);
        writers to the source simply queue behind the move, bounded by
        one epoch's ingest."""
        from weaviate_tpu.runtime import faultline

        src_name = src.name
        with src._lock:
            doc_ids = idx.epoch_doc_ids(eid)
            if not len(doc_ids):
                es.drop_epoch(eid)
                return 0
            objs = [o for o in src.objects_by_doc_ids(doc_ids)
                    if o is not None]
            if not objs:
                return 0
            # 1) durable routing markers FIRST: from here on, deletes
            #    and re-puts of a moving uuid reach BOTH sides no
            #    matter where a kill lands (a marker to a copy that
            #    never ingests is harmless — GETs prefer the ring copy)
            src.mark_migrating([o.uuid for o in objs], dst)
            faultline.fire("epoch.migrate.pre_ingest", shard=src_name,
                           epoch=eid, docs=len(doc_ids))
            try:
                # 2) durable ingest at the target (fresh doc ids there;
                #    vectors land in the target's own device epochs)
                self._load_shard(dst).put_object_batch(objs)
            except MemoryError:
                # the sibling hit ITS watermark mid-ingest: nothing was
                # cut over, the source still serves — clean the markers
                # back off (nothing landed at dst) and report no move
                for o in objs:
                    src.clear_migrated(o.uuid)
                logger.warning(
                    "epoch migration %s/%s e%d -> %s aborted: target "
                    "at watermark", self.config.name, src_name, eid, dst)
                return 0
            faultline.fire("epoch.migrate.post_ingest", shard=src_name,
                           epoch=eid)
            # 3) source cutover: the batched removal from LSM +
            #    slot→doc-id tables (markers already durable)
            src.migrate_out([o.uuid for o in objs], dst)
            faultline.fire("epoch.migrate.post_cutover", shard=src_name,
                           epoch=eid)
            # 4) the (now all-tombstone) epoch's HBM releases through
            #    the ledger finalizers at cutover
            es.drop_epoch(eid)
            es.migrations_total += 1
        monitoring.epoch_migrations.labels(self.config.name,
                                           src_name).inc()
        logger.info(
            "epoch migration: %s/%s e%d -> %s (%d objects)",
            self.config.name, src_name, eid, dst, len(objs))
        return len(objs)

    def _migrate_one_remote(self, src, idx, es, eid: int,
                            dst: str) -> int:
        """Cross-node twin of ``_migrate_one``: same durable-marker
        cutover ordering, with the target-side durable ingest riding
        the remote shard client (``put_objects`` → the destination
        node's ``Shard.put_object_batch``, so vectors land in ITS
        device epochs under ITS admission control). Markers go first (a
        marker to a copy that never ingests is harmless — GETs prefer
        the ring copy); an ingest RPC failure aborts with NOTHING cut
        over and the markers LEFT IN PLACE: a timeout or lost reply is
        ambiguous — the put may have landed durably on the target — and
        dropping the markers would orphan that copy as an undeletable
        zombie (searches would keep surfacing it after the ring copy is
        deleted). Kept markers keep every copy reachable: deletes and
        re-puts clean BOTH sides through them, search dedups by uuid,
        and a later retry simply re-marks and re-ingests (idempotent by
        uuid). The source shard lock is held across the RPC — the same
        writes-queue-behind-the-move contract as the local twin;
        exposure is bounded by the remote client's per-attempt deadline
        (REMOTE_RPC_TIMEOUT_S) + the per-peer circuit breaker failing
        known-dead nodes fast, and migrations are serialized per
        collection. The same ``epoch.migrate.*`` fault points fire, so
        the crashtest harness covers this path too."""
        from weaviate_tpu.cluster.transport import RpcError
        from weaviate_tpu.runtime import faultline

        src_name = src.name
        dst_node = self.sharding.nodes_for(dst)[0]
        with src._lock:
            doc_ids = idx.epoch_doc_ids(eid)
            if not len(doc_ids):
                es.drop_epoch(eid)
                return 0
            objs = [o for o in src.objects_by_doc_ids(doc_ids)
                    if o is not None]
            if not objs:
                return 0
            src.mark_migrating([o.uuid for o in objs], dst)
            faultline.fire("epoch.migrate.pre_ingest", shard=src_name,
                           epoch=eid, docs=len(doc_ids))
            try:
                self._require_remote(dst).put_objects(
                    dst_node, self.config.name, dst,
                    [o.to_bytes() for o in objs])
            except RpcError as e:
                # ambiguous outcome (the put may have landed before a
                # timeout/lost reply): keep the markers so a possibly-
                # present target copy stays reachable for deletes and
                # dedup — clearing them here would orphan it
                logger.warning(
                    "cross-node epoch migration %s/%s e%d -> %s@%s "
                    "aborted (markers kept, nothing cut over): %s",
                    self.config.name, src_name, eid, dst, dst_node, e)
                return 0
            faultline.fire("epoch.migrate.post_ingest", shard=src_name,
                           epoch=eid)
            src.migrate_out([o.uuid for o in objs], dst)
            faultline.fire("epoch.migrate.post_cutover", shard=src_name,
                           epoch=eid)
            es.drop_epoch(eid)
            es.migrations_total += 1
        monitoring.epoch_migrations.labels(self.config.name,
                                           src_name).inc()
        logger.info(
            "cross-node epoch migration: %s/%s e%d -> %s@%s "
            "(%d objects)", self.config.name, src_name, eid, dst,
            dst_node, len(objs))
        return len(objs)

    def epoch_maintenance(self) -> bool:
        """One background policy cycle (registered with the database's
        cyclemanager as ``epoch-maintenance`` — the ONLY driver of epoch
        upkeep, so the work runs once per interval): per-shard seal /
        drop / compact — deletes RECLAIM HBM here, which is what
        relieves the device-GLOBAL admission watermark — then migrate
        the coldest sealed epoch off any shard over its per-shard quota
        watermark to a sibling with headroom instead of letting the
        quota 507 writes. (A local move cannot reduce device-global
        usage — two shards of one process share the chips — so only
        quota pressure, the budget migration genuinely relieves,
        triggers it.)"""
        did = False
        with self._lock:
            shards = list(self.shards.values())
        for shard in shards:
            did = shard.epoch_maintenance() or did
        for shard in shards:
            if shard.over_shard_limit():
                did = self.migrate_epoch(shard.name) > 0 or did
        return did

    def _rescue_shard(self, shard) -> bool:
        """Synchronous memory-pressure rescue (wired as
        ``shard.memory_rescue``): compact first — tombstone-heavy
        epochs give bytes back without moving anything — then migrate
        the coldest sealed epoch to a sibling with headroom. Runs on
        the importing thread, once, before admission re-checks. The
        thread-local reentrancy guard stops a migration's own
        target-side ingest (which runs admission too) from cascading
        rescues across the ring."""
        if getattr(self._rescue_tls, "active", False):
            return False
        self._rescue_tls.active = True
        try:
            did = shard.epoch_maintenance()
            if shard.over_shard_limit():
                did = self.migrate_epoch(shard.name) > 0 or did
            return did
        finally:
            self._rescue_tls.active = False

    @staticmethod
    def _and_masks(a, b) -> np.ndarray:
        """Intersect two allow lists (bool mask or doc-id array forms)."""
        a, b = np.asarray(a), np.asarray(b)
        if a.dtype != np.bool_ and b.dtype != np.bool_:
            # both doc-id arrays: native sorted-set intersect (the roaring
            # AND of the reference, csrc/weaviate_native.cpp)
            from weaviate_tpu import native

            return native.intersect_sorted(
                np.unique(a), np.unique(b)).astype(np.int64)

        def to_mask(x, size):
            if x.dtype == np.bool_:
                m = np.zeros(size, dtype=bool)
                m[: len(x)] = x
                return m
            m = np.zeros(size, dtype=bool)
            m[x[x < size]] = True
            return m

        size = max(len(a) if a.dtype == np.bool_ else (int(a.max()) + 1 if len(a) else 0),
                   len(b) if b.dtype == np.bool_ else (int(b.max()) + 1 if len(b) else 0))
        return to_mask(a, size) & to_mask(b, size)

    @staticmethod
    def _merge_by_distance(gathered: list[list], k: int) -> list:
        """Cross-shard reduce: each shard's list is already ascending, so
        the k-way heap merge runs in the native library
        (csrc/weaviate_native.cpp wn_merge_topk; reference:
        index.go:1644-1648 sort+truncate)."""
        lists = [g for g in gathered if g]
        if not lists:
            return []
        if len(lists) == 1:
            return lists[0][:k]
        from weaviate_tpu import native

        width = max(len(g) for g in lists)
        d = np.full((len(lists), width), np.float32(3.0e38), dtype=np.float32)
        idx = np.full((len(lists), width), -1, dtype=np.int64)
        flat: list = []
        for li, g in enumerate(lists):
            for pos, r in enumerate(g):
                d[li, pos] = r.distance
                idx[li, pos] = len(flat)
                flat.append(r)
        # merge OVERSAMPLED (2k) so the uuid dedup below can drop a
        # transient double-present copy without eating into the k
        # contract — a duplicate pair in the top-k would otherwise
        # shadow the next distinct candidate
        _, out_i = native.merge_topk_host(d, idx, k=min(2 * k, len(flat)))
        # dedup by uuid, best (first, ascending) distance wins: an
        # epoch-migration crash window can briefly leave an object
        # present on two shards — it must never be served twice.
        # Results without a uuid (score-only merges) always pass.
        out, seen = [], set()
        for i in out_i.tolist():
            if i < 0:
                continue
            r = flat[i]
            u = getattr(r, "uuid", None)
            if u is not None:
                if u in seen:
                    continue
                seen.add(u)
            out.append(r)
            if len(out) == k:
                break
        return out

    @_timed("vector")
    def near_vector(self, query, k: int = 10, vec_name: str = "",
                    tenant: str | None = None, include_objects: bool = True,
                    allow_list_by_shard: dict | None = None,
                    max_distance: float | None = None,
                    where=None, autocut: int = 0) -> list[SearchResult]:
        """Scatter-gather nearVector (reference: index.go:1541
        objectVectorSearch -> per-shard parallel search -> merge+truncate).
        ``where``: optional Filter tree, evaluated per shard to an AllowList
        mask applied inside the device scan."""
        query = np.asarray(query, dtype=np.float32)
        names = self._target_shard_names(tenant)

        def one(name: str) -> list[SearchResult]:
            if self._is_local(name):
                shard = self._load_shard(name)
                allow = None if allow_list_by_shard is None else \
                    allow_list_by_shard.get(name)
                if where is not None:
                    fmask = shard.allow_mask(where)
                    allow = fmask if allow is None else \
                        self._and_masks(allow, fmask)
                ids, dists = shard.vector_search(query, k, vec_name, allow)
                out = []
                for doc_id, dist in zip(ids.tolist(), dists.tolist()):
                    uuid = shard._doc_to_uuid.get(doc_id)
                    if uuid is not None:
                        out.append(SearchResult(uuid=uuid, distance=dist,
                                                shard=name))
                return out
            # remote shard: the owning node evaluates filters and resolves
            # objects (reference: remote.SearchShard, index.go:1607);
            # replica failover + degraded (partial) results on total loss
            items = self._remote_search_degraded(
                name, vector=query, k=k, vec_name=vec_name,
                where=where.to_dict() if where is not None else None,
                include_objects=include_objects)
            if items is None:
                return []
            return [_remote_result(i, name) for i in items]

        gathered = [one(names[0])] if len(names) == 1 else \
            list(self._pool.map(tracing.propagate(one), names))

        merged = self._merge_by_distance(gathered, k)
        if max_distance is not None:
            merged = [r for r in merged if r.distance <= max_distance]
        if autocut > 0 and merged:
            from weaviate_tpu.query.autocut import autocut as _autocut

            merged = merged[: _autocut([r.distance for r in merged], autocut)]
        if include_objects:
            self._attach_objects(merged)
        return merged

    @_timed("bm25")
    def bm25(self, query: str, k: int = 10, properties: list[str] | None = None,
             tenant: str | None = None, include_objects: bool = True,
             allow_list_by_shard: dict | None = None,
             where=None, autocut: int = 0) -> list[SearchResult]:
        """Scatter-gather keyword search; merge by score descending
        (reference: Index.objectSearch → per-shard BM25 → merge)."""
        names = self._target_shard_names(tenant)

        def one(name: str) -> list[SearchResult]:
            if self._is_local(name):
                shard = self._load_shard(name)
                allow = None if allow_list_by_shard is None else \
                    allow_list_by_shard.get(name)
                if where is not None:
                    fmask = shard.allow_mask(where)
                    allow = fmask if allow is None else \
                        self._and_masks(allow, fmask)
                ids, scores = shard.bm25_search(query, k, properties, allow)
                out = []
                for doc_id, score in zip(ids.tolist(), scores.tolist()):
                    uuid = shard._doc_to_uuid.get(doc_id)
                    if uuid is not None:
                        out.append(SearchResult(uuid=uuid, score=score,
                                                shard=name))
                return out
            items = self._remote_search_degraded(
                name, query=query, k=k, properties=properties,
                where=where.to_dict() if where is not None else None,
                include_objects=include_objects)
            if items is None:
                return []
            return [_remote_result(i, name) for i in items]

        gathered = [one(names[0])] if len(names) == 1 else \
            list(self._pool.map(tracing.propagate(one), names))

        merged = [r for results in gathered for r in results]
        merged.sort(key=lambda r: -r.score)
        merged = merged[:k]
        if autocut > 0 and merged:
            from weaviate_tpu.query.autocut import autocut as _autocut

            merged = merged[: _autocut([-r.score for r in merged], autocut)]
        if include_objects:
            self._attach_objects(merged)
        return merged

    @_timed("hybrid")
    def hybrid(self, query: str, vector=None, alpha: float = 0.75, k: int = 10,
               properties: list[str] | None = None, vec_name: str = "",
               tenant: str | None = None, fusion: str = "relativeScore",
               where=None, include_objects: bool = True,
               autocut: int = 0) -> list[SearchResult]:
        """Hybrid sparse+dense search (reference: hybrid/searcher.go:74 runs
        both legs in parallel, then fuses). ``alpha`` weighs the dense leg
        (0 = pure BM25, 1 = pure vector). ``vector=None`` degrades to
        sparse-only, as the reference does without a vectorizer.

        Single-local-shard queries with a query vector take the fused
        DEVICE path first (ISSUE 18): one batched device program runs the
        dense scan, scores the packed BM25 candidates, and fuses — the
        host two-thread reference below stays the fallback (and the
        parity oracle) for everything the device path declines."""
        from weaviate_tpu.text.hybrid import fusion_ranked, fusion_relative_score

        # over-fetch each leg so fusion has overlap to work with; legs run on
        # ephemeral threads, NOT self._pool — a leg parked in a pool worker
        # while its inner scatter-gather waits for that same pool can deadlock
        import threading as _threading

        if vector is None:
            alpha = 0.0  # degrade to sparse-only (reference does the same
            # when no vectorizer can produce a query vector)
        # evaluate the filter once per shard and let both legs reuse the
        # masks — only possible when every target shard is local; with
        # remote shards the filter tree travels down instead
        names = self._target_shard_names(tenant)
        allow_by_shard = None
        where_down = where
        if where is not None:
            if all(self._is_local(n) for n in names):
                allow_by_shard = {n: self._load_shard(n).allow_mask(where)
                                  for n in names}
                where_down = None

        if (vector is not None and len(names) == 1
                and self._is_local(names[0]) and where_down is None):
            dev = self._hybrid_device(
                names[0], query, vector, alpha, k, properties, vec_name,
                fusion, None if allow_by_shard is None
                else allow_by_shard.get(names[0]))
            if dev is not None:
                if autocut > 0 and dev:
                    from weaviate_tpu.query.autocut import autocut_results

                    dev = autocut_results(dev, autocut, by="score")
                if include_objects:
                    self._attach_objects(dev)
                return dev

        fetch = max(k * 10, 100)
        legs, weights = [], []
        results: dict[str, list] = {}
        errors: dict[str, BaseException] = {}

        def run(name, fn, *a):
            try:
                results[name] = fn(*a)
            except BaseException as e:  # re-raised on the caller thread
                errors[name] = e

        # legs skip object fetch; only the fused top-k pays for it below
        # (tracing.propagate: Thread targets don't inherit contextvars)
        threads = []
        if alpha < 1.0:
            threads.append(_threading.Thread(
                target=tracing.propagate(run),
                args=("sparse", self.bm25, query, fetch,
                      properties, tenant, False, allow_by_shard,
                      where_down)))
        if vector is not None and alpha > 0.0:
            threads.append(_threading.Thread(
                target=tracing.propagate(run),
                args=("dense", self.near_vector, vector, fetch,
                      vec_name, tenant, False, allow_by_shard,
                      None, where_down)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise next(iter(errors.values()))
        if "sparse" in results:
            legs.append(results["sparse"])
            weights.append(1.0 - alpha)
        if "dense" in results:
            dense = results["dense"]
            # similarity score for fusion: any monotone-decreasing map of
            # distance works (min-max normalization is affine-invariant)
            for r in dense:
                r.score = -r.distance
            legs.append(dense)
            weights.append(alpha)
        if not legs:
            return []
        fuse = fusion_relative_score if fusion == "relativeScore" else fusion_ranked
        # fusion returns (fused_score, result) pairs WITHOUT mutating the
        # leg results (see text/hybrid.py); materialize fresh results so
        # concurrent queries sharing leg objects never race on .score
        fused = [SearchResult(uuid=r.uuid, distance=r.distance, score=s,
                              object=r.object, shard=r.shard)
                 for s, r in fuse(legs, weights, k)]
        if autocut > 0 and fused:
            from weaviate_tpu.query.autocut import autocut_results

            fused = autocut_results(fused, autocut, by="score")
        if include_objects:
            self._attach_objects(fused)
        return fused

    def _hybrid_device(self, name: str, query: str, vector, alpha: float,
                       k: int, properties, vec_name: str, fusion: str,
                       allow_mask) -> list[SearchResult] | None:
        """Fused device hybrid for one local shard (ISSUE 18). None =
        the shard declined (unsupported index, candidate budget, kill
        switch) and the caller runs the host reference path."""
        shard = self._load_shard(name)
        res = shard.hybrid_search(
            query, np.asarray(vector, np.float32), k, alpha=alpha,
            fusion=fusion, properties=properties, vec_name=vec_name,
            allow_mask=allow_mask)
        if res is None:
            return None
        ids, scores = res
        out = []
        for doc_id, score in zip(ids.tolist(), scores.tolist()):
            uuid = shard._doc_to_uuid.get(doc_id)
            if uuid is not None:
                out.append(SearchResult(uuid=uuid, score=score,
                                        shard=name))
        return out

    def hybrid_async(self, query: str, vector=None, alpha: float = 0.75,
                     k: int = 10, properties: list[str] | None = None,
                     vec_name: str = "", tenant: str | None = None,
                     fusion: str = "relativeScore", where=None,
                     include_objects: bool = True, autocut: int = 0):
        """Dispatch-only twin of ``hybrid``: returns a
        ``DeviceResultHandle`` resolving to the same ``list[SearchResult]``.
        On the device path the handle's D2H drains through the
        TransferPipeline while the caller dispatches more work; when the
        device path declines, the host reference runs inline and the
        handle is pre-resolved (``DeviceResultHandle.ready``)."""
        from weaviate_tpu.runtime.transfer import DeviceResultHandle

        names = self._target_shard_names(tenant)
        if (vector is not None and len(names) == 1
                and self._is_local(names[0]) and where is None):
            shard = self._load_shard(names[0])
            h = shard.hybrid_search_async(
                query, np.asarray(vector, np.float32), k, alpha=alpha,
                fusion=fusion, properties=properties, vec_name=vec_name)
            if h is not None:
                name = names[0]

                def _finish(res, _shard=shard, _name=name):
                    ids, scores = res
                    out = []
                    for doc_id, score in zip(ids.tolist(),
                                             scores.tolist()):
                        uuid = _shard._doc_to_uuid.get(doc_id)
                        if uuid is not None:
                            out.append(SearchResult(uuid=uuid,
                                                    score=score,
                                                    shard=_name))
                    if autocut > 0 and out:
                        from weaviate_tpu.query.autocut import \
                            autocut_results

                        out = autocut_results(out, autocut, by="score")
                    if include_objects:
                        self._attach_objects(out)
                    return out

                return h.map(_finish)
        return DeviceResultHandle.ready(self.hybrid(
            query, vector, alpha, k, properties, vec_name, tenant,
            fusion, where, include_objects, autocut))

    # -- maintenance ---------------------------------------------------------

    def flush(self):
        for s in self.shards.values():
            s.flush()

    def close(self):
        self._pool.shutdown(wait=False)
        for s in self.shards.values():
            s.close()
