"""Collection: shard routing + scatter-gather queries.

Reference: adapters/repos/db/index.go (Index struct :156) — putObject routes
by sharding state (:637), objectVectorSearch scatter-gathers across shards
and merges by distance (:1541-1663). Multi-tenant collections address one
shard per tenant.
"""

from __future__ import annotations

import functools
import threading
import uuid as uuid_mod
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from weaviate_tpu.db.shard import Shard
from weaviate_tpu.db.sharding import ShardingState
from weaviate_tpu.runtime import metrics as monitoring
from weaviate_tpu.schema.config import CollectionConfig
from weaviate_tpu.storage.objects import StorageObject


class SearchResult:
    __slots__ = ("uuid", "distance", "score", "object", "shard")

    def __init__(self, uuid, distance=None, score=None, object=None, shard=None):
        self.uuid = uuid
        self.distance = distance
        self.score = score
        self.object = object
        self.shard = shard

    def __repr__(self):
        return f"SearchResult({self.uuid}, dist={self.distance}, score={self.score})"


def _timed(query_type: str):
    """Record query latency per collection (reference: monitoring
    query-duration metric vecs, usecases/monitoring/prometheus.go)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            with monitoring.query_duration.labels(self.config.name,
                                                  query_type).time():
                return fn(self, *args, **kwargs)

        return wrapper

    return deco


class Collection:
    def __init__(self, data_dir: str, config: CollectionConfig,
                 sharding_state: ShardingState | None = None, mesh=None,
                 local_node: str = "node-0", on_sharding_change=None,
                 memwatch=None):
        config.validate()
        self.config = config
        self.data_dir = data_dir
        self.mesh = mesh
        self.local_node = local_node
        self.memwatch = memwatch
        self._lock = threading.RLock()
        if sharding_state is None:
            if config.multi_tenancy.enabled:
                sharding_state = ShardingState.create_partitioned()
            else:
                sharding_state = ShardingState.create(
                    config.sharding.desired_count,
                    replication_factor=config.replication.factor,
                )
        self.sharding = sharding_state
        # persistence hook: auto-created tenants must reach the schema store
        # or they vanish from sharding state on restart
        self._on_sharding_change = on_sharding_change or (lambda col: None)
        self.shards: dict[str, Shard] = {}
        for name in self.sharding.shard_names:
            if self.local_node in self.sharding.nodes_for(name):
                self._load_shard(name)
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix=f"{config.name}-search")

    # -- shard management ----------------------------------------------------

    def _load_shard(self, name: str) -> Shard:
        # check-then-insert under the lock: two concurrent writers must not
        # construct two Shard objects (two WALs, two doc counters) for the
        # same on-disk shard
        with self._lock:
            if name not in self.shards:
                self.shards[name] = Shard(self.data_dir, self.config, name,
                                          mesh=self.mesh,
                                          memwatch=self.memwatch)
            return self.shards[name]

    def _shard_for_write(self, uuid: str, tenant: str | None) -> Shard:
        with self._lock:
            name = self.sharding.shard_for(uuid, tenant)
            if name not in self.shards:
                if self.config.multi_tenancy.enabled:
                    if tenant not in self.sharding.shard_names:
                        if not self.config.multi_tenancy.auto_tenant_creation:
                            raise KeyError(f"tenant {tenant!r} does not exist")
                        self.sharding.add_tenant(tenant)
                        self._on_sharding_change(self)
                self._load_shard(name)
            return self.shards[name]

    def _target_shards(self, tenant: str | None) -> list[Shard]:
        if self.config.multi_tenancy.enabled:
            if not tenant:
                raise ValueError("multi-tenant collection requires a tenant")
            if tenant not in self.sharding.shard_names:
                raise KeyError(f"tenant {tenant!r} does not exist")
            return [self._load_shard(tenant)]
        return [self._load_shard(n) for n in self.sharding.shard_names]

    # -- tenants -------------------------------------------------------------

    def add_tenant(self, tenant: str):
        with self._lock:
            self.sharding.add_tenant(tenant)
            self._load_shard(tenant)
            self._on_sharding_change(self)

    def remove_tenant(self, tenant: str):
        with self._lock:
            shard = self.shards.pop(tenant, None)
            if shard is not None:
                shard.close()
            self.sharding.remove_tenant(tenant)

    def tenants(self) -> list[str]:
        return list(self.sharding.shard_names) if self.config.multi_tenancy.enabled else []

    # -- object CRUD ---------------------------------------------------------

    def put_object(self, properties: dict, vector=None, vectors: dict | None = None,
                   uuid: str | None = None, tenant: str | None = None) -> str:
        uuid = uuid or str(uuid_mod.uuid4())
        obj = StorageObject(uuid=uuid, properties=properties)
        if vector is not None:
            obj.vector = np.asarray(vector, dtype=np.float32)
        for name, vec in (vectors or {}).items():
            obj.vectors[name] = np.asarray(vec, dtype=np.float32)
        shard = self._shard_for_write(uuid, tenant)
        shard.put_object(obj)
        monitoring.objects_total.labels(self.config.name, "put").inc()
        return uuid

    def batch_put(self, objects: list[dict], tenant: str | None = None) -> list[dict]:
        """Batch import; per-object error reporting, not transactional
        (reference: usecases/objects/batch_add.go)."""
        results = []
        by_shard: dict[str, list[StorageObject]] = {}
        metas: dict[str, list[int]] = {}
        for i, spec in enumerate(objects):
            try:
                uid = spec.get("uuid") or str(uuid_mod.uuid4())
                obj = StorageObject(uuid=uid,
                                    properties=spec.get("properties", {}))
                if spec.get("vector") is not None:
                    obj.vector = np.asarray(spec["vector"], dtype=np.float32)
                for name, vec in (spec.get("vectors") or {}).items():
                    obj.vectors[name] = np.asarray(vec, dtype=np.float32)
                shard_name = self.sharding.shard_for(uid, tenant)
                by_shard.setdefault(shard_name, []).append(obj)
                metas.setdefault(shard_name, []).append(i)
                results.append({"uuid": uid, "status": "SUCCESS"})
            except Exception as e:  # per-object failure, keep going
                results.append({"uuid": spec.get("uuid"), "status": "FAILED",
                                "error": str(e)})
        for shard_name, objs in by_shard.items():
            try:
                with self._lock:
                    if (self.config.multi_tenancy.enabled
                            and shard_name not in self.sharding.shard_names):
                        if self.config.multi_tenancy.auto_tenant_creation:
                            self.sharding.add_tenant(shard_name)
                            self._on_sharding_change(self)
                        else:
                            raise KeyError(f"tenant {shard_name!r} does not exist")
                    shard = self._load_shard(shard_name)
                shard.put_object_batch(objs)
                monitoring.objects_total.labels(self.config.name, "put"
                                                ).inc(len(objs))
            except Exception as e:
                for i in metas[shard_name]:
                    results[i] = {"uuid": results[i]["uuid"], "status": "FAILED",
                                  "error": str(e)}
        return results

    def get_object(self, uuid: str, tenant: str | None = None) -> StorageObject | None:
        if self.config.multi_tenancy.enabled:
            shard = self._target_shards(tenant)[0]
            return shard.get_object(uuid)
        name = self.sharding.shard_for(uuid, tenant)
        if name not in self.shards:
            return None
        return self.shards[name].get_object(uuid)

    def delete_object(self, uuid: str, tenant: str | None = None) -> bool:
        if self.config.multi_tenancy.enabled:
            ok = self._target_shards(tenant)[0].delete_object(uuid)
        elif (name := self.sharding.shard_for(uuid, tenant)) not in self.shards:
            ok = False
        else:
            ok = self.shards[name].delete_object(uuid)
        if ok:
            monitoring.objects_total.labels(self.config.name, "delete").inc()
        return ok

    def object_count(self, tenant: str | None = None) -> int:
        shards = self._target_shards(tenant) if (tenant or not
                  self.config.multi_tenancy.enabled) else []
        return sum(s.object_count() for s in shards)

    def iter_objects(self, tenant: str | None = None):
        for shard in self._target_shards(tenant):
            for key, raw in shard.objects.iter_items():
                yield StorageObject.from_bytes(raw)

    def fetch_objects(self, limit: int = 25, offset: int = 0,
                      sort: list[dict] | None = None, where=None,
                      tenant: str | None = None,
                      after: str | None = None) -> list[StorageObject]:
        """List objects with optional filter/sort/cursor (reference:
        /v1/objects listing; sorter/objects_sorter.go; cursor via ?after=
        which requires uuid order — sort and after are mutually exclusive,
        as in the reference API)."""
        from weaviate_tpu.query.sorter import sort_objects

        if after is not None and sort:
            raise ValueError("'after' cursor cannot be combined with sort")
        shards = self._target_shards(tenant)
        if sort:
            # property sort needs the values: materialize candidates
            objs: list[StorageObject] = []
            for shard in shards:
                mask = shard.allow_mask(where) if where is not None else None
                for _key, raw in shard.objects.iter_items():
                    obj = StorageObject.from_bytes(raw)
                    if mask is not None and (obj.doc_id >= len(mask)
                                             or not mask[obj.doc_id]):
                        continue
                    objs.append(obj)
            return sort_objects(objs, sort)[offset: offset + limit]
        # uuid-ordered page: select uuids from the in-RAM docid map, only
        # deserialize the page actually returned
        candidates: list[tuple[str, Shard]] = []
        for shard in shards:
            mask = shard.allow_mask(where) if where is not None else None
            with shard._lock:  # snapshot: writers mutate _doc_to_uuid
                items = list(shard._doc_to_uuid.items())
            for doc_id, uid in items:
                if mask is not None and (doc_id >= len(mask) or not mask[doc_id]):
                    continue
                if after is not None and uid <= after:
                    continue
                candidates.append((uid, shard))
        candidates.sort(key=lambda t: t[0])
        page = candidates[offset: offset + limit]
        out = []
        for uid, shard in page:
            obj = shard.get_object(uid)
            if obj is not None:
                out.append(obj)
        return out

    # -- aggregation ---------------------------------------------------------

    @_timed("aggregate")
    def aggregate(self, properties: list[str] | None = None,
                  group_by: str | None = None, where=None,
                  tenant: str | None = None,
                  requested: dict[str, list[str]] | None = None,
                  near_vector=None, object_limit: int | None = None,
                  top_occurrences_limit: int = 5) -> dict:
        """Scatter-gather aggregation (reference: aggregator/aggregator.go →
        per-shard fold, shard_combiner.go merge). With ``near_vector`` +
        ``object_limit``, aggregates over the top-k of a vector search
        instead of the whole (filtered) corpus (aggregator/hybrid.go)."""
        from weaviate_tpu.query.aggregator import (
            aggregate_objects,
            combine_partials,
            finalize_aggregation,
        )

        if near_vector is not None:
            k = object_limit or 100
            hits = self.near_vector(near_vector, k=k, tenant=tenant,
                                    include_objects=True, where=where)
            partials = [aggregate_objects((r.object for r in hits if r.object),
                                          properties, group_by)]
        else:
            def one(shard: Shard):
                mask = shard.allow_mask(where) if where is not None else None

                def objs():
                    for _key, raw in shard.objects.iter_items():
                        obj = StorageObject.from_bytes(raw)
                        if mask is not None and (obj.doc_id >= len(mask)
                                                 or not mask[obj.doc_id]):
                            continue
                        yield obj

                return aggregate_objects(objs(), properties, group_by)

            shards = self._target_shards(tenant)
            partials = [one(shards[0])] if len(shards) == 1 else \
                list(self._pool.map(one, shards))
        return finalize_aggregation(combine_partials(partials), requested,
                                    top_occurrences_limit)

    # -- search --------------------------------------------------------------

    @staticmethod
    def _and_masks(a, b) -> np.ndarray:
        """Intersect two allow lists (bool mask or doc-id array forms)."""
        def to_mask(x, size):
            x = np.asarray(x)
            if x.dtype == np.bool_:
                m = np.zeros(size, dtype=bool)
                m[: len(x)] = x
                return m
            m = np.zeros(size, dtype=bool)
            m[x[x < size]] = True
            return m

        a, b = np.asarray(a), np.asarray(b)
        size = max(len(a) if a.dtype == np.bool_ else (int(a.max()) + 1 if len(a) else 0),
                   len(b) if b.dtype == np.bool_ else (int(b.max()) + 1 if len(b) else 0))
        return to_mask(a, size) & to_mask(b, size)

    @_timed("vector")
    def near_vector(self, query, k: int = 10, vec_name: str = "",
                    tenant: str | None = None, include_objects: bool = True,
                    allow_list_by_shard: dict | None = None,
                    max_distance: float | None = None,
                    where=None, autocut: int = 0) -> list[SearchResult]:
        """Scatter-gather nearVector (reference: index.go:1541
        objectVectorSearch -> per-shard parallel search -> merge+truncate).
        ``where``: optional Filter tree, evaluated per shard to an AllowList
        mask applied inside the device scan."""
        query = np.asarray(query, dtype=np.float32)
        shards = self._target_shards(tenant)

        def one(shard: Shard):
            allow = None if allow_list_by_shard is None else \
                allow_list_by_shard.get(shard.name)
            if where is not None:
                fmask = shard.allow_mask(where)
                allow = fmask if allow is None else \
                    self._and_masks(allow, fmask)
            ids, dists = shard.vector_search(query, k, vec_name, allow)
            return shard, ids, dists

        if len(shards) == 1:
            gathered = [one(shards[0])]
        else:
            gathered = list(self._pool.map(one, shards))

        merged: list[tuple[float, int, Shard]] = []
        for shard, ids, dists in gathered:
            for doc_id, dist in zip(ids.tolist(), dists.tolist()):
                merged.append((dist, doc_id, shard))
        merged.sort(key=lambda t: t[0])
        merged = merged[:k]
        if max_distance is not None:
            merged = [m for m in merged if m[0] <= max_distance]
        if autocut > 0 and merged:
            from weaviate_tpu.query.autocut import autocut as _autocut

            merged = merged[: _autocut([m[0] for m in merged], autocut)]

        out = []
        for dist, doc_id, shard in merged:
            uuid = shard._doc_to_uuid.get(doc_id)
            if uuid is None:
                continue
            res = SearchResult(uuid=uuid, distance=dist, shard=shard.name)
            if include_objects:
                res.object = shard.get_object(uuid)
            out.append(res)
        return out

    @_timed("bm25")
    def bm25(self, query: str, k: int = 10, properties: list[str] | None = None,
             tenant: str | None = None, include_objects: bool = True,
             allow_list_by_shard: dict | None = None,
             where=None, autocut: int = 0) -> list[SearchResult]:
        """Scatter-gather keyword search; merge by score descending
        (reference: Index.objectSearch → per-shard BM25 → merge)."""
        shards = self._target_shards(tenant)

        def one(shard: Shard):
            allow = None if allow_list_by_shard is None else \
                allow_list_by_shard.get(shard.name)
            if where is not None:
                fmask = shard.allow_mask(where)
                allow = fmask if allow is None else \
                    self._and_masks(allow, fmask)
            ids, scores = shard.bm25_search(query, k, properties, allow)
            return shard, ids, scores

        gathered = [one(shards[0])] if len(shards) == 1 else \
            list(self._pool.map(one, shards))

        merged: list[tuple[float, int, Shard]] = []
        for shard, ids, scores in gathered:
            merged.extend(zip(scores.tolist(), ids.tolist(), [shard] * len(ids)))
        merged.sort(key=lambda t: -t[0])
        merged = merged[:k]
        if autocut > 0 and merged:
            from weaviate_tpu.query.autocut import autocut as _autocut

            merged = merged[: _autocut([-m[0] for m in merged], autocut)]
        out = []
        for score, doc_id, shard in merged:
            uuid = shard._doc_to_uuid.get(doc_id)
            if uuid is None:
                continue
            res = SearchResult(uuid=uuid, score=score, shard=shard.name)
            if include_objects:
                res.object = shard.get_object(uuid)
            out.append(res)
        return out

    @_timed("hybrid")
    def hybrid(self, query: str, vector=None, alpha: float = 0.75, k: int = 10,
               properties: list[str] | None = None, vec_name: str = "",
               tenant: str | None = None, fusion: str = "relativeScore",
               where=None, include_objects: bool = True,
               autocut: int = 0) -> list[SearchResult]:
        """Hybrid sparse+dense search (reference: hybrid/searcher.go:74 runs
        both legs in parallel, then fuses). ``alpha`` weighs the dense leg
        (0 = pure BM25, 1 = pure vector). ``vector=None`` degrades to
        sparse-only, as the reference does without a vectorizer."""
        from weaviate_tpu.text.hybrid import fusion_ranked, fusion_relative_score

        # over-fetch each leg so fusion has overlap to work with; legs run on
        # ephemeral threads, NOT self._pool — a leg parked in a pool worker
        # while its inner scatter-gather waits for that same pool can deadlock
        import threading as _threading

        if vector is None:
            alpha = 0.0  # degrade to sparse-only (reference does the same
            # when no vectorizer can produce a query vector)
        # evaluate the filter once per shard; both legs reuse the masks
        allow_by_shard = None
        if where is not None:
            allow_by_shard = {s.name: s.allow_mask(where)
                              for s in self._target_shards(tenant)}

        fetch = max(k * 10, 100)
        legs, weights = [], []
        results: dict[str, list] = {}
        errors: dict[str, BaseException] = {}

        def run(name, fn, *a):
            try:
                results[name] = fn(*a)
            except BaseException as e:  # re-raised on the caller thread
                errors[name] = e

        # legs skip object fetch; only the fused top-k pays for it below
        threads = []
        if alpha < 1.0:
            threads.append(_threading.Thread(
                target=run, args=("sparse", self.bm25, query, fetch,
                                  properties, tenant, False, allow_by_shard,
                                  None)))
        if vector is not None and alpha > 0.0:
            threads.append(_threading.Thread(
                target=run, args=("dense", self.near_vector, vector, fetch,
                                  vec_name, tenant, False, allow_by_shard,
                                  None, None)))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise next(iter(errors.values()))
        if "sparse" in results:
            legs.append(results["sparse"])
            weights.append(1.0 - alpha)
        if "dense" in results:
            dense = results["dense"]
            # similarity score for fusion: any monotone-decreasing map of
            # distance works (min-max normalization is affine-invariant)
            for r in dense:
                r.score = -r.distance
            legs.append(dense)
            weights.append(alpha)
        if not legs:
            return []
        fuse = fusion_relative_score if fusion == "relativeScore" else fusion_ranked
        fused = fuse(legs, weights, k)
        if autocut > 0 and fused:
            from weaviate_tpu.query.autocut import autocut_results

            fused = autocut_results(fused, autocut, by="score")
        if include_objects:
            by_shard = {s.name: s for s in self._target_shards(tenant)}
            for r in fused:
                shard = by_shard.get(r.shard)
                if shard is not None:
                    r.object = shard.get_object(r.uuid)
        return fused

    # -- maintenance ---------------------------------------------------------

    def flush(self):
        for s in self.shards.values():
            s.flush()

    def close(self):
        self._pool.shutdown(wait=False)
        for s in self.shards.values():
            s.close()
