"""The node-local database: shards, collections, and the DB facade.

Layer map (SURVEY §1): Shard (layer 3) owns an object KV store + vector
index(es) + inverted index; Collection (layer 4, the reference's Index)
routes objects to shards and scatter-gathers queries; Database (layer 5,
the reference's DB repo) holds collections + the schema manager.
"""

from weaviate_tpu.db.database import Database
from weaviate_tpu.db.collection import Collection
from weaviate_tpu.db.shard import Shard

__all__ = ["Database", "Collection", "Shard"]
