"""OIDC bearer-token validation against a JWKS.

Reference: adapters/handlers/rest/configure_api.go:601 + usecases/auth/
authentication/oidc — bearer tokens are validated against the issuer's
JWKS (signature, expiry, issuer, audience) and the username/groups claims
feed authorization.

Zero-egress deployments point ``AUTHENTICATION_OIDC_JWKS_FILE`` at a
local JWKS JSON (the issuer's /.well-known/jwks.json fetched out of
band); otherwise the JWKS is fetched once from the issuer and cached.
RS256 and ES256 keys are supported (the two algorithms real issuers use).
"""

from __future__ import annotations

import base64
import json
import os
import time


class OidcError(Exception):
    """Token failed validation (maps to 401)."""


def _b64url(data: str) -> bytes:
    pad = -len(data) % 4
    return base64.urlsafe_b64decode(data + "=" * pad)


def _b64url_uint(data: str) -> int:
    return int.from_bytes(_b64url(data), "big")


class JwksValidator:
    """Validates JWTs against a JWKS key set."""

    def __init__(self, issuer: str, client_id: str,
                 jwks: dict | None = None, jwks_file: str | None = None,
                 username_claim: str = "sub", groups_claim: str = "",
                 skip_client_id_check: bool = False):
        self.issuer = issuer.rstrip("/")
        self.client_id = client_id
        self.username_claim = username_claim
        self.groups_claim = groups_claim
        self.skip_client_id_check = skip_client_id_check
        self._keys: dict[str, object] = {}
        if jwks is None and jwks_file:
            with open(jwks_file) as f:
                jwks = json.load(f)
        if jwks is None and self.issuer:
            jwks = self._fetch_jwks()
        for jwk in (jwks or {}).get("keys", []):
            key = self._load_jwk(jwk)
            if key is not None:
                # "alg" is OPTIONAL in a JWK (RFC 7517 §4.4) — infer from
                # the key type when absent so verification never trusts the
                # token header's alg
                alg = jwk.get("alg") or (
                    "RS256" if jwk.get("kty") == "RSA" else "ES256")
                self._keys[jwk.get("kid", "")] = (alg, key)

    # -- key loading ---------------------------------------------------------

    def _fetch_jwks(self) -> dict | None:
        """Fetch {issuer}/.well-known/jwks.json — best-effort (a zero-
        egress deployment uses AUTHENTICATION_OIDC_JWKS_FILE instead)."""
        import urllib.request

        for path in ("/.well-known/jwks.json", "/jwks", "/keys"):
            try:
                with urllib.request.urlopen(self.issuer + path,
                                            timeout=5) as r:
                    return json.loads(r.read())
            except Exception:  # noqa: BLE001 — try the next convention
                continue
        return None

    @staticmethod
    def _load_jwk(jwk: dict):
        from cryptography.hazmat.primitives.asymmetric import ec, rsa

        kty = jwk.get("kty")
        try:
            if kty == "RSA":
                pub = rsa.RSAPublicNumbers(
                    e=_b64url_uint(jwk["e"]), n=_b64url_uint(jwk["n"]))
                return pub.public_key()
            if kty == "EC" and jwk.get("crv") == "P-256":
                pub = ec.EllipticCurvePublicNumbers(
                    x=_b64url_uint(jwk["x"]), y=_b64url_uint(jwk["y"]),
                    curve=ec.SECP256R1())
                return pub.public_key()
        except (KeyError, ValueError):
            return None
        return None

    @property
    def has_keys(self) -> bool:
        return bool(self._keys)

    # -- validation ----------------------------------------------------------

    def validate(self, token: str) -> dict:
        """Returns the verified claims dict or raises OidcError."""
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import ec, padding
        from cryptography.hazmat.primitives.asymmetric.utils import (
            encode_dss_signature)

        parts = token.split(".")
        if len(parts) != 3:
            raise OidcError("malformed JWT")
        try:
            header = json.loads(_b64url(parts[0]))
            claims = json.loads(_b64url(parts[1]))
            sig = _b64url(parts[2])
        except (ValueError, json.JSONDecodeError) as e:
            raise OidcError(f"malformed JWT: {e}") from e
        kid = header.get("kid", "")
        entry = self._keys.get(kid)
        if entry is None and len(self._keys) == 1:
            entry = next(iter(self._keys.values()))  # single-key JWKS
        if entry is None:
            raise OidcError(f"no JWKS key for kid {kid!r}")
        alg, key = entry
        # Pin the algorithm to the JWK's declared (or key-type-inferred)
        # alg — never to the attacker-controlled token header (reference
        # go-oidc: supported algs come from config).
        if header.get("alg") != alg:
            raise OidcError(
                f"JWT alg {header.get('alg')!r} does not match JWK alg {alg!r}")
        signed = (parts[0] + "." + parts[1]).encode()
        try:
            if alg == "RS256":
                key.verify(sig, signed, padding.PKCS1v15(), hashes.SHA256())
            elif alg == "ES256":
                if len(sig) != 64:
                    raise OidcError("malformed ES256 signature")
                der = encode_dss_signature(
                    int.from_bytes(sig[:32], "big"),
                    int.from_bytes(sig[32:], "big"))
                key.verify(der, signed, ec.ECDSA(hashes.SHA256()))
            else:
                raise OidcError(f"unsupported JWT alg {alg!r}")
        except InvalidSignature as e:
            raise OidcError("invalid JWT signature") from e
        except OidcError:
            raise
        except Exception as e:  # key-type/alg mismatch etc.
            raise OidcError(f"JWT verification failed: {e}") from e

        now = time.time()
        # Missing expiry = invalid (the reference's go-oidc verifier treats
        # tokens without exp as expired) — otherwise a leaked token without
        # exp would be accepted forever.
        if "exp" not in claims:
            raise OidcError("JWT missing exp claim")
        try:
            exp = float(claims["exp"])
            nbf = float(claims["nbf"]) if "nbf" in claims else None
        except (TypeError, ValueError) as e:
            raise OidcError(f"JWT has non-numeric exp/nbf: {e}") from e
        if now >= exp + 30:
            raise OidcError("JWT expired")
        if nbf is not None and now < nbf - 30:
            raise OidcError("JWT not yet valid")
        if self.issuer and claims.get("iss", "").rstrip("/") != self.issuer:
            raise OidcError(
                f"JWT issuer {claims.get('iss')!r} != {self.issuer!r}")
        if not self.skip_client_id_check and self.client_id:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.client_id not in auds:
                raise OidcError("JWT audience does not include the client id")
        return claims

    def principal_claims(self, token: str) -> tuple[str, list[str]]:
        claims = self.validate(token)
        username = str(claims.get(self.username_claim, "")
                       or claims.get("sub", ""))
        if not username:
            raise OidcError(
                f"JWT missing username claim {self.username_claim!r}")
        groups = []
        if self.groups_claim:
            g = claims.get(self.groups_claim)
            if isinstance(g, list):
                groups = [str(x) for x in g]
            elif g:
                groups = [str(g)]
        return username, groups


def validator_from_env(env=None) -> JwksValidator | None:
    env = env if env is not None else os.environ
    if env.get("AUTHENTICATION_OIDC_ENABLED", "").lower() not in (
            "true", "1", "on"):
        return None
    v = JwksValidator(
        issuer=env.get("AUTHENTICATION_OIDC_ISSUER", ""),
        client_id=env.get("AUTHENTICATION_OIDC_CLIENT_ID", ""),
        jwks_file=env.get("AUTHENTICATION_OIDC_JWKS_FILE") or None,
        username_claim=env.get("AUTHENTICATION_OIDC_USERNAME_CLAIM", "sub"),
        groups_claim=env.get("AUTHENTICATION_OIDC_GROUPS_CLAIM", ""),
        skip_client_id_check=env.get(
            "AUTHENTICATION_OIDC_SKIP_CLIENT_ID_CHECK", "").lower() in (
                "true", "1", "on"),
    )
    return v  # a keyless validator still rejects tokens with a clear error
