"""Authentication and authorization.

Reference:
- authn: API keys (usecases/auth/authentication/apikey — static key list
  mapped to users via AUTHENTICATION_APIKEY_ALLOWED_KEYS/USERS),
  anonymous access (AUTHENTICATION_ANONYMOUS_ACCESS_ENABLED), and OIDC
  (adapters/handlers/rest/configure_api.go:601; validated against the
  issuer's JWKS).
- authz: admin-list (usecases/auth/authorization/adminlist — admins get
  everything, read-only users get GET/HEAD), composed at
  configure_api.go:468.

OIDC bearer tokens validate against the issuer's JWKS (auth/oidc.py —
RS256/ES256 signature, exp/nbf, issuer, audience), with
AUTHENTICATION_OIDC_JWKS_FILE providing the key set offline for
zero-egress deployments (reference: configure_api.go:601).
"""

from __future__ import annotations

import hmac
import os
from dataclasses import dataclass, field


class AuthError(Exception):
    """401 — missing/invalid credentials."""


class ForbiddenError(Exception):
    """403 — authenticated but not allowed."""


@dataclass
class Principal:
    username: str
    auth_method: str = "anonymous"  # anonymous | apikey | oidc

    @property
    def is_anonymous(self) -> bool:
        return self.auth_method == "anonymous"


@dataclass
class AuthConfig:
    anonymous_enabled: bool = True
    # api keys: keys[i] authenticates as users[min(i, len(users)-1)]
    # (reference: AUTHENTICATION_APIKEY_ALLOWED_KEYS / _USERS semantics)
    api_keys: list[str] = field(default_factory=list)
    api_users: list[str] = field(default_factory=list)
    oidc_enabled: bool = False
    oidc_issuer: str = ""
    oidc_client_id: str = ""
    # authorization: admin list (empty admin list = everyone may write)
    admin_users: list[str] = field(default_factory=list)
    readonly_users: list[str] = field(default_factory=list)

    def __post_init__(self):
        # Reference rejects this misconfiguration at startup
        # (usecases/config: keys and users must align, or a single user
        # covers all keys). Without this check, surplus keys silently
        # authenticate as the LAST listed user.
        if len(self.api_users) > 1 and len(self.api_keys) != len(self.api_users):
            raise ValueError(
                "AUTHENTICATION_APIKEY_ALLOWED_KEYS and "
                "AUTHENTICATION_APIKEY_USERS must have the same length "
                f"(got {len(self.api_keys)} keys, {len(self.api_users)} users) "
                "unless at most one user is configured")

    @classmethod
    def from_env(cls, env=os.environ) -> "AuthConfig":
        """Reference env surface (usecases/config/environment.go)."""
        def flag(name, default="false"):
            return env.get(name, default).lower() in ("true", "1", "on")

        def csv(name):
            raw = env.get(name, "")
            return [s.strip() for s in raw.split(",") if s.strip()]

        keys_on = flag("AUTHENTICATION_APIKEY_ENABLED")
        return cls(
            anonymous_enabled=flag(
                "AUTHENTICATION_ANONYMOUS_ACCESS_ENABLED",
                "false" if keys_on else "true"),
            api_keys=csv("AUTHENTICATION_APIKEY_ALLOWED_KEYS")
            if keys_on else [],
            api_users=csv("AUTHENTICATION_APIKEY_USERS") if keys_on else [],
            oidc_enabled=flag("AUTHENTICATION_OIDC_ENABLED"),
            oidc_issuer=env.get("AUTHENTICATION_OIDC_ISSUER", ""),
            oidc_client_id=env.get("AUTHENTICATION_OIDC_CLIENT_ID", ""),
            admin_users=csv("AUTHORIZATION_ADMINLIST_USERS")
            if flag("AUTHORIZATION_ADMINLIST_ENABLED") else [],
            readonly_users=csv("AUTHORIZATION_ADMINLIST_READONLY_USERS")
            if flag("AUTHORIZATION_ADMINLIST_ENABLED") else [],
        )


class Authenticator:
    def __init__(self, config: AuthConfig, oidc_validator=None):
        self.config = config
        if oidc_validator is None and config.oidc_enabled:
            from weaviate_tpu.auth.oidc import validator_from_env

            try:
                oidc_validator = validator_from_env()
            except (OSError, ValueError) as e:
                import logging

                logging.getLogger(__name__).error(
                    "OIDC validator init failed: %s", e)
        self.oidc_validator = oidc_validator

    def authenticate(self, authorization: str | None) -> Principal:
        """``authorization``: the Authorization header value or None."""
        cfg = self.config
        if authorization:
            scheme, _, token = authorization.partition(" ")
            if scheme.lower() != "bearer" or not token:
                raise AuthError("Authorization header must be 'Bearer <key>'")
            token = token.strip()
            # compare as bytes: str compare_digest raises on non-ASCII,
            # which would turn a bad credential into a 500 instead of 401
            token_b = token.encode("utf-8", "surrogatepass")
            for i, key in enumerate(cfg.api_keys):
                if hmac.compare_digest(token_b, key.encode("utf-8")):
                    users = cfg.api_users
                    user = users[min(i, len(users) - 1)] if users else "api-key-user"
                    return Principal(user, "apikey")
            if cfg.oidc_enabled and token.count(".") == 2:
                # JWT validation against the configured JWKS (reference:
                # configure_api.go:601). JWTs have two dots; API keys
                # don't — a mistyped key keeps the crisp "invalid api
                # key" below instead of a confusing JWT-parse error.
                v = self.oidc_validator
                if v is None or not v.has_keys:
                    raise AuthError(
                        "OIDC is enabled but no JWKS is available; set "
                        "AUTHENTICATION_OIDC_JWKS_FILE or check issuer "
                        "connectivity")
                from weaviate_tpu.auth.oidc import OidcError

                try:
                    username, _groups = v.principal_claims(token)
                except OidcError as e:
                    raise AuthError(str(e)) from e
                return Principal(username, "oidc")
            if cfg.oidc_enabled and not cfg.api_keys:
                raise AuthError("bearer token is not a JWT and no API "
                                "keys are configured")
            raise AuthError("invalid api key")
        if cfg.anonymous_enabled:
            return Principal("anonymous", "anonymous")
        raise AuthError("anonymous access is disabled; provide a Bearer key")


class Authorizer:
    """Admin-list authorization (reference: authorization/adminlist):
    - no admin list configured → every authenticated principal may do
      anything (the reference's default 'all allowed' authorizer)
    - admin list configured → admins: everything; read-only users: reads;
      everyone else: denied
    """

    def __init__(self, config: AuthConfig):
        self.config = config

    def authorize(self, principal: Principal, verb: str) -> None:
        """``verb``: "read" or "write"."""
        cfg = self.config
        if not cfg.admin_users and not cfg.readonly_users:
            return
        if principal.username in cfg.admin_users:
            return
        if principal.username in cfg.readonly_users:
            if verb == "read":
                return
            raise ForbiddenError(
                f"user {principal.username!r} has read-only access")
        raise ForbiddenError(
            f"user {principal.username!r} is not on the admin list")


class AuthStack:
    """Authenticator + authorizer bundle the API servers consume."""

    def __init__(self, config: AuthConfig | None = None):
        self.config = config or AuthConfig()
        self.authenticator = Authenticator(self.config)
        self.authorizer = Authorizer(self.config)

    def check(self, authorization: str | None, verb: str) -> Principal:
        p = self.authenticator.authenticate(authorization)
        self.authorizer.authorize(p, verb)
        return p

    def openid_configuration(self) -> dict | None:
        """Payload for /.well-known/openid-configuration (reference serves
        the issuer's discovery document location + client id)."""
        if not self.config.oidc_enabled:
            return None
        return {
            "href": f"{self.config.oidc_issuer.rstrip('/')}"
                    "/.well-known/openid-configuration",
            "clientId": self.config.oidc_client_id,
        }
