"""Server entry point: ``python -m weaviate_tpu`` (or weaviate_tpu.server).

Reference: cmd/weaviate-server/main.go → configure_api.go:456 — assemble
config, auth, modules, DB, cluster, REST + gRPC + metrics listeners, then
serve until signaled. Single-node by default; RAFT_JOIN with >1 member
boots the cluster path (gossip + Raft + internal data plane), mirroring
the reference's startupRoutine ordering.
"""

from __future__ import annotations

import logging
import os
import signal
import threading

from weaviate_tpu.config import ServerConfig

logger = logging.getLogger("weaviate_tpu.server")

VERSION = "0.1.0"


class Server:
    """Owns every subsystem; ``start()`` returns once listeners are up
    (tests drive it in-process), ``serve_forever()`` blocks."""

    def __init__(self, config: ServerConfig | None = None):
        self.config = config or ServerConfig.from_env()
        self._stop = threading.Event()
        self.node = None
        self.db = None
        self.rest = None
        self.grpc = None
        self.telemeter = None
        self.metrics_server = None

    # -- assembly (configure_api.go:456 ordering) -------------------------

    def start(self) -> "Server":
        cfg = self.config
        self._setup_logging()

        # multi-host data plane first (before anything touches jax):
        # with DCN_COORDINATOR_ADDRESS set, jax.devices() spans every
        # host and all meshes/collectives go global (SURVEY §5 comms)
        from weaviate_tpu.parallel.mesh import maybe_initialize_distributed

        if maybe_initialize_distributed():
            logger.info("joined multi-host JAX runtime")

        # persistent XLA compilation cache (shared helper — the offline
        # tools and bulk builds need the same warm starts as the server)
        from weaviate_tpu.runtime.compile_cache import ensure_compile_cache

        ensure_compile_cache()

        from weaviate_tpu.auth import AuthConfig, AuthStack
        from weaviate_tpu.modules import default_provider

        auth_cfg = AuthConfig.from_env()
        auth = None
        if not auth_cfg.anonymous_enabled or auth_cfg.api_keys or \
                auth_cfg.oidc_enabled or auth_cfg.admin_users or \
                auth_cfg.readonly_users:
            auth = AuthStack(auth_cfg)

        # always constructed: the device budget may come from allocator
        # stats alone (TPU rigs report bytes_limit with zero config), so
        # gating must not hinge on any HBM_* env being set — no budget
        # discoverable means check_device_alloc is a no-op anyway
        from weaviate_tpu.runtime import MemoryMonitor

        memwatch = MemoryMonitor(
            host_limit_bytes=cfg.memory_limit_bytes or None,
            device_limit_bytes=cfg.hbm_device_limit_bytes or None,
            high_watermark=cfg.hbm_high_watermark,
            low_watermark=cfg.hbm_low_watermark)

        # device mesh for the serving stack: on a multi-host runtime
        # (or a WEAVIATE_TPU_VIRTUAL_HOSTS pod) collections row-shard
        # over the hierarchical ('host','ici') mesh so the two-level
        # ICI+DCN merge serves queries; single-process single-host
        # keeps the existing single-device placement (mesh=None)
        from weaviate_tpu.parallel.mesh import (default_mesh,
                                                is_multiprocess,
                                                virtual_hosts)

        mesh = (default_mesh()
                if is_multiprocess() or (virtual_hosts() or 1) > 1
                else None)
        if mesh is not None:
            logger.info("serving over %s mesh: %s",
                        "hierarchical" if "host" in mesh.axis_names
                        else "1-D", dict(mesh.shape))

        cluster_mode = len(cfg.raft_join) > 1 or bool(cfg.cluster_join)
        if cluster_mode:
            from weaviate_tpu.cluster.node import ClusterNode

            peers = cfg.raft_join or [cfg.cluster_hostname]
            self.node = ClusterNode(cfg.cluster_hostname, cfg.data_path,
                                    raft_peers=peers, host=cfg.host,
                                    port=cfg.cluster_data_port,
                                    advertise=cfg.cluster_advertise or None,
                                    remote_timeout=cfg.remote_rpc_timeout_s,
                                    sync_wal=cfg.wal_sync, mesh=mesh)
            self.node.start(seed_addrs=cfg.cluster_join or None)
            self.db = self.node.db
        else:
            from weaviate_tpu.db.database import Database

            self.db = Database(cfg.data_path,
                               local_node=cfg.cluster_hostname,
                               start_cycles=True,
                               memory_monitor=memwatch,
                               async_indexing=cfg.async_indexing or None,
                               sync_wal=cfg.wal_sync, mesh=mesh)

        # tailboard wiring: incident flight-recorder snapshots land in
        # the data dir; explicit SLO config (if any) replaces defaults
        from weaviate_tpu.runtime import tailboard

        tailboard.configure(data_dir=cfg.data_path,
                            enabled=cfg.tailboard_enabled,
                            slos_json=cfg.slo_config or None)

        # kernelscope wiring: on-demand kernel captures persist under
        # <data_dir>/kernelscope, pruned to the last PROFILING_KEEP
        from weaviate_tpu.runtime import kernelscope

        kernelscope.configure(data_dir=cfg.data_path,
                              keep=cfg.profile_keep)

        # driftwatch wiring: history ring + self-sealed live baseline
        # live under <data_dir>/driftwatch; the cycle itself is
        # registered by Database (start_cycles=True here runs it)
        from weaviate_tpu.runtime import driftwatch

        driftwatch.configure(data_dir=cfg.data_path,
                             enabled=cfg.driftwatch_enabled,
                             interval=cfg.drift_interval_s)

        modules = default_provider(self.db, enabled=cfg.enabled_modules)

        # FROZEN tenant tier: ship offloaded tenants through a backup
        # backend (reference: offload-s3 module + tenantactivity FROZEN)
        offload_name = os.environ.get("OFFLOAD_BACKEND", "")
        if offload_name:
            self.db.set_offload_backend(modules.backup_backend(offload_name))

        from weaviate_tpu.api.rest import RestServer

        if self.node is not None:
            self.rest = self.node.serve_rest(
                host=cfg.host, port=cfg.rest_port, modules=modules,
                auth=auth, query_deadline_s=cfg.query_deadline_s)
        else:
            self.rest = RestServer(self.db, host=cfg.host,
                                   port=cfg.rest_port, modules=modules,
                                   auth=auth,
                                   query_deadline_s=cfg.query_deadline_s)
            self.rest.start()

        from weaviate_tpu.api.grpc.server import GrpcServer

        use_native_plane = False
        if os.environ.get("WEAVIATE_TPU_NATIVE_DATAPLANE") == "1" \
                and auth is None:
            from weaviate_tpu.native import dataplane as _dpn

            use_native_plane = _dpn.available()
        if use_native_plane:
            # C++ transport serves the port; the (unstarted) GrpcServer
            # donates its handler logic to the fallback path
            from weaviate_tpu.api.grpc.native_plane import NativeDataPlane

            handlers = GrpcServer(self.db, host=cfg.host, port=0,
                                  modules=modules, auth=None)
            self.grpc = NativeDataPlane(self.db, handlers, host=cfg.host,
                                        port=cfg.grpc_port).start()
            logger.info("native gRPC data plane enabled")
        else:
            self.grpc = GrpcServer(self.db, host=cfg.host,
                                   port=cfg.grpc_port,
                                   modules=modules, auth=auth).start()

        self._start_profiler(cfg.profiling_port)

        if cfg.prometheus_enabled:
            from weaviate_tpu.runtime.metrics import serve_metrics

            self.metrics_server = serve_metrics(cfg.host,
                                                cfg.prometheus_port)

        if not cfg.disable_telemetry:
            from weaviate_tpu.runtime.telemetry import Telemeter

            self.telemeter = Telemeter(self.db, version=VERSION,
                                       data_dir=cfg.data_path)
            self.telemeter.start()

        logger.info("weaviate-tpu %s serving REST on %s gRPC on :%s",
                    VERSION, self.rest.address, self.grpc.port)
        return self

    def _start_profiler(self, port: int) -> bool:
        """Start the JAX profiler server on ``port``. Returns whether a
        server was started: ``PROFILING_PORT=0`` (the default) means
        NEVER — the early return is what the config unit test pins.

        Reference: setupGoProfiling serves pprof on PROFILING_PORT
        (configure_api.go:1094); the JAX profiler server is the TPU
        analog — point TensorBoard/xprof at it for device traces.
        One-shot captures don't need this: ``GET
        /v1/debug/profile?ms=N`` runs a programmatic capture inline."""
        if not port:
            return False
        try:
            import jax

            jax.profiler.start_server(port)
            logger.info("JAX profiler server on :%s", port)
            return True
        except Exception as e:  # noqa: BLE001 — profiling is best-effort
            logger.warning("profiler server failed to start: %s", e)
            return False

    def _setup_logging(self) -> None:
        level = getattr(logging, self.config.log_level.upper(),
                        logging.INFO)
        if self.config.log_format == "json":
            import json as _json

            class JsonFormatter(logging.Formatter):
                def format(self, record):
                    return _json.dumps({
                        "level": record.levelname.lower(),
                        "msg": record.getMessage(),
                        "logger": record.name,
                        "time": self.formatTime(record),
                    })

            handler = logging.StreamHandler()
            handler.setFormatter(JsonFormatter())
            logging.basicConfig(level=level, handlers=[handler])
        else:
            logging.basicConfig(
                level=level,
                format="%(asctime)s %(levelname)s %(name)s: %(message)s")

    # -- lifecycle ---------------------------------------------------------

    def serve_forever(self) -> None:
        try:
            signal.signal(signal.SIGTERM, lambda *_: self._stop.set())
            signal.signal(signal.SIGINT, lambda *_: self._stop.set())
        except ValueError:
            pass  # not the main thread
        self._stop.wait()
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        if self.telemeter is not None:
            self.telemeter.stop()
        if self.metrics_server is not None:
            # release the monitoring port — a leaked listener makes an
            # in-process restart fail with EADDRINUSE
            self.metrics_server.shutdown()
            self.metrics_server.server_close()
            self.metrics_server = None
        if self.grpc is not None:
            self.grpc.stop()
        if self.node is not None:
            self.node.close()  # closes rest + db too
        else:
            if self.rest is not None:
                self.rest.stop()
            if self.db is not None:
                self.db.close()


def main() -> None:
    Server().start().serve_forever()


if __name__ == "__main__":
    main()
