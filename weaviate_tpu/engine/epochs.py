"""Epochstore: immutable device epochs with on-device compaction.

The reference's LSM engine (lsmkv ``replace``: immutable segments + one
active memtable + background compaction) applied to HBM (ROADMAP item 3):
instead of one donated buffer that only ever grows, the corpus becomes a
stack of IMMUTABLE device epochs plus one small ACTIVE epoch.

- Writes land in the active epoch through the existing staged-scatter
  fast path; when it reaches ``epoch_rows`` it is SEALED — a frozen
  array whose vectors the serving lock never has to guard again — and a
  fresh active epoch opens.
- Reads fuse across the stack: every epoch runs the SAME scan kernels it
  always did (``fused_topk_scan`` / bq / pq4 scan-reduce), and the
  per-epoch survivor sets merge ON DEVICE with ``ops.topk.
  merge_epoch_topk`` (``fused_topk_pairs`` under ``selection="fused"``)
  — the ICI-merge pattern from ``parallel/sharded_search.py`` turned
  inward, so no new Pallas kernels exist and multi-epoch results are
  bit-identical to a single-buffer scan (the merge is exact; per-epoch
  selection error never compounds).
- Deletes stay tombstone masks, but now they RECLAIM HBM: a background
  policy (``maintain()``, registered with ``runtime/cyclemanager.py`` by
  the database) folds tombstone-heavy sealed epochs — gather live rows
  into a fresh store, release the old one through the HBM ledger's
  weakref finalizers — and drops empty epochs outright.
- Global slot ids are STABLE across compaction: each epoch carries a
  local->global ``slot_map`` the merge gathers through, so the
  ``FlatIndex`` id<->slot tables never need remapping when an epoch
  repacks, and a sealed epoch can migrate to a sibling shard wholesale
  (``extract_epoch``/``drop_epoch`` — db/collection.py orchestrates the
  durable move).

Each epoch's device arrays register in the HBM ledger under a per-epoch
component label (``corpus@e3``, ``codes@e3``): /v1/debug/memory and the
``hbm_bytes`` gauge show exactly which epoch owns which bytes, and
dropping an epoch visibly releases exactly its series.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from weaviate_tpu.engine.quantized import QuantizedVectorStore
from weaviate_tpu.engine.store import DeviceVectorStore, normalize_allow_mask
from weaviate_tpu.ops.topk import merge_epoch_topk
from weaviate_tpu.runtime import hbm_ledger, kernelscope, tracing, transfer
from weaviate_tpu.runtime.transfer import DeviceResultHandle

#: default seal threshold (rows) when epochs are enabled without an
#: explicit size; 0 disables epoching entirely (legacy single buffer)
DEFAULT_EPOCH_ROWS = int(os.environ.get("WEAVIATE_TPU_EPOCH_ROWS", "0") or 0)

#: tombstone fraction past which maintain() folds a sealed epoch
COMPACT_TOMBSTONE_FRAC = 0.25


class _Epoch:
    """One epoch: a backing store + its slice of the global slot space.

    ``base`` is the first global slot; ``span`` the number of global
    slots this epoch ever covered (fixed at seal). ``map_np`` is the
    local->global table (``None`` = identity ``base + local``, the
    pre-compaction layout); ``local_of`` its inverse over ``[0, span)``
    (-1 = dropped). Compaction repacks local rows but keeps the global
    ids — only these maps change.
    """

    __slots__ = ("eid", "base", "span", "store", "sealed", "map_np",
                 "local_of", "_dev_map", "_dev_map_cap", "last_query_t",
                 "created_t")

    def __init__(self, eid: int, base: int, store):
        self.eid = eid
        self.base = base
        self.span = 0
        self.store = store
        self.sealed = False
        self.map_np: np.ndarray | None = None  # None = identity
        self.local_of: np.ndarray | None = None
        self._dev_map = None
        self._dev_map_cap = -1
        self.last_query_t = time.monotonic()
        self.created_t = time.monotonic()

    def slot_map_device(self):
        """Device int32 local->global table for the merge gather,
        rebuilt lazily when the store grows or compacts. On a mesh the
        table is REPLICATED like the candidate sets it gathers for —
        the merge then stays one SPMD program with no implicit
        re-placement (the same alignment contract the column-sharded
        allow masks keep in parallel/sharded_search.py)."""
        import jax.numpy as jnp

        cap = self.store.capacity
        if self._dev_map is None or self._dev_map_cap != cap:
            if self.map_np is None:
                host = self.base + np.arange(cap, dtype=np.int32)
            else:
                host = np.full(cap, -1, dtype=np.int32)
                w = min(len(self.map_np), cap)
                host[:w] = self.map_np[:w]
            mesh = getattr(self.store, "mesh", None)
            if mesh is not None:
                from weaviate_tpu.parallel.sharded_search import (
                    replicate_array)

                self._dev_map = replicate_array(jnp.asarray(host), mesh)
            else:
                self._dev_map = jnp.asarray(host)
            self._dev_map_cap = cap
        return self._dev_map

    def locals_for(self, gslots: np.ndarray) -> np.ndarray:
        """Global slots (already in this epoch's range) -> local rows
        (-1 = dropped by compaction)."""
        off = gslots - self.base
        if self.local_of is None:
            return off
        out = np.full(len(off), -1, dtype=np.int64)
        ok = (off >= 0) & (off < len(self.local_of))
        out[ok] = self.local_of[off[ok]]
        return out

    def live_globals(self) -> np.ndarray:
        """Global slot ids of this epoch's live rows."""
        valid = self.store._valid_np
        locs = np.nonzero(valid[: self.store.capacity])[0]
        if self.map_np is None:
            return self.base + locs.astype(np.int64)
        return self.map_np[locs]

    def live_count(self) -> int:
        return int(self.store.live_count())

    def stats(self) -> dict:
        live = self.live_count()
        return {
            "epoch": self.eid,
            "base": self.base,
            "span": self.span if self.sealed else self.store.count,
            "rows": int(self.store.count),
            "live": live,
            "tombstones": max(int(self.store.count) - live, 0),
            "sealed": self.sealed,
            "capacity": int(self.store.capacity),
            "lastQueryAgeS": round(time.monotonic() - self.last_query_t, 3),
        }


class EpochStore:
    """Epoch-stacked device store with the ``DeviceVectorStore`` method
    surface (and its quantized twin's, when ``quantization`` is set).

    Thread-safe: ``_lock`` guards the epoch list and slot-space
    bookkeeping; each backing store keeps its own lock for buffer swaps
    (always acquired AFTER this one — consistent order, no ABBA).
    """

    def __init__(self, dim: int, *, metric: str = "l2-squared",
                 epoch_rows: int = 0, capacity: int = 8192,
                 dtype=None, mesh=None, chunk_size: int = 8192,
                 normalize_on_add: bool | None = None,
                 selection: str = "approx",
                 quantization: str | None = None,
                 quant_kwargs: dict | None = None):
        import jax.numpy as jnp

        self.dim = dim
        self.metric = metric
        self.epoch_rows = int(epoch_rows) or DEFAULT_EPOCH_ROWS or (1 << 20)
        self.dtype = dtype or jnp.float32
        self.mesh = mesh
        self.chunk_size = chunk_size
        self.selection = selection
        self.quantization = quantization
        self._quant_kwargs = dict(quant_kwargs or {})
        self.normalize_on_add = (
            metric in ("cosine", "cosine-dot")
            if normalize_on_add is None else normalize_on_add)
        self._initial_capacity = min(capacity, self.epoch_rows)
        self._lock = threading.RLock()
        self._owner = hbm_ledger.current_owner()
        self._codebook = self._quant_kwargs.pop("codebook", None)
        self._next_slot = 0
        self._next_eid = 0
        self.compactions_total = 0
        self.migrations_total = 0
        self._published_eids: set[str] = set()
        self.epochs: list[_Epoch] = []
        with self._lock:
            self._open_epoch_locked()

    # -- epoch lifecycle ------------------------------------------------------

    def _new_store(self, capacity: int, eid: int):
        """Backing store for one epoch, ledger-labeled per epoch and
        constructed under this store's captured owner scope (sealing
        happens on the write path, which may run outside the shard's
        construction-time scope)."""
        with hbm_ledger.owner(**self._owner):
            if self.quantization:
                return QuantizedVectorStore(
                    dim=self.dim, metric=self.metric,
                    quantization=self.quantization, capacity=capacity,
                    chunk_size=self.chunk_size, mesh=self.mesh,
                    selection=self.selection,
                    normalize_on_add=self.normalize_on_add,
                    codebook=self._codebook,
                    component_suffix=f"@e{eid}",
                    **self._quant_kwargs)
            return DeviceVectorStore(
                dim=self.dim, metric=self.metric, capacity=capacity,
                dtype=self.dtype, mesh=self.mesh,
                chunk_size=self.chunk_size,
                normalize_on_add=self.normalize_on_add,
                selection=self.selection, component=f"corpus@e{eid}")

    def _open_epoch_locked(self) -> _Epoch:
        """Open a fresh active epoch at the current slot high-water.
        Caller holds ``_lock``."""
        eid = self._next_eid
        self._next_eid += 1
        ep = _Epoch(eid, self._next_slot,
                    self._new_store(self._initial_capacity, eid))
        self.epochs.append(ep)
        return ep

    def _seal_active_locked(self) -> None:
        """Freeze the active epoch (flush its staged rows so the sealed
        arrays are complete) and open a new one. Caller holds
        ``_lock``."""
        act = self.epochs[-1]
        if hasattr(act.store, "flush_staged"):
            act.store.flush_staged()
        act.span = int(act.store.count)
        act.sealed = True
        self._next_slot = act.base + act.span
        self._open_epoch_locked()

    def seal_active(self) -> None:
        """Public seal hook (tests, pre-migration)."""
        with self._lock:
            if self.epochs[-1].store.count > 0:
                self._seal_active_locked()

    # -- slot-space mapping ---------------------------------------------------

    def _group_by_epoch(self, gslots: np.ndarray):
        """Map global slots to (epoch, local rows) groups. Caller holds
        ``_lock``. Slots in dropped/migrated ranges are silently skipped
        (their rows are gone — the same contract as deleting an already
        tombstoned slot)."""
        if len(self.epochs) == 1 and self.epochs[0].base == 0:
            yield self.epochs[0], gslots.astype(np.int64)
            return
        bases = np.array([e.base for e in self.epochs], dtype=np.int64)
        spans = np.array(
            [e.span if e.sealed else e.store.count for e in self.epochs],
            dtype=np.int64)
        gslots = np.asarray(gslots, dtype=np.int64)
        idx = np.searchsorted(bases, gslots, side="right") - 1
        ok = idx >= 0
        ok[ok] &= gslots[ok] - bases[idx[ok]] < np.maximum(
            spans[idx[ok]], 1)
        for ei in np.unique(idx[ok]):
            sel = ok & (idx == ei)
            ep = self.epochs[int(ei)]
            loc = ep.locals_for(gslots[sel])
            loc = loc[loc >= 0]
            if len(loc):
                yield ep, loc

    # -- DeviceVectorStore surface: mutation ----------------------------------

    def add(self, vectors: np.ndarray) -> np.ndarray:
        """Append a batch; returns GLOBAL slot ids. Batches larger than
        the active epoch's remaining room split across a seal boundary —
        slot ids stay contiguous because the new epoch opens exactly at
        the high-water mark."""
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        m = len(vectors)
        out = np.empty(m, dtype=np.int64)
        with self._lock:
            pos = 0
            while pos < m:
                act = self.epochs[-1]
                room = self.epoch_rows - int(act.store.count)
                if room <= 0:
                    self._seal_active_locked()
                    continue
                take = min(room, m - pos)
                locs = act.store.add(vectors[pos:pos + take])
                out[pos:pos + take] = act.base + np.asarray(locs,
                                                            dtype=np.int64)
                pos += take
                self._next_slot = max(self._next_slot,
                                      act.base + int(act.store.count))
        return out

    def set_at(self, slots, vectors: np.ndarray) -> None:
        """Overwrite existing global slots in their owning epochs (the
        update path keeps slot ids; sealed vectors are frozen for scans
        but the donated scatter update is the same LSM exception the
        reference makes for in-place doc-id reuse)."""
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        vectors = np.atleast_2d(np.asarray(vectors, dtype=np.float32))
        with self._lock:
            if len(slots) and int(slots.max()) >= self._addressable():
                raise ValueError(
                    f"set_at slot {int(slots.max())} beyond epoch-store "
                    f"high-water {self._addressable()} — epoch stores "
                    "assign slots at add()")
            order = {int(s): i for i, s in enumerate(slots)}
            for ep, loc in self._group_by_epoch(slots):
                gl = (ep.base + loc if ep.map_np is None
                      else ep.map_np[loc])
                rows = vectors[[order[int(g)] for g in gl]]
                ep.store.set_at(loc, rows)

    def set_at_prenormalized(self, slots, vectors: np.ndarray) -> None:
        """set_at for rows normalized at their original insert
        (restore/compress paths)."""
        with self._lock:
            flips = []
            for ep in self.epochs:
                flips.append((ep.store, ep.store.normalize_on_add))
                ep.store.normalize_on_add = False
            try:
                self.set_at(slots, vectors)
            finally:
                for st, orig in flips:
                    st.normalize_on_add = orig

    def delete(self, slots) -> None:
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        if len(slots) == 0:
            return
        with self._lock:
            for ep, loc in self._group_by_epoch(slots):
                ep.store.delete(loc)

    def flush_staged(self) -> None:
        with self._lock:
            act = self.epochs[-1]
            if hasattr(act.store, "flush_staged"):
                act.store.flush_staged()

    # -- DeviceVectorStore surface: queries -----------------------------------

    def _addressable(self) -> int:
        """Exclusive upper bound on assigned global slots. Caller holds
        ``_lock``."""
        act = self.epochs[-1]
        return max(self._next_slot, act.base + int(act.store.count))

    @property
    def count(self) -> int:
        """Global slot high-water (including tombstones and migrated
        ranges) — the size filters/doc tables key against."""
        with self._lock:
            return self._addressable()

    @property
    def capacity(self) -> int:
        """Addressable global slot space (last epoch's range end) — the
        width of shared allow masks and slot->id tables."""
        with self._lock:
            act = self.epochs[-1]
            return act.base + int(act.store.capacity)

    def live_count(self) -> int:
        with self._lock:
            return sum(ep.live_count() for ep in self.epochs)

    @property
    def epoch_count(self) -> int:
        with self._lock:
            return len(self.epochs)

    @property
    def trained(self) -> bool:
        if not self.quantization:
            return True
        with self._lock:
            return self.epochs[-1].store.trained

    def train(self, vectors: np.ndarray | None = None, iters: int = 8,
              seed: int = 0) -> None:
        """Fit the (shared) PQ codebook and re-encode every epoch — one
        codebook across the stack, so candidates merge in one code
        space."""
        if self.quantization != "pq":
            return
        with self._lock:
            if vectors is None:
                parts = []
                for ep in self.epochs:
                    lg = ep.live_globals()
                    if len(lg):
                        loc = ep.locals_for(lg)
                        parts.append(ep.store._vectors_for(loc))
                vectors = (np.concatenate(parts) if parts
                           else np.zeros((0, self.dim), np.float32))
            act = self.epochs[-1]
            act.store.train(vectors, iters=iters, seed=seed)
            self._codebook = act.store.codebook
            for ep in self.epochs[:-1]:
                ep.store.codebook = self._codebook
                ep.store._reencode_all()
                ep.store._hbm_sync()

    def get(self, slots) -> np.ndarray:
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        out = np.zeros((len(slots), self.dim), dtype=np.float32)
        order = {}
        with self._lock:
            for i, s in enumerate(slots):
                order.setdefault(int(s), []).append(i)
            for ep, loc in self._group_by_epoch(slots):
                gl = (ep.base + loc if ep.map_np is None
                      else ep.map_np[loc])
                rows = ep.store.get(loc)
                for g, row in zip(gl, rows):
                    for i in order.get(int(g), ()):
                        out[i] = row
        return out

    def _slice_allow(self, allow_mask, ep: _Epoch):
        """Column-slice a global allow mask to one epoch's LOCAL row
        space (compaction-aware through ``local_of``). Caller holds
        ``_lock``."""
        if allow_mask is None:
            return None
        base, cap = ep.base, int(ep.store.capacity)
        span = ep.span if ep.sealed else int(ep.store.count)
        if allow_mask.ndim == 1:
            seg = np.zeros(cap, dtype=bool)
            w = max(min(len(allow_mask) - base, span), 0)
            if w > 0:
                g_allowed = allow_mask[base:base + w]
                if ep.local_of is None:
                    seg[:w] = g_allowed
                else:
                    loc = ep.local_of[:w][g_allowed[: len(ep.local_of)]]
                    loc = loc[(loc >= 0) & (loc < cap)]
                    seg[loc] = True
            return seg
        b = allow_mask.shape[0]
        seg = np.zeros((b, cap), dtype=bool)
        w = max(min(allow_mask.shape[1] - base, span), 0)
        if w > 0:
            g_allowed = allow_mask[:, base:base + w]
            if ep.local_of is None:
                seg[:, :w] = g_allowed
            else:
                lo = ep.local_of[:w]
                ok = lo >= 0
                seg[:, lo[ok]] = g_allowed[:, ok]
        return seg

    def search(self, queries: np.ndarray, k: int,
               allow_mask: np.ndarray | None = None):
        return self.search_async(queries, k, allow_mask).result()

    def search_async(self, queries: np.ndarray, k: int,
                     allow_mask: np.ndarray | None = None
                     ) -> DeviceResultHandle:
        """Dispatch-only epoch-fused search: every epoch's scan kernel
        dispatches under ``_lock``, survivor sets merge ON DEVICE
        (``merge_epoch_topk``), and the returned handle's finish step
        runs the one global host rescore (quantized) — so the zero-sync
        serving pipeline drains exactly one D2H per batch no matter how
        many epochs exist."""
        queries = np.asarray(queries, dtype=np.float32)
        squeeze = queries.ndim == 1
        if squeeze:
            queries = queries[None, :]
        now = time.monotonic()
        with self._lock:
            eps = list(self.epochs)
            for ep in eps:
                ep.last_query_t = now
            if len(eps) == 1 and eps[0].base == 0 and eps[0].map_np is None:
                # single-epoch passthrough: the epoch IS the store —
                # full engine behavior including the gathered cutover
                return eps[0].store.search_async(
                    queries[0] if squeeze else queries, k, allow_mask)
        allow_mask = normalize_allow_mask(allow_mask, len(queries))
        with tracing.span("store.epoch_scan", epochs=len(eps),
                          queries=len(queries), k=k,
                          quantized=bool(self.quantization),
                          filtered=allow_mask is not None):
            with self._lock:
                eps = [e for e in self.epochs if int(e.store.count) > 0]
                if not eps:
                    b = len(queries)
                    d0 = np.full((b, k), np.float32(np.inf), np.float32)
                    i0 = np.full((b, k), -1, np.int64)
                    return DeviceResultHandle.ready(
                        (d0[0], i0[0]) if squeeze else (d0, i0))
                if self.quantization:
                    return self._dispatch_quantized_locked(
                        eps, queries, k, allow_mask, squeeze)
                return self._dispatch_flat_locked(
                    eps, queries, k, allow_mask, squeeze)

    def _dispatch_flat_locked(self, eps, queries, k, allow_mask, squeeze):
        """Per-epoch flat scans + device merge. Caller holds ``_lock``."""
        parts, maps = [], []
        for ep in eps:
            d, i = ep.store.epoch_scan(
                queries, k, self._slice_allow(allow_mask, ep))
            parts.append((d, i))
            maps.append(ep.slot_map_device())
        # EXPLAIN (host ints, no-op without a sink): epoch fanout and
        # the on-device merge shape of this dispatch
        kernelscope.explain_note(
            "epochs", epochs=len(parts), merge_fanin=len(parts),
            k_merge=k, rescore_mode="none", queries=len(queries), k=k)
        md, mi = merge_epoch_topk(tuple(parts), tuple(maps), k=k,
                                  selection=self.selection)

        def _finish(d_np, i_np, _squeeze=squeeze):
            i_np = i_np.astype(np.int64, copy=False)
            if _squeeze:
                return d_np[0], i_np[0]
            return d_np, i_np

        return DeviceResultHandle(
            (md, mi), finish=_finish,
            attrs={"rows": self.capacity, "queries": len(queries),
                   "k": k, "epochs": len(parts)})

    def _dispatch_quantized_locked(self, eps, queries, k, allow_mask,
                                   squeeze):
        """Per-epoch compressed scans + device merge + ONE global host
        rescore in the finish step. Caller holds ``_lock``."""
        template = eps[-1].store
        qn = template._maybe_norm(queries)
        mode = template.rescore_mode()
        rl = template.rescore_limit
        snaps = []  # (base, span, local_of, tiers, count) at dispatch
        parts, maps = [], []
        # "plane" (single-device bf16 rows) degrades to "post" here: the
        # merged candidates span per-epoch tier SNAPSHOTS, so the exact
        # pass must route through the epoch-aware _vectors_for gather —
        # the device plane has no cross-snapshot view
        if mode == "plane":
            mode = "post"
        # both rescore modes need the oversampled candidate set — the
        # inline (in-SPMD) rescore sees k_cand code-distance candidates
        # per epoch exactly like the single-buffer path; only
        # rescore-less stores scan at k
        k_cand = max(k * rl, k) if mode in ("post", "inline") else k
        for ep in eps:
            cap = int(ep.store.capacity)
            kc = min(k_cand, cap)
            d, i, tiers = ep.store.epoch_scan(
                qn, kc, kc if mode == "post" else min(k, cap),
                self._slice_allow(allow_mask, ep), pre_normalized=True)
            parts.append((d, i))
            maps.append(ep.slot_map_device())
            snaps.append((ep.base, ep.span or int(ep.store.count),
                          None if ep.local_of is None
                          else ep.local_of.copy(), tiers,
                          int(ep.store.count)))
        k_merge = k_cand if mode == "post" else k
        # EXPLAIN: epoch fanout, merge shape and the (possibly plane->
        # post degraded) rescore mode of this dispatch — host ints only
        kernelscope.explain_note(
            "epochs", epochs=len(parts), merge_fanin=len(parts),
            k_merge=k_merge, k_cand=k_cand, rescore_mode=mode,
            queries=len(queries), k=k)
        md, mi = merge_epoch_topk(tuple(parts), tuple(maps), k=k_merge,
                                  selection=self.selection)
        cap_total = self.capacity
        dim = self.dim

        def _vectors_for(slots, _snaps=snaps, _dim=dim):
            """Global-slot -> full-precision rows across the dispatch-
            time epoch tier snapshots (the finish step's rescore feed)."""
            slots = np.asarray(slots, dtype=np.int64)
            out = np.zeros((len(slots), _dim), dtype=np.float32)
            for base, span, local_of, tiers, cnt in _snaps:
                sel = (slots >= base) & (slots < base + max(span, 1))
                if not sel.any():
                    continue
                loc = slots[sel] - base
                if local_of is not None:
                    lo = np.full(len(loc), 0, dtype=np.int64)
                    ok = loc < len(local_of)
                    lo[ok] = np.where(local_of[loc[ok]] >= 0,
                                      local_of[loc[ok]], 0)
                    loc = lo
                loc = np.clip(loc, 0, max(cnt - 1, 0))
                out[sel] = QuantizedVectorStore._tier_vectors(
                    *tiers, loc)
            return out

        def _finish(d_np, i_np, _queries=qn, _k=k, _squeeze=squeeze,
                    _mode=mode, _cap=cap_total):
            i_np = i_np.astype(np.int64, copy=False)
            if _mode == "post":
                with tracing.span("store.host_rescore",
                                  candidates=int(i_np.shape[1])):
                    d_np, i_np = template._host_rescore(
                        _queries, i_np, _k, capacity=_cap,
                        vectors_for=_vectors_for)
            out_d = d_np[:, :_k].astype(np.float32)
            out_i = i_np[:, :_k]
            if _squeeze:
                return out_d[0], out_i[0]
            return out_d, out_i

        return DeviceResultHandle(
            (md, mi), finish=_finish,
            attrs={"rows": cap_total, "queries": len(queries), "k": k,
                   "epochs": len(parts),
                   "quantization": self.quantization})

    def search_by_distance(self, query: np.ndarray, max_distance: float,
                           allow_mask: np.ndarray | None = None):
        k = min(64, max(self.capacity, 1))
        while True:
            d, i = self.search(query, k, allow_mask)
            within = d <= max_distance
            if ((~within).any() or k >= self.capacity
                    or within.sum() >= self.live_count()):
                return d[within], i[within]
            k = min(k * 4, self.capacity)

    # -- maintenance: compaction / migration ----------------------------------

    def compact(self) -> np.ndarray:
        """Full-store compaction with STABLE global slots: every sealed
        epoch folds its tombstones in place (live global ids unchanged);
        returns the old->new mapping the FlatIndex contract expects —
        identity for live slots, -1 for dead ones."""
        with self._lock:
            cap = self.capacity
            for ep in list(self.epochs):
                if ep.sealed:
                    if ep.live_count() == 0:
                        self.drop_epoch(ep.eid)
                    elif int(ep.store.count) > ep.live_count():
                        self.compact_epoch(ep.eid)
            mapping = np.full(cap, -1, dtype=np.int64)
            for ep in self.epochs:
                lg = ep.live_globals()
                lg = lg[lg < cap]
                mapping[lg] = lg
            return mapping

    def compact_epoch(self, eid: int) -> bool:
        """Fold one sealed epoch's tombstones on device: the backing
        store repacks live rows into a right-sized fresh allocation
        (its ``compact()`` routes the one D2H through ``transfer.d2h``),
        the old arrays release through the ledger's weakref finalizers,
        and this epoch's local->global maps re-point — global slot ids
        do not change, so no index table anywhere needs remapping."""
        with self._lock:
            ep = self._epoch_by_id(eid)
            if ep is None or not ep.sealed:
                return False
            old_cap = int(ep.store.capacity)
            old_map = (ep.base + np.arange(old_cap, dtype=np.int64)
                       if ep.map_np is None else ep.map_np)
            with tracing.span("store.compact_epoch", epoch=ep.eid,
                              rows=old_cap):
                mapping = ep.store.compact()
            new_cap = int(ep.store.capacity)
            new_map = np.full(new_cap, -1, dtype=np.int64)
            moved = mapping >= 0
            src = np.nonzero(moved)[0]
            new_map[mapping[src]] = old_map[src]
            ep.map_np = new_map
            local_of = np.full(ep.span, -1, dtype=np.int64)
            filled = new_map >= 0
            off = new_map[filled] - ep.base
            ok = (off >= 0) & (off < ep.span)
            local_of[off[ok]] = np.nonzero(filled)[0][ok]
            ep.local_of = local_of
            ep._dev_map = None
            self.compactions_total += 1
            try:
                from weaviate_tpu.runtime.metrics import epoch_compactions

                epoch_compactions.labels(
                    self._owner.get("collection", "_unowned"),
                    self._owner.get("shard", "-")).inc()
            except Exception:  # noqa: BLE001 — observability must not gate
                pass
            self._publish_metrics_locked()
            return True

    def drop_epoch(self, eid: int) -> bool:
        """Remove an epoch from the stack (post-migration cutover, or an
        all-tombstone epoch). Its device arrays release through the
        stores' ledger finalizers as soon as the last in-flight handle
        drops its reference."""
        with self._lock:
            ep = self._epoch_by_id(eid)
            if ep is None:
                return False
            if ep is self.epochs[-1] and not ep.sealed:
                return False  # never drop the live write target
            self.epochs.remove(ep)
            if not self.epochs:
                self._open_epoch_locked()
            self._publish_metrics_locked()
            return True

    def extract_epoch(self, eid: int):
        """Serialize one epoch for migration: returns ``(global_slots
        [n], vectors [n, d] f32)`` of its live rows (one ``transfer.d2h``
        for the flat tier; the quantized form reads its full-precision
        tier). The epoch itself is untouched — the caller cuts over
        (``drop_epoch``) only after the target shard acked the ingest."""
        with self._lock:
            ep = self._epoch_by_id(eid)
            if ep is None:
                return np.empty(0, np.int64), np.zeros((0, self.dim),
                                                       np.float32)
            if hasattr(ep.store, "flush_staged"):
                ep.store.flush_staged()
            lg = ep.live_globals()
            loc = ep.locals_for(lg)
            if isinstance(ep.store, QuantizedVectorStore):
                rows = ep.store._vectors_for(loc)
            else:
                (vec_host,) = transfer.d2h(ep.store.vectors)
                rows = vec_host[loc].astype(np.float32)
            return lg, rows

    def live_globals_of(self, eid: int) -> np.ndarray:
        """Global slot ids of one epoch's live rows (the migration
        planner maps these through the index's slot->doc table)."""
        with self._lock:
            ep = self._epoch_by_id(eid)
            return (np.empty(0, np.int64) if ep is None
                    else ep.live_globals())

    def coldest_sealed(self) -> int | None:
        """The sealed epoch least recently touched by a query (the
        migration victim when the ledger crosses watermark)."""
        with self._lock:
            cands = [e for e in self.epochs if e.sealed
                     and e.live_count() > 0]
            if not cands:
                return None
            return min(cands, key=lambda e: e.last_query_t).eid

    def maintain(self, tombstone_frac: float = COMPACT_TOMBSTONE_FRAC
                 ) -> bool:
        """One background cycle (cyclemanager callback body): seal an
        overfull active epoch, drop empty sealed epochs, fold
        tombstone-heavy ones. Returns True when work was done."""
        did = False
        with self._lock:
            if int(self.epochs[-1].store.count) >= self.epoch_rows:
                self._seal_active_locked()
                did = True
            for ep in list(self.epochs):
                if not ep.sealed:
                    continue
                total = int(ep.store.count)
                live = ep.live_count()
                if total and live == 0:
                    did = self.drop_epoch(ep.eid) or did
                elif total and (total - live) / total >= tombstone_frac:
                    did = self.compact_epoch(ep.eid) or did
            self._publish_metrics_locked()
        return did

    def _epoch_by_id(self, eid: int) -> _Epoch | None:
        """Caller holds ``_lock``."""
        for ep in self.epochs:
            if ep.eid == eid:
                return ep
        return None

    def epoch_stats(self) -> list[dict]:
        with self._lock:
            return [ep.stats() for ep in self.epochs]

    # -- observability --------------------------------------------------------

    def _publish_metrics_locked(self) -> None:
        """Refresh the ``weaviate_tpu_epoch_*`` gauges; stale per-epoch
        series are removed when their epoch compacts away or migrates.
        Caller holds ``_lock``; gauges have their own locks and never
        call back in."""
        try:
            from weaviate_tpu.runtime.metrics import (epoch_count,
                                                      epoch_live_rows,
                                                      epoch_tombstone_rows)

            col = self._owner.get("collection", "_unowned")
            shard = self._owner.get("shard", "-")
            epoch_count.labels(col, shard).set(float(len(self.epochs)))
            seen = set()
            for ep in self.epochs:
                label = f"e{ep.eid}"
                seen.add(label)
                st = ep.stats()
                epoch_live_rows.labels(col, shard, label).set(
                    float(st["live"]))
                epoch_tombstone_rows.labels(col, shard, label).set(
                    float(st["tombstones"]))
            for stale in self._published_eids - seen:
                epoch_live_rows.remove(col, shard, stale)
                epoch_tombstone_rows.remove(col, shard, stale)
            self._published_eids = seen
        except Exception:  # noqa: BLE001 — observability must not gate
            pass

    # -- persistence ----------------------------------------------------------

    def snapshot(self) -> dict:
        """Flattened host snapshot over the global slot space (epoch
        boundaries are an HBM layout detail — restore re-splits by
        ``epoch_rows``). Compatible with the ``DeviceVectorStore``
        snapshot schema plus the epoch config."""
        with self._lock:
            self.flush_staged()
            import jax.numpy as jnp

            cap = self._addressable()
            vecs = np.zeros((cap, self.dim), dtype=np.float32)
            valid = np.zeros(max(cap, 1), dtype=bool)
            for ep in self.epochs:
                lg = ep.live_globals()
                lg = lg[lg < cap]
                if not len(lg):
                    continue
                loc = ep.locals_for(lg)
                if isinstance(ep.store, QuantizedVectorStore):
                    rows = ep.store._vectors_for(loc)
                else:
                    (vec_host,) = transfer.d2h(ep.store.vectors)
                    rows = vec_host[loc]
                vecs[lg] = rows
                valid[lg] = True
            snap = {
                "vectors": vecs,
                "valid": valid[:max(cap, 1)],
                "count": cap,
                "dim": self.dim,
                "metric": self.metric,
                "dtype": jnp.dtype(self.dtype).name,
                "chunk_size": self.chunk_size,
                "selection": self.selection,
                "epoch_rows": self.epoch_rows,
                "quantization": self.quantization,
            }
            if self.quantization:
                snap["quant_kwargs"] = dict(self._quant_kwargs)
                snap["codebook"] = (
                    None if self._codebook is None
                    else np.asarray(self._codebook.centroids))
            return snap

    @classmethod
    def restore(cls, snap: dict, mesh=None, **kwargs) -> "EpochStore":
        import jax.numpy as jnp

        store = cls(
            dim=snap["dim"], metric=snap["metric"],
            epoch_rows=snap.get("epoch_rows", 0),
            dtype=jnp.dtype(snap.get("dtype", "float32")),
            mesh=mesh, chunk_size=snap.get("chunk_size", 8192),
            selection=snap.get("selection", "approx"),
            quantization=snap.get("quantization"),
            quant_kwargs=snap.get("quant_kwargs"), **kwargs)
        if snap.get("codebook") is not None:
            from weaviate_tpu.ops import pq as pq_ops

            store._codebook = pq_ops.PQCodebook(
                jnp.asarray(snap["codebook"]))
            store.epochs[-1].store.codebook = store._codebook
        live = np.nonzero(snap["valid"])[0]
        store._restore_rows(live, snap["vectors"], int(snap["count"]))
        return store

    def _restore_rows(self, live: np.ndarray, vectors: np.ndarray,
                      count: int) -> None:
        """Rebuild the epoch stack over ``[0, count)`` global slots from
        flattened rows (restore / compress): epochs re-split every
        ``epoch_rows`` slots, identity maps, all but the last sealed."""
        with self._lock:
            assert self._next_slot == 0 and len(self.epochs) == 1, \
                "_restore_rows only populates a fresh store"
            for base in range(0, max(count, 1), self.epoch_rows):
                act = self.epochs[-1]
                act.base = base
                hi = min(base + self.epoch_rows, count)
                sel = live[(live >= base) & (live < hi)]
                if len(sel):
                    # pre-size the store so local slots exist, then
                    # overwrite the live ones (already normalized rows)
                    act.store.set_at(
                        np.array([hi - base - 1]),
                        np.zeros((1, self.dim), np.float32))
                    flips = act.store.normalize_on_add
                    act.store.normalize_on_add = False
                    try:
                        act.store.set_at(sel - base, vectors[sel])
                    finally:
                        act.store.normalize_on_add = flips
                    # the pre-size scratch row is dead unless slot hi-1
                    # is genuinely live
                    if (hi - 1) not in sel:
                        act.store.delete(np.array([hi - base - 1]))
                elif hi > base:
                    act.store.set_at(
                        np.array([hi - base - 1]),
                        np.zeros((1, self.dim), np.float32))
                    act.store.delete(np.array([hi - base - 1]))
                if hi < count:
                    self._seal_active_locked()
            self._next_slot = count
            self._publish_metrics_locked()
