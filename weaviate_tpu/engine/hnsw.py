"""HNSW graph index — reference-parity ANN with batched candidate scoring.

Reference: adapters/repos/db/vector/hnsw/ (index.go:39 struct, insert.go:226
Add, search.go:64 SearchByVector, heuristic.go neighbor selection,
delete.go tombstones, commit_logger.go:246 durability).

Role in this framework: the TPU-native ANN regime is IVF (engine/ivf.py) —
a graph walk is dependent pointer-chasing, the one shape a systolic array
cannot help with. HNSW exists for reference parity (classes configured with
``vectorIndexType: hnsw`` behave like the reference, including recall
characteristics, tombstone semantics, and filtered-search cutoff) and for
workloads where single-query latency on the host beats a device round-trip.

Design difference vs the reference's hot loop
(search.go:173-341, one SIMD call per neighbor): every hop scores ALL
unvisited neighbors of the popped candidate in one vectorized batch —
the "batched candidate scoring" plan of SURVEY §7 step 5. The batch engine
is the host VPU (numpy/BLAS over an [m,d] block); shipping each ~32-row
batch over PCIe to the TPU would cost more in dispatch latency than the
score itself, so the device is reserved for the flat-cutoff path and bulk
rescore where batches are large enough to fill the MXU.

Durability: optional append-only commit log (reference commit_logger.go)
with snapshot-condense (condensor.go) and replay-on-open (startup.go:57).
The shard layer instead replays vectors from the objects bucket; the commit
log serves standalone/embedded users of the index.
"""

from __future__ import annotations

import heapq
import math
import os
import pickle
import random
import threading

import numpy as np

from weaviate_tpu.runtime import faultline
from weaviate_tpu.storage.wal import WriteAheadLog

# filtered queries with fewer allowed candidates than this do a brute-force
# scan instead of a graph walk (reference: flatSearchCutoff, hnsw/index.go:95)
DEFAULT_FLAT_CUTOFF = 40_000

# reference: dynamic ef bounds (entities/vectorindex/hnsw/config.go defaults)
AUTO_EF_MIN, AUTO_EF_MAX, AUTO_EF_FACTOR = 100, 500, 8


class HNSWIndex:
    """Implements the reference ``VectorIndex`` contract
    (adapters/repos/db/vector_index.go:24-45) with an HNSW graph."""

    index_type = "hnsw"

    def __init__(self, dim: int, metric: str = "l2-squared",
                 max_connections: int = 32, ef_construction: int = 128,
                 ef: int = -1, capacity: int = 1024, seed: int = 0,
                 flat_cutoff: int = DEFAULT_FLAT_CUTOFF,
                 commit_log_dir: str | None = None,
                 condense_above_bytes: int = 16 << 20, **_ignored):
        if metric not in ("l2-squared", "dot", "cosine", "cosine-dot",
                          "manhattan", "hamming"):
            raise ValueError(f"unsupported hnsw metric {metric!r}")
        self.dim = dim
        self.metric = metric
        self.m = max_connections
        self.m0 = 2 * max_connections  # layer-0 budget (reference maxConnections*2)
        self.ef_construction = ef_construction
        self.ef = ef
        self.flat_cutoff = flat_cutoff
        self._ml = 1.0 / math.log(max(self.m, 2))
        self._rng = random.Random(seed)
        self._lock = threading.RLock()

        cap = max(capacity, 64)
        self._vecs = np.zeros((cap, dim), dtype=np.float32)
        self._levels = np.full(cap, -1, dtype=np.int32)  # -1 = unused slot
        self._doc_ids = np.full(cap, -1, dtype=np.int64)
        self._tombstone = np.zeros(cap, dtype=bool)
        # per-slot list over layers of int32 neighbor-slot arrays
        self._links: list[list[np.ndarray]] = [[] for _ in range(cap)]
        self._visited = np.zeros(cap, dtype=np.int64)  # visit-epoch stamps
        self._visit_epoch = 0
        # runtime PQ compression state (compress.go:38): codes + codebook
        # when compressed, and the per-query ADC LUT during a search
        self._codes: np.ndarray | None = None
        self._pq_codebook = None
        self._pq_rescore = 4
        self._adc_lut: np.ndarray | None = None
        self._id_to_slot: dict[int, int] = {}
        self._count = 0
        self._ep = -1  # entrypoint slot
        self._max_level = -1

        # native graph mirror (csrc wn_hnsw_*): the C++ walker replaces the
        # Python heap loop for searches AND the per-layer ef-search of
        # inserts; kept current incrementally via _set_links / vector /
        # tombstone writes, re-uploaded in one batched sync after bulk
        # mutations (bulk_build / restore / WAL replay mark it dirty)
        self._native = None
        self._native_dirty = False

        # WAL appends (and the wal_sync-gated fsync) run inside ``_lock``
        # so the log order matches mutation order — graftlint G9 baselines
        # this cluster with a reason; decoupling needs the sequenced WAL
        # queue sketched in ROADMAP item 6 (enqueue under the lock, append
        # and fsync on a writer thread outside it, replay in sequence)
        self._log: WriteAheadLog | None = None
        self._log_dir = commit_log_dir
        self._condense_above = condense_above_bytes
        if commit_log_dir:
            os.makedirs(commit_log_dir, exist_ok=True)
            self._replay(commit_log_dir)
            self._log = WriteAheadLog(os.path.join(commit_log_dir, "hnsw.wal"))

        if self._native is None:
            from weaviate_tpu import native as _nat

            if _nat.hnsw_supported(metric):
                try:
                    self._native = _nat.HnswNative(dim, metric)
                except Exception:
                    self._native = None
        self._native_dirty = self._count > 0

        # HBM-ledger host-tier entry: the graph's arrays live in host
        # RAM (placement="host" — excluded from device admission totals,
        # visible in the /v1/debug/memory breakdown)
        from weaviate_tpu.runtime import hbm_ledger

        self._hbm_owner = hbm_ledger.current_owner()
        self._hbm_keys: dict[str, int] = {}
        import weakref

        weakref.finalize(self, hbm_ledger.ledger.release_many,
                         self._hbm_keys.values())
        self._hbm_sync()

    def _hbm_sync(self):
        if not hasattr(self, "_hbm_keys"):
            return  # _grow during WAL replay, before the ledger wiring
        from weaviate_tpu.runtime import hbm_ledger

        nbytes = sum(int(a.nbytes) for a in (
            self._vecs, self._levels, self._doc_ids, self._tombstone,
            self._visited))
        if self._codes is not None:
            nbytes += int(self._codes.nbytes)
        hbm_ledger.ledger.set_keyed(
            self._hbm_keys, "graph", nbytes, owner=self._hbm_owner,
            dtype="float32", placement="host")

    # -- distance (host batch engine) ----------------------------------------

    def _norm(self, v: np.ndarray) -> np.ndarray:
        if self.metric in ("cosine", "cosine-dot"):
            n = np.linalg.norm(v, axis=-1, keepdims=True)
            return v / np.where(n > 1e-30, n, 1.0)
        return v

    def _dist(self, q: np.ndarray, slots: np.ndarray) -> np.ndarray:
        """Distance from query [d] to a slot batch [m] — one vectorized op
        (replaces the per-pair asm call of distancer/asm/*.s).

        With an active per-query ADC LUT (compressed graph traversal,
        reference compress.go:38: candidate scoring runs on PQ codes), the
        hop costs one [m_rows, m] code gather + LUT sum instead of a
        [m_rows, d] float read; final candidates rescore exactly."""
        if self._adc_lut is not None:
            codes = self._codes[slots]  # [m_rows, m]
            return np.take_along_axis(
                self._adc_lut, codes.astype(np.int64).T, axis=1
            ).sum(axis=0)
        rows = self._vecs[slots]
        if self.metric == "l2-squared":
            diff = rows - q
            return np.einsum("md,md->m", diff, diff)
        if self.metric in ("dot",):
            return -(rows @ q)
        if self.metric in ("cosine", "cosine-dot"):
            return 1.0 - rows @ q  # both sides normalized at insert/query
        if self.metric == "manhattan":
            return np.abs(rows - q).sum(axis=1)
        # hamming over float values (reference hamming.go:18-27)
        return (rows != q).sum(axis=1).astype(np.float32)

    def _dist_pair(self, a: int, b: int) -> float:
        return float(self._dist(self._vecs[a], np.array([b]))[0])

    # -- capacity -------------------------------------------------------------

    def _grow(self, need: int):
        """Capacity-double every parallel array. Caller holds ``_lock``."""
        cap = len(self._vecs)
        if need <= cap:
            return
        new_cap = cap
        while new_cap < need:
            new_cap *= 2
        self._vecs = np.vstack([self._vecs,
                                np.zeros((new_cap - cap, self.dim), np.float32)])
        self._levels = np.concatenate([self._levels,
                                       np.full(new_cap - cap, -1, np.int32)])
        self._doc_ids = np.concatenate([self._doc_ids,
                                        np.full(new_cap - cap, -1, np.int64)])
        self._tombstone = np.concatenate([self._tombstone,
                                          np.zeros(new_cap - cap, bool)])
        self._visited = np.concatenate([self._visited,
                                        np.zeros(new_cap - cap, np.int64)])
        if self._codes is not None:
            self._codes = np.vstack([
                self._codes,
                np.zeros((new_cap - cap, self._codes.shape[1]), np.uint8)])
        self._links.extend([] for _ in range(new_cap - cap))
        for i in range(cap, new_cap):
            self._links[i] = []
        self._hbm_sync()

    # -- graph search core ----------------------------------------------------

    def _search_layer(self, q: np.ndarray, eps: list[tuple[float, int]],
                      ef: int, layer: int) -> list[tuple[float, int]]:
        """Best-first ef-search on one layer (reference
        searchLayerByVectorWithDistancer, search.go:173-341). Entry/exit is
        a list of (dist, slot) tuples. Tombstoned nodes are traversed but
        returned too — callers filter; pruning them here would disconnect
        regions behind tombstones (same reason the reference keeps them).
        Caller holds ``_lock`` (the epoch-stamped visited marks are
        exactly why: two unlocked searches would share an epoch)."""
        if (self._native is not None and not self._native_dirty
                and self._adc_lut is None):
            d, s = self._native.search_layer(
                q, ef, layer,
                np.asarray([slot for _d, slot in eps], dtype=np.int64),
                np.asarray([dd for dd, _s in eps], dtype=np.float32))
            return list(zip(d.tolist(), s.tolist()))
        # epoch-stamped visited marks: allocation-free per call (a fresh
        # bool[capacity] per layer-search dominates at 1M-slot capacities)
        self._visit_epoch += 1
        epoch = self._visit_epoch
        visited = self._visited
        cand: list[tuple[float, int]] = []  # min-heap
        top: list[tuple[float, int]] = []  # max-heap via negated dist
        for d, s in eps:
            visited[s] = epoch
            heapq.heappush(cand, (d, s))
            heapq.heappush(top, (-d, s))
        while cand:
            d, c = heapq.heappop(cand)
            if top and d > -top[0][0] and len(top) >= ef:
                break
            links = self._links[c]
            if layer >= len(links):
                continue
            neigh = links[layer]
            if len(neigh) == 0:
                continue
            fresh = neigh[visited[neigh] != epoch]
            if len(fresh) == 0:
                continue
            visited[fresh] = epoch
            dists = self._dist(q, fresh)  # ← the batched hop
            worst = -top[0][0] if top else np.inf
            for nd, ns in zip(dists.tolist(), fresh.tolist()):
                if len(top) < ef or nd < worst:
                    heapq.heappush(cand, (nd, ns))
                    heapq.heappush(top, (-nd, ns))
                    if len(top) > ef:
                        heapq.heappop(top)
                    worst = -top[0][0]
        return sorted((-d, s) for d, s in top)

    def _greedy_descend(self, q: np.ndarray, slot: int, dist: float,
                        from_level: int, to_level: int) -> tuple[float, int]:
        """ef=1 walk down the upper layers (search.go:479 descent loop)."""
        for layer in range(from_level, to_level, -1):
            improved = True
            while improved:
                improved = False
                links = self._links[slot]
                if layer >= len(links) or len(links[layer]) == 0:
                    break
                neigh = links[layer]
                dists = self._dist(q, neigh)
                j = int(np.argmin(dists))
                if dists[j] < dist:
                    dist, slot = float(dists[j]), int(neigh[j])
                    improved = True
        return dist, slot

    # -- native mirror --------------------------------------------------------

    def _native_sync(self):
        """Re-upload the whole graph to the native mirror in one batched
        pass — the recovery path after mutations that bypass the
        incremental mirror (bulk_build's direct link writes, restore,
        WAL replay). O(count) once; incremental afterward. Caller
        holds ``_lock``."""
        nat = self._native
        if nat is None:
            return
        nat.reset(len(self._vecs))
        n = self._count
        if n:
            nat.set_vectors(0, np.ascontiguousarray(self._vecs[:n]))
            slots: list[int] = []
            layers: list[int] = []
            counts: list[int] = []
            total = 0
            for s in range(n):
                for ly, arr in enumerate(self._links[s]):
                    slots.append(s)
                    layers.append(ly)
                    counts.append(len(arr))
                    total += len(arr)
            if slots:
                neigh = np.empty(total, dtype=np.int32)
                pos = 0
                for s in range(n):
                    for arr in self._links[s]:
                        neigh[pos:pos + len(arr)] = arr
                        pos += len(arr)
                nat.set_links_batch(
                    np.asarray(slots, dtype=np.int64),
                    np.asarray(layers, dtype=np.int32),
                    np.asarray(counts, dtype=np.int32), neigh)
            dead = np.nonzero(self._tombstone[:n]
                              | (self._doc_ids[:n] < 0))[0]
            if len(dead):
                nat.set_tombstones(dead)
        self._native_dirty = False

    # -- neighbor selection (heuristic.go) ------------------------------------

    def _select_heuristic(self, cands: list[tuple[float, int]],
                          m: int) -> list[int]:
        """Keep a candidate only if it is closer to the query than to every
        already-selected neighbor — the diversity heuristic of
        heuristic.go (selectNeighborsHeuristic) — then BACKFILL pruned
        candidates nearest-first up to the budget (hnswlib
        keepPrunedConnections / reference's returnList top-up): without
        the backfill the graph ends up far under-connected and recall
        collapses (round-2 measured 0.60@ef=64 on 200k without it)."""
        cands = sorted(cands)
        slots = np.asarray([c for _d, c in cands], dtype=np.int64)
        if len(slots) <= 1:
            return [int(s) for s in slots[:m]]
        # pairwise candidate distances in ONE vectorized pass — the greedy
        # scan then only indexes the matrix (the per-candidate _dist-call
        # loop dominated insert time once backfill made graphs dense)
        rows = self._vecs[slots]
        if self.metric == "l2-squared":
            sq = np.einsum("md,md->m", rows, rows)
            pair = sq[:, None] - 2.0 * (rows @ rows.T) + sq[None, :]
        elif self.metric == "dot":
            pair = -(rows @ rows.T)
        elif self.metric in ("cosine", "cosine-dot"):
            pair = 1.0 - rows @ rows.T  # rows pre-normalized at insert
        elif self.metric == "manhattan":
            pair = np.abs(rows[:, None, :] - rows[None, :, :]).sum(-1)
        else:  # hamming over float values
            pair = (rows[:, None, :] != rows[None, :, :]).sum(-1).astype(
                np.float32)
        # greedy scan with a RUNNING dominated mask: selecting candidate j
        # dominates every candidate closer to j than to the query — one
        # vectorized compare per selection instead of one np.all per
        # candidate (the 8.5M tiny-np.all pattern that ate ~60% of insert
        # time in profiling)
        dists = np.asarray([d for d, _c in cands], dtype=np.float32)
        n = len(slots)
        dominated = np.zeros(n, dtype=bool)
        selected: list[int] = []
        for i in range(n):
            if len(selected) >= m:
                break
            if dominated[i]:
                continue
            selected.append(i)
            dominated |= pair[:, i] <= dists
        if len(selected) < m:
            # backfill pruned candidates nearest-first (hnswlib
            # keepPrunedConnections; recall collapses without it)
            sel_mask = np.zeros(n, dtype=bool)
            sel_mask[selected] = True
            for i in np.nonzero(dominated & ~sel_mask)[0]:
                if len(selected) >= m:
                    break
                selected.append(int(i))
        return [int(slots[i]) for i in selected]

    def _set_links(self, slot: int, layer: int, neighbors: list[int]):
        links = self._links[slot]
        while len(links) <= layer:
            links.append(np.empty(0, dtype=np.int32))
        links[layer] = np.asarray(neighbors, dtype=np.int32)
        if self._native is not None:
            self._native.set_links(slot, layer, links[layer])
        if self._log is not None:
            self._log.append(pickle.dumps(
                ("L", int(self._doc_ids[slot]), layer,
                 self._doc_ids[links[layer]].tolist()),
                protocol=pickle.HIGHEST_PROTOCOL))

    def _add_backlink(self, neighbor: int, slot: int, layer: int):
        links = self._links[neighbor]
        while len(links) <= layer:
            links.append(np.empty(0, dtype=np.int32))
        cur = links[layer]
        if slot in cur:
            return
        budget = self.m0 if layer == 0 else self.m
        if len(cur) < budget:
            self._set_links(neighbor, layer, cur.tolist() + [slot])
            return
        # over-full: re-select with the heuristic over old + new
        # (reference insert.go connectNeighbor shrink path)
        q = self._vecs[neighbor]
        cand_slots = np.concatenate([cur, [slot]])
        dists = self._dist(q, cand_slots)
        cands = list(zip(dists.tolist(), cand_slots.tolist()))
        self._set_links(neighbor, layer, self._select_heuristic(cands, budget))

    # -- mutation -------------------------------------------------------------

    def add(self, doc_id: int, vector: np.ndarray) -> None:
        self.add_batch([doc_id], np.asarray(vector, dtype=np.float32)[None, :])

    # empty-index batches at least this large build via the device bulk
    # path (engine/hnsw_build.py) instead of incremental insert
    BULK_BUILD_MIN = 4096

    def add_batch(self, doc_ids, vectors: np.ndarray) -> None:
        doc_ids = np.asarray(doc_ids, dtype=np.int64)
        vectors = self._norm(np.asarray(vectors, dtype=np.float32))
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        if len(doc_ids) != len(vectors):
            raise ValueError(f"{len(doc_ids)} ids != {len(vectors)} vectors")
        if vectors.shape[1] != self.dim:
            raise ValueError(f"vector dim {vectors.shape[1]} != index dim {self.dim}")
        with self._lock:
            if self._native_dirty and self._native is not None:
                # catch up after a bulk mutation so incremental inserts
                # keep the fast per-layer search
                self._native_sync()
            # dispatch decided under the lock: a concurrent first batch
            # must not race two bulk_builds (the RLock makes the nested
            # bulk_build acquisition re-entrant). Non-MXU metrics keep the
            # incremental path — the host knn fallback would materialize
            # O(block*n*d) broadcast temporaries for manhattan/hamming.
            if (self._count == 0 and len(vectors) >= self.BULK_BUILD_MIN
                    and self.metric in ("l2-squared", "dot", "cosine",
                                        "cosine-dot")
                    and len(set(doc_ids.tolist())) == len(doc_ids)):
                from weaviate_tpu.engine.hnsw_build import bulk_build

                bulk_build(self, doc_ids, vectors,
                           knn_k=max(self.m0, self.ef_construction // 2))
                return
            batch_codes = None
            if self._codes is not None:
                # one device encode for the whole batch, not one RTT per row
                from weaviate_tpu.ops.pq import pq_encode

                batch_codes = pq_encode(self._pq_codebook, vectors)
            for j, (doc_id, vec) in enumerate(zip(doc_ids.tolist(), vectors)):
                self._insert_one(
                    int(doc_id), vec,
                    code=None if batch_codes is None else batch_codes[j])

    def _insert_one(self, doc_id: int, vec: np.ndarray, code=None):
        """Graph insert core. Caller holds ``_lock`` (add_batch/replay)."""
        old = self._id_to_slot.get(doc_id)
        if old is not None:
            # update = tombstone old node + fresh insert (the reference
            # re-adds under a new doc id; inside one index this is the analog)
            self._tombstone[old] = True
            self._doc_ids[old] = -1
            if self._native is not None:
                self._native.set_tombstones([old])
        slot = self._count
        self._grow(slot + 1)
        self._count += 1
        level = int(-math.log(max(self._rng.random(), 1e-12)) * self._ml)
        self._vecs[slot] = vec
        if self._native is not None:
            self._native.set_vectors(slot, vec)
        if self._codes is not None:
            if code is None:
                from weaviate_tpu.ops.pq import pq_encode

                code = pq_encode(self._pq_codebook, vec[None, :])[0]
            self._codes[slot] = code
        self._levels[slot] = level
        self._doc_ids[slot] = doc_id
        self._id_to_slot[doc_id] = slot
        if self._log is not None:
            self._log.append(pickle.dumps(
                ("N", doc_id, level, vec.tobytes()),
                protocol=pickle.HIGHEST_PROTOCOL))
        if self._ep < 0:
            self._ep, self._max_level = slot, level
            self._set_links(slot, 0, [])
            self._maybe_condense()
            return
        ep_d = float(self._dist(vec, np.array([self._ep]))[0])
        ep_d, ep = self._greedy_descend(vec, self._ep, ep_d,
                                        self._max_level, level)
        eps = [(ep_d, ep)]
        for layer in range(min(level, self._max_level), -1, -1):
            cands = self._search_layer(vec, eps, self.ef_construction, layer)
            budget = self.m0 if layer == 0 else self.m
            neighbors = self._select_heuristic(cands, budget)
            self._set_links(slot, layer, neighbors)
            for n in neighbors:
                self._add_backlink(n, slot, layer)
            eps = cands
        if level > self._max_level:
            self._ep, self._max_level = slot, level
            if self._log is not None:
                self._log.append(pickle.dumps(("E", doc_id, level),
                                              protocol=pickle.HIGHEST_PROTOCOL))
        self._maybe_condense()

    def delete(self, *doc_ids) -> None:
        """Tombstone (reference delete.go: delete marks, cleanup re-links)."""
        with self._lock:
            dead_slots = []
            for doc_id in doc_ids:
                slot = self._id_to_slot.pop(int(doc_id), None)
                if slot is None:
                    continue
                self._tombstone[slot] = True
                self._doc_ids[slot] = -1
                dead_slots.append(slot)
                if self._log is not None:
                    self._log.append(pickle.dumps(("D", int(doc_id)),
                                                  protocol=pickle.HIGHEST_PROTOCOL))
            if self._native is not None and dead_slots:
                self._native.set_tombstones(dead_slots)

    def cleanup_tombstones(self) -> int:
        """Physically unlink tombstoned nodes, re-linking their neighbors
        through the heuristic (reference tombstone-cleanup cycle,
        hnsw/delete.go + index_cyclecallbacks). Returns nodes removed."""
        with self._lock:
            dead = np.nonzero(self._tombstone[: self._count])[0]
            if len(dead) == 0:
                return 0
            dead_set = set(dead.tolist())
            for slot in range(self._count):
                if slot in dead_set:
                    continue
                for layer, neigh in enumerate(self._links[slot]):
                    if len(neigh) == 0 or not np.any(self._tombstone[neigh]):
                        continue
                    alive = neigh[~self._tombstone[neigh]].tolist()
                    # candidates: alive old neighbors + alive 2-hop via dead
                    cand_set = set(alive)
                    for dn in neigh[self._tombstone[neigh]].tolist():
                        if layer < len(self._links[dn]):
                            for nn in self._links[dn][layer].tolist():
                                if nn != slot and not self._tombstone[nn]:
                                    cand_set.add(nn)
                    budget = self.m0 if layer == 0 else self.m
                    cand = np.fromiter(cand_set, dtype=np.int64)
                    if len(cand):
                        dists = self._dist(self._vecs[slot], cand)
                        sel = self._select_heuristic(
                            list(zip(dists.tolist(), cand.tolist())), budget)
                    else:
                        sel = []
                    self._set_links(slot, layer, sel)
            for slot in dead.tolist():
                self._links[slot] = []
                self._levels[slot] = -1
                self._tombstone[slot] = False  # slot stays burned (not reused)
                if self._native is not None:
                    self._native.clear_links(slot)
            if self._native is not None:
                # burned slots stay tombstoned in the mirror: the native
                # output filter is the only doc_id<0 check it has
                self._native.set_tombstones(dead)
            if self._ep in dead_set:
                self._elect_entrypoint()
            return len(dead)

    def _elect_entrypoint(self):
        """Re-pick ep/max_level after the old entrypoint died. Caller
        holds ``_lock`` (tombstone cleanup)."""
        live = [s for s in range(self._count)
                if self._doc_ids[s] >= 0 and not self._tombstone[s]]
        if not live:
            self._ep, self._max_level = -1, -1
            return
        best = max(live, key=lambda s: int(self._levels[s]))
        self._ep, self._max_level = best, int(self._levels[best])
        if self._log is not None:
            self._log.append(pickle.dumps(
                ("E", int(self._doc_ids[best]), self._max_level),
                protocol=pickle.HIGHEST_PROTOCOL))

    # -- queries --------------------------------------------------------------

    def contains(self, doc_id: int) -> bool:
        return int(doc_id) in self._id_to_slot

    def __len__(self) -> int:
        return len(self._id_to_slot)

    def _effective_ef(self, k: int) -> int:
        if self.ef > 0:
            return max(self.ef, k)
        # dynamic ef (reference autoEf* defaults)
        return min(max(k * AUTO_EF_FACTOR, AUTO_EF_MIN), AUTO_EF_MAX)

    def _allowed_slots(self, allow_list) -> np.ndarray | None:
        if allow_list is None:
            return None
        allow_list = np.asarray(allow_list)
        if allow_list.dtype == np.bool_:
            allow_list = np.nonzero(allow_list)[0]
        slots = [self._id_to_slot[int(i)] for i in allow_list.tolist()
                 if int(i) in self._id_to_slot]
        return np.asarray(slots, dtype=np.int64)

    def search_by_vector(self, query: np.ndarray, k: int,
                         allow_list: np.ndarray | None = None):
        q = self._norm(np.asarray(query, dtype=np.float32).reshape(-1))
        with self._lock:
            allowed = self._allowed_slots(allow_list)
            if allowed is not None and len(allowed) <= self.flat_cutoff:
                # small filter → brute force beats a constrained graph walk
                # (reference flat_search.go + flatSearchCutoff, index.go:95)
                if len(allowed) == 0:
                    return (np.empty(0, np.int64), np.empty(0, np.float32))
                dists = self._dist(q, allowed)
                order = np.argsort(dists, kind="stable")[:k]
                return self._doc_ids[allowed[order]], dists[order].astype(np.float32)
            if self._ep < 0:
                return (np.empty(0, np.int64), np.empty(0, np.float32))
            ef = max(self._effective_ef(k), k)
            if self._native is not None and self._codes is None:
                # fused native walk: greedy descent + layer-0 ef-search +
                # live/allowed filter in one C++ call (the ≥2k-QPS serving
                # path; the Python walker below is the fallback/oracle)
                if self._native_dirty:
                    self._native_sync()
                allow_u8 = None
                if allowed is not None:
                    allow_u8 = np.zeros(len(self._vecs), dtype=np.uint8)
                    allow_u8[allowed] = 1
                d, s = self._native.search(q, k, ef, self._ep,
                                           self._max_level, allow_u8)
                return self._doc_ids[s].copy(), d.astype(np.float32)
            if self._codes is not None:
                # compressed traversal: ADC hops, oversampled frontier,
                # exact rescore of the result set (compress.go pattern)
                ef = max(ef, k * self._pq_rescore)
                self._adc_lut = self._query_lut(q)
                try:
                    d0 = float(self._dist(q, np.array([self._ep]))[0])
                    d0, ep = self._greedy_descend(q, self._ep, d0,
                                                  self._max_level, 0)
                    cands = self._search_layer(q, [(d0, ep)], ef, 0)
                finally:
                    self._adc_lut = None
                slots = np.asarray([s for _d, s in cands], dtype=np.int64)
                exact = self._dist(q, slots)
                cands = sorted(zip(exact.tolist(), slots.tolist()))
            else:
                d0 = float(self._dist(q, np.array([self._ep]))[0])
                d0, ep = self._greedy_descend(q, self._ep, d0,
                                              self._max_level, 0)
                cands = self._search_layer(q, [(d0, ep)], ef, 0)
            allow_mask = None
            if allowed is not None:
                allow_mask = np.zeros(len(self._vecs), dtype=bool)
                allow_mask[allowed] = True
            out_ids, out_d = [], []
            for d, s in cands:
                if self._tombstone[s] or self._doc_ids[s] < 0:
                    continue
                if allow_mask is not None and not allow_mask[s]:
                    continue
                out_ids.append(int(self._doc_ids[s]))
                out_d.append(d)
                if len(out_ids) == k:
                    break
            return (np.asarray(out_ids, dtype=np.int64),
                    np.asarray(out_d, dtype=np.float32))

    # per-query allow lists ride the per-row loop below — the batcher can
    # coalesce filtered requests into one batch_fn call for this index too
    supports_batched_filters = True
    # the loop runs a REAL graph search per row, so pow2 batch padding
    # would buy nothing and cost up to 2x work — the batcher skips it
    compiled_batch_shapes = False

    def search_by_vector_batch(self, queries: np.ndarray, k: int,
                               allow_list=None):
        """``allow_list`` may be one shared allow list or a list/tuple of
        per-query allow lists (entries None or array-like), matching the
        FlatIndex batched contract."""
        from weaviate_tpu.engine.flat import _per_query_allow

        queries = np.asarray(queries, dtype=np.float32)
        ids = np.full((len(queries), k), -1, dtype=np.int64)
        dists = np.full((len(queries), k), np.float32(np.inf), dtype=np.float32)
        per_query = _per_query_allow(allow_list)
        for b, q in enumerate(queries):
            al = allow_list[b] if per_query else allow_list
            i, d = self.search_by_vector(q, k, al)
            ids[b, : len(i)] = i
            dists[b, : len(d)] = d
        return ids, dists

    def search_by_vector_distance(self, query: np.ndarray, max_distance: float,
                                  allow_list: np.ndarray | None = None):
        """Range search by widening ef until the frontier crosses the
        threshold (reference SearchByVectorDistance: iterative widening)."""
        k = 64
        while True:
            ids, d = self.search_by_vector(query, k, allow_list)
            if len(d) < k or (len(d) and d[-1] > max_distance):
                within = d <= max_distance
                return ids[within], d[within]
            if k >= max(len(self._id_to_slot), 1):
                within = d <= max_distance
                return ids[within], d[within]
            k *= 4

    # -- compression hook -----------------------------------------------------

    @property
    def compressed(self) -> bool:
        return self._codes is not None

    def compress(self, quantization: str = "pq", pq_segments: int | None = None,
                 pq_centroids: int = 16, rescore_limit: int = 4,
                 **_ignored) -> None:
        """Runtime compression of a LIVE graph (reference compress.go:38-89:
        train PQ on current contents, swap the cache for a compressed one,
        log AddPQ). Traversal distances switch to per-query ADC lookups
        over uint8 codes; the ef result set is exact-rescored against the
        retained f32 rows before returning, so recall stays within the
        rescore envelope."""
        if quantization != "pq":
            raise ValueError("hnsw supports runtime quantization='pq' "
                             "(bq has no ADC form for graph hops)")
        if self.metric not in ("l2-squared", "dot", "cosine", "cosine-dot"):
            raise ValueError(
                f"no ADC form for metric {self.metric!r}")
        from weaviate_tpu.ops.pq import pq_encode, pq_fit

        with self._lock:
            if self._codes is not None:
                raise RuntimeError("index is already compressed")
            live = np.nonzero(
                (self._doc_ids[: self._count] >= 0)
                & ~self._tombstone[: self._count])[0]
            if len(live) < pq_centroids:
                raise RuntimeError(
                    f"need >= {pq_centroids} live vectors to train PQ, "
                    f"have {len(live)}")
            if not pq_segments:
                from weaviate_tpu.ops.pq import default_pq_segments

                pq_segments = default_pq_segments(self.dim, pq_centroids)
            self._pq_rescore = rescore_limit
            self._pq_codebook = pq_fit(self._vecs[live], m=pq_segments,
                                       k=pq_centroids, iters=8)
            self._codes = np.zeros((len(self._vecs), pq_segments),
                                   dtype=np.uint8)
            if self._count:
                self._codes[: self._count] = pq_encode(
                    self._pq_codebook, self._vecs[: self._count])
            self._hbm_sync()
            # durability: one condensed snapshot carries codes + codebook
            # (the reference logs an AddPQ record; a snapshot is the same
            # fixed point)
            if self._log is not None:
                self.condense()

    def _query_lut(self, q: np.ndarray) -> np.ndarray:
        """Per-query ADC table [m, k]: segment-wise distance from q to
        every centroid (exact ADC for l2; dot/cosine fold linearly).

        Numpy twin of ops/pq.py:pq_lut — the jitted device version would
        cost a tunnel round trip per query on this host-graph path;
        tests/test_runtime_compress.py asserts the two stay equal."""
        cents = np.asarray(self._pq_codebook.centroids)  # [m, k, ds]
        m, kc, ds = cents.shape
        qs = q.reshape(m, ds)
        if self.metric == "l2-squared":
            diff = qs[:, None, :] - cents
            return np.einsum("mkd,mkd->mk", diff, diff)
        if self.metric == "dot":
            return -np.einsum("md,mkd->mk", qs, cents)
        if self.metric in ("cosine", "cosine-dot"):
            lut = -np.einsum("md,mkd->mk", qs, cents)
            lut[0] += 1.0  # constant shift once, exact for the sum
            return lut
        raise RuntimeError(
            f"compressed traversal unsupported for metric {self.metric!r}")

    # -- maintenance ----------------------------------------------------------

    def maintenance(self) -> bool:
        return self.cleanup_tombstones() > 0

    def compact(self):
        self.cleanup_tombstones()

    # -- persistence ----------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "index_type": self.index_type,
                "dim": self.dim,
                "metric": self.metric,
                "m": self.m,
                "ef_construction": self.ef_construction,
                "ef": self.ef,
                "count": self._count,
                "vectors": self._vecs[: self._count].copy(),
                "levels": self._levels[: self._count].copy(),
                "doc_ids": self._doc_ids[: self._count].copy(),
                "tombstone": self._tombstone[: self._count].copy(),
                "links": [[l.tolist() for l in self._links[s]]
                          for s in range(self._count)],
                "ep": self._ep,
                "max_level": self._max_level,
                "pq_codes": (self._codes[: self._count].copy()
                             if self._codes is not None else None),
                "pq_codebook": (
                    np.asarray(self._pq_codebook.centroids)
                    if self._pq_codebook is not None else None),
                "pq_rescore": self._pq_rescore,
            }

    @classmethod
    def restore(cls, snap: dict, **kwargs) -> "HNSWIndex":
        idx = cls(dim=snap["dim"], metric=snap["metric"],
                  max_connections=snap["m"],
                  ef_construction=snap["ef_construction"], ef=snap["ef"],
                  capacity=max(snap["count"], 64), **kwargs)
        n = snap["count"]
        idx._count = n
        idx._vecs[:n] = snap["vectors"]
        idx._levels[:n] = snap["levels"]
        idx._doc_ids[:n] = snap["doc_ids"]
        idx._tombstone[:n] = snap["tombstone"]
        for s in range(n):
            idx._links[s] = [np.asarray(l, dtype=np.int32)
                             for l in snap["links"][s]]
        idx._ep = snap["ep"]
        idx._max_level = snap["max_level"]
        idx._id_to_slot = {int(d): s for s, d in enumerate(snap["doc_ids"])
                           if d >= 0}
        if snap.get("pq_codebook") is not None:
            from weaviate_tpu.ops.pq import PQCodebook

            import jax.numpy as jnp

            idx._pq_codebook = PQCodebook(jnp.asarray(snap["pq_codebook"]))
            idx._pq_rescore = snap.get("pq_rescore", 4)
            m = snap["pq_codes"].shape[1]
            idx._codes = np.zeros((len(idx._vecs), m), dtype=np.uint8)
            idx._codes[:n] = snap["pq_codes"]
        idx._native_dirty = True  # fields were set past the mirror
        idx._hbm_sync()  # codes allocated after __init__'s sync
        return idx

    # -- commit log (reference commit_logger.go / condensor.go) ---------------

    def _maybe_condense(self):
        if self._log is None or self._log.size() < self._condense_above:
            return
        self.condense()

    def condense(self):
        """Replace the op log with a snapshot (reference condensor.go:27 —
        theirs rewrites a minimal op stream; a snapshot is the same
        fixed point).

        Crash ordering: the snapshot must be DURABLY renamed into place
        before the op log resets — fsync tmp, rename, fsync dir, only
        then truncate. The old code reset the log right after an
        un-fsynced ``os.replace``: a crash could leave a zero-length (or
        garbage) hnsw.snap AND an empty log, losing the whole graph.
        The ``hnsw.snap.pre/post_replace`` crashpoints kill in exactly
        those two windows; restart must replay to the same graph."""
        if self._log_dir is None:
            return
        from weaviate_tpu.storage import fsutil

        with self._lock:
            tmp = os.path.join(self._log_dir, "hnsw.snap.tmp")
            final = os.path.join(self._log_dir, "hnsw.snap")
            with open(tmp, "wb") as f:
                pickle.dump(self.snapshot(), f,
                            protocol=pickle.HIGHEST_PROTOCOL)
                f.flush()
                os.fsync(f.fileno())
            fsutil.atomic_replace(tmp, final, fsync_file_first=False,
                                  crashpoint="hnsw.snap.pre_replace")
            faultline.fire("hnsw.snap.post_replace", path=final)
            self._log.reset()

    def _replay(self, log_dir: str):
        """Caller holds ``_lock`` — or, the common case, runs from
        __init__ before the index is shared with any other thread."""
        snap_path = os.path.join(log_dir, "hnsw.snap")
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as f:
                snap = pickle.load(f)
            restored = HNSWIndex.restore(snap)
            # adopt graph state + graph hyperparams from the snapshot, but
            # keep this instance's runtime knobs (flat_cutoff, RNG seed,
            # log config) — restore() would reset them to defaults
            keep = ("_log", "_log_dir", "_condense_above", "flat_cutoff",
                    "_rng", "ef")
            self.__dict__.update(
                {k: v for k, v in restored.__dict__.items() if k not in keep})
        wal_path = os.path.join(log_dir, "hnsw.wal")
        if not os.path.exists(wal_path):
            return
        snap_count = self._count
        from weaviate_tpu.storage import recovery
        from weaviate_tpu.storage.wal import ReplayReport

        rep = ReplayReport()
        parts = os.path.normpath(log_dir).split(os.sep)[-2:]
        rec = recovery.BucketRecovery(
            "/".join([p for p in parts if p] + ["hnsw.wal"]))
        for payload in WriteAheadLog.replay(wal_path, rep):
            op = pickle.loads(payload)
            tag = op[0]
            if tag == "N":
                _, doc_id, level, raw = op
                vec = np.frombuffer(raw, dtype=np.float32)
                old = self._id_to_slot.get(doc_id)
                if old is not None:
                    self._tombstone[old] = True
                    self._doc_ids[old] = -1
                slot = self._count
                self._grow(slot + 1)
                self._count += 1
                self._vecs[slot] = vec
                self._levels[slot] = level
                self._doc_ids[slot] = doc_id
                self._id_to_slot[doc_id] = slot
                if self._ep < 0 or level > self._max_level:
                    self._ep, self._max_level = slot, level
            elif tag == "L":
                _, doc_id, layer, neigh_ids = op
                slot = self._id_to_slot.get(doc_id)
                if slot is None:
                    continue
                neigh = [self._id_to_slot[i] for i in neigh_ids
                         if i in self._id_to_slot]
                links = self._links[slot]
                while len(links) <= layer:
                    links.append(np.empty(0, dtype=np.int32))
                links[layer] = np.asarray(neigh, dtype=np.int32)
            elif tag == "D":
                _, doc_id = op
                slot = self._id_to_slot.pop(doc_id, None)
                if slot is not None:
                    self._tombstone[slot] = True
                    self._doc_ids[slot] = -1
            elif tag == "E":
                _, doc_id, level = op
                slot = self._id_to_slot.get(doc_id)
                if slot is not None:
                    self._ep, self._max_level = slot, level
        rec.wal_files_replayed = 1
        rec.frames_replayed = rep.frames
        rec.bytes_truncated = rep.bytes_truncated
        if rep.quarantined:
            rec.wals_quarantined = 1
            rec.quarantined_files.append("hnsw.wal")
        recovery.record(rec)
        if self._codes is not None and self._count > snap_count:
            # inserts logged after the compress snapshot carry no codes in
            # their WAL records — re-encode the replayed tail in one batch
            # or ADC traversal would score them against all-zero codes
            from weaviate_tpu.ops.pq import pq_encode

            self._codes[snap_count: self._count] = pq_encode(
                self._pq_codebook, self._vecs[snap_count: self._count])
        self._native_dirty = True  # replay mutates links past the mirror

    def close(self):
        if self._log is not None:
            self.condense()
            self._log.close()
