"""TPU bulk construction for HNSW (VERDICT r2 item 4a).

The reference builds its graph by incremental insert (hnsw/insert.go:226):
each vector runs an ef-search against the partial graph — inherently
sequential, pointer-chasing, one-vector-at-a-time. At 1M vectors that path
is hours even in Go; in Python it is days. The TPU-first redesign turns
construction into the workload the MXU is best at:

1. **kNN graph on device**: every node's ``knn_k`` nearest neighbors come
   from the batched exact chunked scan (ops/topk.py — 1Mx128 in ~2.5 ms per
   1024-query batch on a v5e), not from graph walks. One pass per layer
   over that layer's members.
2. **Vectorized diversity heuristic**: the reference's
   selectNeighborsHeuristic (heuristic.go) runs per node over its
   candidates; here it runs BATCHED over thousands of nodes at once with a
   running dominated mask — same selected sets, numpy-wide.
3. **Symmetrize + prune**: reverse edges are added in one bincount pass and
   over-budget adjacency is re-pruned with the same batched heuristic
   (insert.go's connectNeighbor shrink path, applied in bulk).

The result populates the SAME HNSWIndex structures the incremental path
uses — search, deletes, later incremental inserts, persistence all work
unchanged. Graph quality matches incremental construction (links come from
exact kNN candidates, strictly better candidate sets than ef-search
approximations).
"""

from __future__ import annotations

import functools
import logging
import math

import numpy as np

logger = logging.getLogger(__name__)


def _batched_heuristic(cand_d: np.ndarray, pair: np.ndarray, budget: int,
                       valid: np.ndarray | None = None) -> np.ndarray:
    """Diversity-select ``budget`` neighbors per row.

    cand_d [B, C] distances owner->candidate; pair [B, C, C] candidate
    pairwise distances; valid [B, C] optional candidate mask. Returns
    [B, budget] indices into C (-1 padded). Matches
    HNSWIndex._select_heuristic semantics including nearest-first backfill
    of pruned candidates.
    """
    b, c = cand_d.shape
    d = cand_d.copy()
    if valid is not None:
        d[~valid] = np.inf
    order = np.argsort(d, axis=1, kind="stable")
    d_s = np.take_along_axis(d, order, axis=1)
    rows_ix = np.arange(b)[:, None, None]
    pair_s = pair[rows_ix, order[:, :, None], order[:, None, :]]

    dominated = np.zeros((b, c), dtype=bool)
    selected = np.zeros((b, c), dtype=bool)
    count = np.zeros(b, dtype=np.int64)
    rows = np.arange(b)
    for _step in range(min(budget, c)):
        avail = ~dominated & ~selected & np.isfinite(d_s)
        first = np.argmax(avail, axis=1)
        has = avail[rows, first] & (count < budget)
        r = rows[has]
        if len(r) == 0:
            break
        f = first[has]
        selected[r, f] = True
        count[has] += 1
        dominated[r] |= pair_s[r, :, f] <= d_s[r]
    # backfill pruned (dominated, unselected) nearest-first up to budget
    need = budget - count
    if np.any(need > 0):
        fillable = dominated & ~selected & np.isfinite(d_s)
        # rank fillable candidates by position (already distance-sorted)
        prio = np.where(fillable, np.arange(c)[None, :], c)
        fill_order = np.argsort(prio, axis=1, kind="stable")
        fill_rank = np.empty_like(fill_order)
        np.put_along_axis(fill_rank, fill_order,
                          np.arange(c)[None, :].repeat(b, 0), axis=1)
        take = fillable & (fill_rank < need[:, None])
        selected |= take
    # emit selected positions (sorted by distance), mapped back through
    # ``order`` to original candidate indices
    out = np.full((b, budget), -1, dtype=np.int64)
    sel_prio = np.where(selected, np.arange(c)[None, :], c)
    sel_sorted = np.argsort(sel_prio, axis=1, kind="stable")
    n_sel = selected.sum(axis=1)
    width = min(budget, c)
    picks = sel_sorted[:, :width]
    orig = np.take_along_axis(order, picks, axis=1)
    keep = np.arange(width)[None, :] < n_sel[:, None]
    out[:, :width] = np.where(keep, orig, -1)
    return out


def _pairwise_block(vecs: np.ndarray, metric: str) -> np.ndarray:
    """pair [B, C, C] distances between candidate rows [B, C, d].

    np.matmul (batched BLAS) — a 3-operand einsum here falls back to
    numpy's generic loop and is ~50x slower at [1024, 192, 192, 128]."""
    if metric in ("l2-squared", "dot", "cosine", "cosine-dot"):
        dots = np.matmul(vecs, vecs.transpose(0, 2, 1))
        if metric == "l2-squared":
            sq = np.einsum("bcd,bcd->bc", vecs, vecs)
            return sq[:, :, None] - 2.0 * dots + sq[:, None, :]
        if metric == "dot":
            return -dots
        return 1.0 - dots
    if metric == "manhattan":
        return np.abs(vecs[:, :, None, :] - vecs[:, None, :, :]).sum(-1)
    return (vecs[:, :, None, :] != vecs[:, None, :, :]).sum(-1).astype(
        np.float32)


def _owner_dists(owner: np.ndarray, cands: np.ndarray, metric: str):
    """[B, d] x [B, C, d] -> [B, C] distances."""
    if metric in ("l2-squared", "dot", "cosine", "cosine-dot"):
        dots = np.matmul(cands, owner[:, :, None])[:, :, 0]
        if metric == "l2-squared":
            o = np.einsum("bd,bd->b", owner, owner)
            c = np.einsum("bcd,bcd->bc", cands, cands)
            return o[:, None] - 2.0 * dots + c
        if metric == "dot":
            return -dots
        return 1.0 - dots
    if metric == "manhattan":
        return np.abs(cands - owner[:, None, :]).sum(-1)
    return (cands != owner[:, None, :]).sum(-1).astype(np.float32)


# host-BLAS knn ceiling: above this the device path wins. 8192 (not the
# r4 32768): at 1M rows layer 1 has ~31k members and the host O(M^2 d)
# scan there was ~40 s of the build on one core; with the persistent
# compile cache the device path's per-shape jit cost no longer recurs.
_HOST_KNN_MAX = 8192
# CPU-backend ceiling: the XLA chunked scan on CPU beats the naive
# single-threaded numpy O(n^2 d) pass once layers get big (threaded
# matmuls + fused running top-k with bounded [qb, chunk] transients), so
# only modest layers keep the zero-compile host BLAS path there.
_CPU_HOST_KNN_MAX = 65536
_SELECT_DISPATCH_ROWS = 65536  # owners per host-level device dispatch


def _device_backend() -> bool:
    """Device link pipeline pays off on a real accelerator; on CPU the
    gather-heavy selects lose to host BLAS."""
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:  # noqa: BLE001
        return False


def _device_select_dispatch(xd, cand, owner_start, budget, metric, qb=1024):
    """Diversity-select on DEVICE for one dispatch of owners.

    xd [n, d] layer vectors (device-resident), cand [S, C] candidate
    positions (-1 padded, device), owners are rows owner_start..+S of xd.
    Returns [S, budget] selected positions (-1 padded), device array.

    Same semantics as ``_batched_heuristic`` (dominated-mask loop +
    nearest-first backfill), but batched on the chip: the pairwise
    candidate matrices are MXU matmuls and the budget-step loop is a
    ``lax.fori_loop`` over [B, C] masks. Owners are processed in
    ``lax.map`` blocks inside ONE jit per dispatch — per-block host round
    trips would pay a tunnel RTT each, and >200k-row gather-heavy single
    programs crash the TPU worker (hence dispatch-level slicing; the
    jitted program is module-level so every dispatch after the first
    reuses the same trace, with ``start`` as a traced argument).
    """
    import jax.numpy as jnp

    return _select_dispatch_jit(xd, cand, jnp.int32(owner_start), budget,
                                metric, qb)


def _lazy_select_jit():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("budget", "metric", "qb"))
    def run(xd_, cand_, start, budget, metric, qb):
        s_rows, c = cand_.shape
        blocks = s_rows // qb
        def one(args):
            blk_i, cand_blk = args
            # xd_ may arrive bf16 (the scan-precision copy): gathers move
            # half the HBM bytes and the pair matmuls run the MXU's
            # native input width; every contraction accumulates f32
            owners = jax.lax.dynamic_slice(
                xd_, (start + blk_i * qb, 0), (qb, xd_.shape[1]))
            valid = cand_blk >= 0
            safe = jnp.clip(cand_blk, 0, xd_.shape[0] - 1)
            cvecs = xd_[safe]                             # [B, C, d]
            dots = jnp.einsum("bcd,bed->bce", cvecs, cvecs,
                              preferred_element_type=jnp.float32)
            if metric == "l2-squared":
                sq = jnp.einsum("bcd,bcd->bc", cvecs, cvecs,
                                preferred_element_type=jnp.float32)
                pair = sq[:, :, None] - 2.0 * dots + sq[:, None, :]
                osq = jnp.einsum("bd,bd->b", owners, owners,
                                 preferred_element_type=jnp.float32)
                od = jnp.einsum("bcd,bd->bc", cvecs, owners,
                                preferred_element_type=jnp.float32)
                cand_d = osq[:, None] - 2.0 * od + sq
            elif metric == "dot":
                pair = -dots
                cand_d = -jnp.einsum("bcd,bd->bc", cvecs, owners,
                                     preferred_element_type=jnp.float32)
            else:  # cosine family: rows normalized upstream
                pair = 1.0 - dots
                cand_d = 1.0 - jnp.einsum(
                    "bcd,bd->bc", cvecs, owners,
                    preferred_element_type=jnp.float32)
            cand_d = jnp.where(valid, cand_d, jnp.inf)
            # sort candidates by owner distance (full-width top_k = sort)
            negd, order = jax.lax.top_k(-cand_d, c)
            d_s = -negd                                   # [B, C] ascending
            pair_s = jnp.take_along_axis(
                jnp.take_along_axis(pair, order[:, :, None], axis=1),
                order[:, None, :], axis=2)                # [B, C, C]
            iota_c = jax.lax.broadcasted_iota(jnp.int32, (qb, c), 1)

            def step(_i, st):
                dominated, selected, count = st
                avail = (~dominated) & (~selected) & jnp.isfinite(d_s)
                first = jnp.argmax(avail, axis=1)         # [B]
                has = jnp.take_along_axis(
                    avail, first[:, None], axis=1)[:, 0] & (count < budget)
                pick = (iota_c == first[:, None]) & has[:, None]
                selected = selected | pick
                count = count + has.astype(jnp.int32)
                pcol = jnp.take_along_axis(
                    pair_s, first[:, None, None], axis=2)[:, :, 0]
                dominated = dominated | (
                    (pcol <= d_s) & has[:, None])
                return dominated, selected, count

            dom0 = jnp.zeros((qb, c), bool)
            sel0 = jnp.zeros((qb, c), bool)
            cnt0 = jnp.zeros((qb,), jnp.int32)
            dominated, selected, count = jax.lax.fori_loop(
                0, min(budget, c), step, (dom0, sel0, cnt0))
            # nearest-first backfill of pruned candidates up to budget
            need = budget - count
            fillable = dominated & (~selected) & jnp.isfinite(d_s)
            fill_rank = jnp.cumsum(fillable.astype(jnp.int32), axis=1) - 1
            selected = selected | (
                fillable & (fill_rank < need[:, None]))
            # emit selected (distance order), mapped back through `order`
            sel_prio = jnp.where(selected, iota_c, c)
            neg, picks = jax.lax.top_k(-sel_prio, min(budget, c))
            got = -neg < c
            orig = jnp.take_along_axis(order, picks, axis=1)
            out_pos = jnp.where(
                got, jnp.take_along_axis(safe, orig, axis=1), -1)
            if budget > c:
                out_pos = jnp.pad(out_pos, ((0, 0), (0, budget - c)),
                                  constant_values=-1)
            return out_pos

        cand_blocks = cand_.reshape(blocks, qb, c)
        blk_ids = jnp.arange(blocks, dtype=jnp.int32)
        out = jax.lax.map(one, (blk_ids, cand_blocks))
        return out.reshape(s_rows, budget)

    return run


class _SelectJit:
    """Module-level holder so every dispatch shares one jit cache (a
    per-call closure would retrace the large select program each time)."""

    _fn = None

    def __call__(self, *args):
        if _SelectJit._fn is None:
            _SelectJit._fn = _lazy_select_jit()
        return _SelectJit._fn(*args)


_select_dispatch_jit = _SelectJit()


def _device_select(xd, cand, budget, metric, qb=1024):
    """Blocked device selection over all owners; returns a DEVICE array
    [M, budget]. Owners are the first cand.shape[0] rows of xd."""
    import jax.numpy as jnp

    m = cand.shape[0]
    outs = []
    for s in range(0, m, _SELECT_DISPATCH_ROWS):
        rows = min(_SELECT_DISPATCH_ROWS, m - s)
        pad = -(-rows // qb) * qb - rows
        blk = cand[s:s + rows]
        if pad:
            blk = jnp.pad(blk, ((0, pad), (0, 0)), constant_values=-1)
        out = _device_select_dispatch(xd, blk, s, budget, metric, qb)
        outs.append(out[:rows])
    return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]


_SYMMETRIZE_JIT = None
_SELF_DROP_JIT = None


def _self_drop_jit(kd, keep: int):
    global _SELF_DROP_JIT
    if _SELF_DROP_JIT is None:
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("keep",))
        def impl(kd_, keep):
            n_ = kd_.shape[0]
            self_col = (kd_ == jnp.arange(n_)[:, None]).astype(jnp.int32)
            order = jnp.argsort(self_col, axis=1, stable=True)
            return jnp.take_along_axis(kd_, order, axis=1)[
                :, :keep].astype(jnp.int32)

        _SELF_DROP_JIT = impl
    return _SELF_DROP_JIT(kd, keep=keep)


def _device_symmetrize(fwd):
    """Union forward links with reverse edges (cap budget each way), on
    device: one sort of the edge list + position-in-group scatter —
    the vectorized twin of the host path below. Jitted ONCE at module
    scope: eager execution paid a tunnel dispatch per op (77 s of a
    147 s build at 300k rows), and a per-call jit would retrace every
    build."""
    global _SYMMETRIZE_JIT
    if _SYMMETRIZE_JIT is None:
        import jax

        _SYMMETRIZE_JIT = jax.jit(_device_symmetrize_impl)
    return _SYMMETRIZE_JIT(fwd)


def _device_symmetrize_impl(fwd):
    import jax.numpy as jnp

    m, budget = fwd.shape
    src = jnp.repeat(jnp.arange(m, dtype=jnp.int32), budget)
    dst = fwd.reshape(-1)
    dst = jnp.where(dst >= 0, dst, m)  # dead edges sort to the end
    order = jnp.argsort(dst, stable=True)
    dst_s, src_s = dst[order], src[order]
    starts = jnp.searchsorted(dst_s, jnp.arange(m, dtype=jnp.int32))
    pos = jnp.arange(dst_s.shape[0], dtype=jnp.int32) - starts[
        jnp.clip(dst_s, 0, m - 1)]
    keep = (dst_s < m) & (pos < budget)
    union = jnp.full((m, 2 * budget), -1, jnp.int32)
    union = union.at[:, :budget].set(fwd)
    flat = union.reshape(-1)
    tgt = jnp.where(keep, dst_s * 2 * budget + budget + pos,
                    m * 2 * budget)
    flat = flat.at[tgt].set(src_s, mode="drop")
    union = flat.reshape(m, 2 * budget)
    # dedup per row (first occurrence wins)
    srt_idx = jnp.argsort(union, axis=1, stable=True)
    srt_val = jnp.take_along_axis(union, srt_idx, axis=1)
    dup_sorted = jnp.concatenate([
        jnp.zeros((m, 1), bool),
        (srt_val[:, 1:] == srt_val[:, :-1]) & (srt_val[:, 1:] >= 0)],
        axis=1)
    dup = jnp.zeros_like(dup_sorted).at[
        jnp.arange(m)[:, None], srt_idx].set(dup_sorted)
    return jnp.where(dup, -1, union)


def _host_knn(sub: np.ndarray, k_eff: int, metric: str,
              block: int = 4096) -> np.ndarray:
    """Small member sets (upper layers) knn on host BLAS — avoids a fresh
    XLA compile per layer shape (each costs seconds over the tunnel)."""
    n = len(sub)
    if metric == "l2-squared":
        sq = np.einsum("nd,nd->n", sub, sub)
    out = np.empty((n, k_eff), dtype=np.int64)
    for s in range(0, n, block):
        qb = sub[s:s + block]
        dots = qb @ sub.T
        if metric == "l2-squared":
            d = sq[s:s + block, None] - 2.0 * dots + sq[None, :]
        elif metric == "dot":
            d = -dots
        elif metric in ("cosine", "cosine-dot"):
            d = 1.0 - dots
        elif metric == "manhattan":
            d = np.abs(qb[:, None, :] - sub[None, :, :]).sum(-1)
        else:
            d = (qb[:, None, :] != sub[None, :, :]).sum(-1).astype(np.float32)
        part = np.argpartition(d, k_eff - 1, axis=1)[:, :k_eff]
        pd = np.take_along_axis(d, part, axis=1)
        out[s:s + block] = np.take_along_axis(
            part, np.argsort(pd, axis=1, kind="stable"), axis=1)
    return out


def _device_knn(sub: np.ndarray, k_eff: int, metric: str,
                query_block: int = 8192, chunk_size: int = 65536,
                return_device: bool = False):
    """Full-corpus knn in ONE device dispatch: lax.map over fixed-shape
    query blocks inside a single jit — per-block host round trips each
    cost a tunnel RTT, so 1M rows would pay minutes in RTTs otherwise.

    ``return_device=True`` keeps everything on the chip and returns
    (xd_padded, knn_ids_device) so the device link pipeline can run
    without the ~0.5 GB knn download + re-upload (tunnel transfers move
    at tens of MB/s — round-tripping intermediates dominated the r3
    build)."""
    import jax
    import jax.numpy as jnp

    from weaviate_tpu.ops.topk import chunked_topk_distances

    n = len(sub)

    from weaviate_tpu.ops.pallas_kernels import recommended

    use_pallas = recommended()
    # TPU: fold selection INTO the scan kernel (selection="fused" — the
    # per-chunk approx_max_k pass plus its [qb, chunk] HBM round-trip was
    # the dominant cost of the 1M bulk-build knn stage, VERDICT r5);
    # chunked_topk_distances degrades it to "approx" if k_eff > the fused
    # carry width. CPU backend: "approx" lowers to the exact XLA top_k.
    selection = "fused" if use_pallas else "approx"
    if not use_pallas:
        # the XLA CPU scan materializes [qb, chunk] distance transients in
        # RAM — bound them (~64 MB) for the large-layer CPU fallback path
        query_block = min(query_block, 1024)
        chunk_size = min(chunk_size, 16384)
    cs = min(chunk_size, 1 << (n - 1).bit_length())
    pad_rows = -(-n // cs) * cs - n
    x = np.pad(sub, ((0, pad_rows), (0, 0)))
    valid = np.arange(n + pad_rows) < n
    # host-level slices of a few query blocks each: one giant program over
    # 1M queries reproducibly crashes the TPU worker, and per-slice fetches
    # stay small. Queries are dynamic-sliced FROM the device-resident
    # corpus (they ARE corpus rows) — zero query uploads. On the pallas
    # path the fused kernel's [qb, chunk] distance tile must fit scoped
    # VMEM, so blocks are capped at 1024 queries (the serving scan's
    # shape), keeping the slice size by raising the block count.
    blocks_per_slice = 8
    if use_pallas and query_block > 1024:
        if query_block % 1024 == 0:
            blocks_per_slice *= query_block // 1024
        query_block = 1024
    # a slice may not exceed the padded corpus (small layers: the
    # dynamic_slice of queries comes FROM the corpus rows)
    while blocks_per_slice > 1 and \
            blocks_per_slice * query_block > n + pad_rows:
        blocks_per_slice //= 2
    if query_block > n + pad_rows:
        query_block = n + pad_rows
    slice_rows = blocks_per_slice * query_block

    @functools.partial(jax.jit, static_argnames=("k", "cs", "metric"))
    def knn_slice(xscan, vd, norms, start, k, cs, metric):
        qs = jax.lax.dynamic_slice(
            xscan, (start, 0), (slice_rows, xscan.shape[1]))
        qb = qs.reshape(blocks_per_slice, query_block, xscan.shape[1])

        def one(qblk):
            _d, i = chunked_topk_distances(
                qblk, xscan, k=k, chunk_size=cs,
                metric=metric, valid=vd, x_sq_norms=norms,
                selection=selection, use_pallas=use_pallas)
            return i
        return jax.lax.map(one, qb).reshape(slice_rows, k)

    xd = jnp.asarray(x)
    vd = jnp.asarray(valid)
    # the scan runs bf16 on the fused MXU kernel — the same storage/
    # precision choice as the flat serving scan (recall envelope in
    # BASELINE); candidate ids then feed the select stages, which also
    # run at scan precision (bf16 inputs, f32 accumulation — recall
    # parity pinned by the bench ef sweep). The f32 knn scan was 47.8 s
    # of the 121 s 300k build (BASELINE r5).
    xscan = xd.astype(jnp.bfloat16) if use_pallas else xd
    # build-time scratch is the dominant transient HBM consumer at 1M
    # rows — ledger-tracked for exactly as long as the array lives, so
    # peak watermarks and /v1/debug/memory see bulk builds
    from weaviate_tpu.runtime.hbm_ledger import ledger as _hbm

    _hbm.track("build_scratch", xscan)
    norms = jnp.sum(xd.astype(jnp.float32) ** 2, axis=-1)
    norms_arg = norms if metric == "l2-squared" else None
    if return_device:
        parts = []
        for s in range(0, n, slice_rows):
            start = min(s, max(n + pad_rows - slice_rows, 0))
            ids = knn_slice(xscan, vd, norms_arg, start, k_eff, cs, metric)
            parts.append(ids[s - start: s - start + min(slice_rows, n - s)])
        knn_dev = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        # hand back the SCAN-precision corpus (bf16 on the pallas path):
        # the select stages gather from it and run their pair matmuls at
        # the MXU's native width with f32 accumulation, so the f32 copy
        # can be freed now (it is half the pipeline's HBM at 1M rows)
        return xscan, knn_dev
    out = np.empty((n, k_eff), dtype=np.int64)
    for s in range(0, n, slice_rows):
        # clamp the window inside the padded corpus; overlap re-computes a
        # few rows rather than compiling a second (tail) shape
        start = min(s, max(n + pad_rows - slice_rows, 0))
        ids = knn_slice(xscan, vd, norms_arg, start, k_eff, cs, metric)
        take = np.asarray(ids[s - start: s - start + min(slice_rows, n - s)],
                          dtype=np.int64)
        out[s: s + len(take)] = take
    return out


def _knn_graph(vectors: np.ndarray, members: np.ndarray, knn_k: int,
               metric: str) -> np.ndarray:
    """For each member, its knn_k nearest OTHER members (positions into
    ``members``)."""
    sub = vectors[members]
    n = len(sub)
    k_eff = min(knn_k + 1, n)
    supported = metric in ("l2-squared", "dot", "cosine", "cosine-dot")
    # host BLAS for small layers (zero compiles); device path above the
    # backend's ceiling — on CPU backends that's the XLA chunked scan
    # (exact top_k lowering), no longer the unconditional O(n^2 d) numpy
    # pass that made large CPU builds crawl
    host_cap = _HOST_KNN_MAX if _device_backend() else _CPU_HOST_KNN_MAX
    if not supported or n <= host_cap:
        if not supported and n > _CPU_HOST_KNN_MAX:
            logger.warning(
                "hnsw bulk build: %d-row layer falls back to the exact "
                "O(n^2 d) host BLAS knn — metric %r has no device scan",
                n, metric)
        out = _host_knn(sub, k_eff, metric)
    else:
        out = _device_knn(sub, k_eff, metric)
    # drop self-hits, keep knn_k columns: stable-sort by is_self pushes
    # non-self candidates to the front preserving distance order
    self_col = out == np.arange(n)[:, None]
    order = np.argsort(self_col, axis=1, kind="stable")
    res = np.take_along_axis(out, order, axis=1)[:, : min(knn_k, n - 1)]
    return res


def _device_link_layer(vectors: np.ndarray, members: np.ndarray,
                       knn_k: int, budget: int, metric: str) -> np.ndarray:
    """Fully device-resident knn -> select -> symmetrize -> select for one
    layer: intermediates ([M, C] candidate tensors, ~0.5-1 GB at 1M rows)
    never cross the tunnel; only the final [M, budget] link table comes
    back. Selects run at scan precision (bf16 on TPU, f32 accumulation)
    — recall parity is pinned by the bench ef sweep. Returns positions
    into ``members`` (-1 padded)."""
    import os
    import time as _time

    trace = os.environ.get("WEAVIATE_TPU_BUILD_TRACE") == "1"

    def _t(label, fn):
        t0 = _time.perf_counter()
        out = fn()
        # force REAL execution before dispatching the next stage: letting
        # the whole pipeline queue up behind async dispatch made the 300k
        # build 2x slower end-to-end on the tunnel runtime (pathological
        # queue drain), and block_until_ready is not trustworthy there
        # (handles report completion before execution) — a tiny
        # data-dependent fetch is. Costs one RTT per stage.
        probe = out[-1] if isinstance(out, tuple) else out
        np.asarray(probe.ravel()[0])
        if trace:
            print(f"    [build-trace] {label:12s} "
                  f"{_time.perf_counter()-t0:7.2f}s", flush=True)
        return out

    sub = vectors[members]
    n = len(sub)
    k_eff = min(knn_k + 1, n)
    xd, knn_dev = _t("knn", lambda: _device_knn(
        sub, k_eff, metric, return_device=True))

    # drop self-hits on device (stable sort by is-self keeps distance
    # order); module-level jit — eager ops each pay a tunnel dispatch,
    # per-call closures retrace every build
    knn_dev = _t("self_drop", lambda: _self_drop_jit(
        knn_dev, min(knn_k, n - 1)))
    fwd = _t("select1", lambda: _device_select(xd, knn_dev, budget, metric))
    union = _t("symmetrize", lambda: _device_symmetrize(fwd))
    final = _t("select2", lambda: _device_select(xd, union, budget, metric))
    # fetch int32 — the int64 copy doubled a ~0.5 GB tunnel download at
    # 1M; concurrent sliced fetches run ~1.7x faster than one big pull
    # on the tunnel transport (measured at 300k x 64)
    return _t("download", lambda: _parallel_fetch(final))


def _parallel_fetch(arr, chunk_rows: int = 65536, workers: int = 4):
    n = arr.shape[0]
    if n <= chunk_rows:
        return np.asarray(arr)
    import concurrent.futures as cf

    with cf.ThreadPoolExecutor(workers) as ex:
        parts = list(ex.map(lambda s: np.asarray(arr[s:s + chunk_rows]),
                            range(0, n, chunk_rows)))
    return np.concatenate(parts)


def bulk_build(index, doc_ids, vectors: np.ndarray, knn_k: int = 64,
               query_block: int = 1024) -> None:
    """Populate an EMPTY HNSWIndex from scratch at device speed.

    Layer l links every node with level >= l against the other members of
    that layer using exact kNN candidates + the diversity heuristic +
    symmetrize/prune. Per-link WAL writes are skipped; one condensed
    snapshot lands at the end (same durability fixed point,
    condensor.go:27).
    """
    from weaviate_tpu.runtime.compile_cache import ensure_compile_cache

    ensure_compile_cache()  # link-pipeline jits are seconds each, cold
    doc_ids = np.asarray(doc_ids, dtype=np.int64)
    vectors = index._norm(np.asarray(vectors, dtype=np.float32))
    n = len(vectors)
    if len(doc_ids) != n:
        raise ValueError(f"{len(doc_ids)} ids != {n} vectors")
    if len(index) != 0:
        raise RuntimeError("bulk_build requires an empty index")
    with index._lock:
        index._grow(n)
        # vectorized geometric level sampling (a per-node Python RNG loop
        # costs seconds at 1M); seeded from the index RNG for determinism
        rng = np.random.default_rng(int(index._rng.random() * 2**63))
        levels = (-np.log(np.maximum(rng.random(n), 1e-12))
                  * index._ml).astype(np.int32)
        index._vecs[:n] = vectors
        index._levels[:n] = levels
        index._doc_ids[:n] = doc_ids
        index._id_to_slot = {int(d): s for s, d in enumerate(doc_ids)}
        index._count = n
        max_level = int(levels.max())
        for layer in range(max_level + 1):
            members = np.nonzero(levels >= layer)[0]
            if len(members) == 0:
                continue
            if len(members) == 1:
                s = int(members[0])
                links = index._links[s]
                while len(links) <= layer:
                    links.append(np.empty(0, dtype=np.int32))
                continue
            budget = index.m0 if layer == 0 else index.m
            use_device = (
                len(members) > _HOST_KNN_MAX
                and index.metric in ("l2-squared", "dot",
                                     "cosine", "cosine-dot")
                and _device_backend())
            if use_device:
                # device-scan selection cost scales ~linearly with k
                # (k=65 ran 5x the k=10 scan) and 48 candidates measured
                # recall-equivalent to 64 at 300k/1M (0.99 @ ef=24;
                # symmetrize refills the m0 budget with reverse edges).
                # Host BLAS knn below is exact and cheap at its sizes —
                # it keeps the caller's full candidate count (the PQ-ADC
                # traversal is sensitive to thinner graphs there).
                fwd = _device_link_layer(vectors, members, min(48, knn_k),
                                         budget, index.metric)
            else:
                knn = _knn_graph(vectors, members, knn_k, index.metric)
                fwd = _link_layer(index, vectors, members, knn, budget,
                                  query_block)
            _write_links(index, members, fwd, layer)
        # entrypoint: any node at the top level
        top = int(np.nonzero(levels == max_level)[0][0])
        index._ep = top
        index._max_level = max_level
        # vectors/levels/links were written past the native mirror — one
        # batched re-upload on next use
        index._native_dirty = True
        if index._log is not None:
            index.condense()


def _host_select(sub, owner_pos, cand_idx, budget, metric, query_block):
    """Blocked host-side heuristic selection (small layers / non-MXU
    metrics). Returns [M, budget] member positions, -1 padded."""
    m_count, c = cand_idx.shape
    out = np.full((m_count, budget), -1, dtype=np.int64)
    for s in range(0, m_count, query_block):
        blk = cand_idx[s:s + query_block]
        valid = blk >= 0
        safe = np.clip(blk, 0, len(sub) - 1)
        cvecs = sub[safe]
        pair = _pairwise_block(cvecs, metric)
        cand_d = _owner_dists(sub[owner_pos[s:s + query_block]], cvecs,
                              metric)
        sel = _batched_heuristic(cand_d, pair, budget, valid=valid)
        take = sel >= 0
        safe_sel = np.clip(sel, 0, c - 1)
        out[s:s + query_block] = np.where(
            take, np.take_along_axis(safe, safe_sel, axis=1), -1)
    return out


def _link_layer(index, vectors, members, knn, budget, query_block):
    """Heuristic-select forward links, symmetrize, shrink to budget.
    ``knn`` holds positions into ``members``; returns [M, budget] positions
    into ``members`` (-1 padded)."""
    metric = index.metric
    m_count, c = knn.shape
    sub = vectors[members]
    owner_pos = np.arange(m_count)

    # selection runs on HOST BLAS: measured 2x faster than a device
    # fori_loop select on this rig (gather-heavy, tunnel-dispatched), and
    # the knn scan — where the FLOPs are — already ran on the MXU
    fwd = _host_select(sub, owner_pos, knn, budget, metric, query_block)

    # symmetrize: reverse edges via one argsort pass, then cap the union
    # at 2*budget nearest before the final heuristic prune
    src = np.repeat(np.arange(m_count), budget)
    dst = fwd.reshape(-1)
    live = dst >= 0
    src, dst = src[live], dst[live]
    order = np.argsort(dst, kind="stable")
    dst_sorted, src_sorted = dst[order], src[order]
    starts = np.searchsorted(dst_sorted, np.arange(m_count))
    c2 = budget
    union = np.full((m_count, budget + c2), -1, dtype=np.int64)
    union[:, :budget] = fwd
    # vectorized ragged fill: position-within-group scatter, capped at c2
    if len(dst_sorted):
        pos_in_group = np.arange(len(dst_sorted)) - starts[dst_sorted]
        keep = pos_in_group < c2
        union[dst_sorted[keep], budget + pos_in_group[keep]] = \
            src_sorted[keep]
    # dedup rows keeping the first occurrence (stable argsort groups equal
    # values in original order, so repeats after the first flag as dups)
    srt_idx = np.argsort(union, axis=1, kind="stable")
    srt_val = np.take_along_axis(union, srt_idx, axis=1)
    dup_sorted = np.zeros_like(srt_val, dtype=bool)
    dup_sorted[:, 1:] = (srt_val[:, 1:] == srt_val[:, :-1]) & \
        (srt_val[:, 1:] >= 0)
    dup = np.zeros_like(dup_sorted)
    np.put_along_axis(dup, srt_idx, dup_sorted, axis=1)
    union[dup] = -1
    # final shrink runs the FULL diversity heuristic over the capped union
    # — nearest-truncation here was 30% cheaper but collapsed recall@10
    # from 1.00 to 0.69 on 200k gaussian (the diversity property of the
    # reverse-merge is load-bearing, exactly why the reference's
    # connectNeighbor shrink path re-runs its heuristic)
    return _host_select(sub, owner_pos, union, budget, metric, query_block)


def _write_links(index, members, links_pos, layer):
    """Store [M, budget] member-position links as slot-id arrays."""
    for i, slot in enumerate(members.tolist()):
        row = links_pos[i]
        row = row[row >= 0]
        slots = members[row].astype(np.int32)
        lk = index._links[slot]
        while len(lk) <= layer:
            lk.append(np.empty(0, dtype=np.int32))
        lk[layer] = slots
