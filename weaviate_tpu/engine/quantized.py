"""Quantized (PQ/BQ) vector store: compressed codes in HBM, rescore on host
full-precision vectors.

Reference parity:
- flat BQ path with rescore: vector/flat/index.go:347 (searchByVectorBQ)
- HNSW runtime compression hook: vector/hnsw/compress.go:38 (train on
  current contents, swap cache for a compressed one)
- compressor plumbing: compressionhelpers/compression.go:37

Memory layout: HBM holds only the codes ([C, m] uint8 for PQ — 16-64x
smaller than f32; [C, w] uint32 sign-bits for BQ — 32x smaller) plus the
valid mask. Full-precision vectors stay in host RAM for (a) quantizer
(re)training, (b) exact rescore of the oversampled candidate set — the
candidate gather is tiny (k * rescore_factor rows) so the host round-trip
costs microseconds, not the HBM scan.
"""

from __future__ import annotations

import threading

import jax.numpy as jnp
import numpy as np

from weaviate_tpu.ops import bq as bq_ops
from weaviate_tpu.ops import pq as pq_ops
from weaviate_tpu.ops.distances import normalize, pairwise_distance
from weaviate_tpu.ops.topk import topk_smallest

_DEFAULT_CHUNK = 8192


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class QuantizedVectorStore:
    """PQ- or BQ-compressed store with the DeviceVectorStore method surface.

    Single-replica (unsharded) in this round; codes are small enough that a
    100M x 96-byte corpus fits one chip.
    """

    def __init__(
        self,
        dim: int,
        metric: str = "l2-squared",
        quantization: str = "pq",
        capacity: int = _DEFAULT_CHUNK,
        chunk_size: int = _DEFAULT_CHUNK,
        pq_segments: int | None = None,
        pq_centroids: int = 16,
        # oversampling multiplier: the compressed scan returns
        # rescore_limit*k candidates for exact rescore (reference keeps an
        # absolute rescoreLimit, flat/index.go:301; 16x measures ~0.99
        # candidate-recall@10 on clustered 96-dim data)
        rescore_limit: int = 16,
        normalize_on_add: bool | None = None,
        codebook: pq_ops.PQCodebook | None = None,
    ):
        if quantization not in ("pq", "bq"):
            raise ValueError(f"unknown quantization {quantization!r}")
        self.dim = dim
        self.metric = metric
        self.quantization = quantization
        self.chunk_size = chunk_size
        self.rescore_limit = rescore_limit
        if pq_segments:
            self.pq_segments = pq_segments
        else:
            # 4-bit codes default to 1 bit/dim (m = d/4), 8-bit to 1 byte
            # per 8 dims; m must divide d for the orthogonal-segment ADC
            target = max(1, dim // (4 if pq_centroids <= 16 else 8))
            while dim % target:
                target -= 1
            self.pq_segments = target
        self.pq_centroids = pq_centroids
        self.codebook = codebook
        self.normalize_on_add = (
            metric in ("cosine", "cosine-dot")
            if normalize_on_add is None
            else normalize_on_add
        )
        self.mesh = None
        self.n_shards = 1
        self._lock = threading.RLock()
        self._count = 0
        self.capacity = max(_next_pow2(capacity), chunk_size)
        self._host_vectors = np.zeros((self.capacity, dim), dtype=np.float32)
        self._valid_np = np.zeros(self.capacity, dtype=bool)
        self._alloc_codes()

    # -- internals -----------------------------------------------------------

    def _code_width(self) -> int:
        if self.quantization == "pq":
            return self.pq_segments
        return bq_ops.bq_words(self.dim)

    def _alloc_codes(self):
        w = self._code_width()
        dtype = jnp.uint8 if self.quantization == "pq" else jnp.uint32
        self.codes = jnp.zeros((self.capacity, w), dtype=dtype)
        self.valid = jnp.asarray(self._valid_np)

    def _encode(self, vectors: np.ndarray) -> np.ndarray:
        if self.quantization == "pq":
            if self.codebook is None:
                raise RuntimeError("PQ store not trained; call train() first")
            return pq_ops.pq_encode(self.codebook, vectors)
        return np.asarray(bq_ops.bq_encode(jnp.asarray(vectors)))

    def _maybe_norm(self, vectors: np.ndarray) -> np.ndarray:
        if self.normalize_on_add:
            return np.asarray(normalize(jnp.asarray(vectors)))
        return vectors

    # -- training ------------------------------------------------------------

    @property
    def trained(self) -> bool:
        return self.quantization == "bq" or self.codebook is not None

    def train(self, vectors: np.ndarray | None = None, iters: int = 8, seed: int = 0):
        """Fit the PQ codebook (on given vectors or current live contents)
        and (re-)encode everything stored so far."""
        if self.quantization == "bq":
            return
        with self._lock:
            if vectors is None:
                vectors = self._host_vectors[self._valid_np]
            vectors = self._maybe_norm(np.asarray(vectors, dtype=np.float32))
            self.codebook = pq_ops.pq_fit(
                vectors, m=self.pq_segments, k=self.pq_centroids,
                iters=iters, seed=seed,
            )
            self._reencode_all()

    def _reencode_all(self):
        live = np.nonzero(self._valid_np)[0]
        if len(live):
            codes = self._encode(self._host_vectors[live])
            self.codes = self.codes.at[jnp.asarray(live)].set(jnp.asarray(codes))

    # -- mutation ------------------------------------------------------------

    def add(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        m = len(vectors)
        with self._lock:
            slots = np.arange(self._count, self._count + m, dtype=np.int64)
            self._count += m
            if self._count > self.capacity:
                self._grow(self._count)
            self._write(slots, vectors)
            return slots

    def set_at(self, slots, vectors: np.ndarray):
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        vectors = np.asarray(vectors, dtype=np.float32)
        with self._lock:
            if len(slots) and int(slots.max()) >= self.capacity:
                self._grow(int(slots.max()) + 1)
            self._count = max(self._count, int(slots.max()) + 1 if len(slots) else 0)
            self._write(slots, vectors)

    def _write(self, slots: np.ndarray, vectors: np.ndarray):
        vectors = self._maybe_norm(vectors)
        self._host_vectors[slots] = vectors
        self._valid_np[slots] = True
        codes = self._encode(vectors) if self.trained else None
        if codes is not None:
            self.codes = self.codes.at[jnp.asarray(slots)].set(jnp.asarray(codes))
        self.valid = jnp.asarray(self._valid_np)

    def _grow(self, min_capacity: int):
        new_cap = max(_next_pow2(min_capacity), self.chunk_size)
        grown_v = np.zeros((new_cap, self.dim), dtype=np.float32)
        grown_v[: self.capacity] = self._host_vectors
        grown_m = np.zeros(new_cap, dtype=bool)
        grown_m[: self.capacity] = self._valid_np
        self._host_vectors, self._valid_np = grown_v, grown_m
        old_codes = self.codes
        self.capacity = new_cap
        self._alloc_codes()
        self.codes = self.codes.at[: old_codes.shape[0]].set(old_codes)

    def set_at_prenormalized(self, slots, vectors: np.ndarray):
        """set_at for vectors already normalized at their original insert
        (restore/compact/compress paths) — skips re-normalization."""
        orig = self.normalize_on_add
        self.normalize_on_add = False
        try:
            self.set_at(slots, vectors)
        finally:
            self.normalize_on_add = orig

    def delete(self, slots) -> None:
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        if len(slots) == 0:
            return
        with self._lock:
            self._valid_np[slots] = False
            self.valid = jnp.asarray(self._valid_np)

    # -- queries -------------------------------------------------------------

    @property
    def count(self) -> int:
        return self._count

    def live_count(self) -> int:
        return int(self._valid_np.sum())

    def get(self, slots) -> np.ndarray:
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        return self._host_vectors[slots].copy()

    def search(self, queries: np.ndarray, k: int, allow_mask: np.ndarray | None = None):
        """Two-stage: compressed scan (oversampled) -> exact f32 rescore.

        Reference BQ rescore: flat/index.go:347; oversampling factor =
        ``rescore_limit`` (*k candidates pulled from the compressed scan).
        """
        queries = np.asarray(queries, dtype=np.float32)
        squeeze = queries.ndim == 1
        if squeeze:
            queries = queries[None, :]
        queries = self._maybe_norm(queries)
        with self._lock:
            codes, valid = self.codes, self.valid
            capacity = self.capacity
            if allow_mask is not None:
                full = np.zeros(capacity, dtype=bool)
                full[: len(allow_mask)] = allow_mask[:capacity]
                valid = jnp.logical_and(valid, jnp.asarray(full))
            if not self.trained:
                raise RuntimeError("PQ store not trained; call train() first")
            k_cand = min(max(k * self.rescore_limit, k), capacity)
            cs = min(self.chunk_size, capacity)
            metric = "cosine" if self.metric in ("cosine", "cosine-dot") else self.metric
            if self.quantization == "pq":
                if self.pq_centroids <= 16:
                    # 4-bit path: ADC LUT as one MXU matmul per tile
                    # (ops/pallas_kernels.pq4_lut_block)
                    d, i = pq_ops.pq4_topk(
                        jnp.asarray(queries), codes, self.codebook.centroids,
                        k=k_cand, chunk_size=cs, metric=metric, valid=valid,
                    )
                else:
                    d, i = pq_ops.pq_topk(
                        jnp.asarray(queries), codes, self.codebook.centroids,
                        k=k_cand, chunk_size=cs, metric=metric, valid=valid,
                    )
            else:
                from weaviate_tpu.ops.pallas_kernels import recommended

                q_words = bq_ops.bq_encode(jnp.asarray(queries))
                d, i = bq_ops.bq_topk(
                    q_words, codes, k=k_cand, chunk_size=cs, valid=valid,
                    use_pallas=recommended(),
                )
        cand_ids = np.asarray(i)  # [B, k_cand]
        # exact rescore on host vectors (gather candidates, tiny matmul)
        b = len(queries)
        safe = np.clip(cand_ids, 0, capacity - 1)
        cand_vecs = self._host_vectors[safe]  # [B, k_cand, d]
        metric_exact = "cosine" if self.metric in ("cosine", "cosine-dot") else self.metric
        out_d = np.empty((b, min(k, cand_ids.shape[1])), dtype=np.float32)
        out_i = np.empty_like(out_d, dtype=np.int64)
        for bi in range(b):
            dd = np.array(
                pairwise_distance(
                    jnp.asarray(queries[bi : bi + 1]),
                    jnp.asarray(cand_vecs[bi]),
                    metric=metric_exact,
                )
            )[0]
            dead = cand_ids[bi] < 0
            dd[dead] = np.float32(3.0e38)
            order = np.argsort(dd, kind="stable")[: out_d.shape[1]]
            out_d[bi] = dd[order]
            out_i[bi] = np.where(dead[order], -1, cand_ids[bi][order])
        if squeeze:
            return out_d[0], out_i[0]
        return out_d, out_i

    def search_by_distance(self, query: np.ndarray, max_distance: float,
                           allow_mask: np.ndarray | None = None):
        k = min(64, self.capacity)
        while True:
            d, i = self.search(query, k, allow_mask)
            within = d <= max_distance
            if (~within).any() or k >= self.capacity or within.sum() >= self.live_count():
                return d[within], i[within]
            k = min(k * 4, self.capacity)

    # -- maintenance / persistence -------------------------------------------

    def compact(self) -> np.ndarray:
        with self._lock:
            live = np.nonzero(self._valid_np)[0]
            mapping = np.full(self.capacity, -1, dtype=np.int64)
            mapping[live] = np.arange(len(live))
            vecs = self._host_vectors[live]
            self._count = 0
            self.capacity = max(_next_pow2(max(len(live), 1)), self.chunk_size)
            self._host_vectors = np.zeros((self.capacity, self.dim), dtype=np.float32)
            self._valid_np = np.zeros(self.capacity, dtype=bool)
            self._alloc_codes()
            if len(live):
                self.set_at_prenormalized(np.arange(len(live)), vecs)
            return mapping

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "vectors": self._host_vectors.copy(),
                "valid": self._valid_np.copy(),
                "count": self._count,
                "dim": self.dim,
                "metric": self.metric,
                "quantization": self.quantization,
                "pq_segments": self.pq_segments,
                "pq_centroids": self.pq_centroids,
                "rescore_limit": self.rescore_limit,
                "chunk_size": self.chunk_size,
                "codebook": (
                    None if self.codebook is None
                    else np.asarray(self.codebook.centroids)
                ),
            }

    @classmethod
    def restore(cls, snap: dict, **kwargs) -> "QuantizedVectorStore":
        store = cls(
            dim=snap["dim"],
            metric=snap["metric"],
            quantization=snap["quantization"],
            capacity=max(len(snap["valid"]), 2),
            chunk_size=snap["chunk_size"],
            pq_segments=snap["pq_segments"],
            pq_centroids=snap["pq_centroids"],
            rescore_limit=snap["rescore_limit"],
            **kwargs,
        )
        if snap.get("codebook") is not None:
            store.codebook = pq_ops.PQCodebook(jnp.asarray(snap["codebook"]))
        live = np.nonzero(snap["valid"])[0]
        if len(live):
            store.set_at_prenormalized(live, snap["vectors"][live])
        store._count = snap["count"]
        return store
