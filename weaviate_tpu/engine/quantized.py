"""Quantized (PQ/BQ) vector store: compressed codes in HBM, exact rescore.

Reference parity:
- flat BQ path with rescore: vector/flat/index.go:347 (searchByVectorBQ)
- HNSW runtime compression hook: vector/hnsw/compress.go:38 (train on
  current contents, swap cache for a compressed one)
- compressor plumbing: compressionhelpers/compression.go:37
- compression composes with sharding because quantizer state is per-shard
  (compress.go:38 inside usecases/sharding/state.go:28) — here the same
  composition is one SPMD program over a device mesh
  (parallel/sharded_search.py:sharded_quantized_topk).

Memory layout: HBM holds only the codes ([C, m] uint8 for PQ — 16-64x
smaller than f32; [C, w] uint32 sign-bits for BQ — 32x smaller) plus the
valid mask; on a mesh both are row-sharded over the ``shard`` axis. Three
rescore modes pick where full-precision candidates come from:

- ``"host"``  (default): f32 rows in host RAM; the compressed scan returns
  an oversampled candidate set and the exact rescore is a tiny host gather
  + batched numpy distance. Right when host RAM >> HBM.
- ``"device"``: bf16 rows row-sharded in HBM next to the codes; each device
  rescores ITS OWN candidates inside the same SPMD program before the ICI
  merge (owning-device rescore — vectors never cross the interconnect).
  Costs 2 bytes/dim of HBM; the serving path never touches the host.
- ``"none"``: codes only — the capacity regime (e.g. 100M x 768 BQ = 9.6 GB
  across a mesh). Results are code-distance ordered unless ``fetch_fn``
  (ids -> f32 rows, e.g. backed by the shard's LSM objects bucket) is
  given, which re-enables exact rescore from durable storage.
"""

from __future__ import annotations

import functools
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.ops import bq as bq_ops
from weaviate_tpu.ops import pq as pq_ops
from weaviate_tpu.ops.candidates import gather_rescore_topk
from weaviate_tpu.ops.distances import normalize_np
from weaviate_tpu.parallel.mesh import n_row_shards, shardable_capacity
from weaviate_tpu.runtime import hbm_ledger, kernelscope, tracing
from weaviate_tpu.runtime.transfer import DeviceResultHandle

_DEFAULT_CHUNK = 8192


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _scatter_codes(codes, valid, slots, new_codes, write_mask):
    """Donated in-place scatter of code rows (mode='drop' makes redirected
    padding rows no-ops) — same mutability model as store._scatter_rows."""
    tgt = jnp.where(write_mask, slots, codes.shape[0])
    codes = codes.at[tgt].set(new_codes, mode="drop")
    valid = valid.at[tgt].set(True, mode="drop")
    return codes, valid


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_prefix(prefix_t, slots, new_cols, write_mask):
    """Donated column scatter into the transposed prefix array [Wp, C]."""
    tgt = jnp.where(write_mask, slots, prefix_t.shape[1])
    return prefix_t.at[:, tgt].set(new_cols, mode="drop")


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rescore(rows, slots, new_rows, write_mask):
    tgt = jnp.where(write_mask, slots, rows.shape[0])
    return rows.at[tgt].set(new_rows.astype(rows.dtype), mode="drop")


@functools.partial(jax.jit, donate_argnums=(0,))
def _clear_valid(valid, slots):
    return valid.at[slots].set(False, mode="drop")


@functools.partial(jax.jit, donate_argnums=(1,))
def _set_valid(codes, valid, slots, write_mask):
    tgt = jnp.where(write_mask, slots, codes.shape[0])
    return valid.at[tgt].set(True, mode="drop")


class QuantizedVectorStore:
    """PQ- or BQ-compressed store with the DeviceVectorStore method surface.

    On a mesh, codes (and bf16 rescore rows in ``rescore="device"`` mode)
    are row-sharded over the ``shard`` axis and every search runs SPMD.
    """

    def __init__(
        self,
        dim: int,
        metric: str = "l2-squared",
        quantization: str = "pq",
        capacity: int = _DEFAULT_CHUNK,
        chunk_size: int = _DEFAULT_CHUNK,
        pq_segments: int | None = None,
        pq_centroids: int = 16,
        # oversampling multiplier: the compressed scan returns
        # rescore_limit*k candidates for exact rescore (reference keeps an
        # absolute rescoreLimit, flat/index.go:301; 16x measures ~0.99
        # candidate-recall@10 on clustered 96-dim data)
        rescore_limit: int = 16,
        normalize_on_add: bool | None = None,
        codebook: pq_ops.PQCodebook | None = None,
        mesh=None,
        rescore: str = "host",
        fetch_fn=None,
        # BQ capacity regime: width (in bits, multiple of 128) of a
        # separately-stored transposed sign-bit prefix. Searches then run
        # two-stage (prefix scan -> gathered full-width refine ->
        # rescore), reading ~prefix_bits/dim of the code bytes in stage 1
        # (ops/bq.py bq_topk_twostage). Single-device stores only — the
        # mesh path scans full codes per shard.
        prefix_bits: int | None = None,
        # survivor selector for the fused scan-reduce kernels: "approx"
        # (approx_max_k, default) or "fused" (exact in-kernel running-
        # carry top-k — pallas_kernels.fused_topk_pairs)
        selection: str = "approx",
        # HBM-ledger component suffix ("@e3" for epoch stores): codes/
        # prefix/rescore_rows register as "codes@e3" etc. so per-epoch
        # device bytes are individually visible and individually released
        component_suffix: str = "",
    ):
        if quantization not in ("pq", "bq"):
            raise ValueError(f"unknown quantization {quantization!r}")
        if rescore not in ("host", "device", "none"):
            raise ValueError(f"unknown rescore mode {rescore!r}")
        if selection not in ("approx", "fused"):
            # no "exact" here: the compressed scans go through the
            # scan-reduce kernels whose survivor pass is approx or fused —
            # reject rather than silently serving the approx path
            raise ValueError(
                f"quantized stores support selection 'approx' or 'fused', "
                f"got {selection!r}")
        if selection == "fused" and quantization == "pq" and pq_centroids > 16:
            # the 8-bit reconstruct scan (pq_topk) has no fused survivor
            # pass — reject rather than silently serving approx
            raise ValueError(
                "selection='fused' needs the pq4 scan-reduce kernel "
                "(pq_centroids <= 16) or quantization='bq'")
        self.dim = dim
        self.metric = metric
        self.quantization = quantization
        self.chunk_size = chunk_size
        self.rescore_limit = rescore_limit
        self.rescore = rescore
        self.fetch_fn = fetch_fn
        self.selection = selection
        if pq_segments:
            self.pq_segments = pq_segments
        else:
            self.pq_segments = pq_ops.default_pq_segments(dim, pq_centroids)
        self.pq_centroids = pq_centroids
        self.codebook = codebook
        self.normalize_on_add = (
            metric in ("cosine", "cosine-dot")
            if normalize_on_add is None
            else normalize_on_add
        )
        self.mesh = mesh
        self.n_shards = n_row_shards(mesh)
        self.hbm_component_suffix = component_suffix
        self.prefix_words = 0
        if prefix_bits and mesh is None:
            wp = max(4, prefix_bits // 32 // 4 * 4)
            if quantization == "bq":
                # a prefix at least as wide as the code itself saves
                # nothing (and would crash the column scatter for
                # dim <= 128)
                if wp < bq_ops.bq_words(dim):
                    self.prefix_words = wp
            else:
                # PQ two-stage: the prefix is a BQ SIGN slice of the raw
                # vectors (ops/pq.pq_topk_twostage) — it needs that many
                # leading dims to exist
                if wp * 32 <= dim:
                    self.prefix_words = wp
        from weaviate_tpu.ops.pallas_kernels import recommended

        self.use_pallas = recommended()
        self._lock = threading.RLock()
        self._count = 0
        # HBM ledger wiring — same pattern as DeviceVectorStore: labels
        # captured from the ambient owner scope, entries updated across
        # grows, finalizer-released when the store is dropped
        self._hbm_owner = hbm_ledger.current_owner()
        self._hbm_keys: dict[str, int] = {}
        weakref.finalize(self, hbm_ledger.ledger.release_many,
                         self._hbm_keys.values())
        self.capacity = self._align(capacity)
        self._valid_np = np.zeros(self.capacity, dtype=bool)
        self._host_vectors = (
            np.zeros((self.capacity, dim), dtype=np.float32)
            if rescore == "host" else None
        )
        self._alloc_codes()

    # -- internals -----------------------------------------------------------

    def _align(self, capacity: int) -> int:
        capacity = max(capacity, 2 * self.n_shards)
        capacity = _next_pow2(capacity)
        cs = max(1, min(self.chunk_size, capacity // self.n_shards))
        return shardable_capacity(capacity, self.n_shards, cs)

    def _placed(self, arr, dim=0):
        if self.mesh is None:
            return jnp.asarray(arr)
        from weaviate_tpu.parallel.sharded_search import shard_array

        return shard_array(jnp.asarray(arr), self.mesh, dim=dim)

    def _placed_replicated(self, arr):
        if self.mesh is None:
            return jnp.asarray(arr)
        from weaviate_tpu.parallel.sharded_search import replicate_array

        return replicate_array(jnp.asarray(arr), self.mesh)

    def _code_width(self) -> int:
        if self.quantization == "pq":
            return self.pq_segments
        return bq_ops.bq_words(self.dim)

    def _code_dtype(self):
        return jnp.uint8 if self.quantization == "pq" else jnp.uint32

    def _zeros(self, shape, dtype):
        if self.mesh is None:
            return jnp.zeros(shape, dtype)
        from weaviate_tpu.parallel.sharded_search import sharded_zeros

        return sharded_zeros(shape, dtype, self.mesh)

    def _alloc_codes(self):
        w = self._code_width()
        self.codes = self._zeros((self.capacity, w), self._code_dtype())
        self.prefix_t = (
            jnp.zeros((self.prefix_words, self.capacity), jnp.uint32)
            if self.prefix_words else None
        )
        if self._valid_np.any():
            self.valid = self._placed(jnp.asarray(self._valid_np))
        else:
            self.valid = self._zeros((self.capacity,), jnp.bool_)
        self.rescore_rows = (
            self._zeros((self.capacity, self.dim), jnp.bfloat16)
            if self.rescore == "device" else None
        )
        self._hbm_sync()

    def _hbm_sync(self):
        """Publish the device footprint per component: codes (+valid),
        the transposed prefix, bf16 rescore rows, and the PQ codebook."""
        sharding = "sharded" if self.mesh is not None else "single"

        def _set(component, nbytes, dtype=None):
            hbm_ledger.ledger.set_keyed(
                self._hbm_keys, component + self.hbm_component_suffix,
                nbytes, owner=self._hbm_owner,
                dtype=dtype, sharding=sharding)

        _set("codes", int(self.codes.nbytes) + int(self.valid.nbytes),
             dtype=jnp.dtype(self._code_dtype()).name)
        _set("prefix",
             0 if self.prefix_t is None else int(self.prefix_t.nbytes),
             dtype="uint32")
        _set("rescore_rows",
             0 if self.rescore_rows is None
             else int(self.rescore_rows.nbytes), dtype="bfloat16")
        _set("codebook",
             0 if self.codebook is None
             else int(np.asarray(self.codebook.centroids).nbytes),
             dtype="float32")

    def _encode(self, vectors: np.ndarray) -> np.ndarray:
        if self.quantization == "pq":
            if self.codebook is None:
                raise RuntimeError("PQ store not trained; call train() first")
            return pq_ops.pq_encode(self.codebook, vectors)
        (codes,) = tracing.d2h(bq_ops.bq_encode(jnp.asarray(vectors)))
        return codes

    def _maybe_norm(self, vectors: np.ndarray) -> np.ndarray:
        if self.normalize_on_add:
            return normalize_np(vectors)
        return vectors

    # -- training ------------------------------------------------------------

    @property
    def trained(self) -> bool:
        return self.quantization == "bq" or self.codebook is not None

    def train(self, vectors: np.ndarray | None = None, iters: int = 8, seed: int = 0):
        """Fit the PQ codebook (on given vectors or current live contents)
        and (re-)encode everything stored so far."""
        if self.quantization == "bq":
            return
        with self._lock:
            if vectors is None:
                live = np.nonzero(self._valid_np)[0]
                vectors = self._vectors_for(live)
            vectors = self._maybe_norm(np.asarray(vectors, dtype=np.float32))
            self.codebook = pq_ops.pq_fit(
                vectors, m=self.pq_segments, k=self.pq_centroids,
                iters=iters, seed=seed,
            )
            self._reencode_all()
            self._hbm_sync()

    def _vectors_for(self, slots: np.ndarray) -> np.ndarray:
        """Full-precision rows for given slots from whichever tier has them."""
        return self._tier_vectors(self._host_vectors, self.rescore_rows,
                                  self.fetch_fn, slots)

    @staticmethod
    def _tier_vectors(host_vectors, rescore_rows, fetch_fn,
                      slots: np.ndarray) -> np.ndarray:
        """Tier pick shared by the live path (``_vectors_for``) and the
        async finish step's dispatch-time snapshot."""
        if host_vectors is not None:
            return host_vectors[slots]
        if rescore_rows is not None:
            return np.asarray(
                rescore_rows[jnp.asarray(slots)], dtype=np.float32)
        if fetch_fn is not None:
            return np.asarray(fetch_fn(slots), dtype=np.float32)
        raise RuntimeError(
            "no full-precision tier (rescore='none', no fetch_fn) — "
            "train() needs explicit vectors")

    def _reencode_all(self, batch: int = 262144):
        live = np.nonzero(self._valid_np)[0]
        for s in range(0, len(live), batch):
            sl = live[s:s + batch]
            rows = self._vectors_for(sl)
            # rows ride along so _write_codes can (re-)derive the PQ sign
            # prefix — a train() AFTER add() must not leave prefix_t zeroed
            self._write_codes(sl, self._encode(rows), rows=rows)

    # -- mutation ------------------------------------------------------------

    def add(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        m = len(vectors)
        with self._lock:
            slots = np.arange(self._count, self._count + m, dtype=np.int64)
            self._count += m
            if self._count > self.capacity:
                self._grow(self._count)
            self._write(slots, vectors)
            return slots

    def set_at(self, slots, vectors: np.ndarray):
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        vectors = np.asarray(vectors, dtype=np.float32)
        with self._lock:
            if len(slots) and int(slots.max()) >= self.capacity:
                self._grow(int(slots.max()) + 1)
            self._count = max(self._count, int(slots.max()) + 1 if len(slots) else 0)
            self._write(slots, vectors)

    def _write(self, slots: np.ndarray, vectors: np.ndarray):
        vectors = self._maybe_norm(vectors)
        if self._host_vectors is not None:
            self._host_vectors[slots] = vectors
        self._valid_np[slots] = True
        codes = self._encode(vectors) if self.trained else None
        self._write_codes(slots, codes, rows=vectors)

    def _write_codes(self, slots: np.ndarray, codes: np.ndarray | None,
                     rows: np.ndarray | None, pref: np.ndarray | None = None):
        """Scatter codes (and bf16 rescore rows) into the device arrays,
        donated in place; padding to pow2 buckets bounds compiled variants."""
        if (pref is None and rows is not None and self.prefix_words
                and self.quantization == "pq" and codes is not None):
            # PQ prefix comes from the raw vectors' sign bits, not the
            # codes (the BQ store slices its own codes instead); derived
            # here so every write path — add, re-encode after train,
            # restore-from-vectors — carries it
            (pref,) = tracing.d2h(bq_ops.bq_encode(
                jnp.asarray(np.asarray(rows)[:, :self.prefix_words * 32])))
        m = len(slots)
        if m == 0:
            return
        bucket = _next_pow2(max(m, 8))
        slot_buf = np.zeros(bucket, dtype=np.int32)
        slot_buf[:m] = slots
        mask = np.zeros(bucket, dtype=bool)
        mask[:m] = True
        slot_dev = self._placed_replicated(slot_buf)
        mask_dev = self._placed_replicated(mask)
        if codes is not None:
            w = self._code_width()
            cbuf = np.zeros((bucket, w), dtype=np.asarray(codes).dtype)
            cbuf[:m] = codes
            self.codes, self.valid = _scatter_codes(
                self.codes, self.valid, slot_dev,
                self._placed_replicated(cbuf), mask_dev)
            if self.prefix_t is not None:
                if self.quantization == "bq":
                    pcols = cbuf[:, :self.prefix_words].T.copy()
                else:
                    pbuf = np.zeros((bucket, self.prefix_words),
                                    dtype=np.uint32)
                    if pref is not None:
                        pbuf[:m] = pref[:, :self.prefix_words]
                    pcols = pbuf.T.copy()
                self.prefix_t = _scatter_prefix(
                    self.prefix_t, slot_dev, jnp.asarray(pcols), mask_dev)
        else:
            # mask-redirect padding entries like _scatter_codes does —
            # a bare scatter of the zero-padded slot buffer would mark
            # slot 0 valid on every write
            self.valid = _set_valid(self.codes, self.valid, slot_dev,
                                    mask_dev)
        if self.rescore_rows is not None and rows is not None:
            rbuf = np.zeros((bucket, self.dim), dtype=np.float32)
            rbuf[:m] = rows
            self.rescore_rows = _scatter_rescore(
                self.rescore_rows, slot_dev,
                self._placed_replicated(rbuf), mask_dev)

    def _grow(self, min_capacity: int):
        """Capacity-double codes/valid/mirrors. Caller holds ``_lock``."""
        new_cap = self._align(_next_pow2(min_capacity))
        if new_cap <= self.capacity:
            return
        old_cap = self.capacity
        pad = new_cap - old_cap
        grown_m = np.zeros(new_cap, dtype=bool)
        grown_m[:old_cap] = self._valid_np
        self._valid_np = grown_m
        if self._host_vectors is not None:
            grown_v = np.zeros((new_cap, self.dim), dtype=np.float32)
            grown_v[:old_cap] = self._host_vectors
            self._host_vectors = grown_v
        from weaviate_tpu.parallel.sharded_search import grow_rows

        self.capacity = new_cap
        self.codes = grow_rows(self.codes, pad, self.mesh)
        self.valid = grow_rows(self.valid, pad, self.mesh)
        if self.rescore_rows is not None:
            self.rescore_rows = grow_rows(self.rescore_rows, pad, self.mesh)
        if self.prefix_t is not None:
            self.prefix_t = jnp.pad(self.prefix_t, ((0, 0), (0, pad)))
        self._hbm_sync()

    def set_at_prenormalized(self, slots, vectors: np.ndarray):
        """set_at for vectors already normalized at their original insert
        (restore/compact/compress paths) — skips re-normalization."""
        orig = self.normalize_on_add
        self.normalize_on_add = False
        try:
            self.set_at(slots, vectors)
        finally:
            self.normalize_on_add = orig

    def delete(self, slots) -> None:
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        if len(slots) == 0:
            return
        with self._lock:
            self._valid_np[slots] = False
            m = len(slots)
            bucket = _next_pow2(max(m, 8))
            buf = np.full(bucket, self.capacity + 1, dtype=np.int32)  # OOB no-op
            buf[:m] = slots
            self.valid = _clear_valid(self.valid, self._placed_replicated(buf))

    # -- queries -------------------------------------------------------------

    @property
    def count(self) -> int:
        return self._count

    def live_count(self) -> int:
        return int(self._valid_np.sum())

    def get(self, slots) -> np.ndarray:
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        return self._vectors_for(slots).copy()

    def _scan(self, queries_dev, k_cand: int, valid, k_out: int,
              allow_bits=None, allow_rows=None):
        """Dispatch the compressed scan (single-device or SPMD).

        ``allow_bits`` ([B, C/32] uint32 packed per-query masks) feeds the
        single-device kernels; ``allow_rows`` ([B, C] bool, column-sharded)
        feeds the SPMD path, which packs each shard's slice on device."""
        capacity = self.capacity
        cs = min(self.chunk_size, capacity // self.n_shards)
        metric = "cosine" if self.metric in ("cosine", "cosine-dot") else self.metric
        if self.quantization == "pq":
            quant_key = "pq4" if self.pq_centroids <= 16 else "pq"
            cent = self.codebook.centroids
            qw = None
        else:
            quant_key = "bq"
            cent = None
            qw = bq_ops.bq_encode(queries_dev)
        if self.mesh is not None:
            from weaviate_tpu.parallel.sharded_search import (
                sharded_quantized_topk,
            )

            per_dev_k = min(k_cand, capacity // self.n_shards)
            return sharded_quantized_topk(
                queries_dev, qw, self.codes, valid, self.rescore_rows, cent,
                k=per_dev_k, k_out=k_out, chunk_size=cs,
                quantization=quant_key, metric=metric, mesh=self.mesh,
                use_pallas=self.use_pallas, selection=self.selection,
                allow_rows=allow_rows,
            )
        if quant_key in ("pq4", "pq"):
            if self.prefix_t is not None:
                qp = bq_ops.bq_encode(
                    queries_dev[:, :self.prefix_words * 32])
                return pq_ops.pq_topk_twostage(
                    queries_dev, qp, self.codes, cent, self.prefix_t,
                    k=k_cand, refine=max(2, self.rescore_limit // 2),
                    metric=metric, valid=valid, m=self.pq_segments,
                    use_pallas=self.use_pallas, selection=self.selection,
                    allow_bits=allow_bits,
                )
            if quant_key == "pq4":
                return pq_ops.pq4_topk(
                    queries_dev, self.codes, cent, k=k_cand, chunk_size=cs,
                    metric=metric, valid=valid, selection=self.selection,
                    allow_bits=allow_bits,
                )
            return pq_ops.pq_topk(
                queries_dev, self.codes, cent, k=k_cand, chunk_size=cs,
                metric=metric, valid=valid, allow_bits=allow_bits,
            )
        if self.prefix_t is not None:
            return bq_ops.bq_topk_twostage(
                qw, self.codes, self.prefix_t, k=k_cand,
                refine=max(2, self.rescore_limit // 2), valid=valid,
                use_pallas=self.use_pallas, selection=self.selection,
                allow_bits=allow_bits,
            )
        return bq_ops.bq_topk(
            qw, self.codes, k=k_cand, chunk_size=cs, valid=valid,
            use_pallas=self.use_pallas, selection=self.selection,
            allow_bits=allow_bits,
        )

    def rescore_mode(self) -> str:
        """Where the exact rescore happens for this store's config:
        ``"inline"`` (inside the SPMD program, distances already exact),
        ``"plane"`` (single-device bf16 rows: the oversampled candidates
        rescore ON DEVICE through the shared candidate plane — the epoch
        store treats this like ``"post"`` because its candidates span
        per-epoch tier snapshots), ``"post"`` (oversampled candidates
        come back for a host rescore), or ``"none"`` (code-distance
        order is the contract)."""
        if self.rescore == "device" and self.mesh is not None:
            return "inline"
        if self.rescore == "device" and self.rescore_rows is not None:
            return "plane"
        if (self._host_vectors is not None
                or (self.rescore == "device" and self.mesh is None)
                or (self.rescore == "none" and self.fetch_fn is not None)):
            return "post"
        return "none"

    def epoch_scan(self, queries: np.ndarray, k_cand: int, k_out: int,
                   allow_mask: np.ndarray | None = None,
                   pre_normalized: bool = False):
        """Dispatch-only compressed scan for the epoch store: candidates
        stay device-resident with STORE-LOCAL ids for the cross-epoch
        merge; the (single, global) host rescore runs in the epoch
        store's finish step against the returned dispatch-time tier
        snapshot. ``pre_normalized`` skips query normalization when the
        epoch store already normalized once for every epoch (normalizing
        per epoch would not be bit-identical to the single-store path).
        Returns ``(d_dev, i_dev, tiers)``."""
        from weaviate_tpu.engine.store import (batched_mask_operands,
                                               normalize_allow_mask)

        queries = np.asarray(queries, dtype=np.float32)
        if not pre_normalized:
            queries = self._maybe_norm(queries)
        allow_mask = normalize_allow_mask(allow_mask, len(queries))
        with self._lock:
            if not self.trained:
                raise RuntimeError("PQ store not trained; call train() first")
            capacity = self.capacity
            valid = self.valid
            allow_bits = allow_rows_dev = None
            if allow_mask is not None and allow_mask.ndim == 2:
                allow_bits, allow_rows_dev = batched_mask_operands(
                    allow_mask, len(queries), capacity, self.mesh,
                    owner=self._hbm_owner)
            elif allow_mask is not None:
                full = np.zeros(capacity, dtype=bool)
                w = min(len(allow_mask), capacity)
                full[:w] = allow_mask[:w]
                valid = jnp.logical_and(valid, self._placed(full))
            d, i = self._scan(jnp.asarray(queries), min(k_cand, capacity),
                              valid, min(k_out, capacity),
                              allow_bits=allow_bits,
                              allow_rows=allow_rows_dev)
            tiers = (self._host_vectors, self.rescore_rows, self.fetch_fn)
        return d, i, tiers

    def search(self, queries: np.ndarray, k: int, allow_mask: np.ndarray | None = None):
        """Two-stage: compressed scan (oversampled) -> exact rescore.

        Reference BQ rescore: flat/index.go:347; oversampling factor =
        ``rescore_limit`` (*k candidates pulled from the compressed scan).
        In ``rescore="device"`` mode the rescore happens inside the SPMD
        program on the owning device; in ``"host"`` (or ``"none"`` +
        ``fetch_fn``) the oversampled candidates come back to the host for
        a vectorized exact rescore; plain ``"none"`` returns code-distance
        order directly.

        ``allow_mask`` accepts the same two forms as
        ``DeviceVectorStore.search``: a shared [capacity] bool mask, or
        per-query [B, capacity] masks packed into a bitmask consumed
        inside the compressed scan kernels (disallowed rows never even
        become rescore candidates).

        Like the plain store, this is ``search_async(...).result()`` —
        the D2H transfer (and host rescore, which needs host
        candidates) rides the handle's finish step.
        """
        return self.search_async(queries, k, allow_mask).result()

    def search_async(self, queries: np.ndarray, k: int,
                     allow_mask: np.ndarray | None = None
                     ) -> DeviceResultHandle:
        """Dispatch-only twin of ``search``: the compressed scan
        launches under ``_lock``; the oversampled candidates stay
        device-resident in the returned handle, whose finish step runs
        the exact host rescore (when this store's rescore mode needs
        one) after the boundary transfer."""
        from weaviate_tpu.engine.store import normalize_allow_mask

        queries = np.asarray(queries, dtype=np.float32)
        squeeze = queries.ndim == 1
        if squeeze:
            queries = queries[None, :]
        queries = self._maybe_norm(queries)
        allow_mask = normalize_allow_mask(allow_mask, len(queries))
        # inline = exact rescore happens inside the SPMD program; post =
        # oversampled candidates come back for a host-side exact pass
        # (sourced from host rows, single-device HBM rows, or fetch_fn).
        # ONE classifier (rescore_mode) serves this and the epoch-store
        # dispatch so the two paths can never drift.
        mode = self.rescore_mode()
        inline_rescore = mode == "inline"
        plane_rescore = mode == "plane"
        post_rescore = mode == "post"
        with tracing.span("store.quantized_scan", rows=self.capacity,
                          queries=len(queries), k=k,
                          quantization=self.quantization,
                          sharded=self.mesh is not None) as sp:
            with self._lock:
                if not self.trained:
                    raise RuntimeError(
                        "PQ store not trained; call train() first")
                capacity = self.capacity
                valid = self.valid
                allow_bits = allow_rows_dev = None
                if allow_mask is not None and allow_mask.ndim == 2:
                    from weaviate_tpu.engine.store import (
                        batched_mask_operands)

                    sp.set(path="bitmask_batched")
                    allow_bits, allow_rows_dev = batched_mask_operands(
                        allow_mask, len(queries), capacity, self.mesh,
                        owner=self._hbm_owner)
                elif allow_mask is not None:
                    full = np.zeros(capacity, dtype=bool)
                    full[: len(allow_mask)] = allow_mask[:capacity]
                    valid = jnp.logical_and(valid, self._placed(full))
                if inline_rescore:
                    k_cand = min(max(k * self.rescore_limit, k), capacity)
                    k_out = min(k, capacity)
                elif post_rescore or plane_rescore:
                    k_cand = min(max(k * self.rescore_limit, k), capacity)
                    k_out = k_cand
                else:
                    k_cand = min(k, capacity)
                    k_out = k_cand
                # EXPLAIN: host ints only (no device reads), a no-op
                # when nobody asked — the rescore plan of this dispatch
                kernelscope.explain_note(
                    "quantized", quantization=str(self.quantization),
                    rescore_mode=mode, k_cand=k_cand, rows=capacity,
                    queries=len(queries), k=k,
                    path=("bitmask_batched" if allow_bits is not None
                          else "shared_mask" if allow_mask is not None
                          else "full_scan"))
                d, i = self._scan(jnp.asarray(queries), k_cand, valid,
                                  k_out, allow_bits=allow_bits,
                                  allow_rows=allow_rows_dev)
                if plane_rescore:
                    # oversampled candidates rescore ON DEVICE against
                    # the bf16 rescore rows through the shared candidate
                    # plane — the full-precision tier is already in HBM,
                    # so the old host gather roundtrip buys nothing
                    sp.set(path="device_plane_rescore")
                    metric = ("cosine"
                              if self.metric in ("cosine", "cosine-dot")
                              else self.metric)
                    d, i = gather_rescore_topk(
                        jnp.asarray(queries), i.astype(jnp.int32),
                        self.rescore_rows, min(k, k_out), metric)
                # dispatch-time snapshot for the finish step's rescore:
                # the scan's candidate slot-ids are only meaningful
                # against THIS capacity/row layout — compact()/_grow()
                # replace the full-precision tiers wholesale, and with
                # the pipelined drain the dispatch->finish window is a
                # whole overlapped batch, not microseconds
                rescore_tiers = (self._host_vectors, self.rescore_rows,
                                 self.fetch_fn)
        # materialization + host rescore live in the handle's finish
        # step: the candidates cross D2H at the API boundary (or on the
        # serving pipeline's transfer thread), never under the lock

        def _finish(d_np, i_np, _queries=queries, _k=k, _squeeze=squeeze,
                    _post=post_rescore, _cap=capacity,
                    _tiers=rescore_tiers):
            i_np = i_np.astype(np.int64, copy=False)
            if _post:
                with tracing.span("store.host_rescore",
                                  candidates=int(i_np.shape[1])):
                    d_np, i_np = self._host_rescore(
                        _queries, i_np, _k, capacity=_cap,
                        vectors_for=lambda s: self._tier_vectors(
                            *_tiers, s))
            out_d = d_np[:, :_k].astype(np.float32)
            out_i = i_np[:, :_k]
            if _squeeze:
                return out_d[0], out_i[0]
            return out_d, out_i

        return DeviceResultHandle(
            (d, i), finish=_finish,
            attrs={"rows": capacity, "queries": len(queries), "k": k,
                   "quantization": self.quantization})

    def _host_rescore(self, queries: np.ndarray, cand_ids: np.ndarray,
                      k: int, capacity: int | None = None,
                      vectors_for=None):
        """Vectorized exact rescore: one gather + one batched distance over
        [B, k_cand, d] (no per-query Python loop). ``capacity`` /
        ``vectors_for`` pin the row layout the candidate ids were scanned
        against (the async finish step passes its dispatch-time
        snapshot); defaults read the live store."""
        b, kc = cand_ids.shape
        cap = self.capacity if capacity is None else capacity
        safe = np.clip(cand_ids, 0, cap - 1)
        # the tier pick (host rows -> device bf16 rows -> fetch_fn)
        cand = ((vectors_for or self._vectors_for)(
            safe.reshape(-1))).reshape(b, kc, self.dim)
        metric = "cosine" if self.metric in ("cosine", "cosine-dot") else self.metric
        if metric == "dot":
            dd = -np.einsum("bd,bkd->bk", queries, cand)
        elif metric == "cosine":
            dd = 1.0 - np.einsum("bd,bkd->bk", queries, cand)
        else:
            diff = queries[:, None, :] - cand
            dd = np.einsum("bkd,bkd->bk", diff, diff)
        dd = np.where(cand_ids >= 0, dd, np.float32(3.0e38))
        k_eff = min(k, kc)
        part = np.argpartition(dd, k_eff - 1, axis=1)[:, :k_eff]
        pd = np.take_along_axis(dd, part, axis=1)
        order = np.argsort(pd, axis=1, kind="stable")
        sel = np.take_along_axis(part, order, axis=1)
        out_d = np.take_along_axis(dd, sel, axis=1).astype(np.float32)
        out_i = np.take_along_axis(cand_ids, sel, axis=1)
        out_i = np.where(out_d >= np.float32(3.0e38), -1, out_i)
        return out_d, out_i

    def search_by_distance(self, query: np.ndarray, max_distance: float,
                           allow_mask: np.ndarray | None = None):
        k = min(64, self.capacity)
        while True:
            d, i = self.search(query, k, allow_mask)
            within = d <= max_distance
            if (~within).any() or k >= self.capacity or within.sum() >= self.live_count():
                return d[within], i[within]
            k = min(k * 4, self.capacity)

    # -- maintenance / persistence -------------------------------------------

    def compact(self) -> np.ndarray:
        with tracing.span("store.compact", rows=self.capacity,
                          quantization=self.quantization), self._lock:
            live = np.nonzero(self._valid_np)[0]
            mapping = np.full(self.capacity, -1, dtype=np.int64)
            mapping[live] = np.arange(len(live))
            vecs = self._vectors_for(live) if len(live) else np.zeros(
                (0, self.dim), np.float32)
            self._count = 0
            self.capacity = self._align(max(len(live), 1))
            self._valid_np = np.zeros(self.capacity, dtype=bool)
            if self._host_vectors is not None:
                self._host_vectors = np.zeros(
                    (self.capacity, self.dim), dtype=np.float32)
            self._alloc_codes()
            if len(live):
                self.set_at_prenormalized(np.arange(len(live)), vecs)
            return mapping

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "valid": self._valid_np.copy(),
                "count": self._count,
                "dim": self.dim,
                "metric": self.metric,
                "quantization": self.quantization,
                "pq_segments": self.pq_segments,
                "pq_centroids": self.pq_centroids,
                "rescore_limit": self.rescore_limit,
                "rescore": self.rescore,
                "selection": self.selection,
                "prefix_bits": self.prefix_words * 32,
                "chunk_size": self.chunk_size,
                "codebook": (
                    None if self.codebook is None
                    else np.asarray(self.codebook.centroids)
                ),
            }
            if self._host_vectors is not None:
                snap["vectors"] = self._host_vectors.copy()
            elif self.rescore == "device":
                snap["vectors"] = np.asarray(
                    self.rescore_rows, dtype=np.float32)
            else:
                snap["codes"] = np.asarray(self.codes)
                if self.prefix_t is not None and self.quantization == "pq":
                    # PQ prefixes derive from the raw vectors — a
                    # codes-only snapshot must carry them explicitly
                    snap["prefix_t"] = np.asarray(self.prefix_t)
            return snap

    @classmethod
    def restore(cls, snap: dict, mesh=None, **kwargs) -> "QuantizedVectorStore":
        kwargs.setdefault("rescore", snap.get("rescore", "host"))
        kwargs.setdefault("selection", snap.get("selection", "approx"))
        if snap.get("prefix_bits"):
            kwargs.setdefault("prefix_bits", snap["prefix_bits"])
        store = cls(
            dim=snap["dim"],
            metric=snap["metric"],
            quantization=snap["quantization"],
            capacity=max(len(snap["valid"]), 2),
            chunk_size=snap["chunk_size"],
            pq_segments=snap["pq_segments"],
            pq_centroids=snap["pq_centroids"],
            rescore_limit=snap["rescore_limit"],
            mesh=mesh,
            **kwargs,
        )
        if snap.get("codebook") is not None:
            store.codebook = pq_ops.PQCodebook(jnp.asarray(snap["codebook"]))
        live = np.nonzero(snap["valid"])[0]
        if len(live):
            if "vectors" in snap:
                store.set_at_prenormalized(live, snap["vectors"][live])
            else:
                # codes-only snapshot: restore codes directly
                store._valid_np[live] = True
                store._write_codes(live, snap["codes"][live], rows=None)
                if snap.get("prefix_t") is not None \
                        and store.prefix_t is not None:
                    pt = snap["prefix_t"]
                    store.prefix_t = jnp.asarray(np.pad(
                        pt, ((0, 0),
                             (0, store.capacity - pt.shape[1]))))
        store._count = snap["count"]
        store._hbm_sync()  # codebook/prefix set after __init__'s sync
        return store
