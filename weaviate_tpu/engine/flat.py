"""Flat (brute-force) vector index — the TPU-native first-class citizen.

Reference: adapters/repos/db/vector/flat/index.go (lsmkv cursor full scan,
index.go:319). Here the full scan is the MXU's favourite workload: one
batched distance matmul over the HBM-resident corpus per chunk, fused with
a running top-k. On a v5e-8 row-sharded mesh the same call runs SPMD with an
ICI all_gather merge.

Doc-id mapping: callers address vectors by external int64 doc ids (the shard
layer maps UUIDs → doc ids, as the reference does in adapters/repos/db/docid).
Internally ids map to store slots; tombstoned slots are reclaimed by
``compact()``.
"""

from __future__ import annotations

import threading

import numpy as np

from weaviate_tpu import native
from weaviate_tpu.engine.store import DeviceVectorStore
from weaviate_tpu.runtime import kernelscope, tracing
from weaviate_tpu.runtime.transfer import DeviceResultHandle


def _per_query_allow(allow_list) -> bool:
    """True when ``allow_list`` is a sequence of PER-QUERY allow lists
    (entries None or array-like) rather than one shared filter. A plain
    Python list of scalar doc ids — including the empty list (a filter
    matching nothing) — keeps its historical shared-filter meaning."""
    if not isinstance(allow_list, (list, tuple)) or len(allow_list) == 0:
        return False
    return any(a is None or np.ndim(a) > 0 for a in allow_list)


class FlatIndex:
    """Implements the reference ``VectorIndex`` contract
    (adapters/repos/db/vector_index.go:24-45) for brute-force search.

    ``selection`` picks the scan's top-k strategy ("approx" | "exact" |
    "fused" — ops/topk.chunked_topk_distances docstring); "fused" runs
    selection inside the Pallas scan kernel so distances never round-trip
    through HBM. With ``quantization`` set it passes through to the
    quantized store's SURVIVOR selection, which supports "approx" and
    "fused" only (the compressed scan itself is always the scan-reduce
    kernel) and falls back to approx when rescore_limit*k exceeds the
    256-wide fused carry."""

    index_type = "flat"
    # the batched entry point accepts PER-QUERY allow lists (a sequence in
    # the allow_list slot) and runs them as one bitmask-batched device
    # program — the QueryBatcher keys on this to coalesce filtered
    # requests instead of dispatching them solo
    supports_batched_filters = True
    # device scans compile one executable per (B, k) shape — the batcher
    # pads drains to pow2 buckets to bound the variant count
    compiled_batch_shapes = True

    def __init__(self, dim: int, metric: str = "l2-squared", mesh=None,
                 dtype=None, capacity: int = 8192, chunk_size: int = 8192,
                 quantization: str | None = None, store=None,
                 selection: str = "approx", epoch_rows: int = 0,
                 **quant_kwargs):
        import jax.numpy as jnp

        self.dim = dim
        self.metric = metric
        if store is not None:
            # injected store (IVFIndex subclass passes an IVFStore; the
            # id<->slot bookkeeping below is store-agnostic)
            self.store = store
        elif epoch_rows:
            # epoch-stacked device corpus (engine/epochs.py): writes land
            # in a small active epoch, sealed epochs are immutable,
            # tombstone-heavy ones compact in the background and the
            # coldest can migrate to a sibling shard under HBM pressure
            from weaviate_tpu.engine.epochs import EpochStore

            self.store = EpochStore(
                dim=dim, metric=metric, epoch_rows=epoch_rows,
                capacity=capacity, dtype=dtype, mesh=mesh,
                chunk_size=chunk_size, selection=selection,
                quantization=quantization,
                quant_kwargs=quant_kwargs or None)
        elif quantization:
            from weaviate_tpu.engine.quantized import QuantizedVectorStore

            self.store = QuantizedVectorStore(
                dim=dim, metric=metric, quantization=quantization,
                capacity=capacity, chunk_size=chunk_size, mesh=mesh,
                selection=selection, **quant_kwargs,
            )
        else:
            if quant_kwargs:
                raise TypeError(
                    f"unexpected kwargs without quantization: {sorted(quant_kwargs)}"
                )
            self.store = DeviceVectorStore(
                dim=dim,
                metric=metric,
                capacity=capacity,
                dtype=dtype or jnp.float32,
                mesh=mesh,
                chunk_size=chunk_size,
                selection=selection,
            )
        self._lock = threading.RLock()
        self._id_to_slot: dict[int, int] = {}
        self._slot_to_id: np.ndarray = np.full(self.store.capacity, -1, dtype=np.int64)

    # -- VectorIndex contract -------------------------------------------------

    def add(self, doc_id: int, vector: np.ndarray) -> None:
        self.add_batch([doc_id], np.asarray(vector)[None, :])

    def add_batch(self, doc_ids, vectors: np.ndarray) -> None:
        """Insert or update a batch (reference AddBatch, vector_index.go:26).

        Re-adding an existing id overwrites its vector in place."""
        doc_ids = np.asarray(doc_ids, dtype=np.int64)
        vectors = np.asarray(vectors, dtype=np.float32)
        if len(doc_ids) != len(vectors):
            raise ValueError(f"{len(doc_ids)} ids != {len(vectors)} vectors")
        # dedupe within the batch, last occurrence wins — otherwise one id
        # would allocate two live slots and corrupt the id<->slot mapping
        if len(doc_ids) != len(set(doc_ids.tolist())):
            last = {int(i): idx for idx, i in enumerate(doc_ids.tolist())}
            keep = sorted(last.values())
            doc_ids, vectors = doc_ids[keep], vectors[keep]
        with self._lock:
            existing = np.array([i in self._id_to_slot for i in doc_ids.tolist()])
            if existing.any():
                upd_slots = np.array(
                    [self._id_to_slot[int(i)] for i in doc_ids[existing]],
                    dtype=np.int64,
                )
                self.store.set_at(upd_slots, vectors[existing])
            fresh = ~existing
            if fresh.any():
                slots = self.store.add(vectors[fresh])
                self._ensure_slot_map()
                for i, s in zip(doc_ids[fresh].tolist(), slots.tolist()):
                    self._id_to_slot[int(i)] = int(s)
                    self._slot_to_id[int(s)] = int(i)

    def _ensure_slot_map(self):
        """Grow the slot->id reverse map with store capacity. Caller
        holds ``_lock``."""
        if len(self._slot_to_id) < self.store.capacity:
            grown = np.full(self.store.capacity, -1, dtype=np.int64)
            grown[: len(self._slot_to_id)] = self._slot_to_id
            self._slot_to_id = grown

    def delete(self, *doc_ids) -> None:
        """Tombstone docs (reference Delete, vector_index.go:28)."""
        with self._lock:
            slots = [self._id_to_slot.pop(int(i)) for i in doc_ids
                     if int(i) in self._id_to_slot]
            if slots:
                self._slot_to_id[slots] = -1
                self.store.delete(np.asarray(slots))

    def contains(self, doc_id: int) -> bool:
        return int(doc_id) in self._id_to_slot

    def __len__(self) -> int:
        return len(self._id_to_slot)

    def search_by_vector(self, query: np.ndarray, k: int,
                         allow_list: np.ndarray | None = None):
        """Top-k by vector (reference SearchByVector, vector_index.go:29).

        ``allow_list``: bool mask over doc-id space or array of allowed doc
        ids (the reference's roaring-bitmap AllowList). Returns
        (doc_ids [<=k] int64, dists [<=k] f32), ascending.
        """
        # The index lock spans search + id resolution so a concurrent
        # compact() can't remap slots between the scan and _resolve.
        with tracing.span("flat.search", k=k,
                          filtered=allow_list is not None):
            with self._lock:
                allow_mask = self._allow_mask(allow_list)
                d, slots = self.store.search(np.asarray(query), k,
                                             allow_mask)
                return self._resolve(d, slots, k)

    def search_by_vector_batch(self, queries: np.ndarray, k: int,
                               allow_list=None):
        """Batched query path — amortizes one matmul across B queries.

        ``allow_list`` is either ONE allow list shared by the whole batch
        (bool mask over doc-id space or array of allowed doc ids — a
        plain list of scalar ids still means this), or a list/tuple of B
        per-query allow lists (entries None or array-like; None =
        unfiltered). Per-query lists translate to slot masks and run as a
        single bitmask-batched device program (engine/store.py). Returns
        (doc_ids [B,k] int64 with -1 padding, dists [B,k])."""
        queries = np.atleast_2d(np.asarray(queries))
        per_query = _per_query_allow(allow_list)
        with tracing.span("flat.search_batch", k=k, queries=len(queries),
                          filtered=allow_list is not None,
                          per_query_filters=per_query):
            with self._lock:
                kind, allow_mask = self._translate_batch_allow(
                    queries, allow_list, per_query)
                kernelscope.explain_note(
                    "index", kind=str(self.index_type),
                    per_query_filters=bool(per_query),
                    filtered=allow_list is not None,
                    queries=len(queries), k=k)
                if kind == "rowwise":
                    # a store with supports_batched_filters=False takes
                    # shared 1-D masks only — serve per-query filters
                    # row by row rather than crashing on a 2-D mask
                    # (IVF now takes the batched bitmask path above)
                    d = np.full((len(queries), k), np.float32(np.inf),
                                dtype=np.float32)
                    slots = np.full((len(queries), k), -1,
                                    dtype=np.int64)
                    for r, m in enumerate(allow_mask):
                        dr, sr = self.store.search(
                            queries[r:r + 1], k, m)
                        kk = min(k, dr.shape[1])
                        d[r, :kk] = dr[0, :kk]
                        slots[r, :kk] = sr[0, :kk]
                    ids = np.where(slots >= 0,
                                   self._slot_to_id_safe(slots), -1)
                    return ids, d
                d, slots = self.store.search(queries, k, allow_mask)
                ids = np.where(slots >= 0, self._slot_to_id_safe(slots),
                               -1)
                return ids, d

    def _translate_batch_allow(self, queries, allow_list, per_query: bool):
        """Allow-list intake shared by the sync and async batch paths.
        Caller holds ``_lock``. Returns ("mask", mask-or-None) for the
        single-dispatch forms, or ("rowwise", per-row masks) when the
        store cannot take a 2-D mask."""
        if not per_query:
            return "mask", self._allow_mask(allow_list)
        if len(allow_list) != len(queries):
            raise ValueError(
                f"{len(allow_list)} allow lists != "
                f"{len(queries)} queries")
        masks = [self._allow_mask(a) for a in allow_list]
        if all(m is None for m in masks):
            return "mask", None
        if not self.supports_batched_filters:
            return "rowwise", masks
        # unfiltered rows get an all-ones mask (the store still ANDs
        # with its live-slot validity)
        allow_mask = np.ones((len(masks), self.store.capacity),
                             dtype=bool)
        for r, m in enumerate(masks):
            if m is not None:
                allow_mask[r, :] = False
                allow_mask[r, :len(m)] = m
        return "mask", allow_mask

    def search_by_vector_batch_async(self, queries: np.ndarray, k: int,
                                     allow_list=None):
        """Async twin of ``search_by_vector_batch`` (ISSUE 7): dispatch
        under the index lock, results device-resident in the returned
        ``DeviceResultHandle`` (resolving to the same (doc_ids [B,k],
        dists [B,k]) contract). Returns ``None`` when this index cannot
        serve the request async — injected stores without
        ``search_async``, or per-query filters on stores without
        batched-filter support (the IVF store now provides both) — and
        the caller falls back to the sync path.

        The slot -> doc-id resolution in the finish step runs against
        the ``_slot_to_id`` table captured AT DISPATCH: ``compact()``
        replaces the array wholesale, so an in-flight handle keeps the
        mapping its scan was dispatched against; a concurrent
        ``delete()`` writes -1 in place, which drops the row at the
        shard layer exactly like the sync path's post-search delete
        race."""
        if not hasattr(self.store, "search_async"):
            return None
        queries = np.atleast_2d(np.asarray(queries))
        per_query = _per_query_allow(allow_list)
        with tracing.span("flat.search_batch", k=k, queries=len(queries),
                          filtered=allow_list is not None,
                          per_query_filters=per_query, dispatch="async"):
            with self._lock:
                kind, allow_mask = self._translate_batch_allow(
                    queries, allow_list, per_query)
                if kind == "rowwise":
                    return None
                # EXPLAIN: index-level plan facts (host ints only; the
                # store layer notes the cutover it actually takes)
                kernelscope.explain_note(
                    "index", kind=str(self.index_type),
                    per_query_filters=bool(per_query),
                    filtered=allow_mask is not None,
                    queries=len(queries), k=k)
                handle = self.store.search_async(queries, k, allow_mask)
                table = self._slot_to_id  # replaced (not resized) by compact

        def _resolve(res, _table=table):
            d, slots = res
            clipped = np.clip(slots, 0, len(_table) - 1)
            ids = np.where(slots >= 0, _table[clipped], -1)
            return ids, d

        return handle.map(_resolve)

    # -- hybrid dataplane (ISSUE 18) ------------------------------------------

    @property
    def supports_device_hybrid(self) -> bool:
        """True when this index can run the fused sparse+dense hybrid
        program: the plain device store only — quantized/epoch/injected
        stores keep the host hybrid path (their async handles don't
        expose raw (dist, slot) arrays in store-slot space)."""
        return type(self.store) is DeviceVectorStore

    def slots_for_doc_ids(self, doc_ids) -> np.ndarray:
        """Store slots for external doc ids (-1 = not in this index) —
        the shard layer translates BM25 candidates with this before
        packing sparse operands."""
        with self._lock:
            return np.asarray(
                [self._id_to_slot.get(int(d), -1) for d in doc_ids],
                dtype=np.int32)

    def hybrid_batch_async(self, queries: np.ndarray, k: int,
                           allow_list=None, sparse_ops=None):
        """One fused device program for a mixed hybrid + pure-vector
        drain: the dense scan dispatches async, its DEVICE-RESIDENT
        (dist, slot) arrays feed straight into the BM25 scoring + fusion
        program (``ops/bm25.py::hybrid_topk``) — one dispatch chain, one
        D2H through the returned handle. ``sparse_ops`` is a per-row
        list of ``SparseOperand`` (None = pure-vector row riding the
        same batch). Returns None when the device hybrid path can't take
        the request (unsupported store, rowwise filters, or a dispatch
        shape whose finish step remaps on the host) — callers fall back
        to the host hybrid path."""
        from weaviate_tpu.ops.bm25 import hybrid_topk, stack_sparse_operands

        if not self.supports_device_hybrid:
            return None
        queries = np.atleast_2d(np.asarray(queries))
        sparse_ops = list(sparse_ops or [None] * len(queries))
        live_ops = [op for op in sparse_ops if op is not None]
        per_query = _per_query_allow(allow_list)
        # dense leg depth: every row's over-fetch must fit so fusion
        # ranks match the host reference; pow2 so the scan compiles per
        # bucket, not per drain
        fetch = max([k] + [int(op.fetch) for op in live_ops])
        f_depth = 1 << max(0, fetch - 1).bit_length()
        with tracing.span("flat.hybrid_batch", k=k, queries=len(queries),
                          hybrid=len(live_ops), dispatch="async"):
            with self._lock:
                kind, allow_mask = self._translate_batch_allow(
                    queries, allow_list, per_query)
                if kind == "rowwise":
                    return None
                if allow_mask is not None and allow_mask.ndim == 1:
                    # force the bitmask-batched dispatch: the gathered
                    # path's finish step remaps slots on the HOST, which
                    # would break the on-device fusion composition
                    shared = np.zeros(self.store.capacity, dtype=bool)
                    shared[:len(allow_mask)] = allow_mask
                    allow_mask = np.broadcast_to(
                        shared, (len(queries), self.store.capacity))
                kernelscope.explain_note(
                    "hybrid", queries=len(queries),
                    hybrid_rows=len(live_ops), k=k, fetch=fetch,
                    terms=int(sum(op.stats.get("terms", 0)
                                  for op in live_ops)),
                    candidates=int(sum(op.stats.get("candidates", 0)
                                       for op in live_ops)),
                    pruned_frac=round(float(np.mean(
                        [op.stats.get("pruned_frac", 0.0)
                         for op in live_ops])), 6) if live_ops else 0.0,
                    fusion_ranked=int(sum(1 for op in live_ops
                                          if op.fusion == 0)),
                    fusion_relative=int(sum(1 for op in live_ops
                                            if op.fusion == 1)))
                handle = self.store.search_async(queries, f_depth,
                                                 allow_mask)
                if (handle.attrs.get("path") != "device"
                        or len(handle.arrays) != 2):
                    return None
                dn_d, dn_i = handle.arrays
                pack = stack_sparse_operands(sparse_ops, len(queries))
                use_pallas = bool(getattr(self.store, "use_pallas",
                                          False))
                d, i = hybrid_topk(dn_d, dn_i, pack, k,
                                   use_pallas=use_pallas)
                table = self._slot_to_id  # replaced wholesale by compact

        def _resolve(d_np, i_np, _table=table):
            clipped = np.clip(i_np, 0, len(_table) - 1)
            ids = np.where(i_np >= 0, _table[clipped], -1)
            return ids, d_np

        return DeviceResultHandle(
            (d, i), finish=_resolve,
            attrs=dict(handle.attrs, hybrid=len(live_ops), k=k))

    def hybrid_batch(self, queries: np.ndarray, k: int, allow_list=None,
                     sparse_ops=None):
        """Sync twin of ``hybrid_batch_async`` (same fused program, the
        D2H just happens inline). Returns None on the same conditions."""
        h = self.hybrid_batch_async(queries, k, allow_list, sparse_ops)
        return None if h is None else h.result()

    def search_by_vector_distance(self, query: np.ndarray, max_distance: float,
                                  allow_list: np.ndarray | None = None):
        """Range search (reference SearchByVectorDistance,
        vector_index.go:31)."""
        with self._lock:
            allow_mask = self._allow_mask(allow_list)
            d, slots = self.store.search_by_distance(np.asarray(query), max_distance,
                                                     allow_mask)
            return self._resolve(d, slots, len(slots))

    # -- helpers --------------------------------------------------------------

    def _allow_mask(self, allow_list):
        if allow_list is None:
            return None
        allow_list = np.asarray(allow_list)
        if allow_list.dtype == np.bool_:
            allow_list = np.nonzero(allow_list)[0]
        with self._lock:
            # vectorized doc-id -> slot translation via the inverse table;
            # a Python-loop of dict lookups here would dominate filtered
            # queries with large allow lists. Binary-search membership runs
            # in the native library (csrc/weaviate_native.cpp).
            table = self._slot_to_id[: self.store.capacity]
            return native.membership(table, np.unique(allow_list))

    def _slot_to_id_safe(self, slots):
        clipped = np.clip(slots, 0, len(self._slot_to_id) - 1)
        return self._slot_to_id[clipped]

    def _resolve(self, d, slots, k):
        live = slots >= 0
        ids = self._slot_to_id_safe(slots)[live]
        return ids[:k], d[live][:k]

    # -- compression ----------------------------------------------------------

    def compress(self, quantization: str = "pq", **quant_kwargs) -> None:
        """Runtime compression: train a quantizer on current contents and swap
        the store (reference: hnsw/compress.go:38, enabled via a config
        update once enough data exists). Slot layout is preserved, so the
        id<->slot mapping carries over untouched."""
        from weaviate_tpu.engine.epochs import EpochStore
        from weaviate_tpu.engine.quantized import QuantizedVectorStore
        from weaviate_tpu.runtime import hbm_ledger

        with self._lock:
            old = self.store
            if isinstance(old, EpochStore):
                if old.quantization:
                    raise RuntimeError("index is already compressed")
                return self._compress_epochs(old, quantization,
                                             **quant_kwargs)
            if isinstance(old, QuantizedVectorStore):
                raise RuntimeError("index is already compressed")
            snap = old.snapshot()
            # the swapped-in store inherits the old store's HBM-ledger
            # owner labels (compress runs outside the shard's owner
            # scope); the old store's entries release via its finalizer
            # once the swap drops the last reference
            own = getattr(old, "_hbm_owner", None) or \
                hbm_ledger.current_owner()
            with hbm_ledger.owner(**own):
                new = QuantizedVectorStore(
                    dim=self.dim, metric=self.metric,
                    quantization=quantization,
                    capacity=old.capacity, chunk_size=old.chunk_size,
                    mesh=old.mesh, **quant_kwargs,
                )
            live = np.nonzero(snap["valid"])[0]
            live_vecs = snap["vectors"][live]
            if quantization == "pq" and new.codebook is None:
                if len(live) < new.pq_centroids:
                    raise RuntimeError(
                        f"need >= {new.pq_centroids} live vectors to train PQ, "
                        f"have {len(live)}"
                    )
                new.train(live_vecs)
            if len(live):
                # vectors were already normalized at original insert
                new.set_at_prenormalized(live, live_vecs)
            new._count = snap["count"]
            self.store = new

    def _compress_epochs(self, old, quantization: str,
                         **quant_kwargs) -> None:
        """Epoch-preserving compression: the quantized twin keeps the
        SAME global slot layout (epochs re-split by epoch_rows), so the
        id<->slot tables carry over untouched. Caller holds ``_lock``."""
        from weaviate_tpu.engine.epochs import EpochStore
        from weaviate_tpu.runtime import hbm_ledger

        snap = old.snapshot()
        own = getattr(old, "_owner", None) or hbm_ledger.current_owner()
        with hbm_ledger.owner(**own):
            new = EpochStore(
                dim=self.dim, metric=self.metric,
                epoch_rows=old.epoch_rows, chunk_size=old.chunk_size,
                mesh=old.mesh, selection=old.selection,
                quantization=quantization, quant_kwargs=quant_kwargs)
        live = np.nonzero(snap["valid"])[0]
        live_vecs = snap["vectors"][live]
        if quantization == "pq":
            centroids = new._quant_kwargs.get("pq_centroids", 16)
            if len(live) < centroids:
                raise RuntimeError(
                    f"need >= {centroids} live vectors to train PQ, "
                    f"have {len(live)}")
        new._restore_rows(live, snap["vectors"], int(snap["count"]))
        if quantization == "pq":
            new.train(live_vecs)
        self.store = new

    @property
    def compressed(self) -> bool:
        """Reference Compressed() (vector_index.go:37)."""
        from weaviate_tpu.engine.epochs import EpochStore
        from weaviate_tpu.engine.quantized import QuantizedVectorStore

        if isinstance(self.store, EpochStore):
            return bool(self.store.quantization)
        return isinstance(self.store, QuantizedVectorStore)

    # -- epoch hooks (engine/epochs.py; db/collection.py migration) -----------

    @property
    def epoch_store(self):
        """The backing ``EpochStore`` when this index is epoch-backed,
        else None (the maintenance policy keys on this)."""
        from weaviate_tpu.engine.epochs import EpochStore

        return self.store if isinstance(self.store, EpochStore) else None

    def epoch_doc_ids(self, eid: int) -> np.ndarray:
        """Doc ids of one epoch's live rows — the unit the migration
        policy serializes to a sibling shard."""
        es = self.epoch_store
        if es is None:
            return np.empty(0, np.int64)
        with self._lock:
            gslots = es.live_globals_of(eid)
            gslots = gslots[gslots < len(self._slot_to_id)]
            ids = self._slot_to_id[gslots]
            return ids[ids >= 0]

    # -- maintenance / persistence -------------------------------------------

    def compact(self):
        """Reclaim tombstoned rows; remaps id→slot tables."""
        with self._lock:
            mapping = self.store.compact()
            new_slot_to_id = np.full(self.store.capacity, -1, dtype=np.int64)
            for doc_id, slot in list(self._id_to_slot.items()):
                ns = int(mapping[slot])
                self._id_to_slot[doc_id] = ns
                new_slot_to_id[ns] = doc_id
            self._slot_to_id = new_slot_to_id

    def snapshot(self) -> dict:
        with self._lock:
            snap = self.store.snapshot()
            snap["slot_to_id"] = self._slot_to_id.copy()
            snap["index_type"] = self.index_type
            return snap

    @classmethod
    def restore(cls, snap: dict, mesh=None, **kwargs) -> "FlatIndex":
        idx = cls.__new__(cls)
        idx.dim = snap["dim"]
        idx.metric = snap["metric"]
        if snap.get("epoch_rows"):
            from weaviate_tpu.engine.epochs import EpochStore

            idx.store = EpochStore.restore(snap, mesh=mesh, **kwargs)
        elif snap.get("quantization"):
            from weaviate_tpu.engine.quantized import QuantizedVectorStore

            idx.store = QuantizedVectorStore.restore(snap, mesh=mesh, **kwargs)
        else:
            idx.store = DeviceVectorStore.restore(snap, mesh=mesh, **kwargs)
        idx._lock = threading.RLock()
        slot_to_id = snap["slot_to_id"]
        # the snapshot's table can be WIDER than the restored store's
        # capacity (an epoch store sealed early keeps an active epoch's
        # unused range; restore re-splits by epoch_rows) — size to the
        # max so no entry is dropped; slots past the restored count are
        # -1 (nothing live ever pointed there)
        size = max(idx.store.capacity, len(slot_to_id))
        idx._slot_to_id = np.full(size, -1, dtype=np.int64)
        idx._slot_to_id[: len(slot_to_id)] = slot_to_id
        idx._id_to_slot = {
            int(doc): int(slot)
            for slot, doc in enumerate(slot_to_id)
            if doc >= 0 and snap["valid"][slot]
        }
        return idx
