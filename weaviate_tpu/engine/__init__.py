"""TPU vector engine: HBM-resident vector stores and vector indexes.

The index classes implement the semantics of the reference's ``VectorIndex``
interface (adapters/repos/db/vector_index.go:24-45): Add/AddBatch/Delete/
SearchByVector/SearchByVectorDistance, plus compression hooks — re-designed
around immutable device buffers, donation-based in-place updates, and
tombstone masks applied inside the top-k scan.
"""

from weaviate_tpu.engine.store import DeviceVectorStore
from weaviate_tpu.engine.flat import FlatIndex

__all__ = ["DeviceVectorStore", "FlatIndex"]
