"""Dynamic index: exact flat scan below a threshold, IVF ANN above.

Reference: adapters/repos/db/vector/dynamic/index.go — starts flat and
upgrades to HNSW once the object count crosses a threshold
(ShouldUpgrade :348, Upgrade :370; requires ASYNC_INDEXING). Here the
upgrade target is the TPU-native IVF index, and the swap happens inline at
the insert that crosses the threshold (our "async queue" is the IVF delta
buffer itself, which absorbs the migrated rows batched).

Brute force on TPU is fast enough that the default threshold can sit far
above the reference's — exact search IS the preferred regime until the
corpus is large enough that probing beats one more matmul.
"""

from __future__ import annotations

import threading

import numpy as np

from weaviate_tpu.engine.flat import FlatIndex
from weaviate_tpu.engine.ivf import IVFIndex


class DynamicIndex:
    """VectorIndex-contract wrapper delegating to flat, then IVF."""

    index_type = "dynamic"

    def __init__(self, dim: int, metric: str = "l2-squared",
                 threshold: int = 100_000, mesh=None, capacity: int = 8192,
                 chunk_size: int = 8192, nlist: int = 0, nprobe: int = 0,
                 upgrade_quantization: str | None = None,
                 **flat_kwargs):
        self.dim = dim
        self.metric = metric
        self.threshold = threshold
        self.mesh = mesh
        self._nlist = nlist
        self._nprobe = nprobe
        self._chunk_size = chunk_size
        # residency for the upgrade TARGET: the flat regime stays full
        # precision (exact scan is the point), but the IVF index it
        # migrates into can start life residual-quantized
        self._upgrade_quantization = upgrade_quantization
        self._lock = threading.RLock()
        # captured so the runtime flat->IVF upgrade (which runs on an
        # insert thread, outside any shard owner scope) keeps the new
        # index's HBM-ledger attribution
        from weaviate_tpu.runtime import hbm_ledger

        self._hbm_owner = hbm_ledger.current_owner()
        self._impl = FlatIndex(dim=dim, metric=metric, mesh=mesh,
                               capacity=capacity, chunk_size=chunk_size,
                               **flat_kwargs)

    # -- upgrade lifecycle ----------------------------------------------------

    @property
    def upgraded(self) -> bool:
        return isinstance(self._impl, IVFIndex)

    def should_upgrade(self) -> bool:
        """Reference ShouldUpgrade (dynamic/index.go:348). Mesh-sharded and
        quantized flat stay flat: the SPMD exact scan already scales across
        devices, and the PQ/BQ-compressed scan is already the fast path."""
        return (not self.upgraded and self.mesh is None
                and not self._impl.compressed
                and len(self._impl) >= self.threshold)

    def upgrade(self) -> None:
        """Migrate flat contents into a fresh IVF index (reference Upgrade,
        dynamic/index.go:370)."""
        with self._lock:
            if self.upgraded:
                return
            flat = self._impl
            snap = flat.snapshot()
            slot_to_id = snap["slot_to_id"]
            valid = snap["valid"]
            live = [s for s in range(min(len(slot_to_id), len(valid)))
                    if valid[s] and slot_to_id[s] >= 0]
            from weaviate_tpu.runtime import hbm_ledger

            with hbm_ledger.owner(**self._hbm_owner):
                ivf = IVFIndex(dim=self.dim, metric=self.metric,
                               chunk_size=self._chunk_size,
                               nlist=self._nlist, nprobe=self._nprobe,
                               train_threshold=max(self.threshold, 256),
                               dtype=getattr(flat.store, "dtype", None),
                               quantization=self._upgrade_quantization)
            if live:
                ids = slot_to_id[live]
                vecs = snap["vectors"][live]
                ivf.add_batch(ids, vecs)
                if not ivf.trained:
                    ivf.train()
            self._impl = ivf

    # -- VectorIndex contract (delegated) ------------------------------------

    def add(self, doc_id: int, vector) -> None:
        self.add_batch([doc_id], np.asarray(vector)[None, :])

    def add_batch(self, doc_ids, vectors) -> None:
        with self._lock:
            self._impl.add_batch(doc_ids, vectors)
            if self.should_upgrade():
                self.upgrade()

    def maintain(self) -> None:
        """Maintenance tick (db/shard.py epoch_maintenance): catch a
        deferred upgrade (e.g. after a restore that landed above the
        threshold without an insert) and forward the tick to the live
        impl — the IVF regime folds its delta / retrains here."""
        with self._lock:
            if self.should_upgrade():
                self.upgrade()
            impl_maintain = getattr(self._impl, "maintain", None)
            if impl_maintain is not None:
                impl_maintain()

    def __getattr__(self, name):
        # everything else (search/delete/len/compact/...) hits the live impl
        return getattr(self._impl, name)

    def __len__(self) -> int:
        return len(self._impl)

    def snapshot(self) -> dict:
        snap = self._impl.snapshot()
        snap["index_type"] = "dynamic"
        snap["dynamic_threshold"] = self.threshold
        snap["dynamic_upgraded"] = self.upgraded
        snap["dynamic_upgrade_quantization"] = self._upgrade_quantization
        return snap

    @classmethod
    def restore(cls, snap: dict, mesh=None, **kwargs) -> "DynamicIndex":
        idx = cls.__new__(cls)
        idx.threshold = snap.get("dynamic_threshold", 100_000)
        idx.mesh = mesh
        idx.dim = snap["dim"]
        idx.metric = snap["metric"]
        idx._nlist = snap.get("nlist", 0)
        idx._nprobe = snap.get("nprobe", 0)
        idx._chunk_size = snap.get("chunk_size", 8192)
        idx._upgrade_quantization = snap.get("dynamic_upgrade_quantization")
        idx._lock = threading.RLock()
        from weaviate_tpu.runtime import hbm_ledger

        idx._hbm_owner = hbm_ledger.current_owner()
        if snap.get("dynamic_upgraded"):
            idx._impl = IVFIndex.restore(snap, **kwargs)
        else:
            idx._impl = FlatIndex.restore(snap, mesh=mesh, **kwargs)
        return idx
