"""IVF (inverted-file) ANN index — the TPU-native ANN.

The reference's ANN is HNSW (vector/hnsw/index.go): a pointer-chasing graph
whose hot loop (search.go:173-341) is one-vector-at-a-time — the worst
possible shape for a systolic array. The TPU-idiomatic replacement
(SURVEY §7 step 5) is IVF/ScaNN-style partitioning:

- **train**: coarse k-means over the corpus (ops/kmeans.py, MXU Lloyd's)
- **layout**: posting lists as ONE dense padded tensor ``[nlist, cap, d]``
  in HBM (+ valid mask, slot ids, cached norms) — uniform shapes so the
  probe gather is a static-shape `take`, not ragged pointer chasing
- **search**: query→centroid matmul → top-nprobe lists → candidate-slot
  plane (ops/candidates.py): one gather-matmul over the probed blocks,
  per-query ``allow_bits`` folded per candidate, exact top-k. Dispatch
  only — ``search_async`` returns a DeviceResultHandle and ``search`` is
  its ``.result()``, so sync and async are bit-exact by construction.
- **residual PQ** (quantization="pq"): posting lists hold uint8 codes of
  the RESIDUAL ``r = x - centroid[assign]`` (IVF-ADC; the residual has
  ~nlist× less variance than the raw vector, so the same code budget
  buys a tighter quantizer). The probe scores candidates by ADC —
  ``||q-c-r̂||² = ||q-c||² - 2·q·r̂ + t_row`` with
  ``t_row = 2·c·r̂ + ||r̂||²`` precomputed per row at encode — then
  oversampled candidates rescore EXACTLY on device against a full-rows
  tier (gather-matmul via the plane). The f32 host mirror survives only
  for retrain/rebuild/persistence and is ledger-accounted as a host-tier
  component, like HNSW's host graph.
- **delta buffer**: recent inserts land in a small brute-force scanned
  DeviceVectorStore (exact), merged into lists when it fills (the LSM
  memtable idea applied to HBM; mirrors how the reference's async index
  queue batches graph inserts, index_queue.go:42).

Maintenance is incremental: deletes tombstone rows AND record the hole
(list, pos); later scatters refill holes before extending the tail, and
a row that finds its home list full spills to the next-nearest centroid
with room. ``compact()`` therefore just folds the delta in — no full
rebuild (``rebuild_count`` stays flat across compactions) — and
``maintain()`` retrains only past a centroid-drift proxy (live count
grew ``retrain_factor``× since training).

Updates re-route the slot through the delta buffer. Global slot ids are
stable across flushes, so the FlatIndex id<->slot bookkeeping works
unchanged — IVFIndex subclasses FlatIndex and swaps the store.
"""

from __future__ import annotations

import functools
import math
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.engine.flat import FlatIndex
from weaviate_tpu.engine.store import (DeviceVectorStore, _next_pow2,
                                       normalize_allow_mask)
from weaviate_tpu.ops.candidates import gather_rescore_topk
from weaviate_tpu.ops.distances import (MASKED_DISTANCE, normalize,
                                        normalize_np, pairwise_distance)
from weaviate_tpu.ops.kmeans import kmeans_assign, kmeans_fit
from weaviate_tpu.ops.pallas_kernels import _MASK_WORDS, allow_bits_for_ids
from weaviate_tpu.ops.topk import topk_smallest
from weaviate_tpu.runtime import hbm_ledger, kernelscope, tracing
from weaviate_tpu.runtime.transfer import DeviceResultHandle

_SUPPORTED_METRICS = ("l2-squared", "dot", "cosine", "cosine-dot")


@functools.lru_cache(maxsize=1)
def _dummy_bits_cached():
    return jnp.zeros((1, _MASK_WORDS), dtype=jnp.uint32)


def _dummy_bits():
    """Placeholder ``allow_bits`` operand for ``use_allow=False`` probe
    variants: one cached buffer so repeated unfiltered searches reuse the
    same device constant instead of uploading a fresh dummy per call.
    Under an active trace the cache must be bypassed — caching the
    tracer would poison every later eager caller."""
    if jax.core.trace_state_clean():
        return _dummy_bits_cached()
    return jnp.zeros((1, _MASK_WORDS), dtype=jnp.uint32)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _scatter_lists(list_vecs, list_valid, list_slots, list_norms,
                   flat_idx, vecs, slots, write_mask):
    """Scatter rows into the flattened [nlist*cap] list tensor."""
    nlist, cap, dim = list_vecs.shape
    fv = list_vecs.reshape(nlist * cap, dim)
    fva = list_valid.reshape(nlist * cap)
    fs = list_slots.reshape(nlist * cap)
    fn = list_norms.reshape(nlist * cap)
    tgt = jnp.where(write_mask, flat_idx, nlist * cap)  # OOB rows drop
    vecs = vecs.astype(fv.dtype)
    norms = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=-1)
    fv = fv.at[tgt].set(vecs, mode="drop")
    fva = fva.at[tgt].set(True, mode="drop")
    fs = fs.at[tgt].set(slots, mode="drop")
    fn = fn.at[tgt].set(norms, mode="drop")
    return (fv.reshape(nlist, cap, dim), fva.reshape(nlist, cap),
            fs.reshape(nlist, cap), fn.reshape(nlist, cap))


@functools.partial(jax.jit, donate_argnums=(0,))
def _clear_list_rows(list_valid, flat_idx):
    nlist, cap = list_valid.shape
    flat = list_valid.reshape(nlist * cap)
    return flat.at[flat_idx].set(False, mode="drop").reshape(nlist, cap)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _scatter_code_lists(list_codes, list_valid, list_slots, list_tvals,
                        flat_idx, codes, tvals, slots, write_mask):
    """PQ-mode scatter: residual codes [m] uint8 + per-row ADC constant
    ``t_row`` into the [nlist, cap, …] list tensors."""
    nlist, cap, m = list_codes.shape
    fc = list_codes.reshape(nlist * cap, m)
    fva = list_valid.reshape(nlist * cap)
    fs = list_slots.reshape(nlist * cap)
    ft = list_tvals.reshape(nlist * cap)
    tgt = jnp.where(write_mask, flat_idx, nlist * cap)
    fc = fc.at[tgt].set(codes, mode="drop")
    fva = fva.at[tgt].set(True, mode="drop")
    fs = fs.at[tgt].set(slots, mode="drop")
    ft = ft.at[tgt].set(tvals, mode="drop")
    return (fc.reshape(nlist, cap, m), fva.reshape(nlist, cap),
            fs.reshape(nlist, cap), ft.reshape(nlist, cap))


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows_at(rows, idx, vecs, write_mask):
    """Scatter f32 rows into the device rescore tier (PQ mode)."""
    tgt = jnp.where(write_mask, idx, rows.shape[0])  # OOB rows drop
    return rows.at[tgt].set(vecs.astype(rows.dtype), mode="drop")


@functools.partial(jax.jit,
                   static_argnames=("k", "nprobe", "metric", "use_allow"))
def _ivf_probe_topk_pq(q, centroids, c_norms, list_codes, list_valid,
                       list_slots, list_tvals, pq_centroids, allow_bits,
                       k: int, nprobe: int, metric: str, use_allow: bool):
    """Residual-PQ probe: gather CODES from the probed lists and score by
    residual ADC. Codes encode ``r = x - centroid[assign]``, so the
    distance decomposes into a per-(query, probe) base term the coarse
    matmul already produced, a per-row constant ``t_row`` cached at
    encode, and the only data-dependent part — ``q·r̂`` — which the
    one-hot int8 LUT matmul computes on the MXU:

        l2:     ||q-c-r̂||² = ||q-c||²  - 2·q·r̂ + (2·c·r̂ + ||r̂||²)
        dot:    -q·x̂       = -q·c      -   q·r̂
        cosine: 1 - q·x̂    = 1 + (-q·c -   q·r̂)

    ADC order is approximate (rank-only): callers exact-rescore the
    oversampled survivors via the candidate plane. HBM reads per probed
    row are m+4 bytes instead of 4d — the capacity regime IVF-PQ exists
    for (reference: PQ inside each shard's HNSW,
    compressionhelpers/product_quantization.go:372). The one-hot int8
    matmul ADC (chunked over probed rows, bounded [B, Pc, kc*m]
    transients) replaced a per-segment take_along_axis formulation that
    issued B*P*m VPU random gathers and OOM'd beyond nprobe=8.
    Per-query allow bitmasks fold per candidate (allow_bits_for_ids) —
    never a dense [B, capacity] unpack."""
    from weaviate_tpu.ops.pq import quantize_lut_int8

    nlist, cap, m = list_codes.shape
    b = q.shape[0]
    q32 = q.astype(jnp.float32)
    if metric in ("cosine", "cosine-dot"):
        q32 = normalize(q32)
    cd = pairwise_distance(q32, centroids, metric="l2-squared",
                           x_sq_norms=c_norms)
    _, probes = jax.lax.top_k(-cd, nprobe)          # [B, nprobe]
    cd_p = jnp.take_along_axis(cd, probes, axis=1)  # ||q-c||² per probe

    codes = list_codes[probes].reshape(b, nprobe * cap, m)
    vld = list_valid[probes].reshape(b, nprobe * cap)
    slots = list_slots[probes].reshape(b, nprobe * cap)
    tval = list_tvals[probes].reshape(b, nprobe * cap)
    p = codes.shape[1]
    # residual LUT: factor * q_seg · codeword (factor −2 for l2, −1 for
    # the dot family) — no qn/cn terms, those live in base/t_row
    ds = pq_centroids.shape[2]
    kc = pq_centroids.shape[1]
    qs = q32.reshape(b, m, ds)
    rdots = jnp.einsum("bms,mks->bmk", qs, pq_centroids,
                       preferred_element_type=jnp.float32)
    lut = (-2.0 if metric == "l2-squared" else -1.0) * rdots
    lut8, scale = quantize_lut_int8(lut)
    # ~128 MB one-hot transient per scan step ACROSS the query batch
    # (b * pc * kc * m int8)
    pc = max(256, min(p, (1 << 27) // (kc * m * max(b, 1))))
    n_chunks = -(-p // pc)
    pad_p = n_chunks * pc - p
    codes_p = jnp.pad(codes, ((0, 0), (0, pad_p), (0, 0)))
    codes_c = codes_p.reshape(b, n_chunks, pc, m).transpose(1, 0, 2, 3)

    def one_chunk(carry, codes_blk):
        # copy-major tile (lane c*m + s) matching the code-major LUT flatten
        rep = jnp.tile(codes_blk.astype(jnp.int32), (1, 1, kc))
        lane = jax.lax.broadcasted_iota(jnp.int32, rep.shape, 2) // m
        oh = (rep == lane).astype(jnp.int8)          # [B, Pc, kc*m]
        dots = jax.lax.dot_general(
            lut8, oh,
            dimension_numbers=(((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.int32)         # [B, Pc]
        return carry, dots

    _, d8 = jax.lax.scan(one_chunk, None, codes_c)
    adc = (jnp.transpose(d8, (1, 0, 2)).reshape(b, n_chunks * pc)[:, :p]
           .astype(jnp.float32) / scale[:, None])     # ≈ factor · q·r̂
    if metric == "l2-squared":
        d = jnp.maximum(jnp.repeat(cd_p, cap, axis=1) + adc + tval, 0.0)
    else:
        qn = jnp.sum(q32 * q32, axis=-1, keepdims=True)
        base = -0.5 * (qn + c_norms[probes] - cd_p)   # = -q·c per probe
        d = jnp.repeat(base, cap, axis=1) + adc
        if metric != "dot":
            d = 1.0 + d
    if use_allow:
        vld = vld & allow_bits_for_ids(allow_bits, slots)
    d = jnp.where(vld, d, MASKED_DISTANCE)
    td, ts = topk_smallest(d, slots, min(k, p))
    # masked rows keep their slot through top_k — drop them HERE or the
    # exact rescore downstream would resurrect them with real distances
    return td, jnp.where(td >= MASKED_DISTANCE, -1, ts)


@functools.partial(jax.jit,
                   static_argnames=("k", "nprobe", "metric", "use_allow"))
def _ivf_probe_topk(q, centroids, c_norms, list_vecs, list_valid, list_slots,
                    list_norms, allow_bits, k: int, nprobe: int,
                    metric: str, use_allow: bool):
    """Full-rows probe: q [B,d] → centroid distances [B,nlist] (MXU
    matmul) → top-nprobe → flattened probed positions feed the shared
    candidate plane (ops/candidates.py), which gathers, scores, folds
    per-query ``allow_bits`` per candidate, and exact-top-k's. Returns
    (dists [B,k'], slots [B,k']) ascending; dead/filtered rows never
    surface. Memory is O(B * nprobe * cap * d): callers chunk B."""
    nlist, cap, dim = list_vecs.shape
    b = q.shape[0]
    q32 = q.astype(jnp.float32)
    if metric in ("cosine", "cosine-dot"):
        q32 = normalize(q32)
    cd = pairwise_distance(q32, centroids, metric="l2-squared",
                           x_sq_norms=c_norms)
    _, probes = jax.lax.top_k(-cd, nprobe)  # [B, nprobe]
    pos = jax.lax.broadcasted_iota(jnp.int32, (b, nprobe, cap), 2)
    flat = (probes[:, :, None].astype(jnp.int32) * cap
            + pos).reshape(b, nprobe * cap)
    return gather_rescore_topk(
        q32, flat, list_vecs.reshape(nlist * cap, dim), k, metric,
        ids_of_row=list_slots.reshape(nlist * cap),
        row_norms=list_norms.reshape(nlist * cap),
        valid=list_valid.reshape(nlist * cap),
        allow_bits=allow_bits if use_allow else None)


class IVFStore:
    """DeviceVectorStore-compatible store backed by IVF posting lists plus a
    brute-force delta buffer. Slot ids are append-order and stable."""

    mesh = None  # single-replica; collection-level sharding distributes IVF

    def __init__(self, dim: int, metric: str = "l2-squared",
                 capacity: int = 8192, chunk_size: int = 8192,
                 nlist: int = 0, nprobe: int = 0,
                 train_threshold: int = 16_384,
                 delta_threshold: int = 8192,
                 query_chunk: int = 16,
                 dtype=None,
                 quantization: str | None = None,
                 pq_segments: int | None = None,
                 pq_centroids: int = 16,
                 rescore_limit: int = 16,
                 retrain_factor: float = 4.0):
        if metric not in _SUPPORTED_METRICS:
            raise ValueError(
                f"ivf supports {_SUPPORTED_METRICS}, not {metric!r}")
        if quantization not in (None, "pq"):
            raise ValueError(f"ivf quantization must be None or 'pq', "
                             f"not {quantization!r}")
        self.dim = dim
        self.metric = metric
        self.chunk_size = chunk_size
        self.dtype = dtype or jnp.float32
        self.nlist = nlist  # 0 = auto at train time
        self.nprobe = nprobe  # 0 = auto (nlist/8, min 8)
        self.train_threshold = train_threshold
        self.delta_threshold = delta_threshold
        self.query_chunk = query_chunk
        # Residual IVF-PQ residency: posting lists hold uint8 codes of
        # x - centroid[assign]; oversampled candidates rescore EXACTLY on
        # device against the _rescore_rows tier. The host f32 mirror
        # survives for retrain/rebuild/persistence only (ledger: a
        # "host_mirror" host-tier component). The delta buffer stays
        # exact either way.
        self.quantization = quantization
        self.pq_centroids = pq_centroids
        if quantization and not pq_segments:
            from weaviate_tpu.ops.pq import default_pq_segments

            pq_segments = default_pq_segments(dim, pq_centroids)
        self.pq_segments = pq_segments
        self.rescore_limit = rescore_limit
        self.retrain_factor = retrain_factor
        self.codebook = None
        self.list_codes = None
        self.list_tvals = None  # [nlist, cap] f32 per-row ADC constant
        self._host_rows = (
            np.zeros((max(capacity, 1024), dim), dtype=np.float32)
            if quantization else None)
        self._rescore_rows = None  # device [pow2, d] exact-rescore tier
        self.normalize_on_add = metric in ("cosine", "cosine-dot")
        self._lock = threading.RLock()
        self._count = 0  # global slot high-water mark
        # maintenance counters (asserted by tests: compaction must not
        # full-rebuild, retrain only fires past the drift proxy)
        self.rebuild_count = 0
        self.retrain_count = 0
        self._live_at_train = 0
        # HBM ledger: centroid + posting-list tensors publish under the
        # owner labels captured here; the delta store self-accounts (it
        # is a DeviceVectorStore constructed in this same owner scope)
        self._hbm_owner = hbm_ledger.current_owner()
        self._hbm_keys: dict[str, int] = {}
        weakref.finalize(self, hbm_ledger.ledger.release_many,
                         self._hbm_keys.values())
        # delta buffer (exact scan); delta slot -> global slot
        self.delta = DeviceVectorStore(
            dim, metric, capacity=min(capacity, delta_threshold * 2),
            chunk_size=chunk_size)
        self._delta_slots: dict[int, int] = {}  # delta slot -> global
        # slot -> ("delta", dslot) | ("list", flat_idx)
        self._slot_loc: dict[int, tuple] = {}
        # list tensors (allocated at train time)
        self.centroids = None  # jnp [nlist, d]
        self._centroids_np = None  # host twin (assign/residuals/spill)
        self._c_norms = None
        self.list_vecs = None  # [nlist, cap, d]
        self.list_valid = None
        self.list_slots = None
        self.list_norms = None
        self.list_cap = 0
        self._fill: np.ndarray | None = None  # host per-list fill count
        # freed (list, pos) positions, refilled LIFO before the tail
        # grows — positions survive cap growth, flat indices would not
        self._holes: dict[int, list[int]] = {}

    def _hbm_sync(self):
        """Publish centroid + posting-list + rescore-tier device bytes
        and the host mirror (host tier) to the ledger (the delta
        DeviceVectorStore accounts for itself)."""
        cent = 0 if self.centroids is None else (
            int(self.centroids.nbytes) + int(self._c_norms.nbytes))
        hbm_ledger.ledger.set_keyed(
            self._hbm_keys, "centroids", cent, owner=self._hbm_owner,
            dtype="float32")
        lists = sum(int(a.nbytes) for a in (
            self.list_vecs, self.list_codes, self.list_norms,
            self.list_tvals, self.list_valid, self.list_slots)
            if a is not None)
        hbm_ledger.ledger.set_keyed(
            self._hbm_keys, "lists", lists, owner=self._hbm_owner,
            dtype=("uint8" if self.quantization
                   else jnp.dtype(self.dtype).name))
        hbm_ledger.ledger.set_keyed(
            self._hbm_keys, "rescore_rows",
            0 if self._rescore_rows is None
            else int(self._rescore_rows.nbytes),
            owner=self._hbm_owner, dtype=jnp.dtype(self.dtype).name)
        hbm_ledger.ledger.set_keyed(
            self._hbm_keys, "host_mirror",
            0 if self._host_rows is None else int(self._host_rows.nbytes),
            owner=self._hbm_owner, dtype="float32", placement="host")

    # -- properties mirrored from DeviceVectorStore ---------------------------

    @property
    def count(self) -> int:
        return self._count

    @property
    def capacity(self) -> int:
        """Global slot-space bound (exclusive upper bound on slot ids)."""
        return max(_next_pow2(max(self._count, 1)), 8)

    @property
    def trained(self) -> bool:
        return self.centroids is not None

    def live_count(self) -> int:
        with self._lock:
            return len(self._slot_loc)

    # -- mutation -------------------------------------------------------------

    def add(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        with self._lock:
            slots = np.arange(self._count, self._count + len(vectors),
                              dtype=np.int64)
            self._count += len(vectors)
            self._remember_rows(slots, vectors)
            self._add_to_delta(slots, vectors)
            self._maybe_reorganize()
            return slots

    def _remember_rows(self, slots: np.ndarray, vectors: np.ndarray):
        """PQ mode keeps the originals twice: an f32 host mirror (codes
        are lossy — retrain/rebuild/persistence read from here) and the
        device ``_rescore_rows`` tier the exact candidate rescore gathers
        from. Caller holds ``_lock``."""
        if self._host_rows is None or len(slots) == 0:
            return
        if self.normalize_on_add:
            vectors = normalize_np(vectors)
        mx = int(np.max(slots))
        if mx >= len(self._host_rows):
            grown = np.zeros((_next_pow2(mx + 1), self.dim), np.float32)
            grown[: len(self._host_rows)] = self._host_rows
            self._host_rows = grown
        self._host_rows[slots] = vectors
        need = _next_pow2(max(mx + 1, 1024))
        if self._rescore_rows is None:
            self._rescore_rows = jnp.zeros((need, self.dim),
                                           dtype=self.dtype)
        elif mx >= self._rescore_rows.shape[0]:
            old = self._rescore_rows
            self._rescore_rows = (jnp.zeros((need, self.dim),
                                            dtype=self.dtype)
                                  .at[: old.shape[0]].set(old))
        bucket = _next_pow2(max(len(slots), 8))
        i_buf = np.zeros(bucket, np.int32)
        i_buf[: len(slots)] = slots
        v_buf = np.zeros((bucket, self.dim), np.float32)
        v_buf[: len(slots)] = vectors
        m_buf = np.zeros(bucket, bool)
        m_buf[: len(slots)] = True
        self._rescore_rows = _scatter_rows_at(
            self._rescore_rows, jnp.asarray(i_buf), jnp.asarray(v_buf),
            jnp.asarray(m_buf))
        self._hbm_sync()

    def _add_to_delta(self, slots: np.ndarray, vectors: np.ndarray):
        dslots = self.delta.add(vectors)
        for g, d in zip(slots.tolist(), dslots.tolist()):
            self._delta_slots[int(d)] = int(g)
            self._slot_loc[int(g)] = ("delta", int(d))

    def _punch_hole(self, flat_idx: int):
        """Record a freed list position for hole-first refill. Caller
        holds ``_lock``; positions (not flat indices) survive cap growth."""
        l, p = divmod(int(flat_idx), self.list_cap)
        self._holes.setdefault(l, []).append(p)

    def set_at(self, slots: np.ndarray, vectors: np.ndarray):
        """Overwrite slots in place. List-resident slots are tombstoned there
        and re-routed through the delta buffer (their assignment may change)."""
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        with self._lock:
            self._count = max(self._count, int(slots.max()) + 1 if len(slots) else 0)
            self._remember_rows(slots, vectors)
            delta_upd_d, delta_upd_v = [], []
            fresh_s, fresh_v = [], []
            clear_flat = []
            for s, v in zip(slots.tolist(), vectors):
                loc = self._slot_loc.get(int(s))
                if loc is not None and loc[0] == "delta":
                    delta_upd_d.append(loc[1])
                    delta_upd_v.append(v)
                else:
                    if loc is not None:  # list-resident: tombstone there
                        clear_flat.append(loc[1])
                        self._punch_hole(loc[1])
                    fresh_s.append(int(s))
                    fresh_v.append(v)
            if clear_flat:
                self.list_valid = _clear_list_rows(
                    self.list_valid, jnp.asarray(clear_flat, dtype=jnp.int32))
            if delta_upd_d:
                self.delta.set_at(np.asarray(delta_upd_d),
                                  np.stack(delta_upd_v))
            if fresh_s:
                self._add_to_delta(np.asarray(fresh_s), np.stack(fresh_v))
            self._maybe_reorganize()

    def delete(self, slots) -> None:
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        with self._lock:
            clear_flat, delta_del = [], []
            for s in slots.tolist():
                loc = self._slot_loc.pop(int(s), None)
                if loc is None:
                    continue
                if loc[0] == "delta":
                    delta_del.append(loc[1])
                    self._delta_slots.pop(loc[1], None)
                else:
                    clear_flat.append(loc[1])
                    self._punch_hole(loc[1])
            if delta_del:
                self.delta.delete(np.asarray(delta_del))
            if clear_flat:
                self.list_valid = _clear_list_rows(
                    self.list_valid, jnp.asarray(clear_flat, dtype=jnp.int32))

    # -- training / reorganization -------------------------------------------

    def _maybe_reorganize(self):
        if not self.trained:
            if len(self._slot_loc) >= self.train_threshold:
                self.train()
        elif len(self._delta_slots) >= self.delta_threshold:
            self.flush_delta()

    def _auto_nlist(self, n: int) -> int:
        # ~2*sqrt(N) lists, pow2-rounded, clamped: large enough to prune,
        # small enough that centroids fit one matmul
        return int(min(8192, max(16, _next_pow2(int(2 * math.sqrt(n))))))

    def train(self, force_nlist: int | None = None):
        """Learn the coarse partition from current contents and move
        everything into posting lists (reference analog: hnsw compress.go:38
        trains PQ once enough data exists — same lifecycle hook). On an
        already-trained store this is the RETRAIN path (``maintain``'s
        drift gate lands here); routine delta absorption goes through
        ``flush_delta`` without touching the centroids."""
        with self._lock:
            vecs, slots = self._all_live_host()
            n = len(vecs)
            if n == 0:
                raise RuntimeError("cannot train IVF on an empty store")
            was_trained = self.trained
            nlist = force_nlist or self.nlist or self._auto_nlist(n)
            nlist = min(nlist, n)
            self.nlist = nlist
            cents = kmeans_fit(vecs, nlist, iters=10)
            if self.normalize_on_add:
                # keep centroids on the sphere so probe distances stay comparable
                cents = normalize_np(cents)
            self._centroids_np = np.asarray(cents, dtype=np.float32)
            self.centroids = jnp.asarray(self._centroids_np)
            self._c_norms = jnp.sum(self.centroids * self.centroids, axis=1)
            assign = kmeans_assign(vecs, self._centroids_np)
            if self.quantization:
                from weaviate_tpu.ops.pq import pq_fit

                # the codebook quantizes RESIDUALS, not raw vectors — the
                # coarse assignment has already absorbed most of the
                # variance, so the same m×kc budget codes a much tighter
                # distribution (classic IVFADC)
                res = vecs - self._centroids_np[assign]
                self.codebook = pq_fit(res, m=self.pq_segments,
                                       k=self.pq_centroids, iters=8)
            self._rebuild_lists(vecs, slots, assign=assign)
            # delta fully absorbed
            self._reset_delta()
            self._live_at_train = len(self._slot_loc)
            if was_trained:
                self.retrain_count += 1
            self._hbm_sync()

    def maintain(self) -> None:
        """Incremental maintenance hook (db/shard.py epoch maintenance):
        fold the delta into lists; RETRAIN only when the corpus outgrew
        its partition (live count >= retrain_factor x live-at-train — the
        centroid-drift proxy). Compaction never lands here, so steady
        tombstone churn costs hole-refills, not full rebuilds."""
        with self._lock:
            if not self.trained:
                if len(self._slot_loc) >= self.train_threshold:
                    self.train()
                return
            if (len(self._slot_loc)
                    >= self.retrain_factor * max(self._live_at_train, 1)):
                self.train()
                return
            if self._delta_slots:
                self.flush_delta()

    def _all_live_host(self):
        """(vectors [L,d] f32, slots [L] int64) for every live slot."""
        out_v, out_s = [], []
        if self.trained and (self.list_vecs is not None
                             or self.list_codes is not None):
            lval = np.asarray(self.list_valid).reshape(-1)
            lslot = np.asarray(self.list_slots).reshape(-1)
            live = np.nonzero(lval)[0]
            slots_live = lslot[live].astype(np.int64)
            if self.quantization:
                # codes are lossy — originals live in the host mirror
                out_v.append(self._host_rows[slots_live])
            else:
                lv = np.asarray(self.list_vecs,
                                dtype=np.float32).reshape(-1, self.dim)
                out_v.append(lv[live])
            out_s.append(slots_live)
        dsnap = self.delta.snapshot()
        dlive = np.nonzero(dsnap["valid"])[0]
        if len(dlive):
            out_v.append(dsnap["vectors"][dlive])
            out_s.append(np.asarray(
                [self._delta_slots[int(d)] for d in dlive], dtype=np.int64))
        if not out_v:
            return (np.empty((0, self.dim), np.float32),
                    np.empty(0, np.int64))
        return np.concatenate(out_v), np.concatenate(out_s)

    def _rebuild_lists(self, vecs: np.ndarray, slots: np.ndarray,
                       assign: np.ndarray | None = None):
        """Assign + scatter everything into fresh list tensors.
        Caller holds ``_lock`` (train/retrain/compress section)."""
        if assign is None:
            assign = (kmeans_assign(vecs, self._centroids_np)
                      if len(vecs) else np.empty(0, np.int64))
        assign = np.asarray(assign, dtype=np.int64)
        n = len(vecs)
        counts = (np.bincount(assign, minlength=self.nlist) if n
                  else np.zeros(self.nlist, dtype=np.int64))
        # cap targets ~2x the perfectly-even fill (pow2) instead of the
        # fullest list: one hot cluster no longer pads EVERY list to its
        # size — overfull lists spill their farthest members to the
        # next-nearest centroid with room (imbalance-aware nprobe)
        cap = max(8, _next_pow2(-(-2 * n // max(self.nlist, 1))) if n else 8)
        while self.nlist * cap < n:
            cap *= 2
        if n:
            cap = min(cap, max(8, _next_pow2(int(counts.max()))))
        while True:
            spilled = self._spill_overfull(vecs, assign, cap)
            if spilled is not None:
                assign = spilled
                break
            cap *= 2  # unplaceable at this cap — relax and retry
        self.list_cap = cap
        if self.quantization:
            self.list_codes = jnp.zeros(
                (self.nlist, cap, self.pq_segments), dtype=jnp.uint8)
            self.list_tvals = jnp.zeros((self.nlist, cap),
                                        dtype=jnp.float32)
            self.list_vecs = None
            self.list_norms = None
        else:
            self.list_vecs = jnp.zeros((self.nlist, cap, self.dim),
                                       dtype=self.dtype)
            self.list_norms = jnp.zeros((self.nlist, cap), dtype=jnp.float32)
            self.list_codes = None
            self.list_tvals = None
        self.list_valid = jnp.zeros((self.nlist, cap), dtype=jnp.bool_)
        self.list_slots = jnp.full((self.nlist, cap), -1, dtype=jnp.int32)
        self._fill = np.zeros(self.nlist, dtype=np.int64)
        self._holes = {}
        self.rebuild_count += 1
        self._hbm_sync()
        self._scatter_assigned(vecs, slots, assign)

    def _spill_overfull(self, vecs: np.ndarray, assign: np.ndarray,
                        cap: int) -> np.ndarray | None:
        """Rebalance at train time: each overfull list keeps its ``cap``
        CLOSEST members (ties break toward the lower row index —
        deterministic) and spills the rest to the nearest centroid with
        room. Returns the adjusted assignment, or None when some row
        cannot be placed anywhere at this cap (caller doubles cap).
        Keeps cap-padding honest: without it one hot cluster sets cap
        for every list and the probe gathers mostly dead padding."""
        counts = np.bincount(assign, minlength=self.nlist)
        over = np.flatnonzero(counts > cap)
        if len(over) == 0:
            return assign
        cents = self._centroids_np
        assign = assign.copy()
        room = np.clip(cap - counts, 0, None)
        for l in over.tolist():
            members = np.flatnonzero(assign == l)
            d_own = np.sum((vecs[members] - cents[l]) ** 2, axis=1)
            # lexsort's LAST key is primary: distance asc, index tiebreak
            order = members[np.lexsort((members, d_own))]
            for r in order[cap:].tolist():
                d_all = np.sum((cents - vecs[r]) ** 2, axis=1)
                d_all[l] = np.inf
                for t in np.argsort(d_all, kind="stable").tolist():
                    if room[t] > 0:
                        assign[r] = t
                        room[t] -= 1
                        break
                else:
                    return None
        return assign

    def _take_position(self, l: int) -> int:
        """Next free position in list ``l``: holes first (LIFO), then the
        tail. -1 when the list is full. Caller holds ``_lock``."""
        hs = self._holes.get(l)
        if hs:
            return hs.pop()
        if self._fill[l] < self.list_cap:
            p = int(self._fill[l])
            self._fill[l] += 1
            return p
        return -1

    def _find_room(self, vec: np.ndarray, exclude: int) -> int:
        """Nearest centroid (excluding ``exclude``) whose list has a hole
        or tail room — the runtime spill target. -1 if every list is full."""
        d = np.sum((self._centroids_np - vec) ** 2, axis=1)
        d[exclude] = np.inf
        for t in np.argsort(d, kind="stable").tolist():
            if self._holes.get(t) or self._fill[t] < self.list_cap:
                return int(t)
        return -1

    def _scatter_assigned(self, vecs, slots, assign):
        """Place (vec, slot) pairs: holes first, then the list tail, then
        spill to the next-nearest centroid with room; only when EVERY
        list is full does capacity grow. Residual-PQ encodes against the
        FINAL assignment (spill included), so codes always quantize the
        residual of the centroid actually probed."""
        if len(vecs) == 0:
            return
        assign = np.asarray(assign, dtype=np.int64).copy()
        pos = np.empty(len(assign), dtype=np.int64)
        for i, l in enumerate(assign.tolist()):
            p = self._take_position(int(l))
            if p >= 0:
                pos[i] = p
                continue
            t = self._find_room(vecs[i], exclude=int(l))
            if t >= 0:
                assign[i] = t
                pos[i] = self._take_position(t)
            else:
                self._grow_cap()
                pos[i] = self._take_position(int(l))
        # positions stay valid across _grow_cap (p < old_cap < new_cap);
        # flat indices are computed once, against the FINAL cap
        flat_idx = assign * self.list_cap + pos
        bucket = _next_pow2(max(len(vecs), 8))
        i_buf = np.zeros(bucket, np.int32)
        i_buf[:len(vecs)] = flat_idx
        s_buf = np.zeros(bucket, np.int32)
        s_buf[:len(vecs)] = slots
        m_buf = np.zeros(bucket, bool)
        m_buf[:len(vecs)] = True
        if self.quantization:
            from weaviate_tpu.ops.pq import pq_encode, pq_reconstruct

            cents = self._centroids_np[assign]
            res = vecs - cents
            codes = pq_encode(self.codebook, res)
            rhat = np.asarray(pq_reconstruct(  # graftlint: disable=G1 — maintenance-time boundary (encode, not serving)
                jnp.asarray(codes), self.codebook.centroids,
                self.codebook.m))
            tvals = (2.0 * np.sum(cents * rhat, axis=1)
                     + np.sum(rhat * rhat, axis=1)).astype(np.float32)
            c_buf = np.zeros((bucket, self.pq_segments), np.uint8)
            c_buf[:len(vecs)] = codes
            t_buf = np.zeros(bucket, np.float32)
            t_buf[:len(vecs)] = tvals
            (self.list_codes, self.list_valid, self.list_slots,
             self.list_tvals) = _scatter_code_lists(
                self.list_codes, self.list_valid, self.list_slots,
                self.list_tvals,
                jnp.asarray(i_buf), jnp.asarray(c_buf), jnp.asarray(t_buf),
                jnp.asarray(s_buf), jnp.asarray(m_buf))
        else:
            v_buf = np.zeros((bucket, self.dim), np.float32)
            v_buf[:len(vecs)] = vecs
            (self.list_vecs, self.list_valid, self.list_slots,
             self.list_norms) = _scatter_lists(
                self.list_vecs, self.list_valid, self.list_slots,
                self.list_norms,
                jnp.asarray(i_buf), jnp.asarray(v_buf), jnp.asarray(s_buf),
                jnp.asarray(m_buf))
        for s, fi in zip(slots.tolist(), flat_idx.tolist()):
            self._slot_loc[int(s)] = ("list", int(fi))

    def _grow_cap(self):
        """Double per-list capacity (repack on host — rare, amortized)."""
        old_cap = self.list_cap
        new_cap = old_cap * 2
        pad = new_cap - old_cap
        if self.quantization:
            self.list_codes = jnp.concatenate(
                [self.list_codes,
                 jnp.zeros((self.nlist, pad, self.pq_segments),
                           dtype=jnp.uint8)], axis=1)
            self.list_tvals = jnp.concatenate(
                [self.list_tvals,
                 jnp.zeros((self.nlist, pad), dtype=jnp.float32)], axis=1)
        else:
            self.list_vecs = jnp.concatenate(
                [self.list_vecs,
                 jnp.zeros((self.nlist, pad, self.dim), dtype=self.dtype)],
                axis=1)
            self.list_norms = jnp.concatenate(
                [self.list_norms,
                 jnp.zeros((self.nlist, pad), dtype=jnp.float32)], axis=1)
        self.list_valid = jnp.concatenate(
            [self.list_valid, jnp.zeros((self.nlist, pad), dtype=jnp.bool_)],
            axis=1)
        self.list_slots = jnp.concatenate(
            [self.list_slots, jnp.full((self.nlist, pad), -1, dtype=jnp.int32)],
            axis=1)
        self.list_cap = new_cap
        self._hbm_sync()
        # flat indices shift: old flat l*old_cap+p -> l*new_cap+p
        # (hole POSITIONS are cap-invariant and carry over untouched)
        for s, loc in self._slot_loc.items():
            if loc[0] == "list":
                l, p = divmod(loc[1], old_cap)
                self._slot_loc[s] = ("list", l * new_cap + p)

    def flush_delta(self):
        """Merge the delta buffer into posting lists (memtable flush) —
        an INCREMENTAL scatter into holes/tails, never a rebuild."""
        with self._lock:
            if not self.trained:
                return
            dsnap = self.delta.snapshot()
            live = np.nonzero(dsnap["valid"])[0]
            if len(live) == 0:
                self._reset_delta()
                return
            vecs = dsnap["vectors"][live]
            slots = np.asarray([self._delta_slots[int(d)] for d in live],
                               dtype=np.int64)
            if self.quantization and self.codebook is None:
                # compression was enabled while the store was empty —
                # the codebook trains on the first flush with enough data
                # (until then rows stay in the exact delta)
                if len(vecs) < self.pq_centroids:
                    return
                from weaviate_tpu.ops.pq import pq_fit

                a0 = kmeans_assign(vecs, self._centroids_np)
                self.codebook = pq_fit(vecs - self._centroids_np[a0],
                                       m=self.pq_segments,
                                       k=self.pq_centroids, iters=8)
            assign = kmeans_assign(vecs, self._centroids_np)
            self._scatter_assigned(vecs, slots, assign)
            self._reset_delta()

    def _reset_delta(self):
        """Swap in a fresh delta store. Caller holds ``_lock``."""
        # rebuilt outside the shard's construction scope — re-enter the
        # captured owner labels so the fresh delta store stays attributed
        with hbm_ledger.owner(**self._hbm_owner):
            self.delta = DeviceVectorStore(
                self.dim, self.metric,
                capacity=min(self.capacity, self.delta_threshold * 2),
                chunk_size=self.chunk_size)
        self._delta_slots = {}

    # -- queries -------------------------------------------------------------

    def _effective_nprobe(self) -> int:
        if self.nprobe:
            return min(self.nprobe, self.nlist)
        return min(self.nlist, max(8, self.nlist // 8))

    def _delta_allow(self, allow_mask, b: int):
        """Project the GLOBAL allow mask ([cap] shared or [B, cap]
        per-query) onto delta-local slots. Caller holds ``_lock``."""
        if allow_mask is None:
            return None
        cap_d = self.delta.capacity
        if allow_mask.ndim == 2:
            out = np.zeros((b, cap_d), dtype=bool)
            for ds, g in self._delta_slots.items():
                if ds < cap_d and g < allow_mask.shape[1]:
                    out[:, ds] = allow_mask[:, g]
            return out
        out = np.zeros(cap_d, dtype=bool)
        for ds, g in self._delta_slots.items():
            if ds < cap_d and g < len(allow_mask) and allow_mask[g]:
                out[ds] = True
        return out

    def search(self, queries: np.ndarray, k: int,
               allow_mask: np.ndarray | None = None,
               nprobe: int | None = None):
        """Merged top-k over delta (exact) + probed lists (ANN). This IS
        ``search_async(...).result()`` — sync and async agree bit-for-bit
        by construction; the D2H transfer rides the handle's sanctioned
        boundary (transfer.d2h span)."""
        return self.search_async(queries, k, allow_mask,
                                 nprobe=nprobe).result()

    def search_async(self, queries: np.ndarray, k: int,
                     allow_mask: np.ndarray | None = None,
                     nprobe: int | None = None) -> DeviceResultHandle:
        """Dispatch-only twin of ``search``: both legs — the exact delta
        scan (``epoch_scan``, ids remapped to global ON DEVICE) and the
        probe (+ residual-PQ exact rescore via the candidate plane) —
        launch under ``_lock`` and merge on device; results stay
        device-resident in the returned handle. ``allow_mask`` takes the
        DeviceVectorStore forms: [cap] bool shared, or [B, cap] bool
        per-query (packed once to block-strided ``allow_bits`` and folded
        per candidate inside the probe — B differently-filtered requests
        run as ONE device program, which is what lets the QueryBatcher
        coalesce filtered IVF traffic)."""
        queries = np.asarray(queries, dtype=np.float32)
        squeeze = queries.ndim == 1
        if squeeze:
            queries = queries[None, :]
        b = len(queries)
        allow_mask = normalize_allow_mask(allow_mask, b)
        np_probe = 0
        with tracing.span("ivf.search", queries=b, k=k,
                          filtered=allow_mask is not None) as sp, \
                self._lock:
            legs_d, legs_i = [], []
            if self.delta.live_count() > 0:
                dd, di = self.delta.epoch_scan(
                    queries, min(k, self.delta.capacity),
                    self._delta_allow(allow_mask, b))
                gmap = np.full(max(self.delta.capacity, 1), -1, np.int32)
                for ds, g in self._delta_slots.items():
                    if ds < len(gmap):
                        gmap[ds] = g
                gd = jnp.asarray(gmap)
                di = jnp.where(di >= 0,
                               gd[jnp.clip(di, 0, len(gmap) - 1)], -1)
                legs_d.append(jnp.where(di >= 0, dd, MASKED_DISTANCE))
                legs_i.append(di.astype(jnp.int32))
            if (self.trained and self._fill is not None
                    and int(self._fill.sum()) > 0):
                np_probe = min((nprobe or self._effective_nprobe()),
                               self.nlist)
                use_allow = allow_mask is not None
                if use_allow:
                    from weaviate_tpu.ops.pallas_kernels import (
                        mask_pad_cols, pack_allow_bitmask)

                    bits = jnp.asarray(pack_allow_bitmask(
                        allow_mask, mask_pad_cols(self.capacity)))
                    hbm_ledger.ledger.track("allow_bitmask", bits,
                                            **self._hbm_owner)
                else:
                    bits = _dummy_bits()
                k_cand = k * self.rescore_limit if self.quantization else k
                k_eff = min(k_cand, np_probe * self.list_cap)
                # EXPLAIN: the probe plan, host ints only (no device
                # reads — G1 stays empty); a no-op unless a sink is
                # installed for this dispatch
                kernelscope.explain_note(
                    "ivf", nprobe=np_probe, nlist=self.nlist,
                    lists_frac=(round(np_probe / self.nlist, 6)
                                if self.nlist else 0.0),
                    candidates=k_eff,
                    rescored=(k_eff if self.quantization else 0),
                    quantized=bool(self.quantization),
                    filtered=bool(use_allow), queries=b, k=k,
                    delta_leg=bool(legs_d))
                outs_d, outs_i = [], []
                for s in range(0, b, self.query_chunk):
                    q_dev = jnp.asarray(queries[s:s + self.query_chunk])
                    bch = (bits if bits.shape[0] == 1
                           else bits[s:s + self.query_chunk])
                    if self.quantization:
                        _, cand = _ivf_probe_topk_pq(
                            q_dev, self.centroids, self._c_norms,
                            self.list_codes, self.list_valid,
                            self.list_slots, self.list_tvals,
                            self.codebook.centroids, bch, k_eff,
                            np_probe, self.metric, use_allow)
                        # exact device rescore of the ADC oversample —
                        # masks already folded (dropped slots are -1)
                        qd, qs_ = gather_rescore_topk(
                            q_dev, cand, self._rescore_rows,
                            min(k, k_eff), self.metric)
                    else:
                        qd, qs_ = _ivf_probe_topk(
                            q_dev, self.centroids, self._c_norms,
                            self.list_vecs, self.list_valid,
                            self.list_slots, self.list_norms, bch, k_eff,
                            np_probe, self.metric, use_allow)
                    outs_d.append(qd)
                    outs_i.append(qs_)
                legs_d.append(outs_d[0] if len(outs_d) == 1
                              else jnp.concatenate(outs_d))
                legs_i.append((outs_i[0] if len(outs_i) == 1
                               else jnp.concatenate(outs_i))
                              .astype(jnp.int32))
            sp.set(nprobe=np_probe, nlist=self.nlist)
            kernelscope.explain_note("ivf", merge_legs=len(legs_d))
            if not legs_d:
                d_e = np.full((b, k), MASKED_DISTANCE, np.float32)
                i_e = np.full((b, k), -1, np.int64)
                return DeviceResultHandle.ready(
                    (d_e[0], i_e[0]) if squeeze else (d_e, i_e))
            if len(legs_d) == 1:
                md, mi = legs_d[0], legs_i[0]
            else:
                cat_d = jnp.concatenate(legs_d, axis=1)
                cat_i = jnp.concatenate(legs_i, axis=1)
                md, mi = topk_smallest(cat_d, cat_i,
                                       min(k, cat_d.shape[1]))

        def _finish(d_np, i_np, _k=k, _squeeze=squeeze):
            d_np = np.asarray(d_np, dtype=np.float32)
            i_np = np.asarray(i_np, dtype=np.int64)
            i_np = np.where(d_np >= MASKED_DISTANCE, -1, i_np)
            if d_np.shape[1] < _k:  # pad to k like the flat store contract
                pad = _k - d_np.shape[1]
                d_np = np.pad(d_np, ((0, 0), (0, pad)),
                              constant_values=MASKED_DISTANCE)
                i_np = np.pad(i_np, ((0, 0), (0, pad)), constant_values=-1)
            if _squeeze:
                return d_np[0], i_np[0]
            return d_np, i_np

        lists_frac = (np_probe / self.nlist) if self.nlist else 0.0
        return DeviceResultHandle(
            (md, mi), finish=_finish,
            attrs={"queries": b, "k": k, "nprobe": np_probe,
                   "nlist": self.nlist, "lists_frac": lists_frac})

    def search_by_distance(self, query: np.ndarray, max_distance: float,
                           allow_mask: np.ndarray | None = None):
        k = 64
        while True:
            d, i = self.search(query, k, allow_mask)
            within = (d <= max_distance) & (i >= 0)
            if (~within).any() or k >= max(self._count, 1):
                return d[within], i[within]
            k = min(k * 4, max(self._count, 1))

    # -- maintenance ---------------------------------------------------------

    def compact(self) -> np.ndarray:
        """Epoch/tombstone compaction is INCREMENTAL now: deletes already
        punched reusable holes, so compaction just folds the delta into
        lists — no full rebuild (``rebuild_count`` stays flat; the
        epochstore's maintain() relies on this being cheap). Slot ids
        stay stable (identity mapping for live slots) — the IVF layout
        doesn't tie slots to physical rows the way the flat store does."""
        with self._lock:
            mapping = np.full(self.capacity, -1, dtype=np.int64)
            for s in self._slot_loc:
                mapping[s] = s
            if self.trained:
                self.flush_delta()
            return mapping

    # -- persistence ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            vecs, slots = self._all_live_host()
            keep = np.asarray([s in self._slot_loc for s in slots.tolist()],
                              dtype=bool) if len(slots) else np.empty(0, bool)
            return {
                "kind": "ivf",
                "dim": self.dim,
                "metric": self.metric,
                "count": self._count,
                "nlist": self.nlist if self.trained else 0,
                "nprobe": self.nprobe,
                "centroids": (np.asarray(self.centroids, np.float32)
                              if self.trained else None),
                "live_vectors": vecs[keep] if len(slots) else vecs,
                "live_slots": slots[keep] if len(slots) else slots,
                "chunk_size": self.chunk_size,
                "dtype": jnp.dtype(self.dtype).name,
                "train_threshold": self.train_threshold,
                "delta_threshold": self.delta_threshold,
                # FlatIndex.snapshot() compatibility ("quantization" keys
                # the FlatIndex restore dispatch; IVF-PQ state rides under
                # its own keys)
                "valid": self._valid_over_slots(),
                "quantization": None,
                "ivf_quantization": self.quantization,
                "pq_segments": self.pq_segments,
                "pq_centroids": self.pq_centroids,
                "rescore_limit": self.rescore_limit,
                "retrain_factor": self.retrain_factor,
                "pq_codebook": (np.asarray(self.codebook.centroids)
                                if self.codebook is not None else None),
            }

    def _valid_over_slots(self) -> np.ndarray:
        v = np.zeros(self.capacity, dtype=bool)
        for s in self._slot_loc:
            v[s] = True
        return v

    @classmethod
    def restore(cls, snap: dict, **kwargs) -> "IVFStore":
        # storage dtype survives the round-trip unless explicitly overridden
        # (same contract as DeviceVectorStore.restore)
        dtype = kwargs.pop("dtype", None) or jnp.dtype(snap.get("dtype", "float32"))
        store = cls(dim=snap["dim"], metric=snap["metric"],
                    nlist=snap.get("nlist", 0), nprobe=snap.get("nprobe", 0),
                    chunk_size=snap.get("chunk_size", 8192),
                    train_threshold=snap.get("train_threshold", 16_384),
                    delta_threshold=snap.get("delta_threshold", 8192),
                    dtype=dtype,
                    quantization=snap.get("ivf_quantization"),
                    pq_segments=snap.get("pq_segments"),
                    pq_centroids=snap.get("pq_centroids", 16),
                    rescore_limit=snap.get("rescore_limit", 16),
                    retrain_factor=snap.get("retrain_factor", 4.0))
        slots = np.asarray(snap["live_slots"], dtype=np.int64)
        vecs = np.asarray(snap["live_vectors"], dtype=np.float32)
        store._count = snap["count"]
        if snap.get("pq_codebook") is not None:
            from weaviate_tpu.ops.pq import PQCodebook

            store.codebook = PQCodebook(jnp.asarray(snap["pq_codebook"]))
        if store.quantization and len(slots):
            # mirror rows were normalized at original insert
            norm = store.normalize_on_add
            store.normalize_on_add = False
            store._remember_rows(slots, vecs)
            store.normalize_on_add = norm
        if snap.get("centroids") is not None:
            store.nlist = snap["nlist"]
            store._centroids_np = np.asarray(snap["centroids"], np.float32)
            store.centroids = jnp.asarray(store._centroids_np)
            store._c_norms = jnp.sum(store.centroids * store.centroids, axis=1)
            if store.quantization and store.codebook is None:
                # quantization enabled before any codebook could train
                # (empty compress + sub-threshold adds): rows go back to
                # the exact delta; empty code lists keep _fill truthful
                store._rebuild_lists(np.empty((0, store.dim), np.float32),
                                     np.empty(0, np.int64))
                if len(vecs):
                    store._add_to_delta(slots, vecs)
            else:
                # empty corpora still allocate list tensors so later
                # delta flushes have somewhere to scatter (a None _fill
                # would crash the first _maybe_reorganize)
                store._rebuild_lists(vecs, slots)
            store._live_at_train = len(store._slot_loc)
            store._hbm_sync()  # centroids set outside _rebuild_lists
        elif len(vecs):
            # untrained: everything back into the delta buffer
            store._add_to_delta(slots, vecs)
        return store


class IVFIndex(FlatIndex):
    """VectorIndex-contract ANN index: FlatIndex id<->slot bookkeeping over
    an IVFStore (the bookkeeping is store-agnostic). See FlatIndex for the
    contract docs (reference: vector_index.go:24-45)."""

    index_type = "ivf"
    # IVFStore folds [B, capacity] per-query masks into packed allow_bits
    # inside the probe — the QueryBatcher coalesces filtered IVF requests
    # into one device program instead of dispatching them solo
    supports_batched_filters = True

    def __init__(self, dim: int, metric: str = "l2-squared",
                 capacity: int = 8192, chunk_size: int = 8192,
                 nlist: int = 0, nprobe: int = 0,
                 train_threshold: int = 16_384, delta_threshold: int = 8192,
                 mesh=None, dtype=None, quantization: str | None = None,
                 **quant_kwargs):
        if mesh is not None:
            raise NotImplementedError(
                "ivf is single-replica; collection sharding distributes it")
        store = IVFStore(dim=dim, metric=metric, capacity=capacity,
                         chunk_size=chunk_size, nlist=nlist, nprobe=nprobe,
                         train_threshold=train_threshold,
                         delta_threshold=delta_threshold, dtype=dtype,
                         quantization=quantization, **quant_kwargs)
        super().__init__(dim=dim, metric=metric, capacity=capacity,
                         chunk_size=chunk_size, store=store)

    def train(self, nlist: int | None = None):
        """Force coarse training now (normally automatic at threshold)."""
        with self._lock:
            self.store.train(force_nlist=nlist)

    def maintain(self) -> None:
        """Incremental maintenance (db/shard.py epoch_maintenance): delta
        flush always, retrain only past the drift gate — never a
        compaction-triggered full rebuild."""
        with self._lock:
            self.store.maintain()

    def compress(self, quantization: str = "pq", **quant_kwargs) -> None:
        """Runtime switch to residual-PQ residency: fit a codebook on the
        residuals of live contents and rebuild the posting lists as codes
        (reference lifecycle: hnsw/compress.go:38 via config update).
        Slot ids are stable, so the id<->slot maps carry over untouched.
        On an untrained store the codebook deferral stands (residuals
        need centroids): it trains alongside the coarse partition."""
        if quantization != "pq":
            raise ValueError("ivf supports quantization='pq'")
        from weaviate_tpu.ops.pq import default_pq_segments, pq_fit

        with self._lock:
            st = self.store
            if st.quantization:
                raise RuntimeError("index is already compressed")
            vecs, slots = st._all_live_host()
            # every fallible step runs BEFORE any store mutation, so a
            # rejected compress leaves the uncompressed index fully intact
            pq_centroids = quant_kwargs.get("pq_centroids") or st.pq_centroids
            pq_segments = (quant_kwargs.get("pq_segments")
                           or st.pq_segments
                           or default_pq_segments(st.dim, pq_centroids))
            if 0 < len(vecs) < pq_centroids:
                raise RuntimeError(
                    f"need >= {pq_centroids} live vectors to train PQ, "
                    f"have {len(vecs)}")
            codebook = None
            if len(vecs) and st.trained:
                assign = kmeans_assign(vecs, st._centroids_np)
                codebook = pq_fit(vecs - st._centroids_np[assign],
                                  m=pq_segments, k=pq_centroids, iters=8)
            st.quantization = "pq"
            st.pq_segments = pq_segments
            st.pq_centroids = pq_centroids
            if quant_kwargs.get("rescore_limit"):
                st.rescore_limit = quant_kwargs["rescore_limit"]
            st.codebook = codebook
            st._host_rows = np.zeros(
                (max(_next_pow2(max(st.capacity, 1)), 1024), st.dim),
                dtype=np.float32)
            if len(vecs):
                norm = st.normalize_on_add
                st.normalize_on_add = False  # rows already normalized
                st._remember_rows(slots, vecs)
                st.normalize_on_add = norm
            if st.trained:
                # rebuild absorbs delta-resident rows too — reset the
                # delta or its slots would be live in BOTH legs (duplicate
                # results now, double-scatter at the next flush). The
                # empty case still rebuilds so _fill reflects reality.
                st._rebuild_lists(vecs, slots)
                st._reset_delta()
            st._hbm_sync()

    @property
    def trained(self) -> bool:
        return self.store.trained

    @property
    def compressed(self) -> bool:
        return bool(self.store.quantization)

    @classmethod
    def restore(cls, snap: dict, **kwargs) -> "IVFIndex":
        idx = cls.__new__(cls)
        idx.dim = snap["dim"]
        idx.metric = snap["metric"]
        idx.store = IVFStore.restore(snap, **kwargs)
        idx._lock = threading.RLock()
        slot_to_id = snap["slot_to_id"]
        idx._slot_to_id = np.full(idx.store.capacity, -1, dtype=np.int64)
        idx._slot_to_id[: len(slot_to_id)] = slot_to_id
        idx._id_to_slot = {
            int(doc): int(slot)
            for slot, doc in enumerate(slot_to_id)
            if doc >= 0 and slot < len(snap["valid"]) and snap["valid"][slot]
        }
        return idx
