"""IVF (inverted-file) ANN index — the TPU-native ANN.

The reference's ANN is HNSW (vector/hnsw/index.go): a pointer-chasing graph
whose hot loop (search.go:173-341) is one-vector-at-a-time — the worst
possible shape for a systolic array. The TPU-idiomatic replacement
(SURVEY §7 step 5) is IVF/ScaNN-style partitioning:

- **train**: coarse k-means over the corpus (ops/kmeans.py, MXU Lloyd's)
- **layout**: posting lists as ONE dense padded tensor ``[nlist, cap, d]``
  in HBM (+ valid mask, slot ids, cached norms) — uniform shapes so the
  probe gather is a static-shape `take`, not ragged pointer chasing
- **search**: query→centroid matmul → top-nprobe lists → gather probed
  blocks → batched distance → masked top-k. Two matmuls and one gather
  replace thousands of dependent graph hops.
- **delta buffer**: recent inserts land in a small brute-force scanned
  DeviceVectorStore (exact), merged into lists when it fills (the LSM
  memtable idea applied to HBM; mirrors how the reference's async index
  queue batches graph inserts, index_queue.go:42).

Deletes tombstone rows in place (valid mask), exactly like the flat store.
Updates re-route the slot through the delta buffer. Global slot ids are
stable across flushes, so the FlatIndex id<->slot bookkeeping works
unchanged — IVFIndex subclasses FlatIndex and swaps the store.
"""

from __future__ import annotations

import functools
import math
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.engine.flat import FlatIndex
from weaviate_tpu.engine.store import DeviceVectorStore, _next_pow2
from weaviate_tpu.runtime import hbm_ledger
from weaviate_tpu.ops.distances import (MASKED_DISTANCE, normalize,
                                        normalize_np, pairwise_distance)
from weaviate_tpu.ops.kmeans import kmeans_assign, kmeans_fit
from weaviate_tpu.ops.topk import topk_smallest

_SUPPORTED_METRICS = ("l2-squared", "dot", "cosine", "cosine-dot")


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _scatter_lists(list_vecs, list_valid, list_slots, list_norms,
                   flat_idx, vecs, slots, write_mask):
    """Scatter rows into the flattened [nlist*cap] list tensor."""
    nlist, cap, dim = list_vecs.shape
    fv = list_vecs.reshape(nlist * cap, dim)
    fva = list_valid.reshape(nlist * cap)
    fs = list_slots.reshape(nlist * cap)
    fn = list_norms.reshape(nlist * cap)
    tgt = jnp.where(write_mask, flat_idx, nlist * cap)  # OOB rows drop
    vecs = vecs.astype(fv.dtype)
    norms = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=-1)
    fv = fv.at[tgt].set(vecs, mode="drop")
    fva = fva.at[tgt].set(True, mode="drop")
    fs = fs.at[tgt].set(slots, mode="drop")
    fn = fn.at[tgt].set(norms, mode="drop")
    return (fv.reshape(nlist, cap, dim), fva.reshape(nlist, cap),
            fs.reshape(nlist, cap), fn.reshape(nlist, cap))


@functools.partial(jax.jit, donate_argnums=(0,))
def _clear_list_rows(list_valid, flat_idx):
    nlist, cap = list_valid.shape
    flat = list_valid.reshape(nlist * cap)
    return flat.at[flat_idx].set(False, mode="drop").reshape(nlist, cap)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _scatter_code_lists(list_codes, list_valid, list_slots,
                        flat_idx, codes, slots, write_mask):
    """PQ-mode scatter: codes [m] uint8 rows into [nlist, cap, m] lists."""
    nlist, cap, m = list_codes.shape
    fc = list_codes.reshape(nlist * cap, m)
    fva = list_valid.reshape(nlist * cap)
    fs = list_slots.reshape(nlist * cap)
    tgt = jnp.where(write_mask, flat_idx, nlist * cap)
    fc = fc.at[tgt].set(codes, mode="drop")
    fva = fva.at[tgt].set(True, mode="drop")
    fs = fs.at[tgt].set(slots, mode="drop")
    return (fc.reshape(nlist, cap, m), fva.reshape(nlist, cap),
            fs.reshape(nlist, cap))


@functools.partial(jax.jit,
                   static_argnames=("k", "nprobe", "metric", "use_allow"))
def _ivf_probe_topk_pq(q, centroids, c_norms, list_codes, list_valid,
                       list_slots, pq_centroids, allow_by_slot, k: int,
                       nprobe: int, metric: str, use_allow: bool):
    """PQ-resident probe: gather CODES from the probed lists and score by
    per-query ADC lookup (ops/pq.py:pq_lut) — a lax.scan over segments
    accumulating [B, P] gathers, never materializing d-wide
    reconstructions (an earlier reconstruct-matmul formulation held
    [B, nprobe*cap, d] temporaries and OOM'd one chip at nprobe>=64).
    HBM reads per probed row are m bytes instead of 4d — the capacity
    regime IVF-PQ exists for (reference: PQ inside each shard's HNSW,
    compressionhelpers/product_quantization.go:372)."""
    from weaviate_tpu.ops.pq import pq_lut

    nlist, cap, m = list_codes.shape
    q32 = q.astype(jnp.float32)
    if metric in ("cosine", "cosine-dot"):
        q32 = normalize(q32)
    cd = pairwise_distance(q32, centroids, metric="l2-squared",
                           x_sq_norms=c_norms)
    _, probes = jax.lax.top_k(-cd, nprobe)  # [B, nprobe]

    codes = list_codes[probes].reshape(q.shape[0], nprobe * cap, m)
    vld = list_valid[probes].reshape(q.shape[0], nprobe * cap)
    slots = list_slots[probes].reshape(q.shape[0], nprobe * cap)
    b, p = codes.shape[0], codes.shape[1]
    lut = pq_lut(q32, pq_centroids, metric, m)  # [B, m, kc]
    kc = lut.shape[2]
    # ADC via ONE-HOT int8 MATMUL, chunked over the probed rows — the
    # earlier per-segment take_along_axis formulation issued B*P*m VPU
    # random gathers (~2 s/batch at capacity-scale probes and an OOM
    # crash beyond nprobe=8); one-hot + batched matvec puts the sum on
    # the MXU with bounded [B, Pc, kc*m] transients. LUT is per-query
    # int8-quantized (rank-preserving per query; candidates get exactly
    # rescored downstream).
    from weaviate_tpu.ops.pq import quantize_lut_int8

    lut8, scale = quantize_lut_int8(lut)
    # ~128 MB one-hot transient per scan step ACROSS the query batch
    # (b * pc * kc * m int8)
    pc = max(256, min(p, (1 << 27) // (kc * m * max(b, 1))))
    n_chunks = -(-p // pc)
    pad_p = n_chunks * pc - p
    codes_p = jnp.pad(codes, ((0, 0), (0, pad_p), (0, 0)))
    codes_c = codes_p.reshape(b, n_chunks, pc, m).transpose(1, 0, 2, 3)

    def one_chunk(carry, codes_blk):
        # copy-major tile (lane c*m + s) matching the code-major LUT flatten
        rep = jnp.tile(codes_blk.astype(jnp.int32), (1, 1, kc))
        lane = jax.lax.broadcasted_iota(jnp.int32, rep.shape, 2) // m
        oh = (rep == lane).astype(jnp.int8)          # [B, Pc, kc*m]
        dots = jax.lax.dot_general(
            lut8, oh,
            dimension_numbers=(((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.int32)         # [B, Pc]
        return carry, dots

    _, d8 = jax.lax.scan(one_chunk, None, codes_c)
    d = (jnp.transpose(d8, (1, 0, 2)).reshape(b, n_chunks * pc)[:, :p]
         .astype(jnp.float32) / scale[:, None])
    if metric == "l2-squared":
        d = jnp.maximum(d, 0.0)
    if use_allow:
        ok = allow_by_slot[jnp.clip(slots, 0, allow_by_slot.shape[0] - 1)]
        vld = vld & ok & (slots >= 0) & (slots < allow_by_slot.shape[0])
    d = jnp.where(vld, d, MASKED_DISTANCE)
    return topk_smallest(d, slots, min(k, nprobe * cap))


@functools.partial(jax.jit, static_argnames=("k", "nprobe", "metric", "use_allow"))
def _ivf_probe_topk(q, centroids, c_norms, list_vecs, list_valid, list_slots,
                    list_norms, allow_by_slot, k: int, nprobe: int,
                    metric: str, use_allow: bool):
    """Probe + score + select for a query batch.

    q [B,d] → centroid distances [B,nlist] (MXU matmul) → top-nprobe →
    gather [B, nprobe, cap, …] → per-query batched distance → masked top-k.
    Returns (dists [B,k], slots [B,k]) ascending; dead/filtered rows never
    surface. Memory is O(B * nprobe * cap * d): callers chunk B.
    """
    nlist, cap, dim = list_vecs.shape
    q32 = q.astype(jnp.float32)
    if metric in ("cosine", "cosine-dot"):
        q32 = normalize(q32)
    cd = pairwise_distance(q32, centroids, metric="l2-squared",
                           x_sq_norms=c_norms)
    _, probes = jax.lax.top_k(-cd, nprobe)  # [B, nprobe]

    vecs = list_vecs[probes].reshape(q.shape[0], nprobe * cap, dim)
    vld = list_valid[probes].reshape(q.shape[0], nprobe * cap)
    slots = list_slots[probes].reshape(q.shape[0], nprobe * cap)
    nrm = list_norms[probes].reshape(q.shape[0], nprobe * cap)

    dots = jnp.einsum("bd,bpd->bp", q32, vecs.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    if metric == "l2-squared":
        qn = jnp.sum(q32 * q32, axis=-1)[:, None]
        d = jnp.maximum(qn - 2.0 * dots + nrm, 0.0)
    elif metric == "dot":
        d = -dots
    else:  # cosine: rows stored normalized
        d = 1.0 - dots
    if use_allow:
        ok = allow_by_slot[jnp.clip(slots, 0, allow_by_slot.shape[0] - 1)]
        vld = vld & ok & (slots >= 0) & (slots < allow_by_slot.shape[0])
    d = jnp.where(vld, d, MASKED_DISTANCE)
    return topk_smallest(d, slots, min(k, nprobe * cap))


class IVFStore:
    """DeviceVectorStore-compatible store backed by IVF posting lists plus a
    brute-force delta buffer. Slot ids are append-order and stable."""

    mesh = None  # single-replica; collection-level sharding distributes IVF

    def __init__(self, dim: int, metric: str = "l2-squared",
                 capacity: int = 8192, chunk_size: int = 8192,
                 nlist: int = 0, nprobe: int = 0,
                 train_threshold: int = 16_384,
                 delta_threshold: int = 8192,
                 query_chunk: int = 16,
                 dtype=None,
                 quantization: str | None = None,
                 pq_segments: int | None = None,
                 pq_centroids: int = 16,
                 rescore_limit: int = 16):
        if metric not in _SUPPORTED_METRICS:
            raise ValueError(
                f"ivf supports {_SUPPORTED_METRICS}, not {metric!r}")
        if quantization not in (None, "pq"):
            raise ValueError(f"ivf quantization must be None or 'pq', "
                             f"not {quantization!r}")
        self.dim = dim
        self.metric = metric
        self.chunk_size = chunk_size
        self.dtype = dtype or jnp.float32
        self.nlist = nlist  # 0 = auto at train time
        self.nprobe = nprobe  # 0 = auto (nlist/8, min 8)
        self.train_threshold = train_threshold
        self.delta_threshold = delta_threshold
        self.query_chunk = query_chunk
        # IVF-PQ residency (VERDICT r2 item 4b): posting lists hold uint8
        # PQ codes instead of full rows; oversampled candidates rescore
        # exactly against the host f32 mirror. The delta buffer stays
        # exact either way.
        self.quantization = quantization
        self.pq_centroids = pq_centroids
        if quantization and not pq_segments:
            from weaviate_tpu.ops.pq import default_pq_segments

            pq_segments = default_pq_segments(dim, pq_centroids)
        self.pq_segments = pq_segments
        self.rescore_limit = rescore_limit
        self.codebook = None
        self.list_codes = None
        self._host_rows = (
            np.zeros((max(capacity, 1024), dim), dtype=np.float32)
            if quantization else None)
        self.normalize_on_add = metric in ("cosine", "cosine-dot")
        self._lock = threading.RLock()
        self._count = 0  # global slot high-water mark
        # HBM ledger: centroid + posting-list tensors publish under the
        # owner labels captured here; the delta store self-accounts (it
        # is a DeviceVectorStore constructed in this same owner scope)
        self._hbm_owner = hbm_ledger.current_owner()
        self._hbm_keys: dict[str, int] = {}
        weakref.finalize(self, hbm_ledger.ledger.release_many,
                         self._hbm_keys.values())
        # delta buffer (exact scan); delta slot -> global slot
        self.delta = DeviceVectorStore(
            dim, metric, capacity=min(capacity, delta_threshold * 2),
            chunk_size=chunk_size)
        self._delta_slots: dict[int, int] = {}  # delta slot -> global
        # slot -> ("delta", dslot) | ("list", flat_idx)
        self._slot_loc: dict[int, tuple] = {}
        # list tensors (allocated at train time)
        self.centroids = None  # jnp [nlist, d]
        self._c_norms = None
        self.list_vecs = None  # [nlist, cap, d]
        self.list_valid = None
        self.list_slots = None
        self.list_norms = None
        self.list_cap = 0
        self._fill: np.ndarray | None = None  # host per-list fill count

    def _hbm_sync(self):
        """Publish centroid + posting-list device bytes to the ledger
        (the delta DeviceVectorStore accounts for itself)."""
        cent = 0 if self.centroids is None else (
            int(self.centroids.nbytes) + int(self._c_norms.nbytes))
        hbm_ledger.ledger.set_keyed(
            self._hbm_keys, "centroids", cent, owner=self._hbm_owner,
            dtype="float32")
        lists = sum(int(a.nbytes) for a in (
            self.list_vecs, self.list_codes, self.list_norms,
            self.list_valid, self.list_slots) if a is not None)
        hbm_ledger.ledger.set_keyed(
            self._hbm_keys, "lists", lists, owner=self._hbm_owner,
            dtype=("uint8" if self.quantization
                   else jnp.dtype(self.dtype).name))

    # -- properties mirrored from DeviceVectorStore ---------------------------

    @property
    def count(self) -> int:
        return self._count

    @property
    def capacity(self) -> int:
        """Global slot-space bound (exclusive upper bound on slot ids)."""
        return max(_next_pow2(max(self._count, 1)), 8)

    @property
    def trained(self) -> bool:
        return self.centroids is not None

    def live_count(self) -> int:
        with self._lock:
            return len(self._slot_loc)

    # -- mutation -------------------------------------------------------------

    def add(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        with self._lock:
            slots = np.arange(self._count, self._count + len(vectors),
                              dtype=np.int64)
            self._count += len(vectors)
            self._remember_rows(slots, vectors)
            self._add_to_delta(slots, vectors)
            self._maybe_reorganize()
            return slots

    def _remember_rows(self, slots: np.ndarray, vectors: np.ndarray):
        """PQ mode keeps an f32 host mirror (codes are lossy): rescore +
        retrain + rebuild all read from here. Caller holds ``_lock``."""
        if self._host_rows is None or len(slots) == 0:
            return
        if self.normalize_on_add:
            vectors = normalize_np(vectors)
        mx = int(np.max(slots))
        if mx >= len(self._host_rows):
            grown = np.zeros((_next_pow2(mx + 1), self.dim), np.float32)
            grown[: len(self._host_rows)] = self._host_rows
            self._host_rows = grown
        self._host_rows[slots] = vectors

    def _add_to_delta(self, slots: np.ndarray, vectors: np.ndarray):
        dslots = self.delta.add(vectors)
        for g, d in zip(slots.tolist(), dslots.tolist()):
            self._delta_slots[int(d)] = int(g)
            self._slot_loc[int(g)] = ("delta", int(d))

    def set_at(self, slots: np.ndarray, vectors: np.ndarray):
        """Overwrite slots in place. List-resident slots are tombstoned there
        and re-routed through the delta buffer (their assignment may change)."""
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        with self._lock:
            self._count = max(self._count, int(slots.max()) + 1 if len(slots) else 0)
            self._remember_rows(slots, vectors)
            delta_upd_d, delta_upd_v = [], []
            fresh_s, fresh_v = [], []
            clear_flat = []
            for s, v in zip(slots.tolist(), vectors):
                loc = self._slot_loc.get(int(s))
                if loc is not None and loc[0] == "delta":
                    delta_upd_d.append(loc[1])
                    delta_upd_v.append(v)
                else:
                    if loc is not None:  # list-resident: tombstone there
                        clear_flat.append(loc[1])
                    fresh_s.append(int(s))
                    fresh_v.append(v)
            if clear_flat:
                self.list_valid = _clear_list_rows(
                    self.list_valid, jnp.asarray(clear_flat, dtype=jnp.int32))
            if delta_upd_d:
                self.delta.set_at(np.asarray(delta_upd_d),
                                  np.stack(delta_upd_v))
            if fresh_s:
                self._add_to_delta(np.asarray(fresh_s), np.stack(fresh_v))
            self._maybe_reorganize()

    def delete(self, slots) -> None:
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int64))
        with self._lock:
            clear_flat, delta_del = [], []
            for s in slots.tolist():
                loc = self._slot_loc.pop(int(s), None)
                if loc is None:
                    continue
                if loc[0] == "delta":
                    delta_del.append(loc[1])
                    self._delta_slots.pop(loc[1], None)
                else:
                    clear_flat.append(loc[1])
            if delta_del:
                self.delta.delete(np.asarray(delta_del))
            if clear_flat:
                self.list_valid = _clear_list_rows(
                    self.list_valid, jnp.asarray(clear_flat, dtype=jnp.int32))

    # -- training / reorganization -------------------------------------------

    def _maybe_reorganize(self):
        if not self.trained:
            if len(self._slot_loc) >= self.train_threshold:
                self.train()
        elif len(self._delta_slots) >= self.delta_threshold:
            self.flush_delta()

    def _auto_nlist(self, n: int) -> int:
        # ~2*sqrt(N) lists, pow2-rounded, clamped: large enough to prune,
        # small enough that centroids fit one matmul
        return int(min(8192, max(16, _next_pow2(int(2 * math.sqrt(n))))))

    def train(self, force_nlist: int | None = None):
        """Learn the coarse partition from current contents and move
        everything into posting lists (reference analog: hnsw compress.go:38
        trains PQ once enough data exists — same lifecycle hook)."""
        with self._lock:
            vecs, slots = self._all_live_host()
            n = len(vecs)
            if n == 0:
                raise RuntimeError("cannot train IVF on an empty store")
            nlist = force_nlist or self.nlist or self._auto_nlist(n)
            nlist = min(nlist, n)
            train_vecs = vecs
            self.nlist = nlist
            cents = kmeans_fit(train_vecs, nlist, iters=10)
            if self.normalize_on_add:
                # keep centroids on the sphere so probe distances stay comparable
                cents = normalize_np(cents)
            self.centroids = jnp.asarray(cents)
            self._c_norms = jnp.sum(self.centroids * self.centroids, axis=1)
            if self.quantization:
                from weaviate_tpu.ops.pq import pq_fit

                self.codebook = pq_fit(train_vecs, m=self.pq_segments,
                                       k=self.pq_centroids, iters=8)
            self._rebuild_lists(vecs, slots)
            # delta fully absorbed
            self._reset_delta()
            self._hbm_sync()

    def _all_live_host(self):
        """(vectors [L,d] f32, slots [L] int64) for every live slot."""
        out_v, out_s = [], []
        if self.trained and (self.list_vecs is not None
                             or self.list_codes is not None):
            lval = np.asarray(self.list_valid).reshape(-1)
            lslot = np.asarray(self.list_slots).reshape(-1)
            live = np.nonzero(lval)[0]
            slots_live = lslot[live].astype(np.int64)
            if self.quantization:
                # codes are lossy — originals live in the host mirror
                out_v.append(self._host_rows[slots_live])
            else:
                lv = np.asarray(self.list_vecs,
                                dtype=np.float32).reshape(-1, self.dim)
                out_v.append(lv[live])
            out_s.append(slots_live)
        dsnap = self.delta.snapshot()
        dlive = np.nonzero(dsnap["valid"])[0]
        if len(dlive):
            out_v.append(dsnap["vectors"][dlive])
            out_s.append(np.asarray(
                [self._delta_slots[int(d)] for d in dlive], dtype=np.int64))
        if not out_v:
            return (np.empty((0, self.dim), np.float32),
                    np.empty(0, np.int64))
        return np.concatenate(out_v), np.concatenate(out_s)

    def _rebuild_lists(self, vecs: np.ndarray, slots: np.ndarray):
        """Assign + scatter everything into fresh list tensors.
        Caller holds ``_lock`` (train/retrain section)."""
        assign = (kmeans_assign(vecs, np.asarray(self.centroids))
                  if len(vecs) else np.empty(0, np.int64))
        counts = np.bincount(assign, minlength=self.nlist)
        cap = max(8, _next_pow2(int(counts.max()) if len(counts) else 8))
        self.list_cap = cap
        if self.quantization:
            self.list_codes = jnp.zeros(
                (self.nlist, cap, self.pq_segments), dtype=jnp.uint8)
            self.list_vecs = None
            self.list_norms = None
        else:
            self.list_vecs = jnp.zeros((self.nlist, cap, self.dim),
                                       dtype=self.dtype)
            self.list_norms = jnp.zeros((self.nlist, cap), dtype=jnp.float32)
        self.list_valid = jnp.zeros((self.nlist, cap), dtype=jnp.bool_)
        self.list_slots = jnp.full((self.nlist, cap), -1, dtype=jnp.int32)
        self._fill = np.zeros(self.nlist, dtype=np.int64)
        self._hbm_sync()
        self._scatter_assigned(vecs, slots, assign)

    def _scatter_assigned(self, vecs, slots, assign):
        """Place (vec, slot) pairs at the next free position of their list."""
        if len(vecs) == 0:
            return
        pos = np.empty(len(assign), dtype=np.int64)
        order = np.argsort(assign, kind="stable")
        sorted_assign = assign[order]
        # per-list sequential positions after current fill
        starts = {}
        for idx, l in zip(order.tolist(), sorted_assign.tolist()):
            p = starts.get(l)
            if p is None:
                p = int(self._fill[l])
            pos[idx] = p
            starts[l] = p + 1
        for l, nxt in starts.items():
            self._fill[l] = nxt
        max_needed = int(self._fill.max()) if len(self._fill) else 0
        while max_needed > self.list_cap:
            self._grow_cap()
        flat_idx = assign.astype(np.int64) * self.list_cap + pos
        bucket = _next_pow2(max(len(vecs), 8))
        i_buf = np.zeros(bucket, np.int32)
        i_buf[:len(vecs)] = flat_idx
        s_buf = np.zeros(bucket, np.int32)
        s_buf[:len(vecs)] = slots
        m_buf = np.zeros(bucket, bool)
        m_buf[:len(vecs)] = True
        if self.quantization:
            from weaviate_tpu.ops.pq import pq_encode

            codes = pq_encode(self.codebook, vecs)
            c_buf = np.zeros((bucket, self.pq_segments), np.uint8)
            c_buf[:len(vecs)] = codes
            (self.list_codes, self.list_valid,
             self.list_slots) = _scatter_code_lists(
                self.list_codes, self.list_valid, self.list_slots,
                jnp.asarray(i_buf), jnp.asarray(c_buf), jnp.asarray(s_buf),
                jnp.asarray(m_buf))
        else:
            v_buf = np.zeros((bucket, self.dim), np.float32)
            v_buf[:len(vecs)] = vecs
            (self.list_vecs, self.list_valid, self.list_slots,
             self.list_norms) = _scatter_lists(
                self.list_vecs, self.list_valid, self.list_slots,
                self.list_norms,
                jnp.asarray(i_buf), jnp.asarray(v_buf), jnp.asarray(s_buf),
                jnp.asarray(m_buf))
        for s, fi in zip(slots.tolist(), flat_idx.tolist()):
            self._slot_loc[int(s)] = ("list", int(fi))

    def _grow_cap(self):
        """Double per-list capacity (repack on host — rare, amortized)."""
        old_cap = self.list_cap
        new_cap = old_cap * 2
        pad = new_cap - old_cap
        if self.quantization:
            self.list_codes = jnp.concatenate(
                [self.list_codes,
                 jnp.zeros((self.nlist, pad, self.pq_segments),
                           dtype=jnp.uint8)], axis=1)
        else:
            self.list_vecs = jnp.concatenate(
                [self.list_vecs,
                 jnp.zeros((self.nlist, pad, self.dim), dtype=self.dtype)],
                axis=1)
            self.list_norms = jnp.concatenate(
                [self.list_norms,
                 jnp.zeros((self.nlist, pad), dtype=jnp.float32)], axis=1)
        self.list_valid = jnp.concatenate(
            [self.list_valid, jnp.zeros((self.nlist, pad), dtype=jnp.bool_)],
            axis=1)
        self.list_slots = jnp.concatenate(
            [self.list_slots, jnp.full((self.nlist, pad), -1, dtype=jnp.int32)],
            axis=1)
        self.list_cap = new_cap
        self._hbm_sync()
        # flat indices shift: old flat l*old_cap+p -> l*new_cap+p
        for s, loc in self._slot_loc.items():
            if loc[0] == "list":
                l, p = divmod(loc[1], old_cap)
                self._slot_loc[s] = ("list", l * new_cap + p)

    def flush_delta(self):
        """Merge the delta buffer into posting lists (memtable flush)."""
        with self._lock:
            if not self.trained:
                return
            dsnap = self.delta.snapshot()
            live = np.nonzero(dsnap["valid"])[0]
            if len(live) == 0:
                self._reset_delta()
                return
            vecs = dsnap["vectors"][live]
            slots = np.asarray([self._delta_slots[int(d)] for d in live],
                               dtype=np.int64)
            if self.quantization and self.codebook is None:
                # compression was enabled while the store was empty —
                # the codebook trains on the first flush with enough data
                # (until then rows stay in the exact delta)
                if len(vecs) < self.pq_centroids:
                    return
                from weaviate_tpu.ops.pq import pq_fit

                self.codebook = pq_fit(vecs, m=self.pq_segments,
                                       k=self.pq_centroids, iters=8)
            assign = kmeans_assign(vecs, np.asarray(self.centroids))
            self._scatter_assigned(vecs, slots, assign)
            self._reset_delta()

    def _reset_delta(self):
        """Swap in a fresh delta store. Caller holds ``_lock``."""
        # rebuilt outside the shard's construction scope — re-enter the
        # captured owner labels so the fresh delta store stays attributed
        with hbm_ledger.owner(**self._hbm_owner):
            self.delta = DeviceVectorStore(
                self.dim, self.metric,
                capacity=min(self.capacity, self.delta_threshold * 2),
                chunk_size=self.chunk_size)
        self._delta_slots = {}

    # -- queries -------------------------------------------------------------

    def _rescore(self, queries: np.ndarray, cand_slots: np.ndarray, k: int):
        """Exact f32 rescore of PQ candidates against the host mirror
        (reference rescore pattern: flat/index.go:347). Normalizes the
        query side for cosine; mirror rows were normalized at insert."""
        q = queries
        if self.normalize_on_add:
            q = normalize_np(q)
        b, kc = cand_slots.shape
        safe = np.clip(cand_slots, 0, len(self._host_rows) - 1)
        cand = self._host_rows[safe]  # [B, kc, d]
        if self.metric == "dot":
            dd = -np.einsum("bd,bkd->bk", q, cand)
        elif self.metric in ("cosine", "cosine-dot"):
            dd = 1.0 - np.einsum("bd,bkd->bk", q, cand)
        else:
            diff = q[:, None, :] - cand
            dd = np.einsum("bkd,bkd->bk", diff, diff)
        dd = np.where(cand_slots >= 0, dd, MASKED_DISTANCE)
        k_eff = min(k, kc)
        part = np.argpartition(dd, k_eff - 1, axis=1)[:, :k_eff]
        pd = np.take_along_axis(dd, part, axis=1)
        order = np.argsort(pd, axis=1, kind="stable")
        sel = np.take_along_axis(part, order, axis=1)
        out_d = np.take_along_axis(dd, sel, axis=1).astype(np.float32)
        out_s = np.take_along_axis(cand_slots, sel, axis=1)
        out_s = np.where(out_d >= MASKED_DISTANCE, -1, out_s)
        return out_d, out_s

    def _effective_nprobe(self) -> int:
        if self.nprobe:
            return min(self.nprobe, self.nlist)
        return min(self.nlist, max(8, self.nlist // 8))

    def search(self, queries: np.ndarray, k: int,
               allow_mask: np.ndarray | None = None,
               nprobe: int | None = None):
        """Merged top-k over delta (exact) + probed lists (ANN)."""
        queries = np.asarray(queries, dtype=np.float32)
        squeeze = queries.ndim == 1
        if squeeze:
            queries = queries[None, :]
        b = len(queries)
        with self._lock:
            # --- delta leg (exact scan over the small recent set)
            d_d = np.full((b, 0), MASKED_DISTANCE, np.float32)
            d_s = np.full((b, 0), -1, np.int64)
            if self.delta.live_count() > 0:
                delta_allow = None
                if allow_mask is not None:
                    delta_allow = np.zeros(self.delta.capacity, dtype=bool)
                    for ds, g in self._delta_slots.items():
                        if g < len(allow_mask) and allow_mask[g]:
                            delta_allow[ds] = True
                dd, dslots = self.delta.search(queries, min(k, self.delta.capacity),
                                              delta_allow)
                # delta slot -> global slot
                gmap = np.full(self.delta.capacity + 1, -1, np.int64)
                for ds, g in self._delta_slots.items():
                    gmap[ds] = g
                d_s = np.where(dslots >= 0, gmap[np.clip(dslots, 0, None)], -1)
                d_d = np.where(d_s >= 0, dd, MASKED_DISTANCE)
            # --- list leg
            l_d = np.full((b, 0), MASKED_DISTANCE, np.float32)
            l_s = np.full((b, 0), -1, np.int64)
            if self.trained and self._fill is not None and self._fill.sum() > 0:
                np_probe = min((nprobe or self._effective_nprobe()), self.nlist)
                use_allow = allow_mask is not None
                allow_dev = jnp.asarray(
                    allow_mask if use_allow else np.ones(1, bool))
                k_cand = k * self.rescore_limit if self.quantization else k
                k_eff = min(k_cand, np_probe * self.list_cap)
                outs_d, outs_s = [], []
                for s in range(0, b, self.query_chunk):
                    if self.quantization:
                        qd, qs = _ivf_probe_topk_pq(
                            jnp.asarray(queries[s:s + self.query_chunk]),
                            self.centroids, self._c_norms,
                            self.list_codes, self.list_valid,
                            self.list_slots, self.codebook.centroids,
                            allow_dev, k_eff, np_probe,
                            self.metric, use_allow)
                    else:
                        qd, qs = _ivf_probe_topk(
                            jnp.asarray(queries[s:s + self.query_chunk]),
                            self.centroids, self._c_norms,
                            self.list_vecs, self.list_valid, self.list_slots,
                            self.list_norms, allow_dev, k_eff, np_probe,
                            self.metric, use_allow)
                    outs_d.append(np.asarray(qd))
                    outs_s.append(np.asarray(qs, dtype=np.int64))
                l_d = np.concatenate(outs_d)
                l_s = np.concatenate(outs_s)
                # masked rows (deleted / filtered) keep their slot ids in
                # the top-k output — map them to -1 BEFORE rescore, which
                # would otherwise resurrect them with exact distances
                l_s = np.where(l_d >= MASKED_DISTANCE, -1, l_s)
                if self.quantization:
                    l_d, l_s = self._rescore(queries, l_s, k)
        # --- host merge of the two legs
        cat_d = np.concatenate([d_d, l_d], axis=1)
        cat_s = np.concatenate([d_s, l_s], axis=1)
        k_out = min(k, cat_d.shape[1]) if cat_d.shape[1] else 0
        if k_out == 0:
            empty_d = np.full((b, k), MASKED_DISTANCE, np.float32)
            empty_s = np.full((b, k), -1, np.int64)
            return (empty_d[0], empty_s[0]) if squeeze else (empty_d, empty_s)
        cat_d = np.where(cat_s >= 0, cat_d, MASKED_DISTANCE)
        order = np.argsort(cat_d, axis=1, kind="stable")[:, :k]
        out_d = np.take_along_axis(cat_d, order, axis=1)
        out_s = np.take_along_axis(cat_s, order, axis=1)
        out_s = np.where(out_d >= MASKED_DISTANCE, -1, out_s)
        if out_d.shape[1] < k:  # pad to k like the flat store contract
            pad = k - out_d.shape[1]
            out_d = np.pad(out_d, ((0, 0), (0, pad)),
                           constant_values=MASKED_DISTANCE)
            out_s = np.pad(out_s, ((0, 0), (0, pad)), constant_values=-1)
        if squeeze:
            return out_d[0], out_s[0]
        return out_d, out_s

    def search_by_distance(self, query: np.ndarray, max_distance: float,
                           allow_mask: np.ndarray | None = None):
        k = 64
        while True:
            d, i = self.search(query, k, allow_mask)
            within = (d <= max_distance) & (i >= 0)
            if (~within).any() or k >= max(self._count, 1):
                return d[within], i[within]
            k = min(k * 4, max(self._count, 1))

    # -- maintenance ---------------------------------------------------------

    def compact(self) -> np.ndarray:
        """Drop tombstones and repack lists. Slot ids stay stable (identity
        mapping for live slots) — the IVF layout doesn't tie slots to
        physical rows the way the flat store does."""
        with self._lock:
            mapping = np.full(self.capacity, -1, dtype=np.int64)
            for s in self._slot_loc:
                mapping[s] = s
            if self.trained:
                vecs, slots = self._all_live_host()
                # keep only live (slot_loc) entries
                keep = np.asarray([s in self._slot_loc for s in slots.tolist()])
                self._fill = np.zeros(self.nlist, dtype=np.int64)
                self._rebuild_lists(vecs[keep], slots[keep])
                self._reset_delta()
            return mapping

    # -- persistence ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            vecs, slots = self._all_live_host()
            keep = np.asarray([s in self._slot_loc for s in slots.tolist()],
                              dtype=bool) if len(slots) else np.empty(0, bool)
            return {
                "kind": "ivf",
                "dim": self.dim,
                "metric": self.metric,
                "count": self._count,
                "nlist": self.nlist if self.trained else 0,
                "nprobe": self.nprobe,
                "centroids": (np.asarray(self.centroids, np.float32)
                              if self.trained else None),
                "live_vectors": vecs[keep] if len(slots) else vecs,
                "live_slots": slots[keep] if len(slots) else slots,
                "chunk_size": self.chunk_size,
                "dtype": jnp.dtype(self.dtype).name,
                "train_threshold": self.train_threshold,
                "delta_threshold": self.delta_threshold,
                # FlatIndex.snapshot() compatibility ("quantization" keys
                # the FlatIndex restore dispatch; IVF-PQ state rides under
                # its own keys)
                "valid": self._valid_over_slots(),
                "quantization": None,
                "ivf_quantization": self.quantization,
                "pq_segments": self.pq_segments,
                "pq_centroids": self.pq_centroids,
                "rescore_limit": self.rescore_limit,
                "pq_codebook": (np.asarray(self.codebook.centroids)
                                if self.codebook is not None else None),
            }

    def _valid_over_slots(self) -> np.ndarray:
        v = np.zeros(self.capacity, dtype=bool)
        for s in self._slot_loc:
            v[s] = True
        return v

    @classmethod
    def restore(cls, snap: dict, **kwargs) -> "IVFStore":
        # storage dtype survives the round-trip unless explicitly overridden
        # (same contract as DeviceVectorStore.restore)
        dtype = kwargs.pop("dtype", None) or jnp.dtype(snap.get("dtype", "float32"))
        store = cls(dim=snap["dim"], metric=snap["metric"],
                    nlist=snap.get("nlist", 0), nprobe=snap.get("nprobe", 0),
                    chunk_size=snap.get("chunk_size", 8192),
                    train_threshold=snap.get("train_threshold", 16_384),
                    delta_threshold=snap.get("delta_threshold", 8192),
                    dtype=dtype,
                    quantization=snap.get("ivf_quantization"),
                    pq_segments=snap.get("pq_segments"),
                    pq_centroids=snap.get("pq_centroids", 16),
                    rescore_limit=snap.get("rescore_limit", 16))
        slots = np.asarray(snap["live_slots"], dtype=np.int64)
        vecs = np.asarray(snap["live_vectors"], dtype=np.float32)
        store._count = snap["count"]
        if snap.get("pq_codebook") is not None:
            from weaviate_tpu.ops.pq import PQCodebook

            store.codebook = PQCodebook(jnp.asarray(snap["pq_codebook"]))
        if store.quantization and len(slots):
            # mirror rows were normalized at original insert
            norm = store.normalize_on_add
            store.normalize_on_add = False
            store._remember_rows(slots, vecs)
            store.normalize_on_add = norm
        if snap.get("centroids") is not None:
            store.nlist = snap["nlist"]
            store.centroids = jnp.asarray(snap["centroids"])
            store._c_norms = jnp.sum(store.centroids * store.centroids, axis=1)
            if store.quantization and store.codebook is None:
                # quantization enabled before any codebook could train
                # (empty compress + sub-threshold adds): rows go back to
                # the exact delta; empty code lists keep _fill truthful
                store._rebuild_lists(np.empty((0, store.dim), np.float32),
                                     np.empty(0, np.int64))
                if len(vecs):
                    store._add_to_delta(slots, vecs)
            elif len(vecs):
                store._rebuild_lists(vecs, slots)
            else:
                # trained-but-empty: allocate empty list tensors so later
                # delta flushes have somewhere to scatter (a None _fill
                # would crash the first _maybe_reorganize)
                cap = 8
                store.list_cap = cap
                if store.quantization:
                    store.list_codes = jnp.zeros(
                        (store.nlist, cap, store.pq_segments),
                        dtype=jnp.uint8)
                else:
                    store.list_vecs = jnp.zeros(
                        (store.nlist, cap, store.dim), dtype=store.dtype)
                    store.list_norms = jnp.zeros((store.nlist, cap),
                                                 dtype=jnp.float32)
                store.list_valid = jnp.zeros((store.nlist, cap), dtype=jnp.bool_)
                store.list_slots = jnp.full((store.nlist, cap), -1, dtype=jnp.int32)
                store._fill = np.zeros(store.nlist, dtype=np.int64)
            store._hbm_sync()  # centroids set outside _rebuild_lists
        elif len(vecs):
            # untrained: everything back into the delta buffer
            store._add_to_delta(slots, vecs)
        return store


class IVFIndex(FlatIndex):
    """VectorIndex-contract ANN index: FlatIndex id<->slot bookkeeping over
    an IVFStore (the bookkeeping is store-agnostic). See FlatIndex for the
    contract docs (reference: vector_index.go:24-45)."""

    index_type = "ivf"
    # IVFStore.search takes shared [capacity] masks only — the batcher
    # keeps filtered requests on the solo path for this index type
    supports_batched_filters = False

    def __init__(self, dim: int, metric: str = "l2-squared",
                 capacity: int = 8192, chunk_size: int = 8192,
                 nlist: int = 0, nprobe: int = 0,
                 train_threshold: int = 16_384, delta_threshold: int = 8192,
                 mesh=None, dtype=None, quantization: str | None = None,
                 **quant_kwargs):
        if mesh is not None:
            raise NotImplementedError(
                "ivf is single-replica; collection sharding distributes it")
        store = IVFStore(dim=dim, metric=metric, capacity=capacity,
                         chunk_size=chunk_size, nlist=nlist, nprobe=nprobe,
                         train_threshold=train_threshold,
                         delta_threshold=delta_threshold, dtype=dtype,
                         quantization=quantization, **quant_kwargs)
        super().__init__(dim=dim, metric=metric, capacity=capacity,
                         chunk_size=chunk_size, store=store)

    def train(self, nlist: int | None = None):
        """Force coarse training now (normally automatic at threshold)."""
        with self._lock:
            self.store.train(force_nlist=nlist)

    def compress(self, quantization: str = "pq", **quant_kwargs) -> None:
        """Runtime switch to PQ residency: fit a codebook on live contents
        and rebuild the posting lists as codes (reference lifecycle:
        hnsw/compress.go:38 via config update). Slot ids are stable, so
        the id<->slot maps carry over untouched."""
        if quantization != "pq":
            raise ValueError("ivf supports quantization='pq'")
        from weaviate_tpu.ops.pq import default_pq_segments, pq_fit

        with self._lock:
            st = self.store
            if st.quantization:
                raise RuntimeError("index is already compressed")
            vecs, slots = st._all_live_host()
            # every fallible step runs BEFORE any store mutation, so a
            # rejected compress leaves the uncompressed index fully intact
            pq_centroids = quant_kwargs.get("pq_centroids") or st.pq_centroids
            pq_segments = (quant_kwargs.get("pq_segments")
                           or st.pq_segments
                           or default_pq_segments(st.dim, pq_centroids))
            if 0 < len(vecs) < pq_centroids:
                raise RuntimeError(
                    f"need >= {pq_centroids} live vectors to train PQ, "
                    f"have {len(vecs)}")
            codebook = (pq_fit(vecs, m=pq_segments, k=pq_centroids, iters=8)
                        if len(vecs) else None)
            st.quantization = "pq"
            st.pq_segments = pq_segments
            st.pq_centroids = pq_centroids
            if quant_kwargs.get("rescore_limit"):
                st.rescore_limit = quant_kwargs["rescore_limit"]
            st.codebook = codebook
            st._host_rows = np.zeros(
                (max(_next_pow2(max(st.capacity, 1)), 1024), st.dim),
                dtype=np.float32)
            if len(vecs):
                norm = st.normalize_on_add
                st.normalize_on_add = False  # rows already normalized
                st._remember_rows(slots, vecs)
                st.normalize_on_add = norm
            if st.trained:
                # rebuild absorbs delta-resident rows too — reset the
                # delta or its slots would be live in BOTH legs (duplicate
                # results now, double-scatter at the next flush). The
                # empty case still rebuilds so _fill reflects reality.
                st._rebuild_lists(vecs, slots)
                st._reset_delta()

    @property
    def trained(self) -> bool:
        return self.store.trained

    @property
    def compressed(self) -> bool:
        return bool(self.store.quantization)

    @classmethod
    def restore(cls, snap: dict, **kwargs) -> "IVFIndex":
        idx = cls.__new__(cls)
        idx.dim = snap["dim"]
        idx.metric = snap["metric"]
        idx.store = IVFStore.restore(snap, **kwargs)
        idx._lock = threading.RLock()
        slot_to_id = snap["slot_to_id"]
        idx._slot_to_id = np.full(idx.store.capacity, -1, dtype=np.int64)
        idx._slot_to_id[: len(slot_to_id)] = slot_to_id
        idx._id_to_slot = {
            int(doc): int(slot)
            for slot, doc in enumerate(slot_to_id)
            if doc >= 0 and slot < len(snap["valid"]) and snap["valid"][slot]
        }
        return idx
