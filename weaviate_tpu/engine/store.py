"""HBM-resident vector store.

The reference keeps vectors in a RAM cache (vector/cache/sharded_lock_cache.go)
plus an lsmkv bucket on disk (vector/flat/index.go:164-175). On TPU the
authoritative hot copy lives in HBM as capacity-padded JAX arrays:

- ``vectors``  [C, d]  storage dtype f32 (exact) or bf16 (2x capacity)
- ``valid``    [C]     live-slot mask (False = unfilled or tombstoned)
- ``sq_norms`` [C]     cached squared row norms (corpus term of the l2 expansion)

Mutability under XLA's immutable-buffer model (SURVEY §7 hard part #1):
writes are scatter updates inside a jitted function whose buffers are
*donated*, so XLA updates HBM in place — no copy, no realloc per insert.
Deletes flip ``valid`` bits (tombstones, reference: hnsw/index.go:115); the
mask is applied inside the top-k scan so dead slots never win. Capacity
grows by power-of-two re-allocation (one recompile per capacity level).

When a mesh is provided, all three arrays are row-sharded over the ``shard``
axis and every update/search runs SPMD; slot→device placement is implicit
(slot // rows_per_device), the TPU analog of the reference's murmur3
shard ring (usecases/sharding/state.go:167-176).
"""

from __future__ import annotations

import functools
import os
import threading
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.ops.candidates import shared_candidates_topk
from weaviate_tpu.ops.distances import normalize
from weaviate_tpu.ops.topk import chunked_topk_distances
from weaviate_tpu.runtime import hbm_ledger, kernelscope, tracing
from weaviate_tpu.runtime import transfer
from weaviate_tpu.runtime.transfer import DeviceResultHandle
from weaviate_tpu.parallel.mesh import n_row_shards, shardable_capacity
from weaviate_tpu.parallel.sharded_search import (
    replicate_array,
    shard_array,
    sharded_topk,
)

_DEFAULT_CHUNK = 8192


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def normalize_allow_mask(allow_mask, n_queries: int):
    """Shared allow-mask intake for the plain and quantized stores:
    [1, C] broadcasts to the shared [C] form (keeping the gathered
    low-selectivity cutover); a [B, C] mask must match the query count."""
    if allow_mask is None:
        return None
    allow_mask = np.asarray(allow_mask)
    if allow_mask.ndim == 2 and allow_mask.shape[0] == 1:
        allow_mask = allow_mask[0]
    elif allow_mask.ndim == 2 and allow_mask.shape[0] != n_queries:
        raise ValueError(
            f"allow_mask rows {allow_mask.shape[0]} != "
            f"queries {n_queries}")
    return allow_mask


def batched_mask_operands(allow_mask, n_queries: int, capacity: int, mesh,
                          owner: dict | None = None):
    """[B, capacity] per-query mask -> scan-kernel operands, under a
    ``store.mask_pack`` span: single-device packs the bitmask on the host
    (32x smaller transfer); a mesh ships the bool mask column-sharded so
    each device packs its own row-aligned slice on device. Returns
    (allow_bits, allow_rows_dev) — exactly one is non-None. ``owner``
    labels the transient device buffer in the HBM ledger (weakref-
    tracked: the entry lives exactly as long as the buffer)."""
    owner = owner or {}
    with tracing.span("store.mask_pack", queries=n_queries):
        if mesh is None:
            from weaviate_tpu.ops.pallas_kernels import (mask_pad_cols,
                                                         pack_allow_bitmask)

            bits = jnp.asarray(pack_allow_bitmask(
                allow_mask, mask_pad_cols(capacity)))
            hbm_ledger.ledger.track("allow_bitmask", bits, **owner)
            return bits, None
        if (allow_mask.shape == (n_queries, capacity)
                and allow_mask.dtype == np.bool_):
            full = allow_mask  # already the exact shape — no copy
        else:
            full = np.zeros((n_queries, capacity), dtype=bool)
            w = min(allow_mask.shape[1], capacity)
            full[:, :w] = allow_mask[:, :w]
        from weaviate_tpu.parallel.sharded_search import tracked_shard_array

        return None, tracked_shard_array(
            jnp.asarray(full), mesh, dim=1, component="allow_mask",
            owner=owner)


@functools.partial(jax.jit, donate_argnums=(0, 1, 2), static_argnames=("normalize_rows",))
def _scatter_rows(vectors, valid, sq_norms, slots, new_vecs, write_mask,
                  normalize_rows: bool = False):
    """Write ``new_vecs`` [m,d] into rows ``slots`` [m]; rows with
    write_mask=False are redirected to a scratch row (capacity-1 duplicate
    writes are benign because mode='drop' handles OOB)."""
    new_vecs = new_vecs.astype(jnp.float32)
    if normalize_rows:
        new_vecs = normalize(new_vecs)
    new_vecs = new_vecs.astype(vectors.dtype)
    norms = jnp.sum(new_vecs.astype(jnp.float32) ** 2, axis=-1)
    # redirect masked (padding) rows out of range; 'drop' makes them no-ops
    tgt = jnp.where(write_mask, slots, vectors.shape[0])
    vectors = vectors.at[tgt].set(new_vecs, mode="drop")
    valid = valid.at[tgt].set(True, mode="drop")
    sq_norms = sq_norms.at[tgt].set(norms, mode="drop")
    return vectors, valid, sq_norms


@functools.partial(jax.jit, donate_argnums=(0,))
def _clear_slots(valid, slots):
    return valid.at[slots].set(False, mode="drop")


def _probe_scatter(valid, slot: int) -> None:
    """Force one element of a freshly-scattered valid mask to the host.

    jax dispatch is async: ``_scatter_rows`` returning only means the work
    was ENQUEUED. A tiny data-dependent fetch is the trustworthy completion
    probe on the tunnel runtime (block_until_ready reports completion
    before execution there, engine/hnsw_build.py:_t) — it surfaces an async
    runtime failure (device OOM, preemption, poisoned buffer) as an
    exception at the flush site, while the staged rows are still held and
    re-flushable, instead of silently dropping rows whose add() already
    returned success. Module-level so tests can inject async failures."""
    bool(np.asarray(valid[slot]))


class DeviceVectorStore:
    """Mutable (host-managed, device-resident) vector store.

    Thread-safe for interleaved add/delete/search (a single host lock guards
    buffer swaps; reads take a snapshot reference — the analog of the
    reference's sharded RW locks in vector/common/sharded_locks.go).
    """

    def __init__(
        self,
        dim: int,
        metric: str = "l2-squared",
        capacity: int = _DEFAULT_CHUNK,
        dtype=jnp.float32,
        mesh=None,
        chunk_size: int = _DEFAULT_CHUNK,
        normalize_on_add: bool | None = None,
        selection: str = "approx",
        component: str = "corpus",
    ):
        self.dim = dim
        # HBM-ledger component label: the epoch store passes a per-epoch
        # label ("corpus@e3") so /v1/debug/memory and the hbm_bytes gauge
        # attribute device bytes to individual epochs — and releasing an
        # epoch visibly drops exactly its own series
        self.hbm_component = component
        self.metric = metric
        self.dtype = dtype
        self.mesh = mesh
        self.chunk_size = chunk_size
        # "approx" = per-chunk approx_max_k candidates (4x oversampled) with
        # exact carry merges (≥0.999 recall@10, ~10x less selection time at
        # 1M rows). "exact" opts into bit-exact lax.top_k per chunk (and is
        # what non-TPU backends lower to anyway). "fused" folds EXACT
        # selection into the Pallas scan kernel itself (ops/topk.py
        # docstring) — [B, N] distances never round-trip through HBM; on
        # non-TPU backends it runs through the Pallas interpreter, so keep
        # it for tests/TPU serving, not CPU serving.
        self.selection = selection
        self.n_shards = n_row_shards(mesh)
        # cosine provider normalizes at insert (reference stores normalized
        # vectors and uses the dot kernel: cosine_dist.go "cosine-dot")
        self.normalize_on_add = (
            metric in ("cosine", "cosine-dot")
            if normalize_on_add is None
            else normalize_on_add
        )
        self._lock = threading.RLock()
        # Compiled Pallas distance kernels on TPU; XLA path elsewhere
        # (interpret-mode Pallas is test-only — far too slow to serve from).
        from weaviate_tpu.ops.pallas_kernels import PALLAS_METRICS, recommended

        self.use_pallas = recommended() and metric in PALLAS_METRICS
        self._count = 0  # high-water mark of allocated slots
        # Host-side append staging: each small add() batch lands in a numpy
        # buffer (microseconds) and rows reach HBM in large amortized
        # scatters — a per-batch device dispatch costs a fixed round trip
        # that dominated the import path (BASELINE r5: ~65 ms/batch on the
        # tunnel rig). Every read path flushes first, so visibility is
        # unchanged; slot assignment stays eager so callers' id<->slot
        # bookkeeping is identical.
        self._staged_slots: list[np.ndarray] = []
        self._staged_vecs: list[np.ndarray] = []
        self._staged_rows = 0
        self._stage_limit = max(4096, (32 << 20) // (dim * 4))
        # HBM ledger wiring: the (collection, shard, tenant) labels are
        # captured ONCE from the ambient owner scope the shard layer sets
        # around index construction; grows/compacts update the same
        # entries, and a finalizer releases them when the store is
        # dropped (e.g. compress() swapping in a quantized store).
        self._hbm_owner = hbm_ledger.current_owner()
        self._hbm_keys: dict[str, int] = {}
        weakref.finalize(self, hbm_ledger.ledger.release_many,
                         self._hbm_keys.values())
        capacity = self._align(capacity)
        self.capacity = capacity
        # host mirror of the live-slot mask + O(1) live counter, both
        # maintained under ``_lock`` by add/set_at/delete/compact — the
        # serving path never syncs on a device sum for a count (the
        # retired G1 ``live_count`` baseline entry; the device mask
        # stays the authority for scans, and WEAVIATE_TPU_DEBUG_COUNTS=1
        # cross-checks the two)
        self._valid_np = np.zeros(capacity, dtype=bool)
        self._live_count = 0
        self._alloc(capacity)

    # -- capacity management -------------------------------------------------

    def _align(self, capacity: int) -> int:
        capacity = max(capacity, 2 * self.n_shards)
        capacity = _next_pow2(capacity)
        cs = min(self.chunk_size, capacity // self.n_shards)
        return shardable_capacity(capacity, self.n_shards, cs)

    def _placed(self, arr, dim=0):
        if self.mesh is None:
            return jnp.asarray(arr)
        return shard_array(jnp.asarray(arr), self.mesh, dim=dim)

    def _alloc(self, capacity: int):
        self.vectors = self._placed(jnp.zeros((capacity, self.dim), dtype=self.dtype))
        self.valid = self._placed(jnp.zeros((capacity,), dtype=jnp.bool_))
        self.sq_norms = self._placed(jnp.zeros((capacity,), dtype=jnp.float32))
        self._hbm_sync()

    def _hbm_sync(self):
        """(Re-)publish this store's device footprint into the ledger —
        called after every (re)allocation so totals track capacity, not
        just construction."""
        nbytes = sum(int(a.nbytes)
                     for a in (self.vectors, self.valid, self.sq_norms))
        hbm_ledger.ledger.set_keyed(
            self._hbm_keys, self.hbm_component, nbytes,
            owner=self._hbm_owner,
            dtype=jnp.dtype(self.dtype).name,
            sharding="sharded" if self.mesh is not None else "single")

    def _grow(self, min_capacity: int):
        """Capacity-double the device arrays + host valid mirror.
        Caller holds ``_lock``."""
        from weaviate_tpu.parallel.sharded_search import grow_rows

        new_cap = self._align(_next_pow2(min_capacity))
        pad = new_cap - self.capacity
        self.capacity = new_cap
        grown = np.zeros(new_cap, dtype=bool)
        grown[: len(self._valid_np)] = self._valid_np
        self._valid_np = grown
        # Donated, shard-local zero-pad (no full-array round trip through
        # one device, no transient 2x copy).
        self.vectors = grow_rows(self.vectors, pad, self.mesh)
        self.valid = grow_rows(self.valid, pad, self.mesh)
        self.sq_norms = grow_rows(self.sq_norms, pad, self.mesh)
        self._hbm_sync()

    # -- mutation ------------------------------------------------------------

    def add(self, vectors: np.ndarray) -> np.ndarray:
        """Append a batch [m,d]; returns assigned slot ids [m] (int64).

        Slots are assigned sequentially from the high-water mark. Padding to
        power-of-two batch buckets bounds the number of compiled variants.
        """
        vectors = np.asarray(vectors, dtype=np.float32)
        if vectors.ndim == 1:
            vectors = vectors[None, :]
        m, d = vectors.shape
        if d != self.dim:
            raise ValueError(f"vector dim {d} != store dim {self.dim}")
        with self._lock:
            slots = np.arange(self._count, self._count + m, dtype=np.int64)
            if self._count + m > self.capacity:
                self._grow(self._count + m)
            self._count += m
            # fresh slots from the high-water mark: all newly live
            # (staged rows count — every read path flushes first, so
            # their visibility matches the device mask's)
            self._valid_np[slots] = True
            self._live_count += m
            # copy: the caller may reuse/mutate its buffer before flush
            self._staged_slots.append(slots.astype(np.int32))
            self._staged_vecs.append(vectors.copy())
            self._staged_rows += m
            if self._staged_rows >= self._stage_limit:
                self._flush_staged_locked()
            return slots

    def flush_staged(self) -> None:
        """Push any host-staged rows to device HBM (one padded scatter)."""
        with self._lock:
            self._flush_staged_locked()

    def _flush_staged_locked(self) -> None:
        """Scatter the staged rows to HBM. Caller holds ``_lock`` (the
        _locked suffix is the contract; this lint-checks it too)."""
        m = self._staged_rows
        if m == 0:
            return
        vectors = (self._staged_vecs[0] if len(self._staged_vecs) == 1
                   else np.concatenate(self._staged_vecs))
        slots = (self._staged_slots[0] if len(self._staged_slots) == 1
                 else np.concatenate(self._staged_slots))
        bucket = _next_pow2(max(m, 8))
        # sub-f32 storage (bf16) transfers in the STORAGE dtype — half the
        # host->device bytes; the scan reads bf16 rows either way, and the
        # in-kernel norms then derive from exactly the rows being scanned.
        # cosine keeps f32 staging: rows normalize in-kernel pre-cast.
        stage_dt = (jnp.dtype(self.dtype)
                    if (not self.normalize_on_add
                        and jnp.dtype(self.dtype).itemsize < 4)
                    else np.dtype(np.float32))
        padded = np.zeros((bucket, self.dim), dtype=stage_dt)
        padded[:m] = vectors.astype(stage_dt)
        slot_buf = np.zeros(bucket, dtype=np.int32)
        slot_buf[:m] = slots
        mask = np.zeros(bucket, dtype=bool)
        mask[:m] = True
        # the transfer buffers for the scatter are a real (transient)
        # device allocation — ledger-tracked for the duration of the
        # flush so peak watermarks see import bursts
        stage_key = hbm_ledger.ledger.register(
            "staging", padded.nbytes + slot_buf.nbytes + mask.nbytes,
            dtype=str(stage_dt),
            sharding="replicated" if self.mesh is not None else "single",
            **self._hbm_owner)
        try:
            self.vectors, self.valid, self.sq_norms = _scatter_rows(
                self.vectors,
                self.valid,
                self.sq_norms,
                self._placed_replicated(slot_buf),
                self._placed_replicated(padded),
                self._placed_replicated(mask),
                normalize_rows=self.normalize_on_add,
            )
            # drop the staging buffers only after the scatter MATERIALIZED
            # — dispatch is async, so an exception can surface here
            # (transfer OOM, compile failure at a new bucket) or later on
            # the device (runtime failure on the enqueued scatter). The
            # probe forces the result before the rows stop being
            # re-flushable; one host RTT per flush, amortized over
            # >= _stage_limit staged rows.
            _probe_scatter(self.valid, int(slots[m - 1]))
        finally:
            hbm_ledger.ledger.release(stage_key)
        self._staged_vecs.clear()
        self._staged_slots.clear()
        self._staged_rows = 0

    def set_at(self, slots: np.ndarray, vectors: np.ndarray):
        """Overwrite specific slots (update path)."""
        vectors = np.asarray(vectors, dtype=np.float32)
        slots = np.asarray(slots, dtype=np.int32)
        m = len(slots)
        with self._lock:
            self._flush_staged_locked()
            if m and int(slots.max()) >= self.capacity:
                self._grow(int(slots.max()) + 1)
            self._count = max(self._count, int(slots.max()) + 1 if m else 0)
            if m:
                u = np.unique(slots)
                self._live_count += int(np.count_nonzero(
                    ~self._valid_np[u]))
                self._valid_np[u] = True
            bucket = _next_pow2(max(m, 8))
            padded = np.zeros((bucket, self.dim), dtype=np.float32)
            padded[:m] = vectors
            slot_buf = np.zeros(bucket, dtype=np.int32)
            slot_buf[:m] = slots
            mask = np.zeros(bucket, dtype=bool)
            mask[:m] = True
            self.vectors, self.valid, self.sq_norms = _scatter_rows(
                self.vectors, self.valid, self.sq_norms,
                self._placed_replicated(slot_buf),
                self._placed_replicated(padded),
                self._placed_replicated(mask),
                normalize_rows=self.normalize_on_add,
            )

    def delete(self, slots) -> None:
        """Tombstone slots (reference: delete = tombstone + later cleanup,
        hnsw/delete.go). Slots stay allocated until compaction.

        Rows still HOST-STAGED (added but not yet flushed) are
        tombstoned in the staging buffer itself — scrubbed so they never
        reach HBM — instead of paying a full device flush just to clear
        a mask bit the scatter was about to set. The device-side clear
        still runs for every requested slot (clearing a never-set slot
        is a no-op), so interleaved add/delete/flush sequences agree
        with the host mirror no matter which side of the flush the
        delete lands on."""
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int32))
        m = len(slots)
        if m == 0:
            return
        with self._lock:
            in_range = np.unique(slots[(slots >= 0)
                                       & (slots < self.capacity)])
            self._live_count -= int(np.count_nonzero(
                self._valid_np[in_range]))
            self._valid_np[in_range] = False
            if self._staged_rows:
                self._scrub_staged_locked(in_range)
            bucket = _next_pow2(max(m, 8))
            buf = np.full(bucket, self.capacity + 1, dtype=np.int32)  # OOB no-op
            buf[:m] = slots
            self.valid = _clear_slots(self.valid, self._placed_replicated(buf))

    def _scrub_staged_locked(self, dead: np.ndarray) -> None:
        """Drop staged rows whose slots are in ``dead`` so a deleted-
        before-flush row never lands on device at all. Caller holds
        ``_lock``."""
        kept_slots: list[np.ndarray] = []
        kept_vecs: list[np.ndarray] = []
        rows = 0
        for sl, vec in zip(self._staged_slots, self._staged_vecs):
            keep = ~np.isin(sl, dead)
            if keep.all():
                kept_slots.append(sl)
                kept_vecs.append(vec)
                rows += len(sl)
            elif keep.any():
                kept_slots.append(sl[keep])
                kept_vecs.append(vec[keep])
                rows += int(keep.sum())
        self._staged_slots = kept_slots
        self._staged_vecs = kept_vecs
        self._staged_rows = rows

    def _placed_replicated(self, arr):
        if self.mesh is None:
            return jnp.asarray(arr)
        return replicate_array(jnp.asarray(arr), self.mesh)

    # -- queries -------------------------------------------------------------

    @property
    def count(self) -> int:
        """Allocated slots (including tombstones)."""
        return self._count

    def live_count(self) -> int:
        """Live (non-tombstoned) slots — an O(1) host counter maintained
        under ``_lock`` by add/set_at/delete/compact. The device
        ``sum(valid)`` round-trip this used to pay (the second graftlint
        G1 baseline entry) is retired from the serving path; set
        ``WEAVIATE_TPU_DEBUG_COUNTS=1`` to cross-check the counter
        against the device mask on every call."""
        with self._lock:
            if os.environ.get("WEAVIATE_TPU_DEBUG_COUNTS", "").lower() \
                    in ("1", "true", "on"):
                self._flush_staged_locked()
                dev = int(jnp.sum(self.valid))  # graftlint: disable=G1 — debug-only cross-check, env-gated off the serving path
                assert dev == self._live_count, (
                    f"live-count drift: device says {dev}, host counter "
                    f"says {self._live_count}")
            return self._live_count

    def get(self, slots) -> np.ndarray:
        """Fetch vectors by slot (host copy) — object-resolution path."""
        slots = np.atleast_1d(np.asarray(slots, dtype=np.int32))
        with self._lock:
            self._flush_staged_locked()
            rows = self.vectors[jnp.asarray(slots)]
        return np.asarray(rows, dtype=np.float32)

    def search(self, queries: np.ndarray, k: int, allow_mask: np.ndarray | None = None):
        """Brute-force top-k. queries [B,d] (or [d]); returns (dists [B,k],
        slots [B,k]) as numpy, ascending by distance; dead slots never appear.

        ``allow_mask`` is the device-side AllowList (reference:
        helpers/allow_list.go consumed at hnsw/search.go /
        flat/index.go:319) in one of two forms:

        - [capacity] (or [count]) bool — ONE filter shared by the whole
          batch; highly selective masks cut over to the gathered path.
        - [B, capacity] bool — PER-QUERY filters. Rows pack into a
          bitmask (uint32 [B, capacity/32], pallas_kernels.
          pack_allow_bitmask) that the scan kernels unpack tile-locally,
          so B differently-filtered requests still run as one device
          program. A [1, capacity] mask broadcasts to the shared form.

        The D2H transfer happens inside the returned handle's
        ``result()`` (tracing.d2h — the sanctioned boundary), not here:
        this method is ``search_async(...).result()``.
        """
        return self.search_async(queries, k, allow_mask).result()

    def search_async(self, queries: np.ndarray, k: int,
                     allow_mask: np.ndarray | None = None
                     ) -> DeviceResultHandle:
        """Dispatch-only twin of ``search`` (ISSUE 7 tentpole): the scan
        launches under ``_lock`` and the results STAY DEVICE-RESIDENT in
        the returned ``DeviceResultHandle``. ``.result()`` performs the
        one sanctioned device->host transfer (``transfer.d2h`` span) and
        runs the gathered-path host remapping; the serving pipeline
        instead drains the handle on a dedicated transfer thread while
        the next batch dispatches (runtime/query_batcher.py), so the
        device never idles on a host sync."""
        queries = np.asarray(queries, dtype=np.float32)
        squeeze = queries.ndim == 1
        if squeeze:
            queries = queries[None, :]
        allow_mask = normalize_allow_mask(allow_mask, len(queries))
        with tracing.span("store.scan", rows=self.capacity,
                          queries=len(queries), k=k,
                          sharded=self.mesh is not None,
                          filtered=allow_mask is not None) as sp:
            # Dispatch happens under the lock: writers *donate* the store
            # buffers, which invalidates any handle a concurrent reader
            # grabbed but hasn't dispatched against yet. Execution is
            # async, so the lock only covers the (cheap) dispatch —
            # materialization waits outside.
            with self._lock:
                self._flush_staged_locked()
                vectors, valid, norms = (self.vectors, self.valid,
                                         self.sq_norms)
                capacity = self.capacity
                allow_bits = allow_rows_dev = None
                if allow_mask is not None and allow_mask.ndim == 2:
                    slot_buf = None
                    sp.set(path="bitmask_batched")
                    # EXPLAIN notes are host ints only (no device reads
                    # — graftlint G1/G5 pin it) and a one-contextvar-
                    # read no-op when nobody asked
                    kernelscope.explain_note(
                        "store", path="bitmask_batched", rows=capacity,
                        queries=len(queries), k=k)
                    allow_bits, allow_rows_dev = batched_mask_operands(
                        allow_mask, len(queries), capacity, self.mesh,
                        owner=self._hbm_owner)
                elif allow_mask is not None:
                    allowed = np.flatnonzero(allow_mask)
                    # selectivity policy (measured,
                    # tools/bench_filtered.py — BASELINE r5, hoist-proof
                    # harness): masked full scan is selectivity-
                    # independent (~11.1 ms at 1M×128 B=256); gather is
                    # ~1.4 ms + linear (5.2 ms at 10%, 23 ms at 50%) —
                    # crossover ≈22%, policy cut at capacity/8 with a
                    # 1 GB transient-gather HBM budget computed on the
                    # PADDED pow2 bucket at the actual storage dtype
                    m_allowed = len(allowed)
                    bucket = 1 << max(7, (m_allowed - 1).bit_length()) \
                        if m_allowed else 0
                    row_bytes = self.dim * jnp.dtype(
                        self.vectors.dtype).itemsize
                    if (self.mesh is None and m_allowed > 0
                            and m_allowed <= capacity // 8
                            and bucket * row_bytes <= (1 << 30)):
                        sp.set(path="gathered", allowed=m_allowed)
                        kernelscope.explain_note(
                            "store", path="gathered", rows=capacity,
                            m_allowed=m_allowed, queries=len(queries),
                            k=k, selectivity=round(
                                m_allowed / capacity, 6) if capacity
                            else 0.0)
                        d, i, slot_buf = self._dispatch_gathered(
                            queries, k, allowed)
                    else:
                        kernelscope.explain_note(
                            "store", path="shared_mask", rows=capacity,
                            m_allowed=m_allowed, queries=len(queries),
                            k=k, selectivity=round(
                                m_allowed / capacity, 6) if capacity
                            else 0.0)
                        full = np.zeros(capacity, dtype=bool)
                        full[: len(allow_mask)] = allow_mask
                        valid = jnp.logical_and(valid, self._placed(full))
                        slot_buf = None
                else:
                    kernelscope.explain_note(
                        "store", path="full_scan", rows=capacity,
                        queries=len(queries), k=k)
                    slot_buf = None
                if slot_buf is None:
                    k_eff = min(k, capacity)
                    # cosine runs as "cosine" against rows normalized at
                    # insert (the query side is normalized inside the
                    # kernel)
                    metric = ("cosine" if self.metric in ("cosine",
                                                          "cosine-dot")
                              else self.metric)
                    cs = min(self.chunk_size, capacity // self.n_shards)
                    if self.mesh is None:
                        d, i = chunked_topk_distances(
                            jnp.asarray(queries), vectors, k=k_eff,
                            chunk_size=cs, metric=metric, valid=valid,
                            x_sq_norms=norms, use_pallas=self.use_pallas,
                            selection=self.selection,
                            allow_bits=allow_bits,
                        )
                    else:
                        d, i = sharded_topk(
                            jnp.asarray(queries), vectors, valid, norms,
                            k=k_eff, chunk_size=cs, metric=metric,
                            mesh=self.mesh, use_pallas=self.use_pallas,
                            selection=self.selection,
                            allow_rows=allow_rows_dev,
                        )
        # materialization (and its device-time attribution) lives in the
        # handle: a sync here would serialize concurrent readers behind
        # this dispatch AND idle the device between batches

        def _finish(d_np, i_np, _slot_buf=slot_buf, _k=k,
                    _squeeze=squeeze):
            if _slot_buf is not None:
                d_np, i_np = DeviceVectorStore._finish_gathered(
                    d_np, i_np, _slot_buf, _k)
            if _squeeze:
                return d_np[0], i_np[0]
            return d_np, i_np

        return DeviceResultHandle(
            (d, i), finish=_finish,
            attrs={"rows": capacity, "queries": len(queries), "k": k,
                   # which dispatch shape ran: the hybridplane composes
                   # on the device arrays and must refuse the gathered
                   # path (its finish step remaps slots on the HOST)
                   "path": ("gathered" if slot_buf is not None
                            else "device")})

    def epoch_scan(self, queries: np.ndarray, k: int,
                   allow_mask: np.ndarray | None = None):
        """Dispatch-only scan for the epoch store (engine/epochs.py):
        top-k of THIS store alone, ids STORE-LOCAL, results left
        device-resident for the cross-epoch merge. ``allow_mask``
        carries this epoch's column slice of the global filter ([cap]
        shared or [B, cap] per-query). The gathered low-selectivity
        cutover is deliberately not taken here: its bucket-local remap
        is a host finish step, and the epoch merge needs raw device
        candidates (single-epoch stores keep the cutover through the
        ``search`` passthrough)."""
        queries = np.asarray(queries, dtype=np.float32)
        allow_mask = normalize_allow_mask(allow_mask, len(queries))
        with self._lock:
            self._flush_staged_locked()
            vectors, valid, norms = self.vectors, self.valid, self.sq_norms
            capacity = self.capacity
            allow_bits = allow_rows_dev = None
            if allow_mask is not None and allow_mask.ndim == 2:
                allow_bits, allow_rows_dev = batched_mask_operands(
                    allow_mask, len(queries), capacity, self.mesh,
                    owner=self._hbm_owner)
            elif allow_mask is not None:
                full = np.zeros(capacity, dtype=bool)
                w = min(len(allow_mask), capacity)
                full[:w] = allow_mask[:w]
                valid = jnp.logical_and(valid, self._placed(full))
            k_eff = min(k, capacity)
            metric = ("cosine" if self.metric in ("cosine", "cosine-dot")
                      else self.metric)
            cs = min(self.chunk_size, capacity // self.n_shards)
            if self.mesh is None:
                return chunked_topk_distances(
                    jnp.asarray(queries), vectors, k=k_eff, chunk_size=cs,
                    metric=metric, valid=valid, x_sq_norms=norms,
                    use_pallas=self.use_pallas, selection=self.selection,
                    allow_bits=allow_bits)
            return sharded_topk(
                jnp.asarray(queries), vectors, valid, norms, k=k_eff,
                chunk_size=cs, metric=metric, mesh=self.mesh,
                use_pallas=self.use_pallas, selection=self.selection,
                allow_rows=allow_rows_dev)

    def _dispatch_gathered(self, queries: np.ndarray, k: int,
                           allowed: np.ndarray):
        """Filtered search at low selectivity: gather the allowed rows
        into a dense pow2-padded buffer on device and scan THAT
        (reference analog: flatSearchCutoff routes small filters to
        brute force over the allow list, hnsw/index.go:95). Called under
        ``_lock`` by ``search``; dispatch only — results materialize
        outside the lock. Buckets bound compiled variants. Returns
        (d_dev, i_dev, slot_buf)."""
        m = len(allowed)
        bucket = 1 << max(7, (m - 1).bit_length())
        slot_buf = np.full(bucket, -1, dtype=np.int32)
        slot_buf[:m] = allowed
        metric = ("cosine" if self.metric in ("cosine", "cosine-dot")
                  else self.metric)
        d, i = shared_candidates_topk(
            jnp.asarray(queries), jnp.asarray(slot_buf), self.vectors,
            min(k, bucket), metric, row_norms=self.sq_norms,
            valid=self.valid, use_pallas=self.use_pallas,
            selection=self.selection,
        )
        return d, i, slot_buf

    @staticmethod
    def _finish_gathered(d_np: np.ndarray, i_np: np.ndarray,
                         slot_buf: np.ndarray, k: int):
        """Host half of the gathered path. The candidate plane remaps
        bucket-local winners to global slots ON DEVICE (row_ids), so
        this is pad-only up to search()'s [B, k] contract."""
        if i_np.shape[1] < k:
            pad = k - i_np.shape[1]
            i_np = np.pad(i_np, ((0, 0), (0, pad)), constant_values=-1)
            d_np = np.pad(d_np, ((0, 0), (0, pad)),
                          constant_values=np.float32(np.inf))
        return d_np, i_np

    def _search_gathered(self, queries: np.ndarray, k: int,
                         allowed: np.ndarray, squeeze: bool):
        """Dispatch + finish in one call (tools/bench_filtered.py drives
        the gathered path directly through this)."""
        with self._lock:
            d, i, slot_buf = self._dispatch_gathered(queries, k, allowed)
        d_np, i_np = self._finish_gathered(np.asarray(d), np.asarray(i),
                                           slot_buf, k)
        if squeeze:
            return d_np[0], i_np[0]
        return d_np, i_np

    def search_by_distance(self, query: np.ndarray, max_distance: float,
                           allow_mask: np.ndarray | None = None,
                           batch: int = 4096):
        """All slots within ``max_distance`` (reference:
        SearchByVectorDistance, vector_index.go:31). Iteratively widens k
        until the worst returned hit exceeds the threshold."""
        k = min(64, self.capacity)
        while True:
            d, i = self.search(query, k, allow_mask)
            within = d <= max_distance
            # done if some slot beyond threshold surfaced or we've pulled everything
            if (~within).any() or k >= self.capacity or within.sum() >= self.live_count():
                return d[within], i[within]
            k = min(k * 4, self.capacity)

    # -- maintenance ---------------------------------------------------------

    def compact(self) -> np.ndarray:
        """Defragment: drop tombstoned rows, repack live rows contiguously.
        Returns old_slot -> new_slot mapping (-1 for dropped). The HBM analog
        of the reference's tombstone-cleanup cycle (hnsw tombstone cleanup /
        lsmkv compaction)."""
        with tracing.span("store.compact", rows=self.capacity) as sp, \
                self._lock:
            self._flush_staged_locked()
            valid_np = self._valid_np  # host mirror — no device sync
            live = np.nonzero(valid_np)[0]
            mapping = np.full(self.capacity, -1, dtype=np.int64)
            mapping[live] = np.arange(len(live))
            sp.set(live=len(live))
            # the rebuild's one D2H rides the sanctioned boundary
            # (transfer.d2h span, device_ms split from memcpy on sampled
            # traces) instead of a bare np.asarray sync in engine/
            (vec_host,) = transfer.d2h(self.vectors)
            vec_np = vec_host[live]
            self._count = len(live)
            new_cap = self._align(max(len(live), 2))
            self.capacity = new_cap
            self._valid_np = np.zeros(new_cap, dtype=bool)
            self._live_count = 0  # set_at below re-marks the live rows
            self._alloc(new_cap)
            if len(live):
                self.set_at(np.arange(len(live)), vec_np)
            return mapping

    # -- persistence hooks ---------------------------------------------------

    def snapshot(self) -> dict:
        """Host-side snapshot for checkpointing (driver: storage layer WAL +
        snapshot gives restart durability, reference hnsw/startup.go:57)."""
        with self._lock:
            self._flush_staged_locked()
            return {
                "vectors": np.asarray(self.vectors, dtype=np.float32),
                "valid": np.asarray(self.valid),
                "count": self._count,
                "dim": self.dim,
                "metric": self.metric,
                "dtype": jnp.dtype(self.dtype).name,
                "chunk_size": self.chunk_size,
            }

    @classmethod
    def restore(cls, snap: dict, **kwargs) -> "DeviceVectorStore":
        # storage config survives the checkpoint round-trip unless the
        # caller explicitly overrides it
        kwargs.setdefault("dtype", jnp.dtype(snap.get("dtype", "float32")))
        kwargs.setdefault("chunk_size", snap.get("chunk_size", _DEFAULT_CHUNK))
        store = cls(dim=snap["dim"], metric=snap["metric"],
                    capacity=max(len(snap["valid"]), 2), **kwargs)
        live = np.nonzero(snap["valid"])[0]
        store._count = snap["count"]
        if len(live):
            # vectors were already normalized at original insert; don't re-normalize
            orig = store.normalize_on_add
            store.normalize_on_add = False
            store.set_at(live, snap["vectors"][live])
            store.normalize_on_add = orig
        store._count = snap["count"]
        return store
