"""Mesh construction helpers.

The framework uses two mesh shapes for corpus row-sharding (the analog
of the reference's physical shards, usecases/sharding/state.go:28):

- the legacy 1-D ``('shard',)`` mesh: every device is one shard of the
  row axis, collectives span the whole pod in one hop;
- the hierarchical 2-D ``('host', 'ici')`` mesh (ISSUE 13): devices are
  grouped by the OS process that owns them, so the ``ici`` axis stays
  inside a host (fast interconnect) and only the ``host`` axis crosses
  DCN. The two-level candidate merge in sharded_search exploits this:
  candidates reduce over ``ici`` first and only per-host winners cross
  ``host`` — O(hosts*k) DCN traffic instead of O(devices*k).

Single-host, ``make_hierarchical_mesh`` degenerates to the 1-D
``shard`` mesh so every existing call site keeps working unchanged.
Device order is always process-grouped (``_process_grouped_devices``)
so row-contiguous shards are intra-host on BOTH mesh shapes — a flat
``jax.devices()`` interleaving would silently turn every "ICI" hop
into a DCN hop.
"""

from __future__ import annotations

import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"
#: hierarchical mesh axes: ``host`` crosses DCN, ``ici`` stays on-host
HOST_AXIS = "host"
ICI_AXIS = "ici"

#: env knob: fake N hosts on a single process (the 8-device virtual CPU
#: mesh becomes a 2x4 "two-host pod" with WEAVIATE_TPU_VIRTUAL_HOSTS=2)
VIRTUAL_HOSTS_ENV = "WEAVIATE_TPU_VIRTUAL_HOSTS"

_dist_lock = threading.Lock()
_dist_initialized = False


def maybe_initialize_distributed(env=None) -> bool:
    """Join the multi-host JAX runtime when the environment names a
    coordinator (SURVEY §5 distributed comms: ICI inside a host, DCN
    across hosts — the analog of the reference's cluster join,
    usecases/cluster/state.go:61, but for the DATA plane).

    Env surface:
      DCN_COORDINATOR_ADDRESS  host:port of process 0 (required to enable)
      DCN_NUM_PROCESSES        total process count
      DCN_PROCESS_ID           this process's rank

    After this returns True, ``jax.devices()`` spans every host, so
    ``make_mesh()``/``default_mesh()`` build GLOBAL meshes and the same
    shard_map programs scale across DCN with zero further changes —
    collectives over the mesh axis ride ICI within a host and DCN between
    hosts, exactly the scaling-book recipe. Idempotent; returns whether
    the distributed runtime is active.
    """
    global _dist_initialized
    env = env if env is not None else os.environ
    addr = env.get("DCN_COORDINATOR_ADDRESS")
    if not addr:
        return _dist_initialized
    with _dist_lock:
        if _dist_initialized:
            return True
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=int(env.get("DCN_NUM_PROCESSES", "1")),
            process_id=int(env.get("DCN_PROCESS_ID", "0")),
        )
        _dist_initialized = True
    return True


def device_count() -> int:
    return len(jax.devices())


def local_device_count() -> int:
    return len(jax.local_devices())


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def _process_grouped_devices() -> list:
    """All devices, grouped by owning process then device id. jax's
    global device order is USUALLY process-major already, but that is
    not contractual — and a flat interleaved order would assign
    consecutive corpus row blocks to devices on DIFFERENT hosts,
    silently turning every intra-"shard-neighborhood" collective hop
    into a DCN hop (ISSUE 13 satellite). Sorting pins the contract."""
    return sorted(jax.devices(), key=lambda d: (d.process_index, d.id))


def make_mesh(n_devices: int | None = None, axis_name: str = SHARD_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices, process-grouped so
    row-contiguous shards stay intra-host even on the legacy flat axis."""
    devs = _process_grouped_devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def virtual_hosts(env=None) -> int | None:
    """WEAVIATE_TPU_VIRTUAL_HOSTS as an int, or None when unset/invalid."""
    env = env if env is not None else os.environ
    raw = env.get(VIRTUAL_HOSTS_ENV)
    if not raw:
        return None
    try:
        n = int(raw)
    except ValueError:
        return None
    return n if n >= 1 else None


def make_hierarchical_mesh(n_hosts: int | None = None,
                           n_devices: int | None = None) -> Mesh:
    """2-D ``('host', 'ici')`` mesh: one row of local devices per host.

    ``n_hosts`` defaults to ``jax.process_count()`` (overridable by
    WEAVIATE_TPU_VIRTUAL_HOSTS for the single-process virtual pod used
    in tests and the 1B dry run). With one host this DEGENERATES to the
    existing 1-D ``shard`` mesh, so every current call site — store
    placement, sharded_search, grow_rows — keeps working unchanged.

    Device order is process-grouped and rows of the mesh array are
    hosts, so a row-sharded array placed with the composite
    ``(host, ici)`` axes lands consecutive corpus row blocks intra-host
    — the property the two-level merge's traffic math relies on.
    """
    devs = _process_grouped_devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if n_hosts is None:
        n_hosts = virtual_hosts() or jax.process_count()
    if n_hosts <= 1 or len(devs) <= 1:
        return Mesh(np.asarray(devs), (SHARD_AXIS,))
    if len(devs) % n_hosts:
        raise ValueError(
            f"{len(devs)} devices do not split evenly over {n_hosts} "
            "hosts — hierarchical row-sharding needs equal local device "
            "counts per host")
    arr = np.asarray(devs).reshape(n_hosts, len(devs) // n_hosts)
    return Mesh(arr, (HOST_AXIS, ICI_AXIS))


def is_hierarchical(mesh: Mesh | None) -> bool:
    return mesh is not None and HOST_AXIS in mesh.axis_names


def row_axes(mesh: Mesh | None):
    """The mesh axis (or composite axis tuple) corpus rows shard over."""
    if is_hierarchical(mesh):
        return (HOST_AXIS, ICI_AXIS)
    if mesh is not None and len(mesh.axis_names) == 1:
        return mesh.axis_names[0]  # honor a custom 1-D axis name
    return SHARD_AXIS


def n_row_shards(mesh: Mesh | None) -> int:
    """Row shards = devices participating in the row axis (both mesh
    shapes shard rows over every device; hierarchical just names the
    host/ici split). Honors a custom 1-D axis name, like row_axes."""
    if mesh is None:
        return 1
    if is_hierarchical(mesh):
        return int(mesh.shape[HOST_AXIS]) * int(mesh.shape[ICI_AXIS])
    return int(mesh.shape[mesh.axis_names[0]])


def host_count(mesh: Mesh | None = None) -> int:
    """Hosts backing ``mesh`` (1-D meshes report the process count; a
    virtual-host override counts as real hosts for attribution)."""
    if is_hierarchical(mesh):
        return int(mesh.shape[HOST_AXIS])
    if mesh is None:
        return max(1, virtual_hosts() or 1)
    return max(1, virtual_hosts() or jax.process_count())


def host_labels(mesh: Mesh | None = None) -> list[str]:
    return [f"host-{i}" for i in range(host_count(mesh))]


def default_mesh() -> Mesh | None:
    """Mesh over all devices, or None when there is a single device
    (single-chip path skips shard_map entirely). Multi-process runtimes
    — and single-process ones faking hosts via
    WEAVIATE_TPU_VIRTUAL_HOSTS — get the hierarchical mesh so the
    two-level merge engages; everything else keeps the 1-D shard axis."""
    if device_count() <= 1:
        return None
    if is_multiprocess() or (virtual_hosts() or 1) > 1:
        return make_hierarchical_mesh()
    return make_mesh()


def shardable_capacity(capacity: int, n_shards: int, chunk_size: int) -> int:
    """Round ``capacity`` up so each device gets an equal, chunk-aligned
    number of rows."""
    per_device = -(-capacity // n_shards)
    per_device = -(-per_device // chunk_size) * chunk_size
    return per_device * n_shards
