"""Mesh construction helpers.

The framework uses a 1-D ``shard`` axis for corpus row-sharding (the analog
of the reference's physical shards, usecases/sharding/state.go:28). On a
multi-host pod the same axis spans DCN automatically via jax.devices().
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"


def device_count() -> int:
    return len(jax.devices())


def make_mesh(n_devices: int | None = None, axis_name: str = SHARD_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def default_mesh() -> Mesh | None:
    """Mesh over all devices, or None when there is a single device
    (single-chip path skips shard_map entirely)."""
    if device_count() <= 1:
        return None
    return make_mesh()


def shardable_capacity(capacity: int, n_shards: int, chunk_size: int) -> int:
    """Round ``capacity`` up so each device gets an equal, chunk-aligned
    number of rows."""
    per_device = -(-capacity // n_shards)
    per_device = -(-per_device // chunk_size) * chunk_size
    return per_device * n_shards
