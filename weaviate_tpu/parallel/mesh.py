"""Mesh construction helpers.

The framework uses a 1-D ``shard`` axis for corpus row-sharding (the analog
of the reference's physical shards, usecases/sharding/state.go:28). On a
multi-host pod the same axis spans DCN automatically via jax.devices()
once ``maybe_initialize_distributed`` has joined the global runtime.
"""

from __future__ import annotations

import os
import threading

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shard"

_dist_lock = threading.Lock()
_dist_initialized = False


def maybe_initialize_distributed(env=None) -> bool:
    """Join the multi-host JAX runtime when the environment names a
    coordinator (SURVEY §5 distributed comms: ICI inside a host, DCN
    across hosts — the analog of the reference's cluster join,
    usecases/cluster/state.go:61, but for the DATA plane).

    Env surface:
      DCN_COORDINATOR_ADDRESS  host:port of process 0 (required to enable)
      DCN_NUM_PROCESSES        total process count
      DCN_PROCESS_ID           this process's rank

    After this returns True, ``jax.devices()`` spans every host, so
    ``make_mesh()``/``default_mesh()`` build GLOBAL meshes and the same
    shard_map programs scale across DCN with zero further changes —
    collectives over the mesh axis ride ICI within a host and DCN between
    hosts, exactly the scaling-book recipe. Idempotent; returns whether
    the distributed runtime is active.
    """
    global _dist_initialized
    env = env if env is not None else os.environ
    addr = env.get("DCN_COORDINATOR_ADDRESS")
    if not addr:
        return _dist_initialized
    with _dist_lock:
        if _dist_initialized:
            return True
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=int(env.get("DCN_NUM_PROCESSES", "1")),
            process_id=int(env.get("DCN_PROCESS_ID", "0")),
        )
        _dist_initialized = True
    return True


def device_count() -> int:
    return len(jax.devices())


def local_device_count() -> int:
    return len(jax.local_devices())


def is_multiprocess() -> bool:
    return jax.process_count() > 1


def make_mesh(n_devices: int | None = None, axis_name: str = SHARD_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def default_mesh() -> Mesh | None:
    """Mesh over all devices, or None when there is a single device
    (single-chip path skips shard_map entirely)."""
    if device_count() <= 1:
        return None
    return make_mesh()


def shardable_capacity(capacity: int, n_shards: int, chunk_size: int) -> int:
    """Round ``capacity`` up so each device gets an equal, chunk-aligned
    number of rows."""
    per_device = -(-capacity // n_shards)
    per_device = -(-per_device // chunk_size) * chunk_size
    return per_device * n_shards
