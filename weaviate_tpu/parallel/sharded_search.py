"""Sharded brute-force top-k over a device mesh.

The cross-shard query path of the reference — parallel per-shard search plus
a host-side merge (adapters/repos/db/index.go:1576-1648) — becomes one
compiled SPMD program:

    per-device chunked scan  →  local top-k  →  candidate merge
    →  merge top-k (replicated)

On the legacy 1-D ``shard`` mesh the candidate merge is a single
all_gather of [n_shards, B, k] (distance, id) pairs. On the hierarchical
``('host', 'ici')`` mesh (ISSUE 13) it is TWO-LEVEL: an all_gather +
exact reduce over ``ici`` INSIDE each host first, then only the per-host
winner block — sliced over the ICI ranks so exactly one logical copy per
host crosses the wire — all_gathers over ``host``. Cross-host candidate
traffic drops from O(devices*k) to O(hosts*k) pairs per query, which is
the difference between a 1B-vector corpus being DCN-bound or
compute-bound (cross-host DCN bandwidth is orders of magnitude scarcer
than ICI). Results are bit-identical to the 1-D merge: exact top-k is
mergeable, and the host-major candidate order both merges share makes
even distance TIES resolve identically (tests/test_hierarchical.py).

Partition specs are not hand-wired here: every operand resolves through
the regex rule tables in ``parallel/partition.py``
(``match_partition_rules``, the SNIPPETS [1] pattern) — graftlint G8
keeps PartitionSpec literals out of this module.

Allow-mask row alignment contract: ``allow_rows`` is always [B, N_local]
bool, column-sharded over the row axes ROW-ALIGNED with whatever corpus
array the same call scans. Epoch stores (engine/epochs.py) honor this by
column-slicing the global mask to each epoch's LOCAL row space
(compaction-aware through the epoch's slot maps) before dispatching that
epoch's scan — one sliced mask per epoch program, while the per-epoch
candidate sets and their replicated local->global slot maps merge in a
separate tiny program (ops/topk.merge_epoch_topk, this module's ICI
merge pattern turned inward).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

try:  # jax >= 0.6: top-level export, replication check renamed check_vma
    from jax import shard_map as _shard_map_impl

    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental home, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _SHARD_MAP_CHECK_KW = "check_rep"

from weaviate_tpu.ops.pallas_kernels import _MASK_WORDS
from weaviate_tpu.ops.topk import chunked_topk_distances, topk_smallest
from weaviate_tpu.parallel import partition
from weaviate_tpu.parallel.mesh import (
    HOST_AXIS,
    ICI_AXIS,
    SHARD_AXIS,
    is_hierarchical,
    n_row_shards,
)
from weaviate_tpu.runtime import kernelscope, tracing


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable shard_map (the replication-check kwarg moved and
    the symbol left jax.experimental between the pinned jax releases)."""
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_SHARD_MAP_CHECK_KW: check_vma})


def dcn_compact_default() -> bool:
    """WEAVIATE_TPU_DCN_COMPACT=1 packs the cross-host candidate block
    as (bf16 distance, uint32 slot) — 6 bytes/candidate instead of 8.
    OFF by default: bf16 rounding can reorder near-tied candidates, so
    the bit-identical-to-1-D parity contract only holds when distances
    are bf16-exact (e.g. BQ hamming counts at dim <= 256)."""
    return os.environ.get("WEAVIATE_TPU_DCN_COMPACT", "0").lower() in (
        "1", "true", "on")


def _shard_index(mesh: Mesh, axis: str):
    """This device's linear row-shard index (host-major on the
    hierarchical mesh, matching the row-contiguous device order)."""
    if is_hierarchical(mesh):
        return (jax.lax.axis_index(HOST_AXIS) * mesh.shape[ICI_AXIS]
                + jax.lax.axis_index(ICI_AXIS))
    return jax.lax.axis_index(axis)


def _ici_merge_topk(d, ids, axis: str, k_out: int):
    """The 1-D cross-shard candidate merge: all_gather [n_shards, B, kk]
    (distance, id) pairs over the single mesh axis, flatten per query,
    exact top-k (the device analog of the reference's host-side merge,
    index.go:1644)."""
    all_d = jax.lax.all_gather(d, axis)
    all_i = jax.lax.all_gather(ids, axis)
    n_sh, b, kk = all_d.shape
    cat_d = jnp.transpose(all_d, (1, 0, 2)).reshape(b, n_sh * kk)
    cat_i = jnp.transpose(all_i, (1, 0, 2)).reshape(b, n_sh * kk)
    return topk_smallest(cat_d, cat_i, min(k_out, n_sh * kk))


def _two_level_merge_topk(d, ids, mesh: Mesh, k_out: int,
                          compact: bool = False):
    """Hierarchical candidate merge: ICI reduce inside the host, then a
    k-way merge of one compact per-host winner block across DCN.

    Level 1 — ICI: all_gather every local device's kk candidates and
    reduce to the host's top-k1 (k1 = min(k_out, n_ici*kk)). This
    collective never leaves the host.

    Level 2 — DCN: the per-host winner block is replicated across the
    host's ICI ranks after level 1, so a naive all_gather over ``host``
    would ship n_ici REDUNDANT copies and erase the win. Instead each
    ICI rank slices its 1/n_ici of the block, the slices all_gather
    over ``host`` (exactly ONE logical copy per host crosses DCN —
    O(hosts*k) candidate pairs), and a cheap second ICI all_gather
    reassembles the full [n_hosts, k1] block on every device for the
    final exact top-k.

    Bit-identity with the 1-D merge: exact top-k is mergeable (a
    candidate dropped by its host's level-1 reduce is outranked by k1
    same-host candidates that precede it in the flat concat order, so
    the flat merge drops it too), and the final concat is host-major
    with level-1-sorted candidates inside each host — the same derived
    tie order the flat merge's shard-major concat produces. Padding
    (the slice split needs k1 % n_ici == 0) uses +inf distances, which
    sort strictly after every real AND every masked candidate, so pads
    can never displace one.

    ``compact`` casts the DCN block to (bf16 distance, uint32 slot) —
    see ``dcn_compact_default`` for the exactness tradeoff. Ids cross
    the wire bitcast to uint32 either way (free, and -1 survives the
    round trip exactly).
    """
    n_hosts = int(mesh.shape[HOST_AXIS])
    n_ici = int(mesh.shape[ICI_AXIS])
    # level 1: ICI all_gather + on-device exact reduce (the
    # merge_epoch_topk survivor-merge pattern from ops/topk.py: concat
    # in source order, one exact top-k over the union)
    all_d = jax.lax.all_gather(d, ICI_AXIS)
    all_i = jax.lax.all_gather(ids, ICI_AXIS)
    _, b, kk = all_d.shape
    cat_d = jnp.transpose(all_d, (1, 0, 2)).reshape(b, n_ici * kk)
    cat_i = jnp.transpose(all_i, (1, 0, 2)).reshape(b, n_ici * kk)
    k1 = min(k_out, n_ici * kk)
    host_d, host_i = topk_smallest(cat_d, cat_i, k1)
    k_final = min(k_out, n_hosts * n_ici * kk)
    if n_hosts == 1:
        return host_d, host_i  # degenerate: k1 == k_final
    # level 2: slice over ICI ranks so ONE logical copy per host
    # crosses DCN
    per_rank = -(-k1 // n_ici)
    pad = per_rank * n_ici - k1
    if pad:
        host_d = jnp.pad(host_d, ((0, 0), (0, pad)),
                         constant_values=jnp.inf)
        host_i = jnp.pad(host_i, ((0, 0), (0, pad)), constant_values=-1)
    if compact:
        host_d = host_d.astype(jnp.bfloat16)
    host_iu = jax.lax.bitcast_convert_type(host_i, jnp.uint32)
    rank = jax.lax.axis_index(ICI_AXIS)
    sl_d = jax.lax.dynamic_slice_in_dim(host_d, rank * per_rank,
                                        per_rank, axis=1)
    sl_i = jax.lax.dynamic_slice_in_dim(host_iu, rank * per_rank,
                                        per_rank, axis=1)
    g_d = jax.lax.all_gather(sl_d, HOST_AXIS)   # the DCN hop
    g_i = jax.lax.all_gather(sl_i, HOST_AXIS)
    a_d = jax.lax.all_gather(g_d, ICI_AXIS)     # cheap on-host regather
    a_i = jax.lax.all_gather(g_i, ICI_AXIS)
    # (ici_rank, host, B, per_rank) -> [B, host-major contiguous blocks]
    cat2_d = jnp.transpose(a_d, (2, 1, 0, 3)).reshape(
        b, n_hosts * n_ici * per_rank)
    cat2_i = jnp.transpose(a_i, (2, 1, 0, 3)).reshape(
        b, n_hosts * n_ici * per_rank)
    cat2_i = jax.lax.bitcast_convert_type(cat2_i, jnp.int32)
    if compact:
        cat2_d = cat2_d.astype(jnp.float32)
    return topk_smallest(cat2_d, cat2_i, k_final)


def _merge_topk_mesh(d, ids, mesh: Mesh, axis: str, k_out: int,
                     compact: bool = False):
    """Mesh-shape dispatch: 1-D flat merge vs hierarchical two-level."""
    if is_hierarchical(mesh):
        return _two_level_merge_topk(d, ids, mesh, k_out, compact=compact)
    return _ici_merge_topk(d, ids, axis, k_out)


def topology_dcn_candidate_bytes(n_hosts: int, n_local: int, k: int,
                                 kk: int | None = None, *,
                                 level: str = "two_level",
                                 compact: bool = False) -> int:
    """Pure topology math: per-query candidate bytes ONE host sends
    across DCN during the merge, for an ``n_hosts x n_local`` pod.
    Rig-independent — the benchkeeper ``dcn_bytes_ratio`` gate computes
    this for the reference 2x4 topology no matter what hardware the
    bench runs on. ``kk`` is the per-device candidate count (defaults
    to k); ``compact`` counts the bf16+uint32 wire format (6 B/pair vs
    8)."""
    kk = k if kk is None else kk
    if n_hosts <= 1:
        return 0
    if level == "flat":
        # all_gather over the whole axis: each of the host's n_local
        # devices ships kk pairs (f32+int32) to the other hosts
        return n_local * kk * 8 * (n_hosts - 1)
    pair = 6 if compact else 8
    k1 = min(k, n_local * kk)
    per_rank = -(-k1 // n_local)  # ICI-rank slice width (inf-padded)
    return per_rank * n_local * pair * (n_hosts - 1)


def merge_dcn_candidate_bytes(mesh: Mesh, k: int, kk: int | None = None,
                              *, level: str = "auto",
                              compact: bool = False) -> int:
    """``topology_dcn_candidate_bytes`` for a concrete mesh (0 when the
    mesh is single-host)."""
    from weaviate_tpu.parallel.mesh import host_count

    n_hosts = host_count(mesh)
    if n_hosts <= 1:
        return 0
    if level == "auto":
        level = "two_level" if is_hierarchical(mesh) else "flat"
    return topology_dcn_candidate_bytes(
        n_hosts, n_row_shards(mesh) // n_hosts, k, kk, level=level,
        compact=compact)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "chunk_size", "metric", "mesh", "axis", "use_pallas",
        "selection", "dcn_compact",
    ),
)
def _sharded_topk_jit(
    q: jnp.ndarray,
    x: jnp.ndarray,
    valid: jnp.ndarray,
    x_sq_norms: jnp.ndarray | None,
    k: int,
    chunk_size: int,
    metric: str,
    mesh: Mesh,
    axis: str = SHARD_AXIS,
    use_pallas: bool = False,
    selection: str = "exact",
    allow_rows: jnp.ndarray | None = None,
    dcn_compact: bool = False,
):
    """Top-k of q [B,d] against row-sharded corpus x [N,d].

    ``x``/``valid``/``x_sq_norms`` must be row-sharded over the mesh's
    row axes on their leading dim; ``q`` is replicated. ``allow_rows``
    ([B, N] bool — per-query filter masks) is sharded on its COLUMN dim,
    row-aligned with the corpus: each device applies (and, for the fused
    kernel, packs) only its own slice; the candidate merge is unchanged
    because masked rows simply never become candidates. Returns
    replicated (dists [B,k], global_ids [B,k]) where ids index the
    unsharded [N] row space.
    """
    n = x.shape[0]
    n_shards = n_row_shards(mesh)
    local_rows = n // n_shards

    def local_search(q_, x_, valid_, norms_, allow_):
        shard_idx = _shard_index(mesh, axis)
        d, i = chunked_topk_distances(
            q_,
            x_,
            k=k,
            chunk_size=chunk_size,
            metric=metric,
            valid=valid_,
            x_sq_norms=norms_,
            id_offset=shard_idx * local_rows,
            use_pallas=use_pallas,
            selection=selection,
            allow_rows=allow_,
        )
        return _merge_topk_mesh(d, i, mesh, axis, k, compact=dcn_compact)

    specs = partition.match_partition_rules(
        partition.SEARCH_RULES,
        {"q": q, "x": x, "valid": valid, "x_sq_norms": x_sq_norms,
         "allow_rows": allow_rows},
        mesh)
    in_specs = (specs["q"], specs["x"], specs["valid"],
                specs["x_sq_norms"], specs["allow_rows"])
    out_specs = (partition.replicated_spec(), partition.replicated_spec())
    fn = shard_map(
        local_search,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return fn(q, x, valid, x_sq_norms, allow_rows)


def sharded_topk(q, x, valid, x_sq_norms, *, k, chunk_size, metric, mesh,
                 axis=SHARD_AXIS, use_pallas=False, selection="exact",
                 allow_rows=None, dcn_compact=None):
    """Span-wrapped dispatch of the SPMD scan + top-k merge program
    (spans can't live inside jit; the wrapper times the host-side
    dispatch and device_sync at the store level attributes execution)."""
    if dcn_compact is None:
        dcn_compact = dcn_compact_default()
    with tracing.span("spmd.sharded_topk", shards=n_row_shards(mesh),
                      k=k, rows=int(x.shape[0]),
                      hierarchical=is_hierarchical(mesh),
                      filtered=allow_rows is not None):
        # EXPLAIN: ICI/DCN merge shape — pure topology ints computed on
        # the host at dispatch (mesh axis sizes), never device reads
        hier = is_hierarchical(mesh)
        kernelscope.explain_note(
            "merge",
            shards=n_row_shards(mesh), hierarchical=bool(hier),
            hosts=int(mesh.shape[HOST_AXIS]) if hier else 1,
            ici=(int(mesh.shape[ICI_AXIS]) if hier
                 else n_row_shards(mesh)),
            dcn_compact=bool(dcn_compact), k=k)
        return _sharded_topk_jit(
            q, x, valid, x_sq_norms, k=k, chunk_size=chunk_size,
            metric=metric, mesh=mesh, axis=axis, use_pallas=use_pallas,
            selection=selection, allow_rows=allow_rows,
            dcn_compact=dcn_compact)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "k_out", "chunk_size", "quantization", "metric", "mesh", "axis",
        "use_pallas", "selection", "dcn_compact",
    ),
)
def _sharded_quantized_topk_jit(
    q: jnp.ndarray,
    q_words: jnp.ndarray | None,
    codes: jnp.ndarray,
    valid: jnp.ndarray,
    rescore_rows: jnp.ndarray | None,
    centroids: jnp.ndarray | None,
    k: int,
    k_out: int,
    chunk_size: int,
    quantization: str,
    metric: str,
    mesh: Mesh,
    axis: str = SHARD_AXIS,
    use_pallas: bool = False,
    selection: str = "approx",
    allow_rows: jnp.ndarray | None = None,
    dcn_compact: bool = False,
):
    """Compressed scan over a row-sharded code array, one SPMD program.

    The reference composes compression with sharding for free because PQ/BQ
    is per-shard state inside each physical shard (hnsw/compress.go:38 under
    usecases/sharding/state.go:28). The TPU analog: codes [N, m|w] live
    row-sharded over the mesh's row axes; each device scans its rows (MXU
    hamming / LUT-ADC), approx-selects ``k`` local candidates, optionally
    rescores them EXACTLY against its own row-sharded ``rescore_rows``
    (bf16 — owning-device rescore, no cross-device vector traffic), and
    the final merge moves only candidate (distance, id) pairs — one
    all_gather on the 1-D mesh, the two-level ICI+DCN reduce on the
    hierarchical one.

    ``q`` is replicated f32 (pre-normalized for cosine); ``q_words`` packed
    query bits for bq. ``selection`` picks the per-shard survivor selector
    for the bq/pq4 scan-reduce paths ("approx" = approx_max_k, "fused" =
    exact in-kernel running-carry top-k); the merge contract is
    unchanged either way. ``allow_rows`` [B, N] bool per-query filter
    masks are COLUMN-sharded row-aligned with the codes; each device
    packs its slice to the kernel bitmask locally. Returns replicated
    (dists [B, k_out], global ids).
    """
    from weaviate_tpu.ops import bq as bq_ops
    from weaviate_tpu.ops import pq as pq_ops
    from weaviate_tpu.ops.distances import MASKED_DISTANCE

    n = codes.shape[0]
    n_shards = n_row_shards(mesh)
    local_rows = n // n_shards
    b = q.shape[0]

    def local_scan(q_, qw_, cent_, codes_, valid_, resc_, allow_=None):
        shard_idx = _shard_index(mesh, axis)
        ab_ = None
        if allow_ is not None:
            from weaviate_tpu.ops.pallas_kernels import (
                pack_allow_bitmask_jnp)

            ab_ = pack_allow_bitmask_jnp(allow_)
        if quantization == "bq":
            d_c, i_c = bq_ops.bq_topk(
                qw_, codes_, k=min(k, local_rows), chunk_size=chunk_size,
                valid=valid_, use_pallas=use_pallas, selection=selection,
                allow_bits=ab_,
            )
        elif quantization == "pq4":
            d_c, i_c = pq_ops.pq4_topk(
                q_, codes_, cent_, k=min(k, local_rows),
                chunk_size=chunk_size, metric=metric, valid=valid_,
                selection=selection, allow_bits=ab_,
            )
        else:
            d_c, i_c = pq_ops.pq_topk(
                q_, codes_, cent_, k=min(k, local_rows),
                chunk_size=chunk_size, metric=metric, valid=valid_,
                allow_bits=ab_,
            )
        if resc_ is not None:
            # exact rescore of local candidates against local bf16 rows:
            # gather [B, k, d] from this device's shard only
            rows = resc_[jnp.clip(i_c, 0, local_rows - 1)].astype(jnp.float32)
            if metric in ("cosine", "cosine-dot"):
                dd = 1.0 - jnp.einsum("bd,bkd->bk", q_, rows,
                                      preferred_element_type=jnp.float32)
            elif metric == "dot":
                dd = -jnp.einsum("bd,bkd->bk", q_, rows,
                                 preferred_element_type=jnp.float32)
            else:
                diff = q_[:, None, :] - rows
                dd = jnp.sum(diff * diff, axis=-1)
            dd = jnp.where(i_c >= 0, dd, MASKED_DISTANCE)
            d_c, i_c = topk_smallest(dd, i_c, min(k_out, i_c.shape[1]))
        gid = jnp.where(i_c >= 0, i_c + shard_idx * local_rows, -1)
        return _merge_topk_mesh(d_c, gid, mesh, axis, k_out,
                                compact=dcn_compact)

    # assemble args/specs in Python (quantization and rescore/allow
    # presence are static): shard_map can't close over traced arrays and
    # optional operands can't be None, so absent ones become tiny dummies
    qw = q_words if q_words is not None else jnp.zeros((b, 1), jnp.uint32)
    cent = (centroids if centroids is not None
            else jnp.zeros((1, 1, 1), jnp.float32))
    has_resc = rescore_rows is not None
    has_allow = allow_rows is not None
    rule_specs = partition.match_partition_rules(
        partition.QUANTIZED_RULES,
        {"q": q, "q_words": qw, "centroids": cent, "codes": codes,
         "valid": valid, "rescore_rows": rescore_rows,
         "allow_rows": allow_rows},
        mesh)
    args = [q, qw, cent, codes, valid]
    specs = [rule_specs["q"], rule_specs["q_words"],
             rule_specs["centroids"], rule_specs["codes"],
             rule_specs["valid"]]
    if has_resc:
        args.append(rescore_rows)
        specs.append(rule_specs["rescore_rows"])
    if has_allow:
        args.append(allow_rows)
        specs.append(rule_specs["allow_rows"])

    def fn(q_, qw_, cent_, codes_, valid_, *rest):
        resc_ = rest[0] if has_resc else None
        allow_ = rest[-1] if has_allow else None
        return local_scan(q_, qw_, cent_, codes_, valid_, resc_, allow_)

    sharded = shard_map(
        fn, mesh=mesh, in_specs=tuple(specs),
        out_specs=(partition.replicated_spec(),
                   partition.replicated_spec()),
        check_vma=False)
    return sharded(*args)


def sharded_quantized_topk(q, q_words, codes, valid, rescore_rows,
                           centroids, *, k, k_out, chunk_size,
                           quantization, metric, mesh, axis=SHARD_AXIS,
                           use_pallas=False, selection="approx",
                           allow_rows=None, dcn_compact=None):
    """Span-wrapped dispatch of the compressed SPMD scan + merge."""
    if dcn_compact is None:
        dcn_compact = dcn_compact_default()
    with tracing.span("spmd.quantized_topk", shards=n_row_shards(mesh),
                      k=k_out, rows=int(codes.shape[0]),
                      quantization=quantization,
                      hierarchical=is_hierarchical(mesh),
                      filtered=allow_rows is not None):
        hier = is_hierarchical(mesh)
        kernelscope.explain_note(
            "merge",
            shards=n_row_shards(mesh), hierarchical=bool(hier),
            hosts=int(mesh.shape[HOST_AXIS]) if hier else 1,
            ici=(int(mesh.shape[ICI_AXIS]) if hier
                 else n_row_shards(mesh)),
            dcn_compact=bool(dcn_compact), k=k_out)
        return _sharded_quantized_topk_jit(
            q, q_words, codes, valid, rescore_rows, centroids, k=k,
            k_out=k_out, chunk_size=chunk_size, quantization=quantization,
            metric=metric, mesh=mesh, axis=axis, use_pallas=use_pallas,
            selection=selection, allow_rows=allow_rows,
            dcn_compact=dcn_compact)


def shard_array(arr, mesh: Mesh, dim: int = 0):
    """Place ``arr`` on ``mesh`` row-sharded along ``dim`` (the mesh's
    row axes resolve through partition.row_sharding — 'shard' on the
    1-D mesh, ('host','ici') on the hierarchical one; a custom 1-D
    axis name is honored via row_axes).

    On a multi-process (DCN) mesh, device_put can only target addressable
    devices — each process materializes its own shards from the (process-
    locally identical) host array via make_array_from_callback."""
    sharding = partition.row_sharding(mesh, dim=dim)
    if jax.process_count() > 1:
        arr_np = np.asarray(arr)
        return jax.make_array_from_callback(
            arr_np.shape, sharding, lambda idx: arr_np[idx])
    return jax.device_put(arr, sharding)


def replicate_array_multihost(arr, mesh: Mesh):
    arr_np = np.asarray(arr)
    sharding = partition.replicated_sharding(mesh)
    return jax.make_array_from_callback(
        arr_np.shape, sharding, lambda idx: arr_np[idx])


def grow_rows(arr, pad_rows: int, mesh: Mesh | None):
    """Append ``pad_rows`` zero rows to ``arr`` (leading dim), donated and —
    on a mesh — shard-local: both capacities are shard-aligned so each
    device just extends its own shard. An eager concatenate + re-place
    would funnel the full array through one device (minutes + 2x memory at
    100M-row capacities)."""

    def pad(a):
        return jnp.concatenate(
            [a, jnp.zeros((pad_rows,) + a.shape[1:], dtype=a.dtype)])

    if mesh is None:
        return jax.jit(pad, donate_argnums=0)(arr)
    out_sh = partition.row_sharding(mesh, dim=0)
    return jax.jit(pad, donate_argnums=0, out_shardings=out_sh)(arr)


def sharded_zeros(shape, dtype, mesh: Mesh, dim: int = 0):
    """Allocate a zero array directly in its sharded layout — each device
    materializes only its own shard (a host jnp.zeros + device_put round
    trip copies the full array through one device and takes minutes at
    100M-row capacities)."""
    out_sh = partition.row_sharding(mesh, dim=dim)
    return jax.jit(
        functools.partial(jnp.zeros, shape, dtype), out_shardings=out_sh
    )()


def replicate_array(arr, mesh: Mesh):
    if jax.process_count() > 1:
        return replicate_array_multihost(arr, mesh)
    return jax.device_put(arr, partition.replicated_sharding(mesh))


def tracked_shard_array(arr, mesh: Mesh, dim: int = 0,
                        component: str = "sharded",
                        owner: dict | None = None):
    """shard_array + HBM-ledger registration tied to the array's
    lifetime (weakref finalizer) — the placement helper for transient
    sharded operands like per-query allow masks, where nobody holds a
    release key but the peak watermark should still see the bytes."""
    out = shard_array(arr, mesh, dim=dim)
    from weaviate_tpu.runtime.hbm_ledger import ledger

    ledger.track(component, out, sharding="sharded", **(owner or {}))
    return out


@functools.partial(
    jax.jit,
    static_argnames=("k", "nprobe", "metric", "mesh", "axis",
                     "dcn_compact"),
)
def sharded_ivf_pq_topk(
    q: jnp.ndarray,
    centroids: jnp.ndarray,
    list_codes: jnp.ndarray,
    list_valid: jnp.ndarray,
    list_slots: jnp.ndarray,
    list_tvals: jnp.ndarray,
    pq_centroids: jnp.ndarray,
    k: int,
    nprobe: int,
    metric: str,
    mesh: Mesh,
    axis: str = SHARD_AXIS,
    dcn_compact: bool = False,
):
    """SPMD IVF-PQ probe over LIST-sharded posting lists.

    The 100M-per-chip capacity layout (SURVEY §7): ``centroids``
    [nlist, d], ``list_codes`` [nlist, cap, m], ``list_valid``
    [nlist, cap], ``list_slots`` [nlist, cap], ``list_tvals``
    [nlist, cap] (per-row residual-ADC constant) are all sharded over
    the mesh's row axes on the LIST dim; ``q`` and the PQ codebook are
    replicated. Each device ranks ITS local centroids, probes its local
    top-nprobe lists (so the union covers >= the global top-nprobe;
    recall can only exceed the single-device equivalent), scores codes
    via the chunked one-hot int8 matmul (engine/ivf._ivf_probe_topk_pq),
    and contributes k local candidates to the candidate merge — slots,
    not vectors, cross the interconnect (the SPMD analog of the
    reference's scatter-gather, index.go:1541), and on the hierarchical
    mesh only per-host winners cross DCN.

    NOTE: returned distances are int8-quantized ADC approximations (the
    per-query LUT quantization in engine/ivf adds ~0.4% distance error)
    and are NOT exact-rescored here — the merged candidate SLOTS are the
    contract. Callers that surface distances (or need exact ordering at
    the top) must rescore the merged candidates against full-precision
    rows on the owning device or host, as QuantizedVectorStore.search
    does for the single-device path.
    """
    from weaviate_tpu.engine.ivf import _ivf_probe_topk_pq

    # inline, NOT engine.ivf._dummy_bits(): this function body runs under
    # its own jit trace, and a cached helper must never capture a tracer
    dummy_bits = jnp.zeros((1, _MASK_WORDS), dtype=jnp.uint32)

    def local_probe(q_, cent_, codes_, valid_, slots_, tvals_, pqc_):
        local_nlist = cent_.shape[0]
        cn = jnp.sum(cent_.astype(jnp.float32) ** 2, axis=-1)
        d, s = _ivf_probe_topk_pq(
            q_, cent_, cn, codes_, valid_, slots_, tvals_, pqc_,
            dummy_bits, min(k, local_nlist * codes_.shape[1]),
            min(nprobe, local_nlist), metric, False)
        return _merge_topk_mesh(d, s, mesh, axis, k, compact=dcn_compact)

    specs = partition.match_partition_rules(
        partition.IVF_RULES,
        {"q": q, "centroids": centroids, "list_codes": list_codes,
         "list_valid": list_valid, "list_slots": list_slots,
         "list_tvals": list_tvals, "pq_centroids": pq_centroids},
        mesh)
    fn = shard_map(
        local_probe,
        mesh=mesh,
        in_specs=(specs["q"], specs["centroids"], specs["list_codes"],
                  specs["list_valid"], specs["list_slots"],
                  specs["list_tvals"], specs["pq_centroids"]),
        out_specs=(partition.replicated_spec(),
                   partition.replicated_spec()),
        check_vma=False,
    )
    return fn(q, centroids, list_codes, list_valid, list_slots,
              list_tvals, pq_centroids)
