"""Sharded brute-force top-k over a device mesh.

The cross-shard query path of the reference — parallel per-shard search plus
a host-side merge (adapters/repos/db/index.go:1576-1648) — becomes one
compiled SPMD program:

    per-device chunked scan  →  local top-k  →  all_gather(k per device)
    →  merge top-k (replicated)

The all_gather moves only [n_shards, B, k] candidate (distance, id) pairs
over ICI — never raw vectors — so the collective payload is tiny compared
with the HBM traffic of the scan itself.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from weaviate_tpu.ops.topk import chunked_topk_distances, topk_smallest
from weaviate_tpu.parallel.mesh import SHARD_AXIS


@functools.partial(
    jax.jit,
    static_argnames=("k", "chunk_size", "metric", "mesh", "axis", "use_pallas"),
)
def sharded_topk(
    q: jnp.ndarray,
    x: jnp.ndarray,
    valid: jnp.ndarray,
    x_sq_norms: jnp.ndarray | None,
    k: int,
    chunk_size: int,
    metric: str,
    mesh: Mesh,
    axis: str = SHARD_AXIS,
    use_pallas: bool = False,
):
    """Top-k of q [B,d] against row-sharded corpus x [N,d].

    ``x``/``valid``/``x_sq_norms`` must be sharded over ``axis`` on their
    leading dim; ``q`` is replicated. Returns replicated (dists [B,k],
    global_ids [B,k]) where ids index the unsharded [N] row space.
    """
    n = x.shape[0]
    n_shards = mesh.shape[axis]
    local_rows = n // n_shards

    def local_search(q_, x_, valid_, norms_):
        shard_idx = jax.lax.axis_index(axis)
        d, i = chunked_topk_distances(
            q_,
            x_,
            k=k,
            chunk_size=chunk_size,
            metric=metric,
            valid=valid_,
            x_sq_norms=norms_,
            id_offset=shard_idx * local_rows,
            use_pallas=use_pallas,
        )
        # gather every shard's candidates: [n_shards, B, k] each
        all_d = jax.lax.all_gather(d, axis)
        all_i = jax.lax.all_gather(i, axis)
        b = q_.shape[0]
        cat_d = jnp.transpose(all_d, (1, 0, 2)).reshape(b, n_shards * k)
        cat_i = jnp.transpose(all_i, (1, 0, 2)).reshape(b, n_shards * k)
        return topk_smallest(cat_d, cat_i, k)

    in_specs = (
        P(),            # q replicated
        P(axis, None),  # x row-sharded
        P(axis),        # valid row-sharded
        P() if x_sq_norms is None else P(axis),
    )
    out_specs = (P(), P())
    fn = shard_map(
        local_search,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return fn(q, x, valid, x_sq_norms)


def shard_array(arr, mesh: Mesh, axis: str = SHARD_AXIS, dim: int = 0):
    """Place ``arr`` on ``mesh`` sharded along ``dim``."""
    spec = [None] * arr.ndim
    spec[dim] = axis
    return jax.device_put(arr, NamedSharding(mesh, P(*spec)))


def replicate_array(arr, mesh: Mesh):
    return jax.device_put(arr, NamedSharding(mesh, P()))
