"""Sharded brute-force top-k over a device mesh.

The cross-shard query path of the reference — parallel per-shard search plus
a host-side merge (adapters/repos/db/index.go:1576-1648) — becomes one
compiled SPMD program:

    per-device chunked scan  →  local top-k  →  all_gather(k per device)
    →  merge top-k (replicated)

The all_gather moves only [n_shards, B, k] candidate (distance, id) pairs
over ICI — never raw vectors — so the collective payload is tiny compared
with the HBM traffic of the scan itself.

Allow-mask row alignment contract: ``allow_rows`` is always [B, N_local]
bool, column-sharded P(None, shard) ROW-ALIGNED with whatever corpus
array the same call scans. Epoch stores (engine/epochs.py) honor this by
column-slicing the global mask to each epoch's LOCAL row space
(compaction-aware through the epoch's slot maps) before dispatching that
epoch's scan — one sliced mask per epoch program, while the per-epoch
candidate sets and their replicated local->global slot maps merge in a
separate tiny program (ops/topk.merge_epoch_topk, this module's ICI
merge pattern turned inward).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6: top-level export, replication check renamed check_vma
    from jax import shard_map as _shard_map_impl

    _SHARD_MAP_CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental home, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _SHARD_MAP_CHECK_KW = "check_rep"

from weaviate_tpu.ops.topk import chunked_topk_distances, topk_smallest
from weaviate_tpu.parallel.mesh import SHARD_AXIS
from weaviate_tpu.runtime import tracing


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """Version-portable shard_map (the replication-check kwarg moved and
    the symbol left jax.experimental between the pinned jax releases)."""
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_SHARD_MAP_CHECK_KW: check_vma})


def _ici_merge_topk(d, ids, axis: str, k_out: int):
    """The cross-shard candidate merge every SPMD entry point shares:
    all_gather [n_shards, B, kk] (distance, id) pairs over ICI, flatten
    per query, exact top-k (the device analog of the reference's
    host-side merge, index.go:1644)."""
    all_d = jax.lax.all_gather(d, axis)
    all_i = jax.lax.all_gather(ids, axis)
    n_sh, b, kk = all_d.shape
    cat_d = jnp.transpose(all_d, (1, 0, 2)).reshape(b, n_sh * kk)
    cat_i = jnp.transpose(all_i, (1, 0, 2)).reshape(b, n_sh * kk)
    return topk_smallest(cat_d, cat_i, min(k_out, n_sh * kk))


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "chunk_size", "metric", "mesh", "axis", "use_pallas", "selection",
    ),
)
def _sharded_topk_jit(
    q: jnp.ndarray,
    x: jnp.ndarray,
    valid: jnp.ndarray,
    x_sq_norms: jnp.ndarray | None,
    k: int,
    chunk_size: int,
    metric: str,
    mesh: Mesh,
    axis: str = SHARD_AXIS,
    use_pallas: bool = False,
    selection: str = "exact",
    allow_rows: jnp.ndarray | None = None,
):
    """Top-k of q [B,d] against row-sharded corpus x [N,d].

    ``x``/``valid``/``x_sq_norms`` must be sharded over ``axis`` on their
    leading dim; ``q`` is replicated. ``allow_rows`` ([B, N] bool —
    per-query filter masks) is sharded over ``axis`` on its COLUMN dim,
    row-aligned with the corpus: each device applies (and, for the fused
    kernel, packs) only its own slice; the ICI merge is unchanged because
    masked rows simply never become candidates. Returns replicated
    (dists [B,k], global_ids [B,k]) where ids index the unsharded [N]
    row space.
    """
    n = x.shape[0]
    n_shards = mesh.shape[axis]
    local_rows = n // n_shards

    def local_search(q_, x_, valid_, norms_, allow_):
        shard_idx = jax.lax.axis_index(axis)
        d, i = chunked_topk_distances(
            q_,
            x_,
            k=k,
            chunk_size=chunk_size,
            metric=metric,
            valid=valid_,
            x_sq_norms=norms_,
            id_offset=shard_idx * local_rows,
            use_pallas=use_pallas,
            selection=selection,
            allow_rows=allow_,
        )
        return _ici_merge_topk(d, i, axis, k)

    in_specs = (
        P(),            # q replicated
        P(axis, None),  # x row-sharded
        P(axis),        # valid row-sharded
        P() if x_sq_norms is None else P(axis),
        P() if allow_rows is None else P(None, axis),  # mask column-sharded
    )
    out_specs = (P(), P())
    fn = shard_map(
        local_search,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )
    return fn(q, x, valid, x_sq_norms, allow_rows)


def sharded_topk(q, x, valid, x_sq_norms, *, k, chunk_size, metric, mesh,
                 axis=SHARD_AXIS, use_pallas=False, selection="exact",
                 allow_rows=None):
    """Span-wrapped dispatch of the SPMD scan + ICI top-k merge program
    (spans can't live inside jit; the wrapper times the host-side
    dispatch and device_sync at the store level attributes execution)."""
    with tracing.span("spmd.sharded_topk", shards=mesh.shape[axis], k=k,
                      rows=int(x.shape[0]),
                      filtered=allow_rows is not None):
        return _sharded_topk_jit(
            q, x, valid, x_sq_norms, k=k, chunk_size=chunk_size,
            metric=metric, mesh=mesh, axis=axis, use_pallas=use_pallas,
            selection=selection, allow_rows=allow_rows)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "k_out", "chunk_size", "quantization", "metric", "mesh", "axis",
        "use_pallas", "selection",
    ),
)
def _sharded_quantized_topk_jit(
    q: jnp.ndarray,
    q_words: jnp.ndarray | None,
    codes: jnp.ndarray,
    valid: jnp.ndarray,
    rescore_rows: jnp.ndarray | None,
    centroids: jnp.ndarray | None,
    k: int,
    k_out: int,
    chunk_size: int,
    quantization: str,
    metric: str,
    mesh: Mesh,
    axis: str = SHARD_AXIS,
    use_pallas: bool = False,
    selection: str = "approx",
    allow_rows: jnp.ndarray | None = None,
):
    """Compressed scan over a row-sharded code array, one SPMD program.

    The reference composes compression with sharding for free because PQ/BQ
    is per-shard state inside each physical shard (hnsw/compress.go:38 under
    usecases/sharding/state.go:28). The TPU analog: codes [N, m|w] live
    row-sharded over ``axis``; each device scans its rows (MXU hamming /
    LUT-ADC), approx-selects ``k`` local candidates, optionally rescores
    them EXACTLY against its own row-sharded ``rescore_rows`` (bf16 —
    owning-device rescore, no cross-device vector traffic), and the final
    merge all_gathers only [n_shards, B, k] (distance, id) pairs over ICI.

    ``q`` is replicated f32 (pre-normalized for cosine); ``q_words`` packed
    query bits for bq. ``selection`` picks the per-shard survivor selector
    for the bq/pq4 scan-reduce paths ("approx" = approx_max_k, "fused" =
    exact in-kernel running-carry top-k); the ICI merge contract is
    unchanged either way. ``allow_rows`` [B, N] bool per-query filter
    masks are COLUMN-sharded row-aligned with the codes; each device
    packs its slice to the kernel bitmask locally. Returns replicated
    (dists [B, k_out], global ids).
    """
    from weaviate_tpu.ops import bq as bq_ops
    from weaviate_tpu.ops import pq as pq_ops
    from weaviate_tpu.ops.distances import MASKED_DISTANCE

    n = codes.shape[0]
    n_shards = mesh.shape[axis]
    local_rows = n // n_shards
    b = q.shape[0]

    def local_scan(q_, qw_, cent_, codes_, valid_, resc_, allow_=None):
        shard_idx = jax.lax.axis_index(axis)
        ab_ = None
        if allow_ is not None:
            from weaviate_tpu.ops.pallas_kernels import (
                pack_allow_bitmask_jnp)

            ab_ = pack_allow_bitmask_jnp(allow_)
        if quantization == "bq":
            d_c, i_c = bq_ops.bq_topk(
                qw_, codes_, k=min(k, local_rows), chunk_size=chunk_size,
                valid=valid_, use_pallas=use_pallas, selection=selection,
                allow_bits=ab_,
            )
        elif quantization == "pq4":
            d_c, i_c = pq_ops.pq4_topk(
                q_, codes_, cent_, k=min(k, local_rows),
                chunk_size=chunk_size, metric=metric, valid=valid_,
                selection=selection, allow_bits=ab_,
            )
        else:
            d_c, i_c = pq_ops.pq_topk(
                q_, codes_, cent_, k=min(k, local_rows),
                chunk_size=chunk_size, metric=metric, valid=valid_,
                allow_bits=ab_,
            )
        if resc_ is not None:
            # exact rescore of local candidates against local bf16 rows:
            # gather [B, k, d] from this device's shard only
            rows = resc_[jnp.clip(i_c, 0, local_rows - 1)].astype(jnp.float32)
            if metric in ("cosine", "cosine-dot"):
                dd = 1.0 - jnp.einsum("bd,bkd->bk", q_, rows,
                                      preferred_element_type=jnp.float32)
            elif metric == "dot":
                dd = -jnp.einsum("bd,bkd->bk", q_, rows,
                                 preferred_element_type=jnp.float32)
            else:
                diff = q_[:, None, :] - rows
                dd = jnp.sum(diff * diff, axis=-1)
            dd = jnp.where(i_c >= 0, dd, MASKED_DISTANCE)
            d_c, i_c = topk_smallest(dd, i_c, min(k_out, i_c.shape[1]))
        gid = jnp.where(i_c >= 0, i_c + shard_idx * local_rows, -1)
        return _ici_merge_topk(d_c, gid, axis, k_out)

    # assemble args/specs in Python (quantization and rescore/allow
    # presence are static): shard_map can't close over traced arrays and
    # optional operands can't be None, so absent ones become tiny dummies
    qw = q_words if q_words is not None else jnp.zeros((b, 1), jnp.uint32)
    cent = (centroids if centroids is not None
            else jnp.zeros((1, 1, 1), jnp.float32))
    has_resc = rescore_rows is not None
    has_allow = allow_rows is not None
    args = [q, qw, cent, codes, valid]
    specs = [P(), P(), P(), P(axis, None), P(axis)]
    if has_resc:
        args.append(rescore_rows)
        specs.append(P(axis, None))
    if has_allow:
        args.append(allow_rows)
        specs.append(P(None, axis))  # mask column-sharded, row-aligned

    def fn(q_, qw_, cent_, codes_, valid_, *rest):
        resc_ = rest[0] if has_resc else None
        allow_ = rest[-1] if has_allow else None
        return local_scan(q_, qw_, cent_, codes_, valid_, resc_, allow_)

    sharded = shard_map(fn, mesh=mesh, in_specs=tuple(specs),
                        out_specs=(P(), P()), check_vma=False)
    return sharded(*args)


def sharded_quantized_topk(q, q_words, codes, valid, rescore_rows,
                           centroids, *, k, k_out, chunk_size,
                           quantization, metric, mesh, axis=SHARD_AXIS,
                           use_pallas=False, selection="approx",
                           allow_rows=None):
    """Span-wrapped dispatch of the compressed SPMD scan + ICI merge."""
    with tracing.span("spmd.quantized_topk", shards=mesh.shape[axis],
                      k=k_out, rows=int(codes.shape[0]),
                      quantization=quantization,
                      filtered=allow_rows is not None):
        return _sharded_quantized_topk_jit(
            q, q_words, codes, valid, rescore_rows, centroids, k=k,
            k_out=k_out, chunk_size=chunk_size, quantization=quantization,
            metric=metric, mesh=mesh, axis=axis, use_pallas=use_pallas,
            selection=selection, allow_rows=allow_rows)


def shard_array(arr, mesh: Mesh, axis: str = SHARD_AXIS, dim: int = 0):
    """Place ``arr`` on ``mesh`` sharded along ``dim``.

    On a multi-process (DCN) mesh, device_put can only target addressable
    devices — each process materializes its own shards from the (process-
    locally identical) host array via make_array_from_callback."""
    spec = [None] * arr.ndim
    spec[dim] = axis
    sharding = NamedSharding(mesh, P(*spec))
    if jax.process_count() > 1:
        arr_np = np.asarray(arr)
        return jax.make_array_from_callback(
            arr_np.shape, sharding, lambda idx: arr_np[idx])
    return jax.device_put(arr, sharding)


def replicate_array_multihost(arr, mesh: Mesh):
    arr_np = np.asarray(arr)
    sharding = NamedSharding(mesh, P())
    return jax.make_array_from_callback(
        arr_np.shape, sharding, lambda idx: arr_np[idx])


def grow_rows(arr, pad_rows: int, mesh: Mesh | None, axis: str = SHARD_AXIS):
    """Append ``pad_rows`` zero rows to ``arr`` (leading dim), donated and —
    on a mesh — shard-local: both capacities are shard-aligned so each
    device just extends its own shard. An eager concatenate + re-place
    would funnel the full array through one device (minutes + 2x memory at
    100M-row capacities)."""
    shape = (arr.shape[0] + pad_rows,) + arr.shape[1:]

    def pad(a):
        return jnp.concatenate(
            [a, jnp.zeros((pad_rows,) + a.shape[1:], dtype=a.dtype)])

    if mesh is None:
        return jax.jit(pad, donate_argnums=0)(arr)
    spec = [None] * len(shape)
    spec[0] = axis
    out_sh = NamedSharding(mesh, P(*spec))
    return jax.jit(pad, donate_argnums=0, out_shardings=out_sh)(arr)


def sharded_zeros(shape, dtype, mesh: Mesh, axis: str = SHARD_AXIS,
                  dim: int = 0):
    """Allocate a zero array directly in its sharded layout — each device
    materializes only its own shard (a host jnp.zeros + device_put round
    trip copies the full array through one device and takes minutes at
    100M-row capacities)."""
    spec = [None] * len(shape)
    spec[dim] = axis
    out_sh = NamedSharding(mesh, P(*spec))
    return jax.jit(
        functools.partial(jnp.zeros, shape, dtype), out_shardings=out_sh
    )()


def replicate_array(arr, mesh: Mesh):
    if jax.process_count() > 1:
        return replicate_array_multihost(arr, mesh)
    return jax.device_put(arr, NamedSharding(mesh, P()))


def tracked_shard_array(arr, mesh: Mesh, dim: int = 0,
                        component: str = "sharded",
                        owner: dict | None = None):
    """shard_array + HBM-ledger registration tied to the array's
    lifetime (weakref finalizer) — the placement helper for transient
    sharded operands like per-query allow masks, where nobody holds a
    release key but the peak watermark should still see the bytes."""
    out = shard_array(arr, mesh, dim=dim)
    from weaviate_tpu.runtime.hbm_ledger import ledger

    ledger.track(component, out, sharding="sharded", **(owner or {}))
    return out


@functools.partial(
    jax.jit,
    static_argnames=("k", "nprobe", "metric", "mesh", "axis"),
)
def sharded_ivf_pq_topk(
    q: jnp.ndarray,
    centroids: jnp.ndarray,
    list_codes: jnp.ndarray,
    list_valid: jnp.ndarray,
    list_slots: jnp.ndarray,
    pq_centroids: jnp.ndarray,
    k: int,
    nprobe: int,
    metric: str,
    mesh: Mesh,
    axis: str = SHARD_AXIS,
):
    """SPMD IVF-PQ probe over LIST-sharded posting lists.

    The 100M-per-chip capacity layout (SURVEY §7): ``centroids``
    [nlist, d], ``list_codes`` [nlist, cap, m], ``list_valid``
    [nlist, cap], ``list_slots`` [nlist, cap] are all sharded over
    ``axis`` on the LIST dim; ``q`` and the PQ codebook are replicated.
    Each device ranks ITS local centroids, probes its local top-nprobe
    lists (so the union covers >= the global top-nprobe; recall can only
    exceed the single-device equivalent), scores codes via the chunked
    one-hot int8 matmul (engine/ivf._ivf_probe_topk_pq), and contributes
    k local candidates to an all_gather merge over ICI — slots, not
    vectors, cross the interconnect (the SPMD analog of the reference's
    scatter-gather, index.go:1541).

    NOTE: returned distances are int8-quantized ADC approximations (the
    per-query LUT quantization in engine/ivf adds ~0.4% distance error)
    and are NOT exact-rescored here — the merged candidate SLOTS are the
    contract. Callers that surface distances (or need exact ordering at
    the top) must rescore the merged candidates against full-precision
    rows on the owning device or host, as QuantizedVectorStore.search
    does for the single-device path.
    """
    from weaviate_tpu.engine.ivf import _ivf_probe_topk_pq

    n_shards = mesh.shape[axis]
    dummy_allow = jnp.ones((1,), dtype=bool)

    def local_probe(q_, cent_, codes_, valid_, slots_, pqc_):
        local_nlist = cent_.shape[0]
        cn = jnp.sum(cent_.astype(jnp.float32) ** 2, axis=-1)
        d, s = _ivf_probe_topk_pq(
            q_, cent_, cn, codes_, valid_, slots_, pqc_,
            dummy_allow, min(k, local_nlist * codes_.shape[1]),
            min(nprobe, local_nlist), metric, False)
        return _ici_merge_topk(d, s, axis, k)

    fn = shard_map(
        local_probe,
        mesh=mesh,
        in_specs=(P(), P(axis, None), P(axis, None, None),
                  P(axis, None), P(axis, None), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return fn(q, centroids, list_codes, list_valid, list_slots,
              pq_centroids)
