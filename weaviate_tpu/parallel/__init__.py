"""Device-mesh parallelism: sharded HBM corpora and ICI-collective search.

Replaces the reference's distributed data plane for vector search
(HTTP scatter-gather across shards, adapters/repos/db/index.go:1541-1663)
with a single compiled program: each device scans its row-shard of the
corpus, computes a local top-k, and the partial results are combined with
an all_gather over ICI — no host round-trips inside a query.
"""

from weaviate_tpu.parallel.mesh import (
    default_mesh,
    device_count,
    host_count,
    is_hierarchical,
    make_hierarchical_mesh,
    make_mesh,
    n_row_shards,
    shardable_capacity,
)
from weaviate_tpu.parallel.partition import match_partition_rules
from weaviate_tpu.parallel.sharded_search import sharded_topk

__all__ = [
    "default_mesh",
    "device_count",
    "host_count",
    "is_hierarchical",
    "make_hierarchical_mesh",
    "make_mesh",
    "match_partition_rules",
    "n_row_shards",
    "shardable_capacity",
    "sharded_topk",
]
