"""Declarative partition rules: regex -> PartitionSpec (ISSUE 13).

This module is the ONLY place in ``weaviate_tpu/`` allowed to construct
``jax.sharding.PartitionSpec`` (enforced by graftlint G8 "partition
discipline"). The SPMD search entry points name their operands and let
a regex rule table decide placement — the SNIPPETS [1]
``match_partition_rules`` pattern: per-collection placement (corpus
rows, codes, masks, norms, slot maps) is one table per entry point
instead of hand-wired ``P(None, 'shard')`` literals scattered across
call sites — and the device stores' placement helpers resolve through
the ``row_sharding``/``replicated_sharding`` functions below.

Rule values are mesh-independent TEMPLATES: tuples whose entries are
``None`` (replicated dim) or the ``ROWS`` token, which resolves to the
mesh's row axes — ``'shard'`` on the legacy 1-D mesh, the composite
``('host', 'ici')`` pair on the hierarchical mesh. The same table
therefore drives both mesh shapes; the two-level merge needs no
spec changes at call sites. The device stores' placement helpers
(``shard_array``/``grow_rows``/``sharded_zeros``/``replicate_array``/
``tracked_shard_array``) resolve through ``row_sharding``/
``replicated_sharding`` below — dim-parametrized, same ``ROWS``
resolution, no per-operand table needed for a plain leading-dim
row shard.

Templates may be SHORTER than the array rank (PartitionSpec semantics:
unnamed trailing dims are replicated), so ``(ROWS,)`` row-shards any
leading-dim corpus array regardless of rank.
"""

from __future__ import annotations

import re

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from weaviate_tpu.parallel.mesh import row_axes

#: template token: resolves to the mesh's row-sharding axis/axes
ROWS = "@rows"

#: common templates
REPLICATED: tuple = ()
ROW_SHARDED = (ROWS,)          # leading dim = corpus rows / IVF lists
QUERY_MASK = (None, ROWS)      # [B, N] per-query masks: column-sharded,
#                                row-aligned with the corpus

#: operand placement for the flat SPMD scan (sharded_search._sharded_topk_jit)
SEARCH_RULES = (
    (r"^(q|queries)$", REPLICATED),
    (r"^(x|corpus|vectors)$", ROW_SHARDED),
    (r"^(valid|x_sq_norms|sq_norms|norms)$", ROW_SHARDED),
    (r"^allow(_rows|_mask)?$", QUERY_MASK),
)

#: operand placement for the compressed SPMD scan (BQ / PQ / PQ4): the
#: codebook and packed query bits are replicated, codes + per-row state
#: row-shard, the optional bf16 rescore rows stay with their owning
#: device, per-query filter masks column-shard row-aligned
QUANTIZED_RULES = (
    (r"^(q|q_words)$", REPLICATED),
    (r"^(cent|centroids|codebook|pq_centroids)$", REPLICATED),
    (r"^(codes|rescore_rows)$", ROW_SHARDED),
    (r"^(valid|slots)$", ROW_SHARDED),
    (r"^allow(_rows|_mask)?$", QUERY_MASK),
)

#: operand placement for the IVF-PQ probe: EVERY list-dim array shards
#: over the list axis; only the query and the PQ codebook replicate
IVF_RULES = (
    (r"^q$", REPLICATED),
    (r"^pq_centroids$", REPLICATED),
    (r"^(centroids|list_codes|list_valid|list_slots|list_tvals)$",
     ROW_SHARDED),
)

def _is_scalar(arr) -> bool:
    shape = getattr(arr, "shape", None)
    if shape is None:
        return True
    return len(shape) == 0 or int(np.prod(shape)) == 1


def resolve_template(template, mesh: Mesh | None) -> PartitionSpec:
    """Template tuple -> concrete PartitionSpec for ``mesh`` (``ROWS``
    entries become the mesh's row axes)."""
    axes = row_axes(mesh)
    return PartitionSpec(
        *(axes if entry == ROWS else entry for entry in template))


def match_partition_rules(rules, named_arrays: dict, mesh: Mesh | None):
    """``{name: array}`` -> ``{name: PartitionSpec}`` by first-matching
    regex (SNIPPETS [1] pattern). Scalars (0-d or single-element) and
    absent operands (``None``) pass through replicated — partitioning a
    scalar is meaningless and optional operands simply have no bytes to
    place. A non-scalar operand no rule names is an error: silent
    replication of a corpus-sized array is exactly the bug this table
    exists to prevent."""
    out = {}
    for name, arr in named_arrays.items():
        if arr is None or _is_scalar(arr):
            out[name] = resolve_template(REPLICATED, mesh)
            continue
        for pattern, template in rules:
            if re.search(pattern, name) is not None:
                out[name] = resolve_template(template, mesh)
                break
        else:
            raise ValueError(
                f"no partition rule matches operand {name!r} "
                f"(shape {getattr(arr, 'shape', None)}) — add it to the "
                "rule table in parallel/partition.py")
    return out


def replicated_spec() -> PartitionSpec:
    return PartitionSpec()


def row_spec(mesh: Mesh | None, dim: int = 0) -> PartitionSpec:
    """Rows sharded on ``dim``, every other dim replicated — the
    template behind ``shard_array(..., dim=...)``."""
    return resolve_template((None,) * dim + (ROWS,), mesh)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, replicated_spec())


def row_sharding(mesh: Mesh, dim: int = 0) -> NamedSharding:
    return NamedSharding(mesh, row_spec(mesh, dim))


def plan_corpus_placement(n_rows: int, dim: int, mesh: Mesh | None, *,
                          quantization: str = "bq",
                          chunk_size: int = 1024,
                          rescore_bytes_per_dim: int = 0) -> dict:
    """1B-vector DRY RUN (ISSUE 13 acceptance): the placement plan for
    an ``n_rows x dim`` corpus on ``mesh`` — shard-aligned capacity,
    bytes per component from the rule-table placements, and the exact
    per-host HBM load — WITHOUT allocating anything (the 1B BQ layout
    is 96+ GB of codes; the plan is what admission and the HBM ledger
    gate against before a single transfer).

    ``quantization``: "bq" (packed sign bits, dim/32 u32 words/row),
    "pq4"/"pq" (one byte per segment, dim/4 segments assumed), or
    "none" (bf16 rows). ``rescore_bytes_per_dim`` adds owning-device
    bf16 rescore rows (2) when the serving path rescores on device."""
    from weaviate_tpu.parallel.mesh import (host_count, n_row_shards,
                                            shardable_capacity)

    n_shards = max(1, n_row_shards(mesh))
    n_hosts = max(1, host_count(mesh))
    cap = shardable_capacity(int(n_rows), n_shards,
                             min(chunk_size, -(-int(n_rows) // n_shards)))
    if quantization == "bq":
        row_bytes = (dim // 32) * 4
    elif quantization in ("pq", "pq4"):
        row_bytes = dim // 4
    else:
        row_bytes = dim * 2  # bf16 rows
    components = {
        "codes" if quantization != "none" else "vectors": cap * row_bytes,
        "valid": cap * 1,
        "sq_norms": cap * 4 if quantization == "none" else 0,
        "rescore_rows": cap * dim * rescore_bytes_per_dim,
    }
    components = {k: v for k, v in components.items() if v}
    total = sum(components.values())
    per_host = total // n_hosts
    rows_per_host = cap // n_hosts
    return {
        "rows": int(n_rows),
        "capacity": cap,
        "shards": n_shards,
        "hosts": n_hosts,
        "rowsPerHost": rows_per_host,
        "rowsPerDevice": cap // n_shards,
        "components": components,
        "totalBytes": total,
        "perHostBytes": {f"host-{i}": per_host + (total - per_host
                                                  * n_hosts if i == 0
                                                  else 0)
                         for i in range(n_hosts)},
    }
