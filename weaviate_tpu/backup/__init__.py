"""Backup / restore subsystem.

Reference: usecases/backup — Handler validates and spawns async work,
the coordinator runs a 2-phase protocol over participating nodes
(coordinator.go:133 Backup, :199 Restore), each node's backupper pauses
compaction, lists shard files, and streams them to a module backend
(S3/GCS/Azure/filesystem); progress is polled via /v1/backups/.../status.

Single-node manager here (the multi-node path rides the cluster layer's
remote API the same way queries do): snapshot = flush + copy the
collection's on-disk tree through a ``BackupBackend`` module, plus a
``backup_config.json`` descriptor carrying schema + sharding so restore
can rebuild the collection without pre-existing schema.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from weaviate_tpu.modules.base import BackupBackend, ModuleError
from weaviate_tpu.schema.config import CollectionConfig

_ID_RE = re.compile(r"^[a-z0-9][a-z0-9_-]*$")

# reference: entities/backup/status.go
STARTED = "STARTED"
TRANSFERRING = "TRANSFERRING"
TRANSFERRED = "TRANSFERRED"
SUCCESS = "SUCCESS"
FAILED = "FAILED"

DESCRIPTOR = "backup_config.json"


class BackupError(Exception):
    pass


from weaviate_tpu.modules.backup_backends import walk_files as _walk_files

_ACTIVE = (STARTED, TRANSFERRING, TRANSFERRED)


class BackupManager:
    """``modules``: module Provider — backends resolve via
    ``backup_backend(name)`` (reference: module registry lookup,
    usecases/backup/handler.go). ``schema_target``: where restored
    classes are created — the Database itself (single node) or the
    ClusterNode (Raft path), same seam the REST schema routes use."""

    def __init__(self, db, modules, node_name: str = "node-0",
                 schema_target=None):
        self.db = db
        self.modules = modules
        self.node_name = node_name
        self.schema_target = schema_target or db
        self._lock = threading.Lock()
        self._backups: dict[tuple[str, str], dict] = {}
        self._restores: dict[tuple[str, str], dict] = {}

    # -- backup --------------------------------------------------------------

    def start_backup(self, backend_name: str, backup_id: str,
                     include: list[str] | None = None,
                     exclude: list[str] | None = None,
                     wait: bool = False) -> dict:
        backend = self._backend(backend_name)
        self._check_id(backup_id)
        if include and exclude:
            raise BackupError("include and exclude are mutually exclusive")
        all_classes = self.db.list_collections()
        classes = list(include) if include else \
            [c for c in all_classes if c not in set(exclude or [])]
        for c in classes:
            if c not in all_classes:
                raise BackupError(f"class {c!r} does not exist")
        if not classes:
            raise BackupError("no classes to back up")
        if self._descriptor_exists(backend, backend_name, backup_id):
            raise BackupError(
                f"backup {backup_id!r} already exists on {backend_name!r}")
        key = (backend_name, backup_id)
        status = {"id": backup_id, "backend": backend_name,
                  "status": STARTED, "error": None, "classes": classes,
                  "path": self._home(backend, backup_id)}
        with self._lock:
            if key in self._backups and \
                    self._backups[key]["status"] in _ACTIVE:
                raise BackupError(f"backup {backup_id!r} already running")
            self._backups[key] = status

        def work():
            try:
                status["status"] = TRANSFERRING
                backend.initialize(backup_id)
                descriptor = {
                    "id": backup_id,
                    "node": self.node_name,
                    "startedAt": time.time(),
                    "version": "1",
                    "classes": [],
                }
                # pause background compaction/flush cycles for a consistent
                # file set (reference: Shard.BeginBackup pauses compaction
                # + commit-log switching, shard_backup.go)
                with self.db.cycles.pause():
                    self.db.flush()
                    for cls in classes:
                        col = self.db.get_collection(cls)
                        root = os.path.join(self.db.data_dir, cls)
                        files = _walk_files(root) if os.path.isdir(root) \
                            else []
                        for rel in files:
                            # streamed: multi-GB segment files never
                            # materialize in memory
                            backend.put_file(backup_id, f"{cls}/{rel}",
                                             os.path.join(root, rel))
                        descriptor["classes"].append({
                            "name": cls,
                            "config": col.config.to_dict(),
                            "sharding": col.sharding.to_dict(),
                            "files": files,
                        })
                status["status"] = TRANSFERRED
                descriptor["completedAt"] = time.time()
                backend.put(backup_id, DESCRIPTOR,
                            json.dumps(descriptor).encode())
                status["status"] = SUCCESS
            except Exception as e:  # surfaced via status polling
                status["status"] = FAILED
                status["error"] = str(e)

        t = threading.Thread(target=work, daemon=True,
                             name=f"backup-{backup_id}")
        t.start()
        if wait:
            t.join()
        return dict(status)

    # -- restore -------------------------------------------------------------

    def start_restore(self, backend_name: str, backup_id: str,
                      include: list[str] | None = None,
                      exclude: list[str] | None = None,
                      wait: bool = False) -> dict:
        backend = self._backend(backend_name)
        self._check_id(backup_id)
        try:
            descriptor = json.loads(backend.get(backup_id, DESCRIPTOR))
        except Exception:
            raise BackupError(
                f"backup {backup_id!r} not found on {backend_name!r}")
        if include and exclude:
            raise BackupError("include and exclude are mutually exclusive")
        try:
            by_name = {c["name"]: c for c in descriptor["classes"]}
            for c in by_name.values():
                c["files"], c["config"], c["sharding"]
        except (KeyError, TypeError) as e:
            raise BackupError(
                f"backup {backup_id!r} has a malformed descriptor: {e}")
        classes = list(include) if include else \
            [n for n in by_name if n not in set(exclude or [])]
        for c in classes:
            if c not in by_name:
                raise BackupError(f"class {c!r} not in backup {backup_id!r}")
            if c in self.db.list_collections():
                raise BackupError(
                    f"class {c!r} already exists; delete it before restore "
                    "(reference behavior: restore never overwrites)")
        key = (backend_name, backup_id)
        status = {"id": backup_id, "backend": backend_name,
                  "status": STARTED, "error": None, "classes": classes,
                  "path": self._home(backend, backup_id)}
        with self._lock:
            if key in self._restores and \
                    self._restores[key]["status"] in _ACTIVE:
                raise BackupError(f"restore {backup_id!r} already running")
            self._restores[key] = status

        def work():
            try:
                status["status"] = TRANSFERRING
                from weaviate_tpu.db.sharding import ShardingState

                data_root = os.path.abspath(self.db.data_dir)
                for cls in classes:
                    entry = by_name[cls]
                    root = os.path.abspath(
                        os.path.join(self.db.data_dir, cls))
                    # the descriptor is UNTRUSTED backend content: class
                    # names and file paths must stay inside data_dir
                    if os.path.dirname(root) != data_root:
                        raise BackupError(
                            f"descriptor class name {cls!r} escapes the "
                            "data directory")
                    for rel in entry["files"]:
                        dst = os.path.abspath(os.path.join(root, rel))
                        if not dst.startswith(root + os.sep):
                            raise BackupError(
                                f"descriptor file path {rel!r} escapes "
                                "the class directory")
                        backend.get_file(backup_id, f"{cls}/{rel}", dst)
                    cfg = CollectionConfig.from_dict(entry["config"])
                    state = ShardingState.from_dict(entry["sharding"])
                    # through the schema seam so cluster nodes take the
                    # Raft path and peers learn the restored class
                    self.schema_target.create_collection(
                        cfg, sharding_state=state)
                status["status"] = SUCCESS
            except Exception as e:
                status["status"] = FAILED
                status["error"] = str(e)

        t = threading.Thread(target=work, daemon=True,
                             name=f"restore-{backup_id}")
        t.start()
        if wait:
            t.join()
        return dict(status)

    # -- status --------------------------------------------------------------

    @staticmethod
    def _check_id(backup_id: str) -> None:
        if not _ID_RE.match(backup_id or ""):
            raise BackupError(f"invalid backup id {backup_id!r} (lowercase "
                              "letters, numbers, '_', '-' only)")

    @staticmethod
    def _descriptor_exists(backend, backend_name, backup_id) -> bool:
        try:
            return bool(backend.get(backup_id, DESCRIPTOR))
        except (KeyError, FileNotFoundError):
            return False
        except ModuleError as e:
            raise BackupError(str(e))
        except Exception as e:  # unreachable endpoint etc. → clean 422
            raise BackupError(
                f"backend {backend_name!r} probe failed: {e}")

    def backup_status(self, backend_name: str, backup_id: str) -> dict:
        return self._status(self._backups, backend_name, backup_id, "backup")

    def restore_status(self, backend_name: str, backup_id: str) -> dict:
        return self._status(self._restores, backend_name, backup_id,
                            "restore")

    def _status(self, table, backend_name, backup_id, kind) -> dict:
        with self._lock:
            st = table.get((backend_name, backup_id))
        if st is None:
            raise BackupError(f"no {kind} {backup_id!r} on {backend_name!r}")
        return dict(st)

    # -- helpers -------------------------------------------------------------

    def _backend(self, name: str) -> BackupBackend:
        if self.modules is None:
            raise BackupError("backups require a module provider")
        try:
            return self.modules.backup_backend(name)
        except ModuleError as e:
            raise BackupError(str(e))

    @staticmethod
    def _home(backend, backup_id) -> str:
        try:
            return backend.home_dir(backup_id)
        except Exception:
            return ""
