"""Backup / restore subsystem.

Reference: usecases/backup — Handler validates and spawns async work,
the coordinator runs a 2-phase protocol over participating nodes
(coordinator.go:133 Backup, :199 Restore), each node's backupper pauses
compaction, lists shard files, and streams them to a module backend
(S3/GCS/Azure/filesystem); progress is polled via /v1/backups/.../status.

Single-node manager here (the multi-node path rides the cluster layer's
remote API the same way queries do): snapshot = flush + copy the
collection's on-disk tree through a ``BackupBackend`` module, plus a
``backup_config.json`` descriptor carrying schema + sharding so restore
can rebuild the collection without pre-existing schema.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from weaviate_tpu.modules.base import BackupBackend, ModuleError
from weaviate_tpu.schema.config import CollectionConfig

_ID_RE = re.compile(r"^[a-z0-9][a-z0-9_-]*$")

# reference: entities/backup/status.go
STARTED = "STARTED"
TRANSFERRING = "TRANSFERRING"
TRANSFERRED = "TRANSFERRED"
SUCCESS = "SUCCESS"
FAILED = "FAILED"

DESCRIPTOR = "backup_config.json"


class BackupError(Exception):
    pass


from weaviate_tpu.modules.backup_backends import walk_files as _walk_files

_ACTIVE = (STARTED, TRANSFERRING, TRANSFERRED)


class BackupManager:
    """``modules``: module Provider — backends resolve via
    ``backup_backend(name)`` (reference: module registry lookup,
    usecases/backup/handler.go). ``schema_target``: where restored
    classes are created — the Database itself (single node) or the
    ClusterNode (Raft path), same seam the REST schema routes use."""

    def __init__(self, db, modules, node_name: str = "node-0",
                 schema_target=None, node=None):
        self.db = db
        self.modules = modules
        self.node_name = node_name
        self.schema_target = schema_target or db
        # ClusterNode handle: when present and shards live on other nodes,
        # the coordinator fans the transfer out over the internal
        # transport (reference: backup coordinator over clusterapi)
        self.node = node
        self._lock = threading.Lock()
        self._backups: dict[tuple[str, str], dict] = {}
        self._restores: dict[tuple[str, str], dict] = {}

    # -- cluster fan-out helpers --------------------------------------------

    def _owner_map(self, classes: list[str]) -> dict[str, dict[str, list[str]]]:
        """node -> {class: [its shards]} (primary replica owns the copy)."""
        owners: dict[str, dict[str, list[str]]] = {}
        for cls in classes:
            col = self.db.get_collection(cls)
            for shard in col.sharding.shard_names:
                primary = col.sharding.nodes_for(shard)[0]
                owners.setdefault(primary, {}).setdefault(cls, []).append(
                    shard)
        return owners

    def _rpc(self, node: str, path: str, payload: dict) -> dict:
        from weaviate_tpu.cluster.transport import rpc

        return rpc(self.node.membership.resolve(node), path, payload,
                   timeout=600.0)

    # -- backup --------------------------------------------------------------

    def start_backup(self, backend_name: str, backup_id: str,
                     include: list[str] | None = None,
                     exclude: list[str] | None = None,
                     wait: bool = False) -> dict:
        backend = self._backend(backend_name)
        self._check_id(backup_id)
        if include and exclude:
            raise BackupError("include and exclude are mutually exclusive")
        all_classes = self.db.list_collections()
        classes = list(include) if include else \
            [c for c in all_classes if c not in set(exclude or [])]
        for c in classes:
            if c not in all_classes:
                raise BackupError(f"class {c!r} does not exist")
        if not classes:
            raise BackupError("no classes to back up")
        if self._descriptor_exists(backend, backend_name, backup_id):
            raise BackupError(
                f"backup {backup_id!r} already exists on {backend_name!r}")
        key = (backend_name, backup_id)
        status = {"id": backup_id, "backend": backend_name,
                  "status": STARTED, "error": None, "classes": classes,
                  "path": self._home(backend, backup_id)}
        with self._lock:
            if key in self._backups and \
                    self._backups[key]["status"] in _ACTIVE:
                raise BackupError(f"backup {backup_id!r} already running")
            self._backups[key] = status

        def work():
            try:
                status["status"] = TRANSFERRING
                backend.initialize(backup_id)
                descriptor = {
                    "id": backup_id,
                    "node": self.node_name,
                    "startedAt": time.time(),
                    "version": "1",
                    "classes": [],
                }
                owners = self._owner_map(classes)
                cluster = self.node is not None and (
                    set(owners) - {self.node_name})
                if cluster:
                    # fan the transfer out: every owning node streams ITS
                    # shards to the shared backend (reference: coordinator
                    # over clusterapi, coordinator.go:133)
                    from concurrent.futures import ThreadPoolExecutor

                    from weaviate_tpu.backup.cluster import (
                        backup_local_shards,
                    )

                    def one_owner(item):
                        owner, class_shards = item
                        if owner == self.node_name:
                            return owner, backup_local_shards(
                                self.db, self.modules, backend_name,
                                backup_id, class_shards)
                        reply = self._rpc(
                            owner, "/backups/shards:backup",
                            {"backend": backend_name, "id": backup_id,
                             "class_shards": class_shards})
                        return owner, reply["files"]

                    # owners transfer concurrently — wall clock is the
                    # slowest node, not the sum (reference coordinator
                    # runs participants in parallel)
                    with ThreadPoolExecutor(len(owners)) as pool:
                        files_by_node = dict(
                            pool.map(one_owner, owners.items()))
                    for cls in classes:
                        col = self.db.get_collection(cls)
                        per_node = {n: fl.get(cls, [])
                                    for n, fl in files_by_node.items()
                                    if fl.get(cls)}
                        descriptor["classes"].append({
                            "name": cls,
                            "config": col.config.to_dict(),
                            "sharding": col.sharding.to_dict(),
                            "files": [f for fl in per_node.values()
                                      for f in fl],
                            "files_by_node": per_node,
                        })
                else:
                    # single node: pause background compaction/flush cycles
                    # for a consistent file set (reference: BeginBackup
                    # pauses compaction + commit-log switching)
                    with self.db.cycles.pause():
                        self.db.flush()
                        from weaviate_tpu.backup.cluster import (
                            put_file_compressed,
                        )

                        for cls in classes:
                            col = self.db.get_collection(cls)
                            root = os.path.join(self.db.data_dir, cls)
                            files = []
                            for rel in (_walk_files(root)
                                        if os.path.isdir(root) else []):
                                # streamed + gzip'd chunk by chunk:
                                # multi-GB segment files never
                                # materialize in memory (reference:
                                # usecases/backup/zip.go)
                                stored = put_file_compressed(
                                    backend, backup_id, f"{cls}/{rel}",
                                    os.path.join(root, rel))
                                files.append(stored[len(cls) + 1:])
                            descriptor["classes"].append({
                                "name": cls,
                                "config": col.config.to_dict(),
                                "sharding": col.sharding.to_dict(),
                                "files": files,
                            })
                status["status"] = TRANSFERRED
                descriptor["completedAt"] = time.time()
                backend.put(backup_id, DESCRIPTOR,
                            json.dumps(descriptor).encode())
                status["status"] = SUCCESS
            except Exception as e:  # surfaced via status polling
                status["status"] = FAILED
                status["error"] = str(e)

        t = threading.Thread(target=work, daemon=True,
                             name=f"backup-{backup_id}")
        t.start()
        if wait:
            t.join()
        return dict(status)

    # -- restore -------------------------------------------------------------

    def start_restore(self, backend_name: str, backup_id: str,
                      include: list[str] | None = None,
                      exclude: list[str] | None = None,
                      wait: bool = False) -> dict:
        backend = self._backend(backend_name)
        self._check_id(backup_id)
        try:
            descriptor = json.loads(backend.get(backup_id, DESCRIPTOR))
        except Exception:
            raise BackupError(
                f"backup {backup_id!r} not found on {backend_name!r}")
        if include and exclude:
            raise BackupError("include and exclude are mutually exclusive")
        try:
            by_name = {c["name"]: c for c in descriptor["classes"]}
            for c in by_name.values():
                c["files"], c["config"], c["sharding"]
        except (KeyError, TypeError) as e:
            raise BackupError(
                f"backup {backup_id!r} has a malformed descriptor: {e}")
        classes = list(include) if include else \
            [n for n in by_name if n not in set(exclude or [])]
        for c in classes:
            if c not in by_name:
                raise BackupError(f"class {c!r} not in backup {backup_id!r}")
            if c in self.db.list_collections():
                raise BackupError(
                    f"class {c!r} already exists; delete it before restore "
                    "(reference behavior: restore never overwrites)")
        key = (backend_name, backup_id)
        status = {"id": backup_id, "backend": backend_name,
                  "status": STARTED, "error": None, "classes": classes,
                  "path": self._home(backend, backup_id)}
        with self._lock:
            if key in self._restores and \
                    self._restores[key]["status"] in _ACTIVE:
                raise BackupError(f"restore {backup_id!r} already running")
            self._restores[key] = status

        def work():
            try:
                status["status"] = TRANSFERRING
                from weaviate_tpu.db.sharding import ShardingState

                data_root = os.path.abspath(self.db.data_dir)
                for cls in classes:
                    entry = by_name[cls]
                    root = os.path.abspath(
                        os.path.join(self.db.data_dir, cls))
                    # the descriptor is UNTRUSTED backend content: class
                    # names and file paths must stay inside data_dir
                    if os.path.dirname(root) != data_root:
                        raise BackupError(
                            f"descriptor class name {cls!r} escapes the "
                            "data directory")
                    from weaviate_tpu.backup.cluster import (
                        restore_local_files,
                    )

                    by_node = entry.get("files_by_node")
                    if by_node and self.node is not None:
                        # cluster restore: each original owner pulls ITS
                        # shard files back before the class exists, so
                        # the Raft add_class below loads them in place
                        alive = set(
                            self.node.membership.alive_nodes())
                        missing = set(by_node) - alive
                        if missing:
                            raise BackupError(
                                f"restore of {cls!r} needs nodes "
                                f"{sorted(missing)} which are not in the "
                                "cluster (reference: topology must cover "
                                "the backup's owners)")

                        def one_owner(item):
                            owner, files = item
                            # a follower may lag on the delete_class
                            # entry: its handler refuses while the class
                            # still exists locally — retry briefly
                            last = None
                            for _ in range(60):  # 15s: schema deletes
                                # can lag under load
                                try:
                                    if owner == self.node_name:
                                        restore_local_files(
                                            self.db, self.modules,
                                            backend_name, backup_id,
                                            {cls: files})
                                    else:
                                        self._rpc(
                                            owner,
                                            "/backups/shards:restore",
                                            {"backend": backend_name,
                                             "id": backup_id,
                                             "class_files": {cls: files}})
                                    return
                                except Exception as e:
                                    last = e
                                    if "still exists" not in str(e):
                                        raise
                                    time.sleep(0.25)
                            raise BackupError(
                                f"restore on {owner!r} kept failing: "
                                f"{last}")

                        from concurrent.futures import ThreadPoolExecutor

                        with ThreadPoolExecutor(len(by_node)) as pool:
                            list(pool.map(one_owner, by_node.items()))
                    else:
                        try:
                            restore_local_files(
                                self.db, self.modules, backend_name,
                                backup_id, {cls: entry["files"]})
                        except ValueError as e:
                            raise BackupError(str(e))
                    cfg = CollectionConfig.from_dict(entry["config"])
                    state = ShardingState.from_dict(entry["sharding"])
                    # through the schema seam so cluster nodes take the
                    # Raft path and peers learn the restored class
                    self.schema_target.create_collection(
                        cfg, sharding_state=state)
                status["status"] = SUCCESS
            except Exception as e:
                status["status"] = FAILED
                status["error"] = str(e)

        t = threading.Thread(target=work, daemon=True,
                             name=f"restore-{backup_id}")
        t.start()
        if wait:
            t.join()
        return dict(status)

    # -- status --------------------------------------------------------------

    @staticmethod
    def _check_id(backup_id: str) -> None:
        if not _ID_RE.match(backup_id or ""):
            raise BackupError(f"invalid backup id {backup_id!r} (lowercase "
                              "letters, numbers, '_', '-' only)")

    @staticmethod
    def _descriptor_exists(backend, backend_name, backup_id) -> bool:
        try:
            return bool(backend.get(backup_id, DESCRIPTOR))
        except (KeyError, FileNotFoundError):
            return False
        except ModuleError as e:
            raise BackupError(str(e))
        except Exception as e:  # unreachable endpoint etc. → clean 422
            raise BackupError(
                f"backend {backend_name!r} probe failed: {e}")

    def backup_status(self, backend_name: str, backup_id: str) -> dict:
        return self._status(self._backups, backend_name, backup_id, "backup")

    def restore_status(self, backend_name: str, backup_id: str) -> dict:
        return self._status(self._restores, backend_name, backup_id,
                            "restore")

    def _status(self, table, backend_name, backup_id, kind) -> dict:
        with self._lock:
            st = table.get((backend_name, backup_id))
        if st is None:
            raise BackupError(f"no {kind} {backup_id!r} on {backend_name!r}")
        return dict(st)

    # -- helpers -------------------------------------------------------------

    def _backend(self, name: str) -> BackupBackend:
        if self.modules is None:
            raise BackupError("backups require a module provider")
        try:
            return self.modules.backup_backend(name)
        except ModuleError as e:
            raise BackupError(str(e))

    @staticmethod
    def _home(backend, backup_id) -> str:
        try:
            return backend.home_dir(backup_id)
        except Exception:
            return ""
