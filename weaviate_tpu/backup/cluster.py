"""Cluster-wide backup: per-node shard transfer handlers + helpers.

Reference: usecases/backup/coordinator.go (:133 Backup, :199 Restore) runs
a two-phase protocol over clusterapi (/backups/can-commit, /backups/commit,
serve.go:45-48); each participant's backupper pauses compaction, lists its
local shard files, and streams them to the shared module backend.

Here the coordinator (backup/__init__.py BackupManager) asks every owning
node to move ITS shards' files to/from the backend over the internal
transport; the descriptor records which node produced which files so
restore routes them back to the right owners.
"""

from __future__ import annotations

import gzip
import os
import shutil
import tempfile

from weaviate_tpu.modules.backup_backends import walk_files


def compression_level() -> int:
    """BACKUP_COMPRESSION_LEVEL: 0 = store raw, 1-9 = gzip level
    (reference: usecases/backup/zip.go compresses shard files in
    streaming fashion; default there is best-speed)."""
    raw = os.environ.get("BACKUP_COMPRESSION_LEVEL", "1")
    try:
        return max(0, min(9, int(raw)))
    except ValueError:
        return 1


def put_file_compressed(backend, backup_id: str, key: str,
                        src_path: str) -> str:
    """Stream the file into the backend, gzip'd chunk by chunk — a
    multi-GB segment never materializes in RAM. Returns the STORED key
    (``key + '.gz'`` when compressed) for the descriptor."""
    level = compression_level()
    if level == 0:
        backend.put_file(backup_id, key, src_path)
        return key
    fd, tmp_path = tempfile.mkstemp(suffix=".gz")
    os.close(fd)
    try:
        with open(src_path, "rb") as src, \
                gzip.open(tmp_path, "wb", compresslevel=level) as gz:
            shutil.copyfileobj(src, gz, 1 << 20)
        backend.put_file(backup_id, key + ".gz", tmp_path)
    finally:
        try:
            os.remove(tmp_path)
        except OSError:
            pass
    return key + ".gz"


def get_file_decompressed(backend, backup_id: str, key: str,
                          dst_path: str) -> None:
    """Fetch a stored key; '.gz' keys gunzip in streaming fashion.
    Raw keys (old backups, compression off) pass straight through."""
    if not key.endswith(".gz"):
        backend.get_file(backup_id, key, dst_path)
        return
    fd, tmp_path = tempfile.mkstemp(suffix=".gz")
    os.close(fd)
    try:
        backend.get_file(backup_id, key, tmp_path)
        os.makedirs(os.path.dirname(dst_path) or ".", exist_ok=True)
        with gzip.open(tmp_path, "rb") as gz, open(dst_path, "wb") as out:
            shutil.copyfileobj(gz, out, 1 << 20)
    finally:
        try:
            os.remove(tmp_path)
        except OSError:
            pass


def logical_name(stored_key: str) -> str:
    """Stored key -> on-disk relative path (strips the '.gz')."""
    return stored_key[:-3] if stored_key.endswith(".gz") else stored_key


def backup_local_shards(db, modules, backend_name: str, backup_id: str,
                        class_shards: dict[str, list[str]]) -> dict:
    """Stream the given local shards' files to the backend. Returns
    {cls: [relative paths from the class dir]} — the descriptor fragment
    this node contributes."""
    backend = modules.backup_backend(backend_name)
    out: dict[str, list[str]] = {}
    with db.cycles.pause():
        # flushes every LOADED shard; COLD tenant shards were flushed at
        # offload and are backed up straight from their files — loading
        # them here would defeat the offload and leave them resident
        db.flush()
        for cls, shards in class_shards.items():
            files: list[str] = []
            for shard_name in shards:
                sh_dir = os.path.join(db.data_dir, cls, shard_name)
                if not os.path.isdir(sh_dir):
                    continue  # shard never wrote anything
                for rel in walk_files(sh_dir):
                    rel_cls = os.path.join(shard_name, rel)
                    stored = put_file_compressed(
                        backend, backup_id, f"{cls}/{rel_cls}",
                        os.path.join(sh_dir, rel))
                    files.append(stored[len(cls) + 1:])
            out[cls] = files
    return out


def restore_local_files(db, modules, backend_name: str, backup_id: str,
                        class_files: dict[str, list[str]]) -> None:
    """Pull the given files from the backend into this node's data dir
    (descriptor content is UNTRUSTED: paths must stay inside the class
    directory)."""
    backend = modules.backup_backend(backend_name)
    data_root = os.path.abspath(db.data_dir)
    for cls, files in class_files.items():
        # a lagging delete_class Raft entry would rmtree the class dir
        # AFTER these files land — silent shard loss. Refuse and let the
        # coordinator retry once the delete has applied here. The check
        # MUST hold the schema lock: delete_collection holds it through
        # its rmtree, so a lock-free check can pass mid-wipe and have
        # the just-restored files deleted underneath it.
        with db._lock:
            exists = cls in db.collections
        if exists:
            raise ValueError(
                f"class {cls!r} still exists on this node (schema delete "
                "not yet applied) — retry restore shortly")
        root = os.path.abspath(os.path.join(db.data_dir, cls))
        if os.path.dirname(root) != data_root:
            raise ValueError(f"class name {cls!r} escapes the data dir")
        for rel in files:
            dst = os.path.abspath(os.path.join(root, logical_name(rel)))
            if not dst.startswith(root + os.sep):
                raise ValueError(f"file path {rel!r} escapes the class dir")
            get_file_decompressed(backend, backup_id, f"{cls}/{rel}", dst)


def register_backup_handlers(server, db, get_modules) -> None:
    """Mount the participant side on a node's internal transport
    (reference: clusterapi /backups/* routes, serve.go:45-48)."""

    def do_backup(payload: dict) -> dict:
        return {"files": backup_local_shards(
            db, get_modules(), payload["backend"], payload["id"],
            payload["class_shards"]), "node": db.local_node}

    def do_restore(payload: dict) -> dict:
        restore_local_files(db, get_modules(), payload["backend"],
                            payload["id"], payload["class_files"])
        return {"ok": True, "node": db.local_node}

    server.route("/backups/shards:backup", do_backup)
    server.route("/backups/shards:restore", do_restore)
