"""ClusterNode: one process-worth of the distributed database.

Composes (reference: configure_api.go MakeAppState wiring order):
internal HTTP server (clusterapi), gossip membership (usecases/cluster),
Raft schema store (cluster/), remote shard client (adapters/clients),
and the node-local Database. Schema writes go through Raft; object
reads/writes go point-to-point over the data plane.
"""

from __future__ import annotations

import dataclasses
import logging

from weaviate_tpu.cluster.fsm import SchemaFSM
from weaviate_tpu.cluster.membership import Membership
from weaviate_tpu.cluster.raft import RaftNode
from weaviate_tpu.cluster.remote import RemoteShardClient, register_incoming
from weaviate_tpu.cluster.transport import InternalServer
from weaviate_tpu.db.database import Database
from weaviate_tpu.db.sharding import ShardingState
from weaviate_tpu.schema.config import CollectionConfig, Property

logger = logging.getLogger(__name__)


class ClusterNode:
    def __init__(self, name: str, data_dir: str, raft_peers: list[str],
                 host: str = "127.0.0.1", port: int = 0, mesh=None,
                 gossip_interval: float = 0.3,
                 election_timeout: tuple[float, float] = (0.3, 0.6),
                 advertise: str | None = None,
                 remote_timeout: float | None = None,
                 sync_wal: bool | None = None):
        """``raft_peers``: the static bootstrap member set (node names,
        incl. this one) — reference: RAFT_JOIN env (cluster/bootstrap).
        ``advertise``: host:port other nodes reach this one at (container
        deployments bind 0.0.0.0 and advertise their service name).
        ``remote_timeout``: per-attempt ceiling for remote shard ops
        (None = REMOTE_RPC_TIMEOUT_S / 30s; always deadline-capped).
        ``sync_wal``: fsync acked data-plane writes (None =
        PERSISTENCE_WAL_SYNC; the raft bucket is pinned sync below
        either way)."""
        self.name = name
        self.server = InternalServer(host, port, advertise=advertise)
        # handlers that fan out (raft forwarding, 2PC, read repair) and
        # the faultline partition topology need to know which node a
        # thread acts as
        self.server.node_name = name
        self.membership = Membership(name, self.server,
                                     interval=gossip_interval)
        self.remote = RemoteShardClient(self.membership.resolve,
                                        timeout=remote_timeout)
        self.db = Database(data_dir, mesh=mesh, local_node=name,
                           remote=self.remote,
                           nodes_provider=self.membership.alive_nodes,
                           sync_wal=sync_wal)
        register_incoming(self.server, self.db)
        from weaviate_tpu.replication import register_replication

        register_replication(self.server, self.db)
        self.fsm = SchemaFSM(self.db)
        # pinned sync regardless of PERSISTENCE_WAL_SYNC: raft answers
        # votes/appends only after (term, votedFor, log) are durable —
        # an unsynced ack can double-vote or lose committed entries
        # across a crash (see raft.py persistence notes)
        raft_bucket = self.db._schema_store.bucket("raft", "replace",
                                                   sync_wal=True)
        self.raft = RaftNode(name, raft_peers, self.membership.resolve,
                             self.server, self.fsm.apply,
                             store_bucket=raft_bucket,
                             election_timeout=election_timeout,
                             snapshot_fn=self.fsm.snapshot,
                             restore_fn=self.fsm.restore)
        # auto tenant creation must take the Raft path in a cluster
        self.db.set_auto_tenant_hook(self.add_tenants)
        # ledger-driven placement (ROADMAP item 2): every node gossips
        # its HBM ledger total; placement + cross-node epoch migration
        # read the peers' readings from membership meta
        self.db.node_hbm_provider = self._gossiped_hbm
        self.server.start()
        self.rest = None

    @property
    def address(self) -> str:
        return self.server.address

    def start(self, seed_addrs: list[str] | None = None,
              join: str | None = None) -> None:
        """``join``: internal address of any existing cluster member —
        this node gossips in AND submits a Raft conf change to become a
        voter (reference: cluster/bootstrap/bootstrap.go:33 joiner)."""
        if seed_addrs:
            self.membership.join(seed_addrs)
        self.membership.start()
        self.raft.start()
        if join:
            self.raft.request_join(join)
        # anti-entropy beat over all replicated collections
        # (reference: shard_hashbeater launched per shard at shard load)
        self.db.cycles.register("hashbeat", self._hashbeat_cycle,
                                interval=5.0, max_interval=60.0)
        # broadcast this node's HBM ledger total (reference:
        # delegate.go piggybacks disk space on gossip the same way)
        self._publish_hbm()
        self.db.cycles.register("hbm-gossip", self._publish_hbm,
                                interval=2.0, max_interval=30.0)
        self.db.cycles.start()

    def _publish_hbm(self) -> bool:
        """Refresh the gossiped ``hbmBytes`` meta from the local HBM
        ledger. Returns True ("did work") every time: a False return
        is the cyclemanager's IDLE/backoff signal and would decay this
        heartbeat from its 2s cadence toward max_interval — placement
        would then rank nodes on up-to-30s-stale readings."""
        from weaviate_tpu.runtime.hbm_ledger import ledger

        self.membership.set_meta(hbmBytes=ledger.total_bytes())
        return True

    def _gossiped_hbm(self) -> dict:
        """node -> last gossiped HBM ledger bytes (nodes that never
        reported are absent — placement treats them as unknown)."""
        out = {}
        for name, info in self.membership.nodes().items():
            v = (info.meta or {}).get("hbmBytes")
            if isinstance(v, (int, float)):
                out[name] = int(v)
        return out

    def _hashbeat_cycle(self) -> bool:
        from weaviate_tpu.replication import HashBeater
        from weaviate_tpu.runtime import faultline

        did = False
        # the cycle thread beats AS this node (partition topology src)
        with faultline.node_scope(self.name):
            for col in list(self.db.collections.values()):
                if col.config.replication.factor > 1:
                    did = HashBeater(col).beat() or did
        return did

    def serve_rest(self, host: str = "127.0.0.1", port: int = 0,
                   modules=None, auth=None,
                   query_deadline_s: float | None = None):
        """Start the public /v1 REST API for this node (schema writes
        take the Raft path; reads/writes hit the local Database which
        scatter-gathers as needed). ``modules``/``auth`` pass through to
        the server so cluster nodes get the same vectorizer/backup/auth
        surface as standalone ones."""
        from weaviate_tpu.api.rest import RestServer

        if modules is not None:
            # participant side of cluster-wide backups (reference:
            # clusterapi /backups/* routes on the internal port)
            from weaviate_tpu.backup.cluster import register_backup_handlers

            register_backup_handlers(self.server, self.db, lambda: modules)
        self.rest = RestServer(self.db, host=host, port=port,
                               schema_target=self, node=self,
                               modules=modules, auth=auth,
                               query_deadline_s=query_deadline_s)
        self.rest.start()
        return self.rest

    def close(self) -> None:
        if self.rest is not None:
            self.rest.stop()
        self.raft.stop()
        self.membership.stop()
        self.server.stop()
        self.db.close()

    # -- schema API (through Raft; reference raft_apply_endpoints.go) --------

    def create_collection(self, config: CollectionConfig,
                          sharding_state=None):
        """``sharding_state``: a pre-computed placement (backup restore
        replays the descriptor's original placement so restored files
        match their shards)."""
        config.validate()
        # placement computed ONCE here, applied identically everywhere.
        # Ledger-driven: candidates rank by gossiped HBM headroom
        # (lightest first, stable for un-reported nodes), so new
        # collections land on the nodes with room (ROADMAP item 2).
        if sharding_state is not None:
            state = sharding_state
        elif config.multi_tenancy.enabled:
            state = ShardingState.create_partitioned()
        else:
            from weaviate_tpu.runtime.hbm_ledger import ledger

            hbm = self._gossiped_hbm()
            nodes = self.membership.alive_nodes()
            # rank only when at least one PEER has reported: right
            # after cluster formation the peers' hbmBytes meta has not
            # gossiped yet, and comparing the local live ledger against
            # unreported-as-zero peers would spuriously demote the
            # local node (same guard as Collection._placement_nodes)
            if any(n != self.name for n in hbm):
                hbm[self.name] = ledger.total_bytes()
                nodes = sorted(nodes, key=lambda n: hbm.get(n, 0))
            state = ShardingState.create(
                config.sharding.desired_count,
                nodes=nodes,
                replication_factor=config.replication.factor)
        self.raft.propose({"type": "add_class", "config": config.to_dict(),
                           "sharding": state.to_dict()})
        return self.db.get_collection(config.name)

    def delete_collection(self, name: str) -> None:
        self.raft.propose({"type": "delete_class", "name": name})

    def update_collection(self, new_cfg: CollectionConfig) -> None:
        # validate WITHOUT mutating, then replicate — the FSM applies the
        # update on every node including this one; mutating before a
        # successful propose would diverge this node from its peers
        self.db.validate_collection_update(new_cfg)
        cur = self.db.get_collection(new_cfg.name).config
        if new_cfg.replication.factor != cur.replication.factor:
            # factor changes ship shard data first (usecases/scaler) and
            # raft-commit placement+factor via "update_sharding"; by the
            # time update_class applies, the factor already matches, so
            # no node re-runs the scaler during FSM apply
            from weaviate_tpu.cluster.scaler import Scaler

            Scaler(self.db, propose=self.raft.propose).scale(
                new_cfg.name, new_cfg.replication.factor)
        self.raft.propose({"type": "update_class",
                           "config": new_cfg.to_dict()})

    def add_property(self, collection: str, prop: Property) -> None:
        self.raft.propose({"type": "add_property", "class": collection,
                           "prop": dataclasses.asdict(prop)})

    def update_tenant_status(self, collection: str,
                             tenants: list[dict]) -> None:
        # validate BEFORE proposing: a garbage op would commit to the
        # replicated log, fail on every node's apply, and re-fail on
        # every replay — while the client saw a 200
        col = self.db.get_collection(collection)
        for t in tenants:
            if t.get("name") not in col.sharding.shard_names:
                raise KeyError(f"tenant {t.get('name')!r} does not exist")
            if t.get("activityStatus", "HOT").upper() not in ("HOT",
                                                              "COLD"):
                raise ValueError("tenant activityStatus must be HOT or "
                                 "COLD")
        self.raft.propose({"type": "set_tenant_status",
                           "class": collection, "tenants": tenants})

    def add_tenants(self, collection: str, tenants: list[str]) -> None:
        col = self.db.get_collection(collection)
        nodes = self.membership.alive_nodes()
        placed = []
        for t in tenants:
            # placement decided at propose time, like shards
            probe = ShardingState.create_partitioned()
            probe.add_tenant(t, nodes=nodes,
                             replication_factor=col.config.replication.factor)
            placed.append({"name": t, "nodes": probe.placement[t]})
        self.raft.propose({"type": "add_tenants", "class": collection,
                           "tenants": placed})

    def remove_tenants(self, collection: str, tenants: list[str]) -> None:
        self.raft.propose({"type": "remove_tenants", "class": collection,
                           "tenants": tenants})

    # -- convenience ---------------------------------------------------------

    def get_collection(self, name: str):
        return self.db.get_collection(name)
