"""Gossip membership: node discovery, metadata, failure detection.

Reference: usecases/cluster/state.go (Init joins a memberlist cluster),
delegate.go (per-node metadata broadcast — disk space — and
NotifyJoin/NotifyLeave events :283-305). hashicorp/memberlist does
SWIM-style UDP gossip; here nodes push their full membership view to a
few random peers per interval over the internal HTTP port and merge
views by (incarnation, last_seen) — same eventual outcome (every node
learns every node + liveness) with much simpler machinery.
"""

from __future__ import annotations

import logging
import random
import threading
import time

from weaviate_tpu.cluster.transport import RpcError, on_peer_alive, rpc
from weaviate_tpu.runtime import faultline

logger = logging.getLogger(__name__)

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"

#: every Nth gossip tick also probes one DEAD peer. Without this a
#: partition that outlives ``dead_after`` never heals at the membership
#: layer: both sides mark each other DEAD, DEAD peers are excluded from
#: gossip targets, and with nobody left to talk to the views stay split
#: forever even though the network recovered (hashicorp/memberlist
#: solves the same problem with its dead-node gossip probability).
DEAD_PROBE_EVERY = 4


class NodeInfo:
    __slots__ = ("name", "addr", "status", "incarnation", "last_seen", "meta")

    def __init__(self, name: str, addr: str, status: str = ALIVE,
                 incarnation: int = 0, last_seen: float = 0.0,
                 meta: dict | None = None):
        self.name = name
        self.addr = addr
        self.status = status
        self.incarnation = incarnation
        self.last_seen = last_seen or time.time()
        self.meta = meta or {}

    def to_dict(self) -> dict:
        return {"name": self.name, "addr": self.addr, "status": self.status,
                "incarnation": self.incarnation, "last_seen": self.last_seen,
                "meta": self.meta}

    @classmethod
    def from_dict(cls, d: dict) -> "NodeInfo":
        return cls(d["name"], d["addr"], d.get("status", ALIVE),
                   d.get("incarnation", 0), d.get("last_seen", 0.0),
                   d.get("meta", {}))


class Membership:
    """One node's view of the cluster.

    ``server`` is an InternalServer to mount /cluster/gossip on; gossip
    rounds are driven by ``tick()`` (callers register it on a
    CycleManager) or the built-in thread via start().
    """

    def __init__(self, name: str, server, fanout: int = 3,
                 interval: float = 0.5, suspect_after: float = 2.0,
                 dead_after: float = 5.0, on_change=None):
        self.name = name
        self.server = server
        self.fanout = fanout
        self.interval = interval
        self.suspect_after = suspect_after
        self.dead_after = dead_after
        self.on_change = on_change  # fn(node_name, old_status, new_status)
        self._lock = threading.RLock()
        self_info = NodeInfo(name, server.address)
        self._nodes: dict[str, NodeInfo] = {name: self_info}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._tick_count = 0
        faultline.register_node(name, server.address)
        server.route("/cluster/gossip", self._handle_gossip)

    # -- views ---------------------------------------------------------------

    def nodes(self) -> dict[str, NodeInfo]:
        with self._lock:
            return dict(self._nodes)

    def alive_nodes(self) -> list[str]:
        with self._lock:
            return sorted(n.name for n in self._nodes.values()
                          if n.status == ALIVE)

    def all_names(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    def addr_of(self, name: str) -> str | None:
        with self._lock:
            info = self._nodes.get(name)
            return info.addr if info is not None else None

    def resolve(self, name: str) -> str:
        addr = self.addr_of(name)
        if addr is None:
            raise KeyError(f"unknown node {name!r}")
        return addr

    def set_meta(self, **meta) -> None:
        """Update this node's broadcast metadata (reference: delegate.go
        NodeMeta carries disk usage)."""
        with self._lock:
            me = self._nodes[self.name]
            me.meta.update(meta)
            me.incarnation += 1

    # -- lifecycle -----------------------------------------------------------

    def join(self, seed_addrs: list[str]) -> int:
        """Push our view to seeds and adopt theirs (state.go:61 Init)."""
        joined = 0
        with faultline.node_scope(self.name):
            for addr in seed_addrs:
                if addr == self.server.address:
                    continue
                try:
                    view = rpc(addr, "/cluster/gossip",
                               {"nodes": self._view()})
                    self._merge(view.get("nodes", []))
                    joined += 1
                except RpcError as e:
                    logger.warning("join via %s failed: %s", addr, e)
        return joined

    def start(self) -> None:
        # under _lock: two concurrent start()s would otherwise both see
        # _thread is None and run two gossip loops for one node
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name=f"gossip-{self.name}")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # read the handle under _lock, join OUTSIDE it — the gossip loop
        # takes _lock on every tick and could never exit otherwise. Keep
        # the handle if the join times out, so a later start() cannot
        # clear _stop under a still-live loop and double it.
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(2.0)
            if not t.is_alive():
                with self._lock:
                    if self._thread is t:
                        self._thread = None

    def _loop(self) -> None:
        faultline.bind_node(self.name)  # this thread gossips AS us
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:
                logger.exception("gossip tick failed")

    # -- gossip mechanics ----------------------------------------------------

    def _view(self) -> list[dict]:
        with self._lock:
            me = self._nodes[self.name]
            me.last_seen = time.time()
            me.status = ALIVE
            return [n.to_dict() for n in self._nodes.values()]

    def tick(self) -> bool:
        """One gossip round: push view to ``fanout`` random peers, merge
        what they answer; then sweep liveness. Every
        ``DEAD_PROBE_EVERY``-th round additionally probes one DEAD peer
        (round-robin) — the heal path for partitions that outlived
        ``dead_after``, after which both sides would otherwise have
        nobody left willing to gossip to the other."""
        with self._lock:
            peers = [n for n in self._nodes.values()
                     if n.name != self.name and n.status != DEAD]
            dead = sorted((n for n in self._nodes.values()
                           if n.name != self.name and n.status == DEAD),
                          key=lambda n: n.name)
            self._tick_count += 1
            tick = self._tick_count
        targets = [(p, 2.0) for p in
                   random.sample(peers, min(self.fanout, len(peers)))]
        if dead and tick % DEAD_PROBE_EVERY == 0:
            # short timeout: a black-holed dead peer must not stall the
            # single gossip thread (and the liveness sweep behind it)
            # for the full 2s ceiling every probe round — the probe only
            # needs to catch a peer that is actually back
            targets.append(
                (dead[(tick // DEAD_PROBE_EVERY) % len(dead)],
                 min(2.0, max(0.25, self.interval * 2))))
        with faultline.node_scope(self.name):
            for peer, timeout in targets:
                try:
                    reply = rpc(peer.addr, "/cluster/gossip",
                                {"nodes": self._view()}, timeout=timeout)
                    self._merge(reply.get("nodes", []))
                    self._touch(peer.name)
                except RpcError:
                    pass  # liveness sweep handles persistent failures
        self._sweep()
        return True

    def _handle_gossip(self, payload: dict) -> dict:
        self._merge(payload.get("nodes", []))
        return {"nodes": self._view()}

    def _touch(self, name: str) -> None:
        addr = None
        with self._lock:
            info = self._nodes.get(name)
            if info is not None:
                info.last_seen = time.time()
                self._set_status(info, ALIVE)
                addr = info.addr
        # DIRECT round-trip proof the peer (and therefore its shared
        # data-plane port) is reachable from HERE: release any open
        # circuit breaker for an immediate half-open probe. Only _touch
        # gets this — a relayed third-party view in _merge proves
        # nothing about OUR link under an asymmetric partition.
        if addr is not None:
            on_peer_alive(addr)

    def _merge(self, remote_nodes: list[dict]) -> None:
        for d in remote_nodes:
            info = NodeInfo.from_dict(d)
            if info.name == self.name:
                continue
            faultline.register_node(info.name, info.addr)
            with self._lock:
                mine = self._nodes.get(info.name)
                if mine is None:
                    self._nodes[info.name] = info
                    self._notify(info.name, None, info.status)
                elif (info.incarnation, info.last_seen) > (mine.incarnation,
                                                           mine.last_seen):
                    mine.addr = info.addr
                    mine.incarnation = info.incarnation
                    mine.last_seen = info.last_seen
                    mine.meta = info.meta
                    self._set_status(mine, info.status)

    def _sweep(self) -> None:
        now = time.time()
        with self._lock:
            for info in self._nodes.values():
                if info.name == self.name:
                    continue
                age = now - info.last_seen
                if age > self.dead_after:
                    self._set_status(info, DEAD)
                elif age > self.suspect_after and info.status == ALIVE:
                    self._set_status(info, SUSPECT)

    def _set_status(self, info: NodeInfo, status: str) -> None:
        if info.status != status:
            old = info.status
            info.status = status
            self._notify(info.name, old, status)

    def _notify(self, name: str, old, new) -> None:
        logger.info("membership %s: %s %s -> %s", self.name, name, old, new)
        if self.on_change is not None:
            try:
                self.on_change(name, old, new)
            except Exception:
                logger.exception("membership on_change callback failed")
