"""Schema FSM: committed Raft ops applied to the node-local Database.

Reference: cluster/store_apply.go:71,133-160 — the op set
(ADD_CLASS, UPDATE_CLASS, DELETE_CLASS, ADD_PROPERTY, ADD_TENANT,
DELETE_TENANT, ...) applied on EVERY node; the executor then creates the
local shards (usecases/schema/executor.go). Ops are idempotent so log
replay after restart converges.
"""

from __future__ import annotations

import logging

from weaviate_tpu.db.sharding import ShardingState
from weaviate_tpu.schema.config import CollectionConfig, Property

logger = logging.getLogger(__name__)


class SchemaFSM:
    def __init__(self, db):
        self.db = db

    def apply(self, op: dict) -> None:
        t = op["type"]
        if t == "add_class":
            cfg = CollectionConfig.from_dict(op["config"])
            state = ShardingState.from_dict(op["sharding"])
            if cfg.name in self.db.collections:
                return  # replay idempotence
            self.db.create_collection(cfg, sharding_state=state)
        elif t == "delete_class":
            self.db.delete_collection(op["name"])
        elif t == "add_property":
            p = dict(op["prop"])
            nested = p.get("nested")
            p["nested"] = [Property(**n) for n in nested] if nested else None
            try:
                self.db.add_property(op["class"], Property(**p))
            except ValueError:
                pass  # duplicate on replay
        elif t == "update_class":
            cfg = CollectionConfig.from_dict(op["config"])
            try:
                # merge only the mutable surface + push runtime knobs into
                # live objects (NOT a wholesale overwrite: the proposed
                # config may carry defaults for fields the proposer's
                # client omitted)
                # allow_scale=False: a stale factor in a concurrent
                # update_class must not trigger per-node scaler runs inside
                # FSM apply — factor only changes via "update_sharding"
                self.db.update_collection(cfg, allow_scale=False)
            except (KeyError, ValueError) as e:
                # replay tolerance: class deleted later in the log etc.
                logger.warning("update_class %s skipped: %s", cfg.name, e)
        elif t == "add_tenants":
            col = self.db.get_collection(op["class"])
            for tenant in op["tenants"]:
                if tenant["name"] not in col.sharding.shard_names:
                    col.add_tenant(tenant["name"], nodes=tenant.get("nodes"))
            self.db._persist(col)
        elif t == "remove_tenants":
            col = self.db.get_collection(op["class"])
            for name in op["tenants"]:
                col.remove_tenant(name)
            self.db._persist(col)
        elif t == "set_tenant_status":
            try:
                self.db.update_tenant_status(op["class"], op["tenants"])
            except (KeyError, ValueError) as e:
                # replay tolerance (tenant removed later in the log)
                logger.warning("set_tenant_status skipped: %s", e)
        elif t == "update_sharding":
            # replica scale-out/in (usecases/scaler): every node applies
            # the same placement + factor; nodes that just became owners
            # load their (already-copied) shards
            col = self.db.get_collection(op["class"])
            col.sharding.placement = {k: list(v)
                                      for k, v in op["placement"].items()}
            col.config.replication.factor = op["factor"]
            for shard in col.sharding.shard_names:
                if self.db.local_node in col.sharding.nodes_for(shard) \
                        and shard not in col.shards:
                    col._load_shard(shard)
            self.db._persist(col)
        else:
            logger.warning("unknown FSM op type %r", t)

    # -- snapshot / restore (reference: cluster/store_snapshot.go -----------
    # Persist()/Restore() marshal the schema FSM state; ours is the full
    # class set + sharding placements + tenant statuses)

    def snapshot(self) -> dict:
        classes = []
        for name, col in self.db.collections.items():
            classes.append({
                "config": col.config.to_dict(),
                "sharding": col.sharding.to_dict(),
            })
        return {"classes": classes}

    def restore(self, state: dict) -> None:
        """Make the local DB MATCH the snapshot's schema: create missing
        classes, drop classes the snapshot no longer has (their delete op
        was compacted away), and overwrite config/placement of existing
        ones. Log entries after the snapshot index replay on top, so
        converging to the snapshot state exactly is what keeps a
        caught-up-via-InstallSnapshot follower consistent."""
        entries = {CollectionConfig.from_dict(e["config"]).name: e
                   for e in state.get("classes", [])}
        for name in list(self.db.collections):
            if name not in entries:
                try:
                    self.db.delete_collection(name)
                except KeyError:
                    pass
        for name, entry in entries.items():
            cfg = CollectionConfig.from_dict(entry["config"])
            sharding = ShardingState.from_dict(entry["sharding"])
            if name not in self.db.collections:
                self.db.create_collection(cfg, sharding_state=sharding)
                continue
            col = self.db.collections[name]
            try:
                self.db.update_collection(cfg, allow_scale=False)
            except (KeyError, ValueError) as e:
                logger.warning("snapshot restore: update of %s skipped: %s",
                               name, e)
            # placement + tenant statuses follow the snapshot (the same
            # surface update_sharding owns)
            col.sharding.placement = dict(sharding.placement)
            col.sharding.tenant_status = dict(sharding.tenant_status)
            for shard in col.sharding.shard_names:
                if self.db.local_node in col.sharding.nodes_for(shard) \
                        and shard not in col.shards \
                        and col.sharding.status_of(shard) not in (
                            "COLD", "FROZEN"):
                    col._load_shard(shard)
            self.db._persist(col)
