"""Cluster layer: membership, schema consensus, remote shard data plane.

Reference: usecases/cluster/ (memberlist gossip), cluster/ (raft schema
store), adapters/handlers/rest/clusterapi/ + adapters/clients/ (internal
HTTP data plane), usecases/sharding (remote index).
"""

from weaviate_tpu.cluster.membership import Membership, NodeInfo
from weaviate_tpu.cluster.node import ClusterNode
from weaviate_tpu.cluster.raft import RaftNode
from weaviate_tpu.cluster.remote import RemoteShardClient, register_incoming
from weaviate_tpu.cluster.transport import InternalServer, rpc

__all__ = [
    "Membership",
    "NodeInfo",
    "ClusterNode",
    "RaftNode",
    "RemoteShardClient",
    "register_incoming",
    "InternalServer",
    "rpc",
]
