"""Raft consensus for cluster metadata (schema, tenants).

Reference: cluster/store.go (hashicorp/raft + boltdb log store),
store_apply.go (FSM ops ADD_CLASS...DELETE_TENANT), raft.go:26 (leader
forwarding from followers), store_snapshot.go (FSM snapshot persist/
restore), cluster/bootstrap/bootstrap.go:33 (joining an existing
cluster). Scope parity: only schema/tenant METADATA goes through Raft —
object data takes the replication data plane.

This is a compact Raft: leader election with randomized timeouts,
AppendEntries log replication with the log-matching backtrack, majority
commit, persisted (term, votedFor, log). Three §7/§6 features beyond the
round-1 core:

- **Snapshots + log compaction**: once the applied log grows past
  ``snapshot_threshold`` entries, the FSM state (``snapshot_fn``) is
  persisted and the covered log prefix dropped — restart restores from
  the snapshot instead of replaying every schema op ever
  (reference store_snapshot.go). Log indices are ABSOLUTE; the in-RAM
  list holds [log_start, ...).
- **InstallSnapshot RPC**: a follower whose next entry was compacted
  away receives the snapshot + trailing log instead of an append.
- **Dynamic membership**: ``raft_conf`` add/remove entries flow through
  the log itself; each node recomputes its peer set from
  (snapshot peers + conf entries in the log) so the set is consistent
  with whatever log prefix a node has (single-server changes, Raft §6).
  A new node calls ``request_join`` against any member (reference
  bootstrap joiner) and suppresses elections until a leader contacts it.
"""

from __future__ import annotations

import logging
import random
import threading
import time

from weaviate_tpu.cluster.transport import RpcError, rpc
from weaviate_tpu.runtime import faultline

logger = logging.getLogger(__name__)

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeaderError(RuntimeError):
    def __init__(self, leader: str | None):
        super().__init__(f"not the leader (leader={leader})")
        self.leader = leader


class RaftNode:
    def __init__(self, name: str, peers: list[str], resolver, server,
                 apply_fn, store_bucket=None,
                 election_timeout: tuple[float, float] = (0.3, 0.6),
                 heartbeat_interval: float = 0.08,
                 snapshot_fn=None, restore_fn=None,
                 snapshot_threshold: int = 256,
                 step_down_timeout: float | None = None):
        """``peers``: bootstrap member names incl. self (later changed via
        conf entries). ``resolver(name) -> addr``. ``apply_fn(op)``
        applies a committed entry to the FSM. ``snapshot_fn() -> dict`` /
        ``restore_fn(state)`` serialize/install FSM state for compaction
        and joiner catch-up. ``store_bucket``: KV bucket for persistence.
        ``step_down_timeout``: a leader that has heard no reply from a
        majority for this long abdicates (default 4x the upper election
        timeout) — without it, a one-way-partitioned leader that can
        SEND but not RECEIVE keeps heartbeating followers forever, no
        election ever fires, and the cluster wedges unavailable."""
        self.name = name
        self.bootstrap_peers = sorted(set(peers) | {name})
        self.peers = list(self.bootstrap_peers)
        self.resolver = resolver
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.snapshot_threshold = snapshot_threshold
        self._bucket = store_bucket
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.step_down_timeout = (4 * election_timeout[1]
                                  if step_down_timeout is None
                                  else step_down_timeout)

        self._lock = threading.RLock()
        self._applied_cv = threading.Condition(self._lock)
        self.role = FOLLOWER
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[dict] = []  # {"term": int, "op": dict}; log[0] is
        self.log_start = 0  # ...absolute index ``log_start``
        self.snap_last_term = 0  # term of entry log_start-1 (snapshot tail)
        self.commit_index = -1
        self.last_applied = -1
        self.leader_id: str | None = None
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        # last reply (ANY reply — an unsuccessful append still proves
        # connectivity) received from each peer while leading, and the
        # last time a leader's RPC reached US while following — the
        # inputs to step-down and vote stickiness respectively
        self._peer_contact: dict[str, float] = {}
        self._last_leader_contact = 0.0
        self._deadline = self._new_deadline()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        self._restore()
        server.route("/raft/vote", self._handle_vote)
        server.route("/raft/append", self._handle_append)
        server.route("/raft/propose", self._handle_propose)
        server.route("/raft/snapshot", self._handle_install_snapshot)
        server.route("/raft/join", self._handle_join)
        server.route("/raft/leave", self._handle_leave)

    # -- absolute-index helpers ----------------------------------------------

    def _abs_last(self) -> int:
        return self.log_start + len(self.log) - 1

    def _entry(self, i: int) -> dict:
        return self.log[i - self.log_start]

    def _term_at(self, i: int) -> int:
        """Term of absolute index i; the snapshot remembers its tail term."""
        if i < self.log_start - 1:
            return -1  # compacted away (only valid to ask about the tail)
        if i == self.log_start - 1:
            return self.snap_last_term
        return self._entry(i)["term"]

    # -- persistence ---------------------------------------------------------
    #
    # Raft's safety argument requires (term, votedFor, log) to hit DISK
    # before the RPC that exposed them is answered — a node that votes,
    # crashes, and forgets it voted can grant a second vote in the same
    # term (two leaders); a follower that acks an append and loses the
    # entries lets the leader count a majority that doesn't exist. The
    # raft bucket is therefore pinned ``sync_wal=True`` at construction
    # (cluster/node.py) regardless of PERSISTENCE_WAL_SYNC, and every
    # persist below batches its records into ONE WAL frame — one fsync
    # per RPC response, not one per record (the hashicorp/raft+boltdb
    # reference gets the same through one bolt transaction per persist).

    def _meta_pair(self) -> tuple[bytes, dict]:
        return (b"meta", {"term": self.current_term,
                          "voted_for": self.voted_for})

    def _span_pair(self) -> tuple[bytes, dict]:
        return (b"log_span", {"start": self.log_start,
                              "len": len(self.log),
                              "snap_last_term": self.snap_last_term})

    def _persist_meta(self) -> None:
        if self._bucket is not None:
            faultline.fire("raft.persist.meta", term=self.current_term)
            self._bucket.put(*self._meta_pair())

    def _persist_log(self, start_abs: int | None = None,
                     extra_pairs=None) -> None:
        """Persist entries >= start_abs, the span, AND the meta in one
        synced frame — callers answer their RPC right after, so this is
        the per-response fsync. ``extra_pairs`` ride the SAME frame: the
        snapshot-taking paths pass the snapshot record here so a crash
        can never land between the snapshot and the span that must
        agree with it."""
        if self._bucket is None:
            return
        start_abs = self.log_start if start_abs is None else start_abs
        faultline.fire("raft.persist.log", start=start_abs)
        pairs: list[tuple[bytes, object]] = list(extra_pairs or [])
        pairs.extend(
            (f"log-{i:012d}".encode(), self._entry(i))
            for i in range(max(start_abs, self.log_start),
                           self.log_start + len(self.log)))
        pairs.append(self._span_pair())
        pairs.append(self._meta_pair())
        self._bucket.put_many(pairs)

    def _snapshot_pair(self, state: dict, last_index: int,
                       last_term: int, peers: list[str]
                       ) -> tuple[bytes, dict]:
        faultline.fire("raft.persist.snapshot", last_index=last_index)
        return (b"snapshot", {"state": state,
                              "last_index": last_index,
                              "last_term": last_term,
                              "peers": peers})

    def _truncate_log_from(self, abs_i: int, persist: bool = True) -> None:
        """Drop entries >= abs_i (conflict truncation).

        ``persist=False`` is for the append-conflict path whose very
        next statement is a full ``_persist_log`` — the span in that
        batched frame supersedes this one, so writing it here too
        would pay a second fsync per conflicting AppendEntries."""
        del self.log[abs_i - self.log_start:]
        if persist and self._bucket is not None:
            faultline.fire("raft.persist.log", start=abs_i)
            self._bucket.put(*self._span_pair())
        self._recompute_peers()

    def _restore(self) -> None:
        if self._bucket is None:
            return
        meta = self._bucket.get(b"meta")
        if meta:
            self.current_term = meta["term"]
            self.voted_for = meta.get("voted_for")
        snap = self._bucket.get(b"snapshot")
        snap_peers = None
        if snap:
            self.log_start = snap["last_index"] + 1
            self.snap_last_term = snap["last_term"]
            self.commit_index = snap["last_index"]
            self.last_applied = snap["last_index"]
            snap_peers = list(snap.get("peers") or [])
            if self.restore_fn is not None:
                try:
                    self.restore_fn(snap["state"])
                except Exception:
                    logger.exception("raft %s: snapshot restore failed",
                                     self.name)
        span = self._bucket.get(b"log_span")
        if span:
            snap_start = self.log_start  # boundary the snapshot set
            start, n = span["start"], span["len"]
            # tolerate a snapshot taken after the last log persist
            start = max(start, snap_start)
            self.log = [self._bucket.get(f"log-{i:012d}".encode())
                        for i in range(start, span["start"] + n)]
            self.log_start = start
            if span["start"] >= snap_start:
                self.snap_last_term = span.get("snap_last_term",
                                               self.snap_last_term)
            # else: the span predates the snapshot (a crash between the
            # two persist frames of the pre-batching format) — its tail
            # term describes an OLDER boundary; adopting it would make
            # _last_log() under-report this node's last term and let it
            # grant votes to candidates with older logs (Raft §5.4.1).
            # The snapshot's own last_term stands.
        else:
            n = self._bucket.get(b"log_len") or 0  # round-1 format
            self.log = [self._bucket.get(f"log-{i:012d}".encode())
                        for i in range(n)]
            self.log_start = 0
        if snap_peers is not None:
            self.bootstrap_peers = sorted(set(snap_peers) | {self.name})
        self._recompute_peers()

    # -- membership ----------------------------------------------------------

    def _recompute_peers(self) -> None:
        """Peer set = snapshot/bootstrap peers + conf entries in the log.
        Deterministic in the log prefix, so truncation reverts cleanly and
        conf changes take effect at APPEND time (Raft §6). Caller holds
        ``_lock`` (or runs during single-threaded restore)."""
        peers = set(self.bootstrap_peers)
        for e in self.log:
            op = e.get("op") or {}
            if op.get("type") == "raft_conf":
                if op.get("add"):
                    peers.add(op["add"])
                if op.get("remove"):
                    peers.discard(op["remove"])
        self.peers = sorted(peers | {self.name})
        self._next_index = {p: self._next_index.get(p, self._abs_last() + 1)
                            for p in self.peers if p != self.name}
        self._match_index = {p: self._match_index.get(p, -1)
                             for p in self.peers if p != self.name}

    def request_join(self, member_addr: str, timeout: float = 15.0) -> None:
        """Join a running cluster through any member (reference
        cluster/bootstrap/bootstrap.go:33). Blocks until the conf entry
        commits and this node has been contacted by the leader."""
        with self._lock:
            # don't elect ourselves while joining a real cluster
            self._deadline = time.monotonic() + timeout
        deadline = time.time() + timeout
        last: Exception | None = None
        while time.time() < deadline:
            try:
                with faultline.node_scope(self.name):
                    reply = rpc(member_addr, "/raft/join",
                                {"name": self.name},
                                timeout=min(5.0, deadline - time.time()))
                with self._lock:
                    # learn the existing membership from the reply — the
                    # original members predate any conf entry in the log
                    self.bootstrap_peers = sorted(
                        set(reply.get("peers") or []) | {self.name})
                    self._recompute_peers()
                    self._deadline = time.monotonic() + 5.0
                # wait until the leader's appends reach us
                while time.time() < deadline:
                    with self._lock:
                        if self.leader_id is not None and \
                                self.name in self.peers:
                            return
                    time.sleep(0.05)
            except (RpcError, KeyError) as e:
                last = e
                time.sleep(0.2)
        raise TimeoutError(f"raft join via {member_addr} timed out: {last}")

    def _handle_join(self, payload: dict) -> dict:
        """Any member accepts a join request; non-leaders forward."""
        name = payload["name"]
        with self._lock:
            role, leader = self.role, self.leader_id
            already = name in self.peers
            peers = list(self.peers)
        if already:
            return {"ok": True, "peers": peers}
        if role != LEADER:
            if leader is None or leader == self.name:
                raise NotLeaderError(leader)
            return rpc(self.resolver(leader), "/raft/join", payload,
                       timeout=5.0)
        self.propose_local({"type": "raft_conf", "add": name})
        with self._lock:
            peers = list(self.peers)
        return {"ok": True, "peers": peers}

    def _handle_leave(self, payload: dict) -> dict:
        name = payload["name"]
        with self._lock:
            role, leader = self.role, self.leader_id
            present = name in self.peers
        if not present:
            return {"ok": True}
        if role != LEADER:
            if leader is None or leader == self.name:
                raise NotLeaderError(leader)
            return rpc(self.resolver(leader), "/raft/leave", payload,
                       timeout=5.0)
        self.propose_local({"type": "raft_conf", "remove": name})
        return {"ok": True}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        # under _lock: two concurrent start()s would otherwise both see
        # _thread is None and spawn two raft loops against one log
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name=f"raft-{self.name}")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        # read the handle under _lock but join OUTSIDE it — the loop
        # thread takes _lock every tick and could never exit otherwise.
        # On a timed-out join KEEP the handle: dropping it would let a
        # later start() clear _stop (un-stopping the live loop) and
        # spawn a second one against the same log.
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(2.0)
            if not t.is_alive():
                with self._lock:
                    if self._thread is t:
                        self._thread = None

    def _new_deadline(self) -> float:
        return time.monotonic() + random.uniform(*self.election_timeout)

    def _loop(self) -> None:
        faultline.bind_node(self.name)  # this thread campaigns AS us
        while not self._stop.wait(0.01):
            try:
                with self._lock:
                    role = self.role
                if role == LEADER:
                    self._replicate_all()
                    time.sleep(self.heartbeat_interval)
                elif time.monotonic() >= self._deadline:
                    self._run_election()
            except Exception:
                logger.exception("raft %s loop error", self.name)

    # -- election ------------------------------------------------------------

    def _last_log(self) -> tuple[int, int]:
        last = self._abs_last()
        return (last, self._term_at(last) if last >= 0 else 0)

    def _run_election(self) -> None:
        with self._lock:
            self.role = CANDIDATE
            self.current_term += 1
            self.voted_for = self.name
            self.leader_id = None
            term = self.current_term
            last_index, last_term = self._last_log()
            peers = list(self.peers)
            self._persist_meta()
            self._deadline = self._new_deadline()
        votes = 1
        for peer in peers:
            if peer == self.name:
                continue
            try:
                with faultline.node_scope(self.name):
                    reply = rpc(self.resolver(peer), "/raft/vote",
                                {"term": term, "candidate": self.name,
                                 "last_log_index": last_index,
                                 "last_log_term": last_term}, timeout=1.0)
            except (RpcError, KeyError):
                continue
            with self._lock:
                if reply["term"] > self.current_term:
                    self._become_follower(reply["term"])
                    return
                if reply.get("granted") and self.role == CANDIDATE \
                        and self.current_term == term:
                    votes += 1
        with self._lock:
            if self.role == CANDIDATE and self.current_term == term \
                    and votes > len(peers) // 2:
                self._become_leader()

    def _become_leader(self) -> None:
        """Caller holds ``_lock`` (vote-count section of the election)."""
        logger.info("raft %s: leader for term %d", self.name, self.current_term)
        self.role = LEADER
        self.leader_id = self.name
        n = self._abs_last() + 1
        now = time.monotonic()
        self._next_index = {p: n for p in self.peers if p != self.name}
        self._match_index = {p: -1 for p in self.peers if p != self.name}
        # fresh lease: every peer counts as heard-from at election time
        # (they just voted) so the quorum-contact check gets a full
        # step_down_timeout grace window before it can fire
        self._peer_contact = {p: now for p in self.peers if p != self.name}
        self._reanchor_warned: set[str] = set()
        # no-op barrier entry so the new leader can commit prior-term
        # entries (Raft §5.4.2)
        self.log.append({"term": self.current_term, "op": {"type": "noop"}})
        self._persist_log(n)

    def _become_follower(self, term: int) -> None:
        """Caller holds ``_lock`` (every RPC reply / handler section)."""
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_meta()
        self.role = FOLLOWER
        self._deadline = self._new_deadline()

    # -- replication (leader side) -------------------------------------------

    def _replicate_all(self) -> None:
        with self._lock:
            peers = list(self.peers)
        with faultline.node_scope(self.name):
            for peer in peers:
                if peer != self.name:
                    self._replicate_one(peer)
        self._check_quorum_contact()
        self._advance_commit()
        self._maybe_snapshot()

    def _recent_quorum_contact(self, window: float) -> bool:
        """Did a majority (incl. self) answer within ``window``?
        Caller holds ``_lock``."""
        now = time.monotonic()
        heard = 1 + sum(
            1 for p in self.peers if p != self.name
            and now - self._peer_contact.get(p, 0.0) <= window)
        return heard > len(self.peers) // 2

    def _check_quorum_contact(self) -> None:
        """Leader lease check: step down when no majority has answered
        within ``step_down_timeout``. The one-way partition this exists
        for: a leader that can SEND but not RECEIVE keeps resetting its
        followers' election deadlines with heartbeats whose acks all
        vanish — nobody ever campaigns, nothing ever commits. Abdicating
        stops the heartbeats so the reachable majority elects a leader
        that can actually hear acks. Same term kept: this is a lease
        expiry, not a new election."""
        with self._lock:
            if self.role != LEADER or len(self.peers) <= 1:
                return
            if self._recent_quorum_contact(self.step_down_timeout):
                return
            logger.warning(
                "raft %s: no majority contact in the last %.1fs — "
                "stepping down (term %d kept)", self.name,
                self.step_down_timeout, self.current_term)
            self.role = FOLLOWER
            self.leader_id = None
            self._deadline = self._new_deadline()

    def _replicate_one(self, peer: str) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            next_i = self._next_index.get(peer, self._abs_last() + 1)
            if next_i < self.log_start:
                # the entries this follower needs were compacted away —
                # ship the snapshot instead (InstallSnapshot, Raft §7)
                snap = (self._bucket.get(b"snapshot")
                        if self._bucket is not None else None)
                if snap is None and self.snapshot_fn is not None:
                    snap = {"state": self.snapshot_fn(),
                            "last_index": self.log_start - 1,
                            "last_term": self.snap_last_term,
                            "peers": list(self.peers)}
                if snap is None:
                    # No persisted snapshot and no snapshot_fn: an incomplete
                    # payload would KeyError on the follower and retry
                    # forever. Re-anchor the peer at log_start and serve what
                    # log remains; warn once per peer — a follower that truly
                    # needs the compacted prefix cannot catch up in this
                    # state and an operator has to intervene.
                    if peer not in self._reanchor_warned:
                        self._reanchor_warned.add(peer)
                        logger.warning(
                            "raft %s: follower %s needs compacted entries "
                            "(< %d) but no snapshot source exists; "
                            "re-anchoring at log_start — it may never "
                            "catch up", self.name, peer, self.log_start)
                    self._next_index[peer] = self.log_start
                    return
                payload = dict(snap, term=term, leader=self.name)
            else:
                payload = None
                prev_i = next_i - 1
                prev_t = self._term_at(prev_i) if prev_i >= 0 else 0
                entries = self.log[next_i - self.log_start:]
                commit = self.commit_index
        try:
            if payload is not None:
                reply = rpc(self.resolver(peer), "/raft/snapshot", payload,
                            timeout=5.0)
                with self._lock:
                    self._peer_contact[peer] = time.monotonic()
                    if reply["term"] > self.current_term:
                        self._become_follower(reply["term"])
                        return
                    self._match_index[peer] = payload["last_index"]
                    self._next_index[peer] = payload["last_index"] + 1
                return
            reply = rpc(self.resolver(peer), "/raft/append",
                        {"term": term, "leader": self.name,
                         "prev_index": prev_i, "prev_term": prev_t,
                         "entries": entries, "leader_commit": commit},
                        timeout=1.0)
        except (RpcError, KeyError):
            return
        with self._lock:
            self._peer_contact[peer] = time.monotonic()
            if reply["term"] > self.current_term:
                self._become_follower(reply["term"])
                return
            if self.role != LEADER or self.current_term != term:
                return
            if reply.get("success"):
                self._match_index[peer] = prev_i + len(entries)
                self._next_index[peer] = self._match_index[peer] + 1
            else:
                # log-matching backtrack
                self._next_index[peer] = max(self.log_start - 1, next_i - 1)

    def _advance_commit(self) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            for n in range(self._abs_last(), self.commit_index, -1):
                if self._term_at(n) != self.current_term:
                    break  # only current-term entries commit by counting
                replicas = 1 + sum(1 for m in self._match_index.values()
                                   if m >= n)
                if replicas > len(self.peers) // 2:
                    self.commit_index = n
                    break
            self._apply_committed()

    def _apply_committed(self) -> None:
        # caller holds the lock
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self._entry(self.last_applied)
            op_type = entry["op"].get("type")
            if op_type in ("noop", "raft_conf"):
                continue  # conf changes applied at append time
            try:
                self.apply_fn(entry["op"])
            except Exception:
                logger.exception("raft %s: FSM apply failed at %d",
                                 self.name, self.last_applied)
        self._applied_cv.notify_all()

    # -- snapshot / compaction -----------------------------------------------

    def _maybe_snapshot(self) -> None:
        """Compact the applied log prefix into an FSM snapshot
        (reference: store_snapshot.go + raft's SnapshotThreshold)."""
        if self.snapshot_fn is None:
            return
        with self._lock:
            applied_in_log = self.last_applied - self.log_start + 1
            if applied_in_log < self.snapshot_threshold:
                return
            self.take_snapshot()

    def take_snapshot(self) -> int:
        """Snapshot now; returns the covered last index."""
        with self._lock:
            if self.last_applied < self.log_start:
                return self.log_start - 1
            state = self.snapshot_fn() if self.snapshot_fn else {}
            last = self.last_applied
            last_term = self._term_at(last)
            snap_pair = self._snapshot_pair(state, last, last_term,
                                            list(self.peers))
            # bootstrap_peers absorbs conf entries covered by the snapshot
            # so _recompute_peers stays correct over the shorter log
            self.bootstrap_peers = list(self.peers)
            drop = last - self.log_start + 1
            del self.log[:drop]
            self.log_start = last + 1
            self.snap_last_term = last_term
            # snapshot + span + meta land in ONE synced frame — a crash
            # can never leave a snapshot whose span disagrees with it
            self._persist_log(extra_pairs=[snap_pair])
            if self._bucket is not None:
                # drop compacted entry records — one batched tombstone
                # frame, after the snapshot + span are durable (a crash
                # in between replays consistently: span bounds the read)
                self._bucket.delete_many(
                    f"log-{i:012d}".encode()
                    for i in range(self.log_start - drop, self.log_start))
            logger.info("raft %s: snapshot through index %d (log now %d "
                        "entries)", self.name, last, len(self.log))
            return last

    def _handle_install_snapshot(self, payload: dict) -> dict:
        with self._lock:
            term = payload["term"]
            if term < self.current_term:
                return {"term": self.current_term}
            if term > self.current_term or self.role != FOLLOWER:
                self._become_follower(term)
            self.leader_id = payload["leader"]
            self._last_leader_contact = time.monotonic()
            self._deadline = self._new_deadline()
            last = payload["last_index"]
            if last <= self.last_applied:
                return {"term": self.current_term}
            if self.restore_fn is not None:
                try:
                    self.restore_fn(payload["state"])
                except Exception:
                    logger.exception("raft %s: snapshot install failed",
                                     self.name)
            self.log = []
            self.log_start = last + 1
            self.snap_last_term = payload["last_term"]
            self.commit_index = last
            self.last_applied = last
            self.bootstrap_peers = sorted(
                set(payload.get("peers") or []) | {self.name})
            self._persist_log(extra_pairs=[self._snapshot_pair(
                payload["state"], last, payload["last_term"],
                list(payload.get("peers") or []))])
            self._recompute_peers()
            self._applied_cv.notify_all()
            return {"term": self.current_term}

    # -- RPC handlers (follower side) -----------------------------------------

    def _handle_vote(self, payload: dict) -> dict:
        with self._lock:
            term = payload["term"]
            # leader stickiness (Raft §4.2.3): refuse higher-term vote
            # requests WITHOUT adopting the term while the cluster
            # demonstrably has a live leader. The one-way-partitioned
            # old leader ("can send but not receive") times out and
            # campaigns at ever-growing terms; honoring those requests
            # would bump the healthy majority's term every cycle and
            # keep deposing the leader it just elected. Two cases:
            # a FOLLOWER is sticky while heartbeats keep arriving; the
            # ACTIVE LEADER is sticky while its own quorum lease is
            # fresh (it never receives heartbeats, so the follower
            # clock alone would leave it permanently deposable).
            if term > self.current_term \
                    and self.leader_id != payload["candidate"]:
                sticky = (
                    self._recent_quorum_contact(self.election_timeout[0])
                    if self.role == LEADER and len(self.peers) > 1
                    else self.leader_id is not None
                    and time.monotonic() - self._last_leader_contact
                    < self.election_timeout[0])
                if sticky:
                    return {"term": self.current_term, "granted": False}
            if term > self.current_term:
                self._become_follower(term)
            granted = False
            if term == self.current_term and \
                    self.voted_for in (None, payload["candidate"]):
                my_index, my_term = self._last_log()
                up_to_date = (payload["last_log_term"], payload["last_log_index"]) \
                    >= (my_term, my_index)
                if up_to_date:
                    granted = True
                    self.voted_for = payload["candidate"]
                    self._persist_meta()
                    self._deadline = self._new_deadline()
            return {"term": self.current_term, "granted": granted}

    def _handle_append(self, payload: dict) -> dict:
        with self._lock:
            term = payload["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.role != FOLLOWER:
                self._become_follower(term)
            self.leader_id = payload["leader"]
            self._last_leader_contact = time.monotonic()
            self._deadline = self._new_deadline()

            prev_i = payload["prev_index"]
            if prev_i >= self.log_start - 1:
                if prev_i > self._abs_last() or \
                        (prev_i >= self.log_start - 1 and prev_i >= 0
                         and self._term_at(prev_i) != payload["prev_term"]):
                    return {"term": self.current_term, "success": False}
            # prev_i < log_start-1: covered by our snapshot — entries
            # overlapping the snapshot are already applied; skip them below
            entries = payload["entries"]
            insert = prev_i + 1
            appended = False
            for k, e in enumerate(entries):
                i = insert + k
                if i < self.log_start:
                    continue  # snapshot already covers it
                if i <= self._abs_last():
                    if self._term_at(i) != e["term"]:
                        self._truncate_log_from(i, persist=False)
                        self.log.extend(entries[k:])
                        self._persist_log(i)
                        appended = True
                        break
                else:
                    self.log.extend(entries[k:])
                    self._persist_log(i)
                    appended = True
                    break
            if appended:
                self._recompute_peers()
            if payload["leader_commit"] > self.commit_index:
                self.commit_index = min(payload["leader_commit"],
                                        self._abs_last())
                self._apply_committed()
            return {"term": self.current_term, "success": True}

    def _handle_propose(self, payload: dict) -> dict:
        """Leader-forwarded proposal endpoint (reference raft.go:26-38:
        followers forward schema writes to the leader over gRPC)."""
        index = self.propose_local(payload["op"], timeout=payload.get("timeout", 10.0))
        return {"index": index}

    # -- public API ----------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == LEADER

    def propose(self, op: dict, timeout: float = 10.0) -> int:
        """Submit an FSM op; blocks until applied locally. Followers
        forward to the leader."""
        deadline = time.time() + timeout
        last_err: Exception | None = None
        while time.time() < deadline:
            with self._lock:
                role, leader = self.role, self.leader_id
            if role == LEADER:
                return self.propose_local(op, timeout=deadline - time.time())
            if leader is not None:
                try:
                    with faultline.node_scope(self.name):
                        reply = rpc(
                            self.resolver(leader), "/raft/propose",
                            {"op": op,
                             "timeout": max(0.1, deadline - time.time())},
                            timeout=max(0.1, deadline - time.time()))
                    index = reply["index"]
                    # wait until OUR node applies it too (read-your-writes
                    # for schema; the reference schema manager reads its
                    # local FSM after Raft apply)
                    with self._applied_cv:
                        while self.last_applied < index:
                            if time.time() >= deadline:
                                raise TimeoutError(
                                    f"raft entry {index} committed on the "
                                    "leader but not yet applied locally")
                            self._applied_cv.wait(
                                max(0.05, deadline - time.time()))
                    return index
                except (RpcError, KeyError) as e:
                    last_err = e
            time.sleep(0.05)
        raise TimeoutError(f"raft propose timed out: {last_err}")

    def propose_local(self, op: dict, timeout: float = 10.0) -> int:
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_id)
            index = self._abs_last() + 1
            self.log.append({"term": self.current_term, "op": op})
            self._persist_log(index)
            if op.get("type") == "raft_conf":
                self._recompute_peers()  # conf effective at append (§6)
        # replicate eagerly rather than waiting a heartbeat
        self._replicate_all()
        deadline = time.time() + timeout
        with self._applied_cv:
            while self.last_applied < index:
                if time.time() >= deadline:
                    raise TimeoutError("raft commit timed out")
                self._applied_cv.wait(max(0.05, deadline - time.time()))
        return index

    def wait_for_leader(self, timeout: float = 10.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if self.leader_id is not None:
                    return self.leader_id
            time.sleep(0.05)
        raise TimeoutError("no raft leader elected")
