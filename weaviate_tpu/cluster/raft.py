"""Raft consensus for cluster metadata (schema, tenants).

Reference: cluster/store.go (hashicorp/raft + boltdb log store),
store_apply.go (FSM ops ADD_CLASS...DELETE_TENANT), raft.go:26 (leader
forwarding from followers). Scope parity: only schema/tenant METADATA
goes through Raft — object data takes the replication data plane.

This is a compact Raft: leader election with randomized timeouts,
AppendEntries log replication with the log-matching backtrack, majority
commit, persisted (term, votedFor, log) so a restarted node rejoins with
its history. Schema-op volume is tiny, so the log persists as one KV
record per entry and snapshotting is simply the applied FSM state
(the schema store itself).
"""

from __future__ import annotations

import logging
import random
import threading
import time

from weaviate_tpu.cluster.transport import RpcError, rpc

logger = logging.getLogger(__name__)

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class NotLeaderError(RuntimeError):
    def __init__(self, leader: str | None):
        super().__init__(f"not the leader (leader={leader})")
        self.leader = leader


class RaftNode:
    def __init__(self, name: str, peers: list[str], resolver, server,
                 apply_fn, store_bucket=None,
                 election_timeout: tuple[float, float] = (0.3, 0.6),
                 heartbeat_interval: float = 0.08):
        """``peers``: all member names incl. self (static bootstrap set,
        reference cluster/bootstrap). ``resolver(name) -> addr``.
        ``apply_fn(op: dict)`` applies a committed entry to the FSM.
        ``store_bucket``: KV bucket for persistence (term/vote/log)."""
        self.name = name
        self.peers = sorted(set(peers) | {name})
        self.resolver = resolver
        self.apply_fn = apply_fn
        self._bucket = store_bucket
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval

        self._lock = threading.RLock()
        self._applied_cv = threading.Condition(self._lock)
        self.role = FOLLOWER
        self.current_term = 0
        self.voted_for: str | None = None
        self.log: list[dict] = []  # {"term": int, "op": dict}
        self.commit_index = -1
        self.last_applied = -1
        self.leader_id: str | None = None
        self._next_index: dict[str, int] = {}
        self._match_index: dict[str, int] = {}
        self._last_heard = time.monotonic()
        self._deadline = self._new_deadline()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        self._restore()
        server.route("/raft/vote", self._handle_vote)
        server.route("/raft/append", self._handle_append)
        server.route("/raft/propose", self._handle_propose)

    # -- persistence ---------------------------------------------------------

    def _persist_meta(self) -> None:
        if self._bucket is not None:
            self._bucket.put(b"meta", {"term": self.current_term,
                                       "voted_for": self.voted_for})

    def _persist_log(self, start: int = 0) -> None:
        if self._bucket is not None:
            for i in range(start, len(self.log)):
                self._bucket.put(f"log-{i:012d}".encode(), self.log[i])
            self._bucket.put(b"log_len", len(self.log))

    def _truncate_log(self, new_len: int) -> None:
        if self._bucket is not None:
            self._bucket.put(b"log_len", new_len)
        del self.log[new_len:]

    def _restore(self) -> None:
        if self._bucket is None:
            return
        meta = self._bucket.get(b"meta")
        if meta:
            self.current_term = meta["term"]
            self.voted_for = meta.get("voted_for")
        n = self._bucket.get(b"log_len") or 0
        self.log = [self._bucket.get(f"log-{i:012d}".encode())
                    for i in range(n)]

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"raft-{self.name}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

    def _new_deadline(self) -> float:
        return time.monotonic() + random.uniform(*self.election_timeout)

    def _loop(self) -> None:
        while not self._stop.wait(0.01):
            try:
                with self._lock:
                    role = self.role
                if role == LEADER:
                    self._replicate_all()
                    time.sleep(self.heartbeat_interval)
                elif time.monotonic() >= self._deadline:
                    self._run_election()
            except Exception:
                logger.exception("raft %s loop error", self.name)

    # -- election ------------------------------------------------------------

    def _last_log(self) -> tuple[int, int]:
        if not self.log:
            return (-1, 0)
        return (len(self.log) - 1, self.log[-1]["term"])

    def _run_election(self) -> None:
        with self._lock:
            self.role = CANDIDATE
            self.current_term += 1
            self.voted_for = self.name
            self.leader_id = None
            term = self.current_term
            last_index, last_term = self._last_log()
            self._persist_meta()
            self._deadline = self._new_deadline()
        votes = 1
        for peer in self.peers:
            if peer == self.name:
                continue
            try:
                reply = rpc(self.resolver(peer), "/raft/vote",
                            {"term": term, "candidate": self.name,
                             "last_log_index": last_index,
                             "last_log_term": last_term}, timeout=1.0)
            except (RpcError, KeyError):
                continue
            with self._lock:
                if reply["term"] > self.current_term:
                    self._become_follower(reply["term"])
                    return
                if reply.get("granted") and self.role == CANDIDATE \
                        and self.current_term == term:
                    votes += 1
        with self._lock:
            if self.role == CANDIDATE and self.current_term == term \
                    and votes > len(self.peers) // 2:
                self._become_leader()

    def _become_leader(self) -> None:
        logger.info("raft %s: leader for term %d", self.name, self.current_term)
        self.role = LEADER
        self.leader_id = self.name
        n = len(self.log)
        self._next_index = {p: n for p in self.peers if p != self.name}
        self._match_index = {p: -1 for p in self.peers if p != self.name}
        # no-op barrier entry so the new leader can commit prior-term
        # entries (Raft §5.4.2)
        self.log.append({"term": self.current_term, "op": {"type": "noop"}})
        self._persist_log(n)

    def _become_follower(self, term: int) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_meta()
        self.role = FOLLOWER
        self._deadline = self._new_deadline()

    # -- replication (leader side) -------------------------------------------

    def _replicate_all(self) -> None:
        for peer in self.peers:
            if peer != self.name:
                self._replicate_one(peer)
        self._advance_commit()

    def _replicate_one(self, peer: str) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            next_i = self._next_index.get(peer, len(self.log))
            prev_i = next_i - 1
            prev_t = self.log[prev_i]["term"] if prev_i >= 0 else 0
            entries = self.log[next_i:]
            commit = self.commit_index
        try:
            reply = rpc(self.resolver(peer), "/raft/append",
                        {"term": term, "leader": self.name,
                         "prev_index": prev_i, "prev_term": prev_t,
                         "entries": entries, "leader_commit": commit},
                        timeout=1.0)
        except (RpcError, KeyError):
            return
        with self._lock:
            if reply["term"] > self.current_term:
                self._become_follower(reply["term"])
                return
            if self.role != LEADER or self.current_term != term:
                return
            if reply.get("success"):
                self._match_index[peer] = prev_i + len(entries)
                self._next_index[peer] = self._match_index[peer] + 1
            else:
                # log-matching backtrack
                self._next_index[peer] = max(0, next_i - 1)

    def _advance_commit(self) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            for n in range(len(self.log) - 1, self.commit_index, -1):
                if self.log[n]["term"] != self.current_term:
                    break  # only current-term entries commit by counting
                replicas = 1 + sum(1 for m in self._match_index.values()
                                   if m >= n)
                if replicas > len(self.peers) // 2:
                    self.commit_index = n
                    break
            self._apply_committed()

    def _apply_committed(self) -> None:
        # caller holds the lock
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            entry = self.log[self.last_applied]
            if entry["op"].get("type") != "noop":
                try:
                    self.apply_fn(entry["op"])
                except Exception:
                    logger.exception("raft %s: FSM apply failed at %d",
                                     self.name, self.last_applied)
        self._applied_cv.notify_all()

    # -- RPC handlers (follower side) -----------------------------------------

    def _handle_vote(self, payload: dict) -> dict:
        with self._lock:
            term = payload["term"]
            if term > self.current_term:
                self._become_follower(term)
            granted = False
            if term == self.current_term and \
                    self.voted_for in (None, payload["candidate"]):
                my_index, my_term = self._last_log()
                up_to_date = (payload["last_log_term"], payload["last_log_index"]) \
                    >= (my_term, my_index)
                if up_to_date:
                    granted = True
                    self.voted_for = payload["candidate"]
                    self._persist_meta()
                    self._deadline = self._new_deadline()
            return {"term": self.current_term, "granted": granted}

    def _handle_append(self, payload: dict) -> dict:
        with self._lock:
            term = payload["term"]
            if term < self.current_term:
                return {"term": self.current_term, "success": False}
            if term > self.current_term or self.role != FOLLOWER:
                self._become_follower(term)
            self.leader_id = payload["leader"]
            self._deadline = self._new_deadline()

            prev_i = payload["prev_index"]
            if prev_i >= 0 and (prev_i >= len(self.log)
                                or self.log[prev_i]["term"] != payload["prev_term"]):
                return {"term": self.current_term, "success": False}
            entries = payload["entries"]
            insert = prev_i + 1
            for k, e in enumerate(entries):
                i = insert + k
                if i < len(self.log):
                    if self.log[i]["term"] != e["term"]:
                        self._truncate_log(i)
                        self.log.extend(entries[k:])
                        self._persist_log(i)
                        break
                else:
                    self.log.extend(entries[k:])
                    self._persist_log(i)
                    break
            if payload["leader_commit"] > self.commit_index:
                self.commit_index = min(payload["leader_commit"],
                                        len(self.log) - 1)
                self._apply_committed()
            return {"term": self.current_term, "success": True}

    def _handle_propose(self, payload: dict) -> dict:
        """Leader-forwarded proposal endpoint (reference raft.go:26-38:
        followers forward schema writes to the leader over gRPC)."""
        index = self.propose_local(payload["op"], timeout=payload.get("timeout", 10.0))
        return {"index": index}

    # -- public API ----------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self.role == LEADER

    def propose(self, op: dict, timeout: float = 10.0) -> int:
        """Submit an FSM op; blocks until applied locally. Followers
        forward to the leader."""
        deadline = time.time() + timeout
        last_err: Exception | None = None
        while time.time() < deadline:
            with self._lock:
                role, leader = self.role, self.leader_id
            if role == LEADER:
                return self.propose_local(op, timeout=deadline - time.time())
            if leader is not None:
                try:
                    reply = rpc(self.resolver(leader), "/raft/propose",
                                {"op": op, "timeout": max(0.1, deadline - time.time())},
                                timeout=max(0.1, deadline - time.time()))
                    index = reply["index"]
                    # wait until OUR node applies it too (read-your-writes
                    # for schema; the reference schema manager reads its
                    # local FSM after Raft apply)
                    with self._applied_cv:
                        while self.last_applied < index:
                            if time.time() >= deadline:
                                raise TimeoutError(
                                    f"raft entry {index} committed on the "
                                    "leader but not yet applied locally")
                            self._applied_cv.wait(
                                max(0.05, deadline - time.time()))
                    return index
                except (RpcError, KeyError) as e:
                    last_err = e
            time.sleep(0.05)
        raise TimeoutError(f"raft propose timed out: {last_err}")

    def propose_local(self, op: dict, timeout: float = 10.0) -> int:
        with self._lock:
            if self.role != LEADER:
                raise NotLeaderError(self.leader_id)
            index = len(self.log)
            self.log.append({"term": self.current_term, "op": op})
            self._persist_log(index)
        # replicate eagerly rather than waiting a heartbeat
        self._replicate_all()
        deadline = time.time() + timeout
        with self._applied_cv:
            while self.last_applied < index:
                if time.time() >= deadline:
                    raise TimeoutError("raft commit timed out")
                self._applied_cv.wait(max(0.05, deadline - time.time()))
        return index

    def wait_for_leader(self, timeout: float = 10.0) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._lock:
                if self.leader_id is not None:
                    return self.leader_id
            time.sleep(0.05)
        raise TimeoutError("no raft leader elected")
