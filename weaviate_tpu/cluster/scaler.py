"""Replica scale-out/in.

Reference: usecases/scaler/scaler.go:38 — raising a class's replication
factor ships existing shard data to the new replica nodes
(ShardsBackup → CreateShard/ReInitShard over clusterapi); lowering just
trims placement. Resharding (changing shard count) is NOT supported, same
as the reference.
"""

from __future__ import annotations

from weaviate_tpu.storage.objects import StorageObject


class ScaleError(ValueError):
    """ValueError so the REST layer maps it to 422, not 500."""


class Scaler:
    """``db``: node-local Database (its ``nodes_provider``/``local_node``/
    ``remote`` wire the cluster view, the same plumbing queries use).
    ``propose``: optional Raft-propose callable (ClusterNode passes
    ``raft.propose``) so the new placement reaches EVERY node's schema —
    without it (single node) the placement applies locally."""

    def __init__(self, db, propose=None):
        self.db = db
        self.propose = propose

    def scale(self, collection_name: str, new_factor: int,
              batch: int = 500) -> dict:
        col = self.db.get_collection(collection_name)
        old_factor = col.config.replication.factor
        if new_factor < 1:
            raise ScaleError("replication factor must be >= 1")
        nodes = list(self.db.nodes_provider())
        if new_factor > len(nodes):
            raise ScaleError(
                f"replication factor {new_factor} exceeds cluster size "
                f"{len(nodes)}")
        # plan first, mutate nothing: a failed copy must leave the live
        # sharding state untouched
        new_placement: dict[str, list[str]] = {}
        to_copy: list[tuple[str, list[str], list[str]]] = []
        for shard in list(col.sharding.shard_names):
            current = list(col.sharding.nodes_for(shard))
            if len(current) >= new_factor:
                # scale-in: trim placement (reference only ever trims;
                # data on removed replicas is orphaned until cleanup)
                new_placement[shard] = current[:new_factor]
                continue
            additions = [n for n in nodes if n not in current]
            new_nodes = additions[: new_factor - len(current)]
            if len(current) + len(new_nodes) < new_factor:
                raise ScaleError(
                    f"not enough distinct nodes for shard {shard!r}")
            new_placement[shard] = current + new_nodes
            to_copy.append((shard, current, new_nodes))
        copied: dict[str, list[str]] = {}
        for shard, current, new_nodes in to_copy:
            for node in new_nodes:
                self._copy_shard(col, shard, current, node, batch)
            copied[shard] = new_nodes
        # all copies landed: commit placement + factor — through Raft on
        # a cluster so every node converges, locally otherwise
        if self.propose is not None:
            self.propose({"type": "update_sharding",
                          "class": collection_name,
                          "placement": new_placement,
                          "factor": new_factor})
        else:
            col.sharding.placement = new_placement
            col.config.replication.factor = new_factor
            self.db._persist(col)
        return {"collection": collection_name, "from": old_factor,
                "to": new_factor, "copied": copied}

    # -- data movement -------------------------------------------------------

    def _copy_shard(self, col, shard: str, sources: list[str],
                    target: str, batch: int) -> None:
        """Stream one shard's objects to ``target`` (reference:
        ShardsBackup + CreateShard file shipping; here the object stream
        rides the same remote-shard API replication writes use)."""
        local = self.db.local_node
        raws = self._read_raw(col, shard, sources)
        if target == local:
            dst = col._load_shard(shard)
            for i in range(0, len(raws), batch):
                dst.put_object_batch(
                    [StorageObject.from_bytes(r)
                     for r in raws[i:i + batch]])
            return
        if self.db.remote is None:
            raise ScaleError(
                f"no remote client to reach node {target!r}")
        for i in range(0, len(raws), batch):
            self.db.remote.put_objects(target, col.config.name, shard,
                                       raws[i:i + batch])

    def _read_raw(self, col, shard: str, sources: list[str]) -> list[bytes]:
        local = self.db.local_node
        if local in sources:
            src = col._load_shard(shard)
            return [raw for _k, raw in src.objects.iter_items()]
        if self.db.remote is None:
            raise ScaleError(f"shard {shard!r} has no local replica and no "
                             "remote client")
        errors = []
        for node in sources:
            try:
                return self.db.remote.list_objects(node, col.config.name,
                                                   shard)
            except Exception as e:  # try the next replica
                errors.append(f"{node}: {e}")
        raise ScaleError(f"could not read shard {shard!r} from any "
                         f"replica: {'; '.join(errors)}")
