"""Remote shard client + incoming handlers: the intra-cluster data plane.

Reference: adapters/clients/remote_index.go (client), routed server side
by clusterapi/indices.go:184-260 into Index.Incoming* methods
(index.go:1665 IncomingSearch etc.). Payloads here are JSON with
base64-wrapped binary objects (the reference uses custom binary
payloads, clusterapi/indices_payloads.go — same boundary, simpler
encoding).

Paths: POST /indices/{collection}/{shard}/{op}
ops: search | objects (batch put) | object:get | object:delete |
     object:exists | aggregate | overview
"""

from __future__ import annotations

import logging
import os

import numpy as np

from weaviate_tpu.cluster.transport import RpcError, rpc
from weaviate_tpu.runtime import faultline, tracing
from weaviate_tpu.runtime.retry import RetryPolicy
from weaviate_tpu.storage.objects import StorageObject

logger = logging.getLogger(__name__)

def default_timeout_s() -> float:
    """Fallback per-attempt timeout for remote shard ops — used to be a
    hard-coded 30.0 in the constructor. Server-managed clients receive
    ``ServerConfig.remote_rpc_timeout_s`` explicitly (the CONFIG_FILE
    overlay applies there); this env read is the fallback for directly
    constructed clients, evaluated lazily so it is not frozen at import
    time. Like every transport call, the ceiling is additionally capped
    by the request's remaining deadline budget inside ``rpc``."""
    return float(os.environ.get("REMOTE_RPC_TIMEOUT_S", "30"))

#: ops safe to retry: reads and existence probes. Writes stay
#: single-shot — the replication layer owns write-failure semantics
#: (2PC abort + anti-entropy), and a blind transport retry of a put
#: could double-apply side effects the coordinator already accounted
_IDEMPOTENT_OPS = frozenset({
    "search", "object:get", "objects:get", "objects:list",
    "object:exists", "aggregate", "overview",
})


class RemoteShardClient:
    """Client side: every method targets one shard on one node
    (reference: sharding.RemoteIndexClient)."""

    def __init__(self, resolver, timeout: float | None = None):
        self.resolver = resolver  # node name -> "host:port"
        self.timeout = default_timeout_s() if timeout is None else timeout
        self.retry = RetryPolicy(op="remote.shard_op")

    def _call(self, node: str, collection: str, shard: str, op: str,
              payload: dict) -> dict:
        with tracing.span("remote.shard_op", op=op, node=node,
                          shard=shard):
            def attempt():
                # fault point INSIDE the attempt and mapped to RpcError:
                # an injected fault takes the exact path a real one
                # would — through the retry policy, replica failover,
                # and degraded-read handling (retries count as separate
                # schedule calls, like every transport-level point)
                try:
                    faultline.fire("remote.shard_op", op=op, node=node,
                                   shard=shard)
                except faultline.FaultInjected as e:
                    raise RpcError(
                        f"remote {op} on {node}/{shard} failed: {e}") from e
                return rpc(self.resolver(node),
                           f"/indices/{collection}/{shard}/{op}", payload,
                           timeout=self.timeout)

            if op in _IDEMPOTENT_OPS:
                return self.retry.call(attempt)
            return attempt()

    def search_shard(self, node: str, collection: str, shard: str, *,
                     vector=None, k: int = 10, vec_name: str = "",
                     query: str | None = None,
                     properties: list[str] | None = None,
                     where: dict | None = None,
                     include_objects: bool = True) -> list[dict]:
        payload = {
            "k": k, "vec_name": vec_name, "query": query,
            "properties": properties, "where": where,
            "include_objects": include_objects,
        }
        if vector is not None:
            payload["vector"] = np.asarray(vector, dtype=np.float32)
        return self._call(node, collection, shard, "search", payload)["results"]

    def put_objects(self, node: str, collection: str, shard: str,
                    raw_objects: list[bytes]) -> None:
        self._call(node, collection, shard, "objects",
                   {"objects": raw_objects})

    def get_object(self, node: str, collection: str, shard: str,
                   uuid: str) -> bytes | None:
        reply = self._call(node, collection, shard, "object:get",
                           {"uuid": uuid})
        return reply.get("object")

    def get_objects(self, node: str, collection: str, shard: str,
                    uuids: list[str]) -> list[bytes | None]:
        """Batched multi-get (one RPC per shard, not per object)."""
        reply = self._call(node, collection, shard, "objects:get",
                           {"uuids": uuids})
        return reply["objects"]

    def list_objects(self, node: str, collection: str, shard: str,
                     limit: int | None = None, after: str | None = None,
                     where: dict | None = None) -> list[bytes]:
        """uuid-ordered page of raw objects (cursor listing across nodes)."""
        reply = self._call(node, collection, shard, "objects:list",
                           {"limit": limit, "after": after, "where": where})
        return reply["objects"]

    def delete_object(self, node: str, collection: str, shard: str,
                      uuid: str) -> bool:
        return self._call(node, collection, shard, "object:delete",
                          {"uuid": uuid})["deleted"]

    def exists(self, node: str, collection: str, shard: str, uuid: str) -> bool:
        return self._call(node, collection, shard, "object:exists",
                          {"uuid": uuid})["exists"]

    def aggregate(self, node: str, collection: str, shard: str,
                  properties: list[str] | None = None,
                  group_by: str | None = None,
                  where: dict | None = None) -> dict:
        return self._call(node, collection, shard, "aggregate",
                          {"properties": properties, "group_by": group_by,
                           "where": where})["partial"]

    def overview(self, node: str, collection: str, shard: str) -> dict:
        return self._call(node, collection, shard, "overview", {})


def register_incoming(server, db) -> None:
    """Mount the incoming shard-op handlers for a node's local Database
    (reference: clusterapi indices.go router → Index.Incoming*)."""

    def handler(subpath: str, payload: dict):
        parts = subpath.split("/")
        if len(parts) != 3:
            raise KeyError(subpath)
        collection_name, shard_name, op = parts
        col = db.get_collection(collection_name)
        if db.local_node not in col.sharding.nodes_for(shard_name):
            raise ValueError(
                f"node {db.local_node} does not own shard {shard_name!r}")
        shard = col._load_shard(shard_name)

        if op == "search":
            return _incoming_search(shard, payload)
        if op == "objects":
            objs = [StorageObject.from_bytes(raw) for raw in payload["objects"]]
            shard.put_object_batch(objs)
            return {"ok": True}
        if op == "object:get":
            raw = shard.objects.get(payload["uuid"].encode())
            return {"object": raw}
        if op == "objects:get":
            return {"objects": [shard.objects.get(u.encode())
                                for u in payload["uuids"]]}
        if op == "objects:list":
            return {"objects": _incoming_list(shard, payload)}
        if op == "object:delete":
            return {"deleted": shard.delete_object(payload["uuid"])}
        if op == "object:exists":
            return {"exists": shard.exists(payload["uuid"])}
        if op == "aggregate":
            return {"partial": _incoming_aggregate(shard, payload)}
        if op == "overview":
            return {"object_count": shard.object_count(),
                    "doc_id_space": shard.doc_id_space}
        raise KeyError(op)

    server.route("/indices/", handler)


def _where_from(payload: dict):
    if payload.get("where") is None:
        return None
    from weaviate_tpu.filters.filters import Filter

    return Filter.from_dict(payload["where"])


def _incoming_search(shard, payload: dict) -> dict:
    where = _where_from(payload)
    allow = shard.allow_mask(where) if where is not None else None
    include = payload.get("include_objects", True)
    k = payload.get("k", 10)
    results = []
    if payload.get("vector") is not None:
        ids, dists = shard.vector_search(
            np.asarray(payload["vector"], dtype=np.float32), k,
            payload.get("vec_name", ""), allow)
        for doc_id, dist in zip(ids.tolist(), dists.tolist()):
            uuid = shard._doc_to_uuid.get(doc_id)
            if uuid is None:
                continue
            item = {"uuid": uuid, "distance": float(dist)}
            if include:
                item["object"] = shard.objects.get(uuid.encode())
            results.append(item)
    else:
        ids, scores = shard.bm25_search(payload["query"], k,
                                        payload.get("properties"), allow)
        for doc_id, score in zip(ids.tolist(), scores.tolist()):
            uuid = shard._doc_to_uuid.get(doc_id)
            if uuid is None:
                continue
            item = {"uuid": uuid, "score": float(score)}
            if include:
                item["object"] = shard.objects.get(uuid.encode())
            results.append(item)
    return {"results": results}


def _incoming_list(shard, payload: dict) -> list[bytes]:
    where = _where_from(payload)
    mask = shard.allow_mask(where) if where is not None else None
    after = payload.get("after")
    limit = payload.get("limit")
    with shard._lock:
        items = sorted(shard._doc_to_uuid.items(), key=lambda t: t[1])
    out: list[bytes] = []
    for doc_id, uuid in items:
        if after is not None and uuid <= after:
            continue
        if mask is not None and (doc_id >= len(mask) or not mask[doc_id]):
            continue
        raw = shard.objects.get(uuid.encode())
        if raw is not None:
            out.append(raw)
            if limit is not None and len(out) >= limit:
                break
    return out


def _incoming_aggregate(shard, payload: dict) -> dict:
    from weaviate_tpu.query.aggregator import aggregate_objects

    where = _where_from(payload)
    mask = shard.allow_mask(where) if where is not None else None

    def objs():
        for _key, raw in shard.objects.iter_items():
            obj = StorageObject.from_bytes(raw)
            if mask is not None and (obj.doc_id >= len(mask)
                                     or not mask[obj.doc_id]):
                continue
            yield obj

    return aggregate_objects(objs(), payload.get("properties"),
                             payload.get("group_by"))
