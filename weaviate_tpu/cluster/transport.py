"""Internal cluster transport: JSON-over-HTTP on a dedicated port.

Reference: adapters/handlers/rest/clusterapi/serve.go — a separate HTTP
mux on CLUSTER_DATA_BIND_PORT carries all intra-cluster traffic (shard
ops, replicas, backups); adapters/clients/* are the matching clients.
Raft RPCs ride the same transport here (the reference uses gRPC for
those; same boundary, different encoding).

Numpy arrays cross the wire base64-encoded inside JSON ("b64npy"
envelopes) — compact enough for control + small data payloads while
staying dependency-free.
"""

from __future__ import annotations

import base64
import http.client
import io
import json
import logging
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from weaviate_tpu.runtime import tracing

logger = logging.getLogger(__name__)

# response header carrying the serving node's finished spans back to the
# caller (base64 json) so a distributed query stitches into ONE trace
TRACE_SPANS_HEADER = "X-Trace-Spans"


def _encode_spans(spans: list[dict] | None) -> str | None:
    if not spans:
        return None
    try:
        return base64.b64encode(
            json.dumps(spans, separators=(",", ":")).encode()).decode()
    except (TypeError, ValueError):
        return None


def _decode_spans(header: str | None) -> list[dict] | None:
    if not header:
        return None
    try:
        out = json.loads(base64.b64decode(header))
        return out if isinstance(out, list) else None
    except (ValueError, TypeError):
        return None  # a corrupt trace header must never fail the RPC


# -- numpy-aware JSON encoding -------------------------------------------------


def encode_array(a: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return {"__b64npy__": base64.b64encode(buf.getvalue()).decode("ascii")}


def _decode_hook(d: dict):
    if "__b64npy__" in d:
        return np.load(io.BytesIO(base64.b64decode(d["__b64npy__"])),
                       allow_pickle=False)
    if "__b64__" in d:
        return base64.b64decode(d["__b64__"])
    return d


class _Encoder(json.JSONEncoder):
    def default(self, o):
        if isinstance(o, np.ndarray):
            return encode_array(o)
        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        if isinstance(o, bytes):
            return {"__b64__": base64.b64encode(o).decode("ascii")}
        return super().default(o)


def dumps(payload) -> bytes:
    return json.dumps(payload, cls=_Encoder).encode()


def loads(raw: bytes):
    return json.loads(raw.decode(), object_hook=_decode_hook)


# -- server --------------------------------------------------------------------


class InternalServer:
    """Route table + ThreadingHTTPServer. Handlers: fn(payload) -> payload.

    Routes are exact paths ("/raft/vote") or prefixes ending in "/"
    ("/indices/" receives (subpath, payload))."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 advertise: str | None = None):
        """``advertise``: the host:port OTHER nodes reach this one at —
        required when binding 0.0.0.0 in containers (reference:
        CLUSTER_ADVERTISE_ADDR/PORT in usecases/cluster config)."""
        self._advertise = advertise
        self.routes: dict[str, object] = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                # adopt an incoming traceparent: spans recorded while
                # handling chain to the caller's span and are exported
                # back in the response for cross-node stitching
                seg = None
                try:
                    payload = loads(raw) if raw else {}
                    with tracing.remote_segment(
                            self.headers.get("traceparent"),
                            name="rpc.server", path=self.path) as seg:
                        result = outer.dispatch(self.path, payload)
                    body = dumps(result)
                    code = 200
                except KeyError as e:
                    body = dumps({"error": f"not found: {e}"})
                    code = 404
                except Exception as e:
                    logger.exception("internal handler %s failed", self.path)
                    body = dumps({"error": str(e)})
                    code = 500
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                exported = _encode_spans(
                    seg.export() if seg is not None else None)
                if exported is not None:
                    self.send_header(TRACE_SPANS_HEADER, exported)
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        if self._advertise:
            return self._advertise
        return f"{self.host}:{self.port}"

    def route(self, path: str, handler) -> None:
        self.routes[path] = handler

    def dispatch(self, path: str, payload):
        handler = self.routes.get(path)
        if handler is not None:
            return handler(payload)
        # longest-prefix match for "/prefix/" routes
        best = None
        for p in self.routes:
            if p.endswith("/") and path.startswith(p):
                if best is None or len(p) > len(best):
                    best = p
        if best is None:
            raise KeyError(path)
        return self.routes[best](path[len(best):], payload)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=f"internal-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread = None


# -- client --------------------------------------------------------------------


class RpcError(RuntimeError):
    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


def rpc(addr: str, path: str, payload=None, timeout: float = 10.0):
    """POST ``payload`` to http://addr/path; raises RpcError on transport
    or handler failure. Inside a trace the call carries a ``traceparent``
    header and absorbs the remote node's exported spans on return."""
    host, _, port = addr.partition(":")
    body = dumps(payload or {})
    headers = {"Content-Type": "application/json"}
    with tracing.span("rpc.client", addr=addr, path=path) as sp:
        tp = tracing.current_traceparent()
        if tp is not None:
            headers["traceparent"] = tp
        try:
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=timeout)
            try:
                conn.request("POST", path, body=body, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                remote_spans = _decode_spans(
                    resp.getheader(TRACE_SPANS_HEADER))
            finally:
                conn.close()
        except (ConnectionError, socket.timeout, OSError) as e:
            raise RpcError(f"rpc to {addr}{path} failed: {e}") from e
        if remote_spans:
            tracing.absorb(remote_spans,
                           base_ms=getattr(sp, "start_ms", 0.0))
        result = loads(raw)
        if resp.status != 200:
            raise RpcError(
                result.get("error", f"status {resp.status}")
                if isinstance(result, dict) else f"status {resp.status}",
                status=resp.status)
        return result
