"""Internal cluster transport: JSON-over-HTTP on a dedicated port.

Reference: adapters/handlers/rest/clusterapi/serve.go — a separate HTTP
mux on CLUSTER_DATA_BIND_PORT carries all intra-cluster traffic (shard
ops, replicas, backups); adapters/clients/* are the matching clients.
Raft RPCs ride the same transport here (the reference uses gRPC for
those; same boundary, different encoding).

Numpy arrays cross the wire base64-encoded inside JSON ("b64npy"
envelopes) — compact enough for control + small data payloads while
staying dependency-free.
"""

from __future__ import annotations

import base64
import http.client
import io
import json
import logging
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from weaviate_tpu.runtime import faultline, retry, tracing

logger = logging.getLogger(__name__)

# response header carrying the serving node's finished spans back to the
# caller (base64 json) so a distributed query stitches into ONE trace
TRACE_SPANS_HEADER = "X-Trace-Spans"

# request headers for the faultline partition topology: which cluster
# node issued this RPC, and whether the sender's process already
# consulted its topology registry (so an in-process server does not
# double-count the same rule the client side just evaluated)
SOURCE_NODE_HEADER = "X-Weaviate-Node"
TOPOLOGY_CHECKED_HEADER = "X-Topology-Checked"


def _encode_spans(spans: list[dict] | None) -> str | None:
    if not spans:
        return None
    try:
        return base64.b64encode(
            json.dumps(spans, separators=(",", ":")).encode()).decode()
    except (TypeError, ValueError):
        return None


def _decode_spans(header: str | None) -> list[dict] | None:
    if not header:
        return None
    try:
        out = json.loads(base64.b64decode(header))
        return out if isinstance(out, list) else None
    except (ValueError, TypeError):
        return None  # a corrupt trace header must never fail the RPC


# -- numpy-aware JSON encoding -------------------------------------------------


def encode_array(a: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return {"__b64npy__": base64.b64encode(buf.getvalue()).decode("ascii")}


def _decode_hook(d: dict):
    if "__b64npy__" in d:
        return np.load(io.BytesIO(base64.b64decode(d["__b64npy__"])),
                       allow_pickle=False)
    if "__b64__" in d:
        return base64.b64decode(d["__b64__"])
    return d


class _Encoder(json.JSONEncoder):
    def default(self, o):
        if isinstance(o, np.ndarray):
            return encode_array(o)
        if isinstance(o, (np.integer,)):
            return int(o)
        if isinstance(o, (np.floating,)):
            return float(o)
        if isinstance(o, bytes):
            return {"__b64__": base64.b64encode(o).decode("ascii")}
        return super().default(o)


def dumps(payload) -> bytes:
    return json.dumps(payload, cls=_Encoder).encode()


def loads(raw: bytes):
    return json.loads(raw.decode(), object_hook=_decode_hook)


# -- server --------------------------------------------------------------------


class InternalServer:
    """Route table + ThreadingHTTPServer. Handlers: fn(payload) -> payload.

    Routes are exact paths ("/raft/vote") or prefixes ending in "/"
    ("/indices/" receives (subpath, payload))."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 advertise: str | None = None):
        """``advertise``: the host:port OTHER nodes reach this one at —
        required when binding 0.0.0.0 in containers (reference:
        CLUSTER_ADVERTISE_ADDR/PORT in usecases/cluster config)."""
        self._advertise = advertise
        self.routes: dict[str, object] = {}
        #: owning cluster node's name (set by ClusterNode) — handlers
        #: that fan out further RPCs (raft forwarding, replication,
        #: read repair) issue them AS this node, which is what the
        #: faultline topology layer partitions on
        self.node_name: str | None = None
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                # server-side partition topology: requests from callers
                # that did NOT consult this process's registry (a
                # subprocess cluster node) are checked here. "never
                # arrived" = close without dispatching; "ack lost" =
                # dispatch, then close without answering. Either way the
                # caller sees a dead connection, never an HTTP status —
                # a partitioned peer must not look alive.
                link = None
                if outer.node_name is not None and \
                        self.headers.get(TOPOLOGY_CHECKED_HEADER) \
                        != faultline.PROCESS_TOKEN:
                    link = faultline.check_link_incoming(
                        self.headers.get(SOURCE_NODE_HEADER),
                        outer.node_name)
                    if link == "unreachable":
                        self.close_connection = True
                        return
                # adopt an incoming traceparent: spans recorded while
                # handling chain to the caller's span and are exported
                # back in the response for cross-node stitching
                seg = None
                try:
                    payload = loads(raw) if raw else {}
                    with faultline.node_scope(outer.node_name), \
                            tracing.remote_segment(
                            self.headers.get("traceparent"),
                            name="rpc.server", path=self.path) as seg:
                        result = outer.dispatch(self.path, payload)
                    body = dumps(result)
                    code = 200
                except KeyError as e:
                    body = dumps({"error": f"not found: {e}"})
                    code = 404
                except Exception as e:
                    logger.exception("internal handler %s failed", self.path)
                    body = dumps({"error": str(e)})
                    code = 500
                if link == "drop":
                    # the handler ran; its ack dies on the cut reply
                    # direction — close the connection unanswered
                    self.close_connection = True
                    return
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                exported = _encode_spans(
                    seg.export() if seg is not None else None)
                if exported is not None:
                    self.send_header(TRACE_SPANS_HEADER, exported)
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> str:
        if self._advertise:
            return self._advertise
        return f"{self.host}:{self.port}"

    def route(self, path: str, handler) -> None:
        self.routes[path] = handler

    def dispatch(self, path: str, payload):
        handler = self.routes.get(path)
        if handler is not None:
            return handler(payload)
        # longest-prefix match for "/prefix/" routes
        best = None
        for p in self.routes:
            if p.endswith("/") and path.startswith(p):
                if best is None or len(p) > len(best):
                    best = p
        if best is None:
            raise KeyError(path)
        return self.routes[best](path[len(best):], payload)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=f"internal-{self.port}")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread = None


# -- client --------------------------------------------------------------------


class RpcError(RuntimeError):
    #: True when the failure was a per-attempt timeout: the call already
    #: burned its full time ceiling, so the retry policy treats it as
    #: terminal (failover handles it) instead of burning another ceiling
    timed_out = False

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


class CircuitOpenError(RpcError):
    """Fail-fast refusal: the peer's breaker is open. Subclasses
    RpcError so every existing per-replica failure handler treats it
    like the dead peer it represents — without paying the dead peer's
    timeout. Carries the breaker's retry hint."""

    def __init__(self, message: str, retry_after_s: float = 0.0):
        super().__init__(message, status=503)
        self.retry_after_s = retry_after_s


# -- per-peer circuit breakers -------------------------------------------------

#: consecutive transport-level failures before a peer's circuit opens
CB_THRESHOLD = int(os.environ.get("WEAVIATE_TPU_CB_THRESHOLD", "5"))
#: seconds an open circuit refuses calls before allowing ONE half-open
#: probe through
CB_COOLDOWN_S = float(os.environ.get("WEAVIATE_TPU_CB_COOLDOWN_S", "2.0"))

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_VALUE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitBreaker:
    """closed -> (N consecutive transport failures) -> open ->
    (cooldown) -> half-open: one probe call goes through; success closes
    the circuit, failure re-opens it for another cooldown. Only
    TRANSPORT-level failures count — an HTTP error status proves the
    peer is alive and must reset the streak."""

    def __init__(self, peer: str, threshold: int | None = None,
                 cooldown_s: float | None = None):
        self.peer = peer
        self.threshold = CB_THRESHOLD if threshold is None else threshold
        self.cooldown_s = CB_COOLDOWN_S if cooldown_s is None else cooldown_s
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call proceed right now? In OPEN past the cooldown,
        exactly one caller wins the half-open probe; everyone else keeps
        failing fast until the probe reports."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN \
                    and time.monotonic() - self._opened_at >= self.cooldown_s:
                self._transition(HALF_OPEN)
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0,
                       self.cooldown_s - (time.monotonic() - self._opened_at))

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN:
                self._probing = False
                self._transition(OPEN)
            elif self._state == CLOSED and self._failures >= self.threshold:
                self._transition(OPEN)

    def release_probe(self) -> None:
        """Give back a half-open probe slot WITHOUT recording an
        outcome — for exceptions that escape ``rpc`` between ``allow``
        and the record calls (not transport evidence either way). A
        leaked slot would otherwise wedge the peer in fail-fast
        forever."""
        with self._lock:
            self._probing = False

    def notify_alive(self) -> None:
        """Membership proved DIRECT contact with this peer (a gossip
        round-trip on the same host:port the data plane uses): collapse
        whatever cooldown remains so the very next call runs the
        half-open probe. Recovery latency after a partition heals is
        then probe-bound, not cooldown-bound — without this, a breaker
        opened moments before the heal kept fail-fasting a provably
        alive peer for the full CB_COOLDOWN_S."""
        with self._lock:
            if self._state == OPEN:
                self._transition(HALF_OPEN)

    def _transition(self, to: str) -> None:
        """Caller holds ``_lock``."""
        self._state = to
        if to == OPEN:
            self._opened_at = time.monotonic()
        try:
            from weaviate_tpu.runtime.metrics import (circuit_state,
                                                      circuit_transitions_total)

            circuit_state.labels(self.peer).set(_STATE_VALUE[to])
            circuit_transitions_total.labels(self.peer, to).inc()
        except Exception:  # pragma: no cover
            pass


_breaker_lock = threading.Lock()
_breakers: dict[str, CircuitBreaker] = {}


def breaker_for(addr: str) -> CircuitBreaker:
    # lock-free fast path (benign race, same pattern as
    # degrade.is_unhealthy): every data-plane rpc() calls this, and a
    # process-wide mutex just to read an existing dict entry would be
    # avoidable fan-out contention. The lock only guards first-insert.
    br = _breakers.get(addr)
    if br is not None:
        return br
    with _breaker_lock:
        br = _breakers.get(addr)
        if br is None:
            br = _breakers[addr] = CircuitBreaker(addr)
        return br


def on_peer_alive(addr: str) -> None:
    """Gossip's membership-alive signal for ``addr`` (direct contact
    only — relayed third-party views don't prove OUR link works). A
    breaker that never opened is a cheap no-op."""
    br = _breakers.get(addr)
    if br is not None:
        br.notify_alive()


def reset_breakers() -> None:
    """Test hook: forget every peer's breaker state (OS-assigned ports
    get reused across in-process test clusters; a previous cluster's
    open circuit must not poison the next one's fresh node)."""
    with _breaker_lock:
        for addr in list(_breakers):
            try:
                from weaviate_tpu.runtime.metrics import circuit_state

                circuit_state.remove(addr)
            except Exception:  # pragma: no cover
                pass
            del _breakers[addr]


#: control-plane prefixes exempt from the circuit breaker: raft and
#: gossip ARE the cluster's failure detectors — their probes must keep
#: flowing to notice recovery (a raft heartbeat doubles as the
#: half-open probe), and the connection storm against a peer that has
#: not bound its port yet during cluster boot must not open the
#: breaker that then fail-fasts DATA-plane calls to the same address
BREAKER_EXEMPT_PREFIXES = ("/raft/", "/cluster/")

#: default per-attempt timeout when a call site passes none explicitly
#: (graftlint G6 keeps serving-path call sites explicit)
RPC_DEFAULT_TIMEOUT_S = float(os.environ.get("RPC_DEFAULT_TIMEOUT_S", "10"))


def rpc(addr: str, path: str, payload=None, timeout: float | None = None):
    """POST ``payload`` to http://addr/path; raises RpcError on transport
    or handler failure. Inside a trace the call carries a ``traceparent``
    header and absorbs the remote node's exported spans on return.

    Failure policy (the faultline tentpole): the per-attempt ``timeout``
    is capped by the request's remaining deadline budget (an RPC never
    gets more time than its request has left; an exhausted budget raises
    the TYPED ``retry.DeadlineExceeded``); every transport-level failure
    — connection, socket timeout, malformed/incomplete HTTP, corrupt
    payload — maps to ``RpcError`` and feeds ``addr``'s circuit breaker;
    an open breaker fails fast with ``CircuitOpenError`` so a dead peer
    stops eating the deadline budget of every request that fans out to
    it."""
    if timeout is None:
        timeout = RPC_DEFAULT_TIMEOUT_S
    timeout = retry.budget_timeout(timeout, layer="transport.rpc")
    host, _, port = addr.partition(":")
    # serialize BEFORE the breaker check: a caller-side encoding bug
    # must not consume (and then leak) a half-open probe slot
    body = dumps(payload or {})
    headers = {"Content-Type": "application/json"}
    src_node = faultline.current_node()
    if src_node is not None:
        headers[SOURCE_NODE_HEADER] = src_node
    if faultline.topology_armed():
        # this process's registry is consulted below — tell a server in
        # the SAME process (token match) not to evaluate the same rules
        # again; a server in another process with its OWN armed rules
        # still enforces them
        headers[TOPOLOGY_CHECKED_HEADER] = faultline.PROCESS_TOKEN
    breaker = None if path.startswith(BREAKER_EXEMPT_PREFIXES) \
        else breaker_for(addr)
    if breaker is not None and not breaker.allow():
        raise CircuitOpenError(
            f"rpc to {addr}{path} refused: circuit open "
            f"({breaker._failures} consecutive failures)",
            retry_after_s=breaker.retry_after_s())
    recorded = False
    try:
        with tracing.span("rpc.client", addr=addr, path=path) as sp:
            tp = tracing.current_traceparent()
            if tp is not None:
                headers["traceparent"] = tp
            try:
                directive = faultline.fire("transport.rpc.send", addr=addr,
                                           path=path)
                # topology layer: a cut REQUEST direction fails like an
                # unreachable peer (raised here, mapped to RpcError +
                # breaker below); a cut REPLY direction completes the
                # send — the handler runs — and loses the ack via the
                # same drop directive a scheduled reply-loss uses
                link = faultline.check_link(addr, path=path)
                if link == "unreachable":
                    raise faultline.LinkDown(
                        faultline.current_node(), addr, "topology")
                if link == "drop" and directive is None:
                    directive = "drop"
                conn = http.client.HTTPConnection(host, int(port),
                                                  timeout=timeout)
                try:
                    conn.request("POST", path, body=body, headers=headers)
                    resp = conn.getresponse()
                    raw = resp.read()
                    remote_spans = _decode_spans(
                        resp.getheader(TRACE_SPANS_HEADER))
                finally:
                    conn.close()
                if directive == "drop":
                    # the request REACHED the peer (its handler ran); the
                    # reply is lost on the way back — the 2PC "prepare
                    # landed, ack lost" scenario a refused connection
                    # can't produce
                    raise FaultDropped(
                        f"rpc reply from {addr}{path} dropped")
                if directive == "corrupt":
                    raw = b"\x00corrupt\xff" + raw[:8]
            except (ConnectionError, socket.timeout, OSError,
                    http.client.HTTPException, FaultDropped,
                    faultline.FaultInjected) as e:
                # http.client.HTTPException covers the half-dead-peer
                # modes (IncompleteRead, BadStatusLine, ...) that used
                # to escape as raw exceptions instead of RpcError
                if breaker is not None:
                    breaker.record_failure()
                    recorded = True
                err = RpcError(f"rpc to {addr}{path} failed: {e}")
                err.timed_out = isinstance(e, (socket.timeout,
                                               TimeoutError))
                raise err from e
            try:
                result = loads(raw)
            except (ValueError, UnicodeDecodeError) as e:
                # a garbled/truncated body is a wire-level failure too:
                # it feeds the breaker like the half-dead-peer modes
                if breaker is not None:
                    breaker.record_failure()
                    recorded = True
                raise RpcError(f"rpc to {addr}{path} returned a corrupt "
                               f"payload: {e}") from e
            if breaker is not None:
                breaker.record_success()
                recorded = True
            if remote_spans:
                tracing.absorb(remote_spans,
                               base_ms=getattr(sp, "start_ms", 0.0))
            if resp.status != 200:
                raise RpcError(
                    result.get("error", f"status {resp.status}")
                    if isinstance(result, dict) else f"status {resp.status}",
                    status=resp.status)
            return result
    finally:
        # an exception that escaped between allow() and the record calls
        # (a custom faultline error=, a tracing bug) is not transport
        # evidence either way — but the probe slot it may hold must be
        # returned or the peer wedges in fail-fast forever
        if breaker is not None and not recorded:
            breaker.release_probe()


class FaultDropped(Exception):
    """Internal marker for faultline's ``drop`` directive (never escapes
    ``rpc`` — mapped to RpcError like the timeout it simulates)."""
