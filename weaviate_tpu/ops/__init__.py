"""TPU compute ops: distance kernels, top-k, quantization.

This package replaces the reference's native hot-path code
(adapters/repos/db/vector/hnsw/distancer/ — SIMD assembly for single-pair
distances) with batched, MXU-friendly ops: one call scores a whole [B, d]
query block against an [N, d] corpus block instead of one pair at a time.
"""

from weaviate_tpu.ops.distances import (
    DISTANCE_METRICS,
    pairwise_distance,
    single_distance,
    normalize,
)
from weaviate_tpu.ops.topk import chunked_topk, merge_topk

__all__ = [
    "DISTANCE_METRICS",
    "pairwise_distance",
    "single_distance",
    "normalize",
    "chunked_topk",
    "merge_topk",
]
