"""Binary quantization (BQ) on TPU.

Reference: adapters/repos/db/vector/compressionhelpers/binary_quantization.go
(:22 — sign bit per dimension packed into uint64 words, hamming distance via
XOR + popcount, with full-precision rescore in the flat index,
vector/flat/index.go:347).

TPU re-design: bits pack into uint32 words (int64 lanes are wasteful on
TPU); hamming runs as `population_count(xor(q, x))` on the VPU over [N, w]
word arrays — one vectorized pass instead of per-pair scalar loops. 32x
HBM compression; candidates are rescored against full-precision vectors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

WORD_BITS = 32


def bq_words(dim: int) -> int:
    return -(-dim // WORD_BITS)


@jax.jit
def bq_encode(vectors: jnp.ndarray) -> jnp.ndarray:
    """Pack sign bits: [N, d] float -> [N, ceil(d/32)] uint32.

    Bit j of word w is set iff vectors[:, w*32+j] >= 0 (reference uses the
    sign bit the same way, binary_quantization.go:30).
    """
    n, d = vectors.shape
    w = bq_words(d)
    pad = w * WORD_BITS - d
    bits = (vectors >= 0).astype(jnp.uint32)
    if pad:
        bits = jnp.concatenate([bits, jnp.zeros((n, pad), dtype=jnp.uint32)], axis=1)
    bits = bits.reshape(n, w, WORD_BITS)
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)[None, None, :]
    return jnp.sum(bits << shifts, axis=-1, dtype=jnp.uint32)


def _auto_reduce_l(n: int) -> int:
    """Strided-reduction factor: keep >= ~16k candidate slots so the
    birthday-bound top-k loss stays negligible, cap at the kernel's 64."""
    l = max(1, min(n // 16384, 64))
    return 1 << (l.bit_length() - 1)


@functools.partial(jax.jit, static_argnames=("k", "chunk_size", "use_pallas",
                                             "reduce_l", "selection"))
def bq_topk(
    q_words: jnp.ndarray,
    x_words: jnp.ndarray,
    k: int,
    chunk_size: int = 0,
    valid: jnp.ndarray | None = None,
    id_offset: jnp.ndarray | int = 0,
    use_pallas: bool = False,
    reduce_l: int | None = None,
    selection: str = "approx",
    allow_bits: jnp.ndarray | None = None,
):
    """Hamming top-k over packed words: q [B, w] uint32, x [N, w] uint32.

    ``use_pallas`` takes the fused scan kernel (pallas_kernels.
    bq_scan_reduce: ±64-int8 MXU matmul + in-kernel strided block-argmin,
    then one approx_max_k over the N/L survivors). The fallback is a plain
    XLA XOR+popcount pass (small corpora / CPU tests). ``chunk_size`` is
    accepted for API compatibility; the fused kernel supertiles
    internally.

    EXACTNESS: the two paths do NOT return identical result sets. The
    fallback (``use_pallas=False``) is fully exact. The pallas path is
    approximate twice over — the strided block-argmin keeps one winner
    per ``reduce_l`` rows (a true top-k member is dropped whenever two
    winners share a block; birthday-bound loss ~k^2/(2*N/reduce_l)) and
    the survivor selection uses ``approx_max_k`` (recall~0.95 per spec).
    ``reduce_l=1`` removes only the block-argmin loss — with the default
    ``selection="approx"`` the survivor selection still runs approx_max_k,
    so the pallas path never matches the fallback bit-for-bit.
    ``selection="fused"`` replaces that survivor pass with the exact
    in-kernel running-carry fold (pallas_kernels.fused_topk_pairs), so the
    only remaining loss is the block-argmin (and ``reduce_l=1`` + fused is
    bit-exact); k above the 256-wide fused carry falls back to the approx
    pass. Production callers oversample + rescore as
    QuantizedVectorStore does, which absorbs the loss (measured recall
    deltas in PARITY.md).

    ``allow_bits`` [B, ceil(N_512/32)] uint32 adds a per-query allow
    bitmask (pallas_kernels.pack_allow_bitmask layout): the pallas path
    unpacks it subtile-locally in VMEM, the XLA fallback unpacks once and
    folds a per-chunk where.
    """
    from weaviate_tpu.ops.distances import MASKED_DISTANCE
    from weaviate_tpu.ops.topk import topk_smallest

    n, w = x_words.shape
    b = q_words.shape[0]

    if use_pallas:
        from weaviate_tpu.ops.pallas_kernels import bq_scan_reduce
        from weaviate_tpu.ops.topk import select_survivors

        rl = reduce_l if reduce_l is not None else _auto_reduce_l(n)
        vals, ids = bq_scan_reduce(q_words, x_words, valid=valid,
                                   reduce_l=rl, allow_bits=allow_bits)
        return select_survivors(vals, ids, k, selection, id_offset)

    allow_rows = None
    if allow_bits is not None:
        from weaviate_tpu.ops.pallas_kernels import unpack_allow_bitmask

        allow_rows = unpack_allow_bitmask(allow_bits, n)

    # XLA fallback: chunked XOR+popcount pass; pad odd sizes with dead rows
    # so peak memory stays O(B * chunk)
    chunk_size = min(chunk_size or 8192, n)
    if n % chunk_size:
        pad = chunk_size - n % chunk_size
        x_words = jnp.pad(x_words, ((0, pad), (0, 0)))
        valid = ((jnp.arange(n + pad) < n) if valid is None
                 else jnp.pad(valid.astype(bool), (0, pad)))
        if allow_rows is not None:
            allow_rows = jnp.pad(allow_rows, ((0, 0), (0, pad)))
        n += pad
    num_chunks = n // chunk_size
    x_chunks = x_words.reshape(num_chunks, chunk_size, w)
    valid_chunks = None if valid is None else valid.reshape(num_chunks, chunk_size)
    allow_chunks = (
        None if allow_rows is None
        else jnp.moveaxis(
            allow_rows.reshape(b, num_chunks, chunk_size), 1, 0))

    init_d = jnp.full((b, k), MASKED_DISTANCE, dtype=jnp.float32)
    init_i = jnp.full((b, k), -1, dtype=jnp.int32)

    def body(carry, inp):
        best_d, best_i = carry
        chunk_idx, xc, vc, ac = inp
        x_or = jax.lax.bitwise_xor(q_words[:, None, :], xc[None, :, :])
        d = jnp.sum(
            jax.lax.population_count(x_or), axis=-1, dtype=jnp.int32
        ).astype(jnp.float32)
        if vc is not None:
            d = jnp.where(vc[None, :], d, MASKED_DISTANCE)
        if ac is not None:
            d = jnp.where(ac, d, MASKED_DISTANCE)
        ids = (
            chunk_idx * chunk_size
            + id_offset
            + jax.lax.broadcasted_iota(jnp.int32, (1, chunk_size), 1)
        )
        ids = jnp.broadcast_to(ids, (b, chunk_size))
        new_d, new_i = topk_smallest(
            jnp.concatenate([best_d, d], axis=1),
            jnp.concatenate([best_i, ids], axis=1),
            k,
        )
        new_i = jnp.where(new_d >= MASKED_DISTANCE, -1, new_i)
        return (new_d, new_i), None

    chunk_ids = jnp.arange(num_chunks, dtype=jnp.int32)
    if num_chunks == 1:
        (fd, fi), _ = body(
            (init_d, init_i),
            (chunk_ids[0], x_chunks[0],
             None if valid_chunks is None else valid_chunks[0],
             None if allow_chunks is None else allow_chunks[0]),
        )
    else:
        (fd, fi), _ = jax.lax.scan(
            body, (init_d, init_i),
            (chunk_ids, x_chunks, valid_chunks, allow_chunks)
        )
    return fd, fi


@functools.partial(jax.jit, static_argnames=("k", "refine", "use_pallas",
                                             "selection"))
def bq_topk_twostage(
    q_words: jnp.ndarray,
    x_words: jnp.ndarray,
    x_prefix_t: jnp.ndarray,
    k: int,
    refine: int = 8,
    valid: jnp.ndarray | None = None,
    id_offset: jnp.ndarray | int = 0,
    use_pallas: bool = True,
    selection: str = "approx",
    allow_bits: jnp.ndarray | None = None,
):
    """Two-stage BQ scan for the capacity regime.

    Stage 1 scans a CONTIGUOUS transposed prefix array ``x_prefix_t``
    [Wp, N] (the first 32*Wp sign bits of every row, stored separately so
    the scan reads Wp/W of the bytes — column-slicing the full row-major
    code array would still fetch whole HBM lines) and keeps refine*k
    candidates per query. Stage 2 gathers the candidates' FULL rows from
    the row-major ``x_words`` [N, W] (contiguous row gathers) and scores
    exact hamming with one XOR+popcount over [B, R, W]. Exact top-k of
    stage 2 follows; the only approximation is stage-1 candidate recall
    (tunable via ``refine`` and the prefix width). ``selection="fused"``
    makes the stage-1 refine exact too (fused_topk_pairs instead of
    approx_max_k, refine*k <= its 256-wide carry).
    """
    from weaviate_tpu.ops.distances import MASKED_DISTANCE
    from weaviate_tpu.ops.topk import topk_smallest

    n, w = x_words.shape
    wp = x_prefix_t.shape[0]
    b = q_words.shape[0]

    if use_pallas:
        from weaviate_tpu.ops.pallas_kernels import bq_scan_reduce

        # the per-query mask prunes in stage 1: disallowed rows never
        # become candidates, so stage 2 inherits the filter for free
        vals1, ids1 = bq_scan_reduce(
            q_words[:, :wp], x_prefix_t, valid=valid,
            reduce_l=_auto_reduce_l(n), transposed=True,
            allow_bits=allow_bits)
        r = min(refine * k, vals1.shape[1])
        if selection == "fused" and r <= 256:
            from weaviate_tpu.ops.pallas_kernels import fused_topk_pairs

            cand_d1, cand = fused_topk_pairs(vals1, ids1, k=r)
            cand = jnp.where(cand < 0, 0, cand)  # unfilled: masked below
        else:
            negd, pos = jax.lax.approx_max_k(-vals1, r, recall_target=0.95)
            cand_d1 = -negd
            cand = jnp.take_along_axis(ids1, pos, axis=1)  # [B, R] rows
    else:
        # fallback top-k already returns the pruned candidate set, sorted
        cand_d1, ids1 = bq_topk(q_words[:, :wp], x_prefix_t.T,
                                k=min(refine * k, n), valid=valid,
                                use_pallas=False, allow_bits=allow_bits)
        cand = jnp.where(ids1 < 0, 0, ids1)
        r = cand.shape[1]
    # stage 2: full-width exact hamming on the gathered candidates
    xg = x_words[jnp.clip(cand, 0, n - 1)]         # [B, R, W]
    x_or = jax.lax.bitwise_xor(q_words[:, None, :], xg)
    ham = jnp.sum(jax.lax.population_count(x_or), axis=-1,
                  dtype=jnp.int32).astype(jnp.float32)
    ham = jnp.where(cand_d1 >= MASKED_DISTANCE * 0.5, MASKED_DISTANCE, ham)
    kk = min(k, r)
    fd, fi = topk_smallest(ham, cand, kk)
    if kk < k:
        fd = jnp.pad(fd, ((0, 0), (0, k - kk)),
                     constant_values=MASKED_DISTANCE)
        fi = jnp.pad(fi, ((0, 0), (0, k - kk)), constant_values=-1)
    fi = jnp.where(fd >= MASKED_DISTANCE * 0.5, -1, fi + id_offset)
    return fd, fi


def bq_hamming_np(a_words: np.ndarray, b_words: np.ndarray) -> np.ndarray:
    """Host reference: hamming between packed rows [A, w] x [B, w] -> [A, B]."""
    x = np.bitwise_xor(a_words[:, None, :], b_words[None, :, :])
    return np.unpackbits(x.view(np.uint8), axis=-1).sum(-1)
