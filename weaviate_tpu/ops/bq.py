"""Binary quantization (BQ) on TPU.

Reference: adapters/repos/db/vector/compressionhelpers/binary_quantization.go
(:22 — sign bit per dimension packed into uint64 words, hamming distance via
XOR + popcount, with full-precision rescore in the flat index,
vector/flat/index.go:347).

TPU re-design: bits pack into uint32 words (int64 lanes are wasteful on
TPU); hamming runs as `population_count(xor(q, x))` on the VPU over [N, w]
word arrays — one vectorized pass instead of per-pair scalar loops. 32x
HBM compression; candidates are rescored against full-precision vectors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

WORD_BITS = 32


def bq_words(dim: int) -> int:
    return -(-dim // WORD_BITS)


@jax.jit
def bq_encode(vectors: jnp.ndarray) -> jnp.ndarray:
    """Pack sign bits: [N, d] float -> [N, ceil(d/32)] uint32.

    Bit j of word w is set iff vectors[:, w*32+j] >= 0 (reference uses the
    sign bit the same way, binary_quantization.go:30).
    """
    n, d = vectors.shape
    w = bq_words(d)
    pad = w * WORD_BITS - d
    bits = (vectors >= 0).astype(jnp.uint32)
    if pad:
        bits = jnp.concatenate([bits, jnp.zeros((n, pad), dtype=jnp.uint32)], axis=1)
    bits = bits.reshape(n, w, WORD_BITS)
    shifts = jnp.arange(WORD_BITS, dtype=jnp.uint32)[None, None, :]
    return jnp.sum(bits << shifts, axis=-1, dtype=jnp.uint32)


@functools.partial(jax.jit, static_argnames=("k", "chunk_size", "use_pallas"))
def bq_topk(
    q_words: jnp.ndarray,
    x_words: jnp.ndarray,
    k: int,
    chunk_size: int,
    valid: jnp.ndarray | None = None,
    id_offset: jnp.ndarray | int = 0,
    use_pallas: bool = False,
):
    """Hamming top-k over packed words: q [B, w] uint32, x [N, w] uint32.

    XOR + popcount + reduce on the VPU, chunk-scanned like the float path.
    """
    from weaviate_tpu.ops.distances import MASKED_DISTANCE
    from weaviate_tpu.ops.topk import approx_topk_smallest, topk_smallest

    n, w = x_words.shape
    assert n % chunk_size == 0, f"{n} rows not a multiple of {chunk_size}"
    num_chunks = n // chunk_size
    b = q_words.shape[0]

    x_chunks = x_words.reshape(num_chunks, chunk_size, w)
    valid_chunks = None if valid is None else valid.reshape(num_chunks, chunk_size)

    init_d = jnp.full((b, k), MASKED_DISTANCE, dtype=jnp.float32)
    init_i = jnp.full((b, k), -1, dtype=jnp.int32)

    if use_pallas:
        # hoist the loop-invariant query unpack out of the scan body —
        # XLA does not lift computation out of while-loop bodies
        from weaviate_tpu.ops.pallas_kernels import (_SUBLANE, _pad_to,
                                                     bq_queries_to_planes)

        pb = _pad_to(max(b, 1), _SUBLANE)
        q_padded = jnp.pad(q_words, ((0, pb - b), (0, 0))) if pb != b else q_words
        q_planes = bq_queries_to_planes(q_padded, w)
        q_pop = jnp.sum(q_planes.astype(jnp.float32), axis=1, keepdims=True)

    def body(carry, inp):
        best_d, best_i = carry
        chunk_idx, xc, vc = inp
        if use_pallas:
            # MXU path: unpack-in-VMEM + bf16 matmul (pallas_kernels
            # bq_mxu_block) — the VPU popcount kernel loses to the MXU by
            # ~2 orders of magnitude on TPU
            from weaviate_tpu.ops.pallas_kernels import bq_mxu_block

            d = bq_mxu_block(q_words, xc, valid=None, interpret=None,
                             q_planes=q_planes, q_pop=q_pop)
        else:
            x_or = jax.lax.bitwise_xor(q_words[:, None, :], xc[None, :, :])
            d = jnp.sum(
                jax.lax.population_count(x_or), axis=-1, dtype=jnp.int32
            ).astype(jnp.float32)
        if vc is not None:
            d = jnp.where(vc[None, :], d, MASKED_DISTANCE)
        ids = (
            chunk_idx * chunk_size
            + id_offset
            + jax.lax.broadcasted_iota(jnp.int32, (1, chunk_size), 1)
        )
        ids = jnp.broadcast_to(ids, (b, chunk_size))
        # two-stage: approx-select within THIS chunk only (one 0.95-recall
        # invocation per candidate), then EXACT merge of the tiny carried
        # set — carried winners can never be dropped by the approx op
        ck_d, ck_i = approx_topk_smallest(d, ids, min(k, chunk_size))
        ck_d = ck_d.astype(jnp.float32)  # bf16 kernel output -> f32 merge
        new_d, new_i = topk_smallest(
            jnp.concatenate([best_d, ck_d], axis=1),
            jnp.concatenate([best_i, ck_i], axis=1),
            k,
        )
        return (new_d, new_i), None

    chunk_ids = jnp.arange(num_chunks, dtype=jnp.int32)
    if num_chunks == 1:
        (fd, fi), _ = body(
            (init_d, init_i),
            (chunk_ids[0], x_chunks[0],
             None if valid_chunks is None else valid_chunks[0]),
        )
    else:
        (fd, fi), _ = jax.lax.scan(
            body, (init_d, init_i), (chunk_ids, x_chunks, valid_chunks)
        )
    return fd, fi


def bq_hamming_np(a_words: np.ndarray, b_words: np.ndarray) -> np.ndarray:
    """Host reference: hamming between packed rows [A, w] x [B, w] -> [A, B]."""
    x = np.bitwise_xor(a_words[:, None, :], b_words[None, :, :])
    return np.unpackbits(x.view(np.uint8), axis=-1).sum(-1)
