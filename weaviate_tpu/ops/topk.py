"""Top-k selection over large corpora.

The reference merges per-shard results with a host-side sort
(adapters/repos/db/index.go:1644-1648) and maintains per-query binary heaps
in the HNSW hot loop (priorityqueue/queue.go). On TPU, selection is done
with ``jax.lax.top_k`` over distance tiles, with two composition primitives:

- ``chunked_topk``: scan an [N] axis in fixed-size chunks, carrying a running
  top-k — bounds peak memory to O(B * chunk) instead of O(B * N) so a single
  query batch can scan an HBM-resident corpus of any size.
- ``merge_topk``: merge candidate sets (e.g. per-device partial top-k after an
  all_gather over ICI) into a final top-k.

All shapes static; distances follow the "lower = closer" convention.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from weaviate_tpu.ops.distances import MASKED_DISTANCE, pairwise_distance


@functools.partial(jax.jit, static_argnames=("k",))
def topk_smallest(dists: jnp.ndarray, ids: jnp.ndarray, k: int):
    """Smallest-k along the last axis. dists [B,N] f32, ids [N] or [B,N] int32.

    Returns (top_dists [B,k], top_ids [B,k]) sorted ascending by distance.
    """
    neg_d, idx = jax.lax.top_k(-dists, k)
    if ids.ndim == 1:
        top_ids = ids[idx]
    else:
        top_ids = jnp.take_along_axis(ids, idx, axis=-1)
    return -neg_d, top_ids


@functools.partial(jax.jit, static_argnames=("k",))
def approx_topk_smallest(dists: jnp.ndarray, ids: jnp.ndarray, k: int):
    """Approximate smallest-k via the TPU PartialReduce op
    (jax.lax.approx_min_k — the TPU-KNN paper's bucketed-argmin
    instruction; recall_target 0.95 per invocation). The right primitive
    for CANDIDATE generation: exact f32 rescore follows, so a rare
    dropped candidate costs recall epsilon while the selection itself
    stays O(N) with a tiny constant — lax.top_k at k~100 costs ~sort."""
    neg_d, idx = jax.lax.approx_max_k(-dists, k, recall_target=0.95)
    if ids.ndim == 1:
        top_ids = ids[idx]
    else:
        top_ids = jnp.take_along_axis(ids, idx, axis=-1)
    return -neg_d, top_ids


def select_survivors(vals, ids, k: int, selection: str = "approx",
                     id_offset=0):
    """Final selection over a scan-reduce survivor array: vals [B, M] f32
    (dead entries at MASKED_DISTANCE), ids [B, M] i32 global rows.

    The shared tail of the bq/pq4 fused-scan consumers: ``"approx"`` runs
    one ``approx_max_k`` oversample (4x k) + exact merge; ``"fused"`` the
    exact in-kernel running-carry fold (pallas_kernels.fused_topk_pairs,
    k <= its 256-wide carry — larger k falls back to approx). Pads to
    [B, k] with (MASKED_DISTANCE, -1) and applies ``id_offset`` to live
    entries only."""
    ncand = vals.shape[1]
    kk = min(k, ncand)
    if selection == "fused" and kk <= 256:
        from weaviate_tpu.ops.pallas_kernels import fused_topk_pairs

        fd, fi = fused_topk_pairs(vals, ids, k=kk)
    else:
        if ncand > 4 * kk:
            negd, pos = jax.lax.approx_max_k(-vals, min(4 * kk, ncand),
                                             recall_target=0.95)
            vals = -negd
            ids = jnp.take_along_axis(ids, pos, axis=1)
        fd, fi = topk_smallest(vals, ids, kk)
    if kk < k:
        fd = jnp.pad(fd, ((0, 0), (0, k - kk)),
                     constant_values=MASKED_DISTANCE)
        fi = jnp.pad(fi, ((0, 0), (0, k - kk)), constant_values=-1)
    fi = jnp.where(fd >= MASKED_DISTANCE * 0.5, -1, fi + id_offset)
    return fd, fi


@functools.partial(jax.jit, static_argnames=("k", "selection"))
def merge_epoch_topk(parts, slot_maps, k: int, selection: str = "approx"):
    """Cross-epoch candidate merge (engine/epochs.py): the single-device
    twin of the ICI merge — per-epoch survivor sets become one global
    top-k without the distances ever leaving HBM.

    ``parts`` is a tuple of per-epoch ``(d [B, k_e], i [B, k_e])`` pairs
    with EPOCH-LOCAL row ids (-1 dead); ``slot_maps`` a matching tuple of
    ``[cap_e] int32`` local->global slot tables (compaction repacks an
    epoch's rows but keeps global slots stable through its map). Each
    epoch's ids gather through its map, the candidate sets concatenate in
    epoch order (so distance ties resolve to the lower global slot, same
    as a single-buffer scan), and the merge itself is EXACT:
    ``fused_topk_pairs`` (the in-kernel running-carry fold) under
    ``selection="fused"``, ``lax.top_k`` otherwise — per-epoch selection
    error never compounds across epochs, mirroring the chunk-carry
    contract of ``chunked_topk_distances``. Returns ``(d [B, k],
    i [B, k])`` global ids, (MASKED_DISTANCE, -1) padded."""
    mapped_d, mapped_i = [], []
    for (d, i), smap in zip(parts, slot_maps):
        cap = smap.shape[0]
        g = smap[jnp.clip(i, 0, cap - 1)]
        mapped_d.append(d)
        mapped_i.append(jnp.where(i >= 0, g, -1))
    cat_d = jnp.concatenate(mapped_d, axis=1)
    cat_i = jnp.concatenate(mapped_i, axis=1)
    ncand = cat_d.shape[1]
    kk = min(k, ncand)
    if selection == "fused" and kk <= 256:
        from weaviate_tpu.ops.pallas_kernels import fused_topk_pairs

        fd, fi = fused_topk_pairs(cat_d, cat_i, k=kk)
    else:
        fd, fi = topk_smallest(cat_d, cat_i, kk)
    if kk < k:
        fd = jnp.pad(fd, ((0, 0), (0, k - kk)),
                     constant_values=MASKED_DISTANCE)
        fi = jnp.pad(fi, ((0, 0), (0, k - kk)), constant_values=-1)
    fi = jnp.where(fd >= MASKED_DISTANCE * 0.5, -1, fi)
    return fd, fi


@functools.partial(jax.jit, static_argnames=("k",))
def merge_topk(dists: jnp.ndarray, ids: jnp.ndarray, k: int):
    """Merge candidate sets: dists [B, M], ids [B, M] -> top-k of the union.

    Used for the cross-shard reduce: every device contributes its local top-k,
    the [n_shards*k] candidates are all-gathered over ICI, and this picks the
    global winners (replaces the reference's host-side merge+sort+truncate,
    index.go:1644-1648).
    """
    return topk_smallest(dists, ids, k)


@functools.partial(
    jax.jit,
    static_argnames=("k", "chunk_size", "metric", "use_pallas", "selection"),
)
def chunked_topk_distances(
    q: jnp.ndarray,
    x: jnp.ndarray,
    k: int,
    chunk_size: int,
    metric: str = "l2-squared",
    valid: jnp.ndarray | None = None,
    x_sq_norms: jnp.ndarray | None = None,
    id_offset: jnp.ndarray | int = 0,
    use_pallas: bool = False,
    selection: str = "exact",
    allow_bits: jnp.ndarray | None = None,
    allow_rows: jnp.ndarray | None = None,
    row_ids: jnp.ndarray | None = None,
):
    """Brute-force top-k of ``q`` [B,d] against ``x`` [N,d], scanning in chunks.

    ``valid`` is an optional [N] bool mask (live slots / filter AllowList —
    the device-side analog of the reference's roaring-bitmap allow list,
    helpers/allow_list.go:19); invalid slots get MASKED_DISTANCE so they never
    surface. ``id_offset`` shifts local row indices into global id space for
    sharded corpora. N must be a multiple of chunk_size (pad the store, not
    the query path). Returns (dists [B,k], ids [B,k]) ascending.

    ``allow_bits`` adds a PER-QUERY allow bitmask ([B, ceil(N_512/32)]
    uint32, ``pallas_kernels.pack_allow_bitmask`` layout) — the batched
    filtered-search dataplane. The fused path unpacks it tile-locally in
    VMEM; the XLA paths unpack once and fold a [B, chunk] where into each
    tile. ``allow_rows`` ([B, N] bool) is the unpacked equivalent for
    callers that already hold a sliced bool mask (the sharded local path);
    pass at most one of the two.

    ``row_ids`` ([N] int32) remaps scanned row POSITIONS to global ids on
    device before returning — the candidate plane's slot remap
    (ops/candidates.shared_candidates_topk scans a gathered bucket whose
    row r is global slot ``row_ids[r]``; -1 marks bucket padding). Use
    with ``id_offset=0``; winners carrying a -1 row id surface as -1.

    ``selection`` picks the per-chunk candidate selector:

    - ``"exact"``: ``lax.top_k`` over every [B, k+chunk] tile — bit-exact,
      but at k~10-100 a wide top_k costs ~a sort and dominates the scan
      (~95% of device time at 1M rows, VERDICT r2).
    - ``"approx"``: ``lax.approx_max_k`` (the TPU PartialReduce bucketed
      argmin — Chern et al., the TPU-KNN paper) pulls an OVERSAMPLED
      candidate set (4x k) per chunk at O(chunk) with a tiny constant; the
      carried running set is then merged EXACTLY, so selection error never
      compounds across chunks. Distances themselves are exact either way —
      the only approximation is which candidates survive a chunk, and with
      4x oversampling measured recall@10 vs exact is ≥0.999. On non-TPU
      backends XLA lowers approx_max_k to an exact top_k, so CPU tests see
      bit-exact results.
    - ``"fused"``: selection happens INSIDE the Pallas scan kernel
      (pallas_kernels.fused_topk_scan): each grid step folds its VMEM
      distance tile into a per-query running top-k carry, so the [B, N]
      distance matrix never round-trips through HBM and no per-chunk
      top_k/approx_max_k pass exists at all. EXACT top-k semantics (ties
      break like lax.top_k); unfilled slots surface as (MASKED, -1)
      instead of arbitrary dead-row ids. Runs compiled on TPU and through
      the Pallas interpreter elsewhere (tests; too slow to serve from on
      CPU). Requires a Pallas metric and k <= 128 — other metrics fall
      back to ``"exact"`` and k > 128 falls back to ``"approx"``
      (``search_by_distance`` widens k past the carry width).
    """
    n = x.shape[0]
    assert n % chunk_size == 0, f"corpus rows {n} not a multiple of chunk {chunk_size}"
    if selection == "fused":
        from weaviate_tpu.ops.pallas_kernels import (
            _FUSED_TOPK_MAX_K,
            PALLAS_METRICS,
            fused_topk_scan,
        )

        if metric in PALLAS_METRICS and k <= _FUSED_TOPK_MAX_K:
            d, i = fused_topk_scan(
                q, x, k=k, metric=metric, valid=valid,
                x_sq_norms=x_sq_norms, allow_bits=allow_bits,
                allow_rows=allow_rows,
            )
            if row_ids is not None:
                return d, jnp.where(
                    i < 0, i, row_ids[jnp.clip(i, 0, n - 1)])
            return d, jnp.where(i < 0, i, i + id_offset)
        # degrade gracefully: non-Pallas metrics take the exact XLA scan,
        # oversized k the approx per-chunk selection (same recall story)
        selection = "approx" if metric in PALLAS_METRICS else "exact"
    num_chunks = n // chunk_size
    b = q.shape[0]

    if allow_rows is None and allow_bits is not None:
        # one elementwise unpack pass; the per-chunk fold below is then a
        # plain where like the shared-valid one
        from weaviate_tpu.ops.pallas_kernels import unpack_allow_bitmask

        allow_rows = unpack_allow_bitmask(allow_bits, n)
    if allow_rows is not None:
        allow_rows = allow_rows.astype(bool)
        if allow_rows.shape[1] < n:
            allow_rows = jnp.pad(
                allow_rows, ((0, 0), (0, n - allow_rows.shape[1])))
        allow_rows = allow_rows[:, :n]

    x_chunks = x.reshape(num_chunks, chunk_size, x.shape[1])
    valid_chunks = None if valid is None else valid.reshape(num_chunks, chunk_size)
    norm_chunks = (
        None if x_sq_norms is None else x_sq_norms.reshape(num_chunks, chunk_size)
    )
    allow_chunks = (
        None if allow_rows is None
        else jnp.moveaxis(
            allow_rows.reshape(b, num_chunks, chunk_size), 1, 0)
    )

    init_d = jnp.full((b, k), MASKED_DISTANCE, dtype=jnp.float32)
    init_i = jnp.full((b, k), -1, dtype=jnp.int32)

    def body(carry, inp):
        best_d, best_i = carry
        chunk_idx, xc, vc, nc, ac = inp
        if use_pallas:
            # Fused Pallas tile kernel: MXU matmul + mask epilogue in VMEM
            # (ops/pallas_kernels.py) — the TPU stand-in for the reference's
            # SIMD distance asm.
            from weaviate_tpu.ops.pallas_kernels import distance_block

            # interpret=None → compiled on TPU, interpreter elsewhere (tests)
            d = distance_block(
                q, xc, metric=metric, valid=vc, x_sq_norms=nc, interpret=None
            )
        else:
            d = pairwise_distance(q, xc, metric=metric, x_sq_norms=nc)
            if vc is not None:
                d = jnp.where(vc[None, :], d, MASKED_DISTANCE)
        if ac is not None:
            d = jnp.where(ac, d, MASKED_DISTANCE)
        local_ids = (
            chunk_idx * chunk_size
            + id_offset
            + jax.lax.broadcasted_iota(jnp.int32, (1, chunk_size), 1)
        )
        local_ids = jnp.broadcast_to(local_ids, (b, chunk_size))
        if selection == "approx" and chunk_size > 4 * k:
            k_sel = min(max(4 * k, 32), chunk_size)
            neg_c, pos = jax.lax.approx_max_k(-d, k_sel, recall_target=0.95)
            cand_d = -neg_c
            cand_i = jnp.take_along_axis(local_ids, pos, axis=1)
        else:
            cand_d, cand_i = d, local_ids
        cat_d = jnp.concatenate([best_d, cand_d], axis=1)
        cat_i = jnp.concatenate([best_i, cand_i], axis=1)
        new_d, new_i = topk_smallest(cat_d, cat_i, k)
        return (new_d, new_i), None

    chunk_ids = jnp.arange(num_chunks, dtype=jnp.int32)
    xs = (chunk_ids, x_chunks, valid_chunks, norm_chunks, allow_chunks)
    if num_chunks == 1:
        # Avoid scan overhead for small corpora.
        (final_d, final_i), _ = body(
            (init_d, init_i),
            (
                chunk_ids[0],
                x_chunks[0],
                None if valid_chunks is None else valid_chunks[0],
                None if norm_chunks is None else norm_chunks[0],
                None if allow_chunks is None else allow_chunks[0],
            ),
        )
    else:
        (final_d, final_i), _ = jax.lax.scan(body, (init_d, init_i), xs)
    if row_ids is not None:
        final_i = jnp.where(final_i < 0, final_i,
                            row_ids[jnp.clip(final_i, 0, n - 1)])
    return final_d, final_i


def chunked_topk(q, x, k, chunk_size=8192, metric="l2-squared", valid=None,
                 x_sq_norms=None, id_offset=0, selection="exact",
                 allow_bits=None, allow_rows=None):
    """Non-jit convenience wrapper (jit happens inside).

    Unlike the raw kernel, this accepts any corpus size: when ``chunk_size``
    does not divide N the corpus is padded with dead (masked) rows up to the
    next multiple, preserving the O(B*chunk) memory bound. The store path
    keeps capacity chunk-aligned and never pays this copy.
    """
    n = x.shape[0]
    chunk_size = min(chunk_size, n) or 1
    rem = n % chunk_size
    if rem:
        pad = chunk_size - rem
        x = jnp.concatenate([x, jnp.zeros((pad, x.shape[1]), dtype=x.dtype)])
        if valid is None:
            valid = jnp.arange(n + pad) < n
        else:
            valid = jnp.concatenate([valid, jnp.zeros(pad, dtype=valid.dtype)])
        if x_sq_norms is not None:
            x_sq_norms = jnp.concatenate(
                [x_sq_norms, jnp.zeros(pad, dtype=x_sq_norms.dtype)]
            )
    return chunked_topk_distances(
        q, x, k, chunk_size, metric, valid, x_sq_norms, id_offset,
        selection=selection, allow_bits=allow_bits, allow_rows=allow_rows,
    )
