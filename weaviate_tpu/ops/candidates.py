"""Shared device candidate-slot gather/rescore plane (ISSUE 16).

One idea, two memory regimes: score a BOUNDED candidate set instead of
the whole corpus, entirely on device, and return exact top-k over it.
Candidate sets arrive as static padded int32 slot tensors (-1 = empty
slot), so every consumer compiles to the same gather → matmul → fused
top-k shape regardless of how many candidates are actually live:

- ``gather_rescore_topk`` — PER-QUERY candidate sets ``[B, C]`` (IVF
  multi-probe unions, residual-PQ rescore oversets, ISSUE-3 posting
  candidates later): one batched row gather ``[B, C, d]``, one einsum
  distance, masked exact top-k. Per-query allow bitmasks (the PR 3
  block-strided ``allow_bits`` format) fold per CANDIDATE via
  ``allow_bits_for_ids`` — a word gather per slot, never a dense
  ``[B, capacity]`` unpack.
- ``shared_candidates_topk`` — ONE candidate set shared by the whole
  batch (the low-selectivity filter cutover in ``engine/store.py``):
  gather the bucket once ``[C, d]``, run the standard chunked scan over
  the dense bucket, and remap bucket-local winners back to global slots
  ON DEVICE (so the host finish step only pads — no host remap).

The reference engine has no equivalent: its HNSW walk re-reads
neighbours pointer-by-pointer from an in-RAM graph. Here the candidate
set is materialized as one gather so the MXU sees a dense matmul
(SURVEY §7 step 5 — "recast the walk as gather-matmuls").
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from weaviate_tpu.ops.distances import MASKED_DISTANCE, normalize
from weaviate_tpu.ops.pallas_kernels import allow_bits_for_ids
from weaviate_tpu.ops.topk import chunked_topk_distances, topk_smallest


@functools.partial(jax.jit, static_argnames=("k",))
def masked_candidate_topk(vals, ids, k: int):
    """The candidate plane's shared finishing move: exact top-k over
    ``(vals [B, M], ids [B, M])`` where dead entries already carry
    ``MASKED_DISTANCE``, with masked winners normalized to ``-1`` ids so
    every consumer (dense rescore, IVF probe unions, the hybridplane's
    sparse/fused legs) hands the SAME (dist, -1) tail convention to its
    finish step. Ties resolve to the lower index (``lax.top_k``)."""
    fd, fi = topk_smallest(vals, ids, k)
    fi = jnp.where(fd >= MASKED_DISTANCE, -1, fi)
    return fd, fi


@functools.partial(jax.jit, static_argnames=("k", "metric"))
def gather_rescore_topk(q, cand_idx, rows, k: int, metric: str, *,
                        ids_of_row=None, row_norms=None, valid=None,
                        allow_bits=None):
    """Exact top-k over per-query candidate sets, one gather-matmul.

    ``q`` [B, d] f32; ``cand_idx`` [B, C] (or [1, C], broadcast) int32
    gather indices into ``rows`` [N, d]; negative indices are empty
    padding. ``ids_of_row`` [N] int32 optionally maps row positions to
    the GLOBAL ids reported in the result (and folded against
    ``allow_bits``) — IVF passes flattened list positions as
    ``cand_idx`` and ``list_slots`` as ``ids_of_row``; plain rescore
    passes slot ids directly and omits it. ``valid`` [N] bool masks dead
    rows; ``allow_bits`` [B or 1, W] uint32 is the packed per-query
    allow mask over global ids. Returns ``(dists [B, k'], ids [B, k'])``
    ascending with ``k' = min(k, C)``; empty/masked tail is
    ``(MASKED_DISTANCE, -1)``. Cosine queries are normalized here; ``rows`` are
    expected pre-normalized (the store invariant).
    """
    b = q.shape[0]
    n = rows.shape[0]
    c = cand_idx.shape[1]
    idx = jnp.broadcast_to(cand_idx, (b, c))
    safe = jnp.clip(idx, 0, n - 1)
    live = (idx >= 0) & (idx < n)
    g = rows[safe].astype(jnp.float32)                    # [B, C, d]
    q32 = q.astype(jnp.float32)
    if metric in ("cosine", "cosine-dot"):
        q32 = normalize(q32)
    dots = jnp.einsum("bd,bcd->bc", q32, g,
                      preferred_element_type=jnp.float32)
    if metric == "l2-squared":
        if row_norms is not None:
            g_norms = row_norms[safe].astype(jnp.float32)
        else:
            g_norms = jnp.sum(g * g, axis=-1)
        q_norms = jnp.sum(q32 * q32, axis=-1, keepdims=True)
        d = jnp.maximum(q_norms - 2.0 * dots + g_norms, 0.0)
    elif metric == "dot":
        d = -dots
    else:  # cosine family: rows and q unit-norm -> distance 1 - cos
        d = 1.0 - dots
    if ids_of_row is not None:
        ids = jnp.where(live, ids_of_row[safe], -1)
    else:
        ids = jnp.where(live, idx, -1)
    ok = live & (ids >= 0)
    if valid is not None:
        ok = ok & valid[safe]
    if allow_bits is not None:
        ok = ok & allow_bits_for_ids(allow_bits, ids)
    d = jnp.where(ok, d, MASKED_DISTANCE)
    return masked_candidate_topk(d, ids, min(k, c))


def shared_candidates_topk(q, cand_slots, rows, k: int, metric: str, *,
                           row_norms=None, valid=None, use_pallas=False,
                           selection: str = "exact"):
    """Top-k over ONE candidate slot set shared by the whole batch.

    ``cand_slots`` [C] int32 global slots (-1 padding, C a power of
    two); the bucket is gathered ONCE to ``[C, d]`` and scanned with the
    standard chunked kernel (fused Pallas top-k when eligible), then
    bucket-local winner positions remap to global slots on device via
    ``row_ids`` — callers get global ids straight off the handle. This
    is the low-selectivity gathered path: total work is O(B·C), not
    O(B·N), and C tracks the allow-list size.
    """
    n = rows.shape[0]
    slots = jnp.asarray(cand_slots, dtype=jnp.int32)
    safe = jnp.clip(slots, 0, n - 1)
    live = (slots >= 0) & (slots < n)
    g_rows = jnp.where(live[:, None], rows[safe], 0)
    g_valid = live if valid is None else live & valid[safe]
    g_norms = None
    if metric == "l2-squared":
        g_norms = (row_norms[safe].astype(jnp.float32)
                   if row_norms is not None
                   else jnp.sum(g_rows.astype(jnp.float32) ** 2, axis=-1))
    return chunked_topk_distances(
        q, g_rows, k=min(k, slots.shape[0]), chunk_size=slots.shape[0],
        metric=metric, valid=g_valid, x_sq_norms=g_norms,
        use_pallas=use_pallas, selection=selection, row_ids=slots)
