"""Batched distance kernels.

Reference semantics (adapters/repos/db/vector/hnsw/distancer/):
- ``l2-squared``  sum((a-b)^2)                       l2.go:16-24
- ``dot``         -dot(a, b)  (negated so that lower = closer)
                                                     dot_product.go:32-34
- ``cosine``      1 - dot(a, b) with both vectors pre-normalized at insert
                  (the provider is literally "cosine-dot")
                                                     cosine_dist.go:28,44
- ``hamming``     count of positions where a[i] != b[i] (float vectors)
                                                     hamming.go:18-27
- ``manhattan``   sum(|a-b|)                         manhattan.go:20-29

The reference dispatches to per-pair SIMD assembly (AVX2/AVX512/NEON/SVE,
distancer/asm/*.s). On TPU the idiomatic shape is the transpose of that
design: score a whole query block against a whole corpus block in one
matmul-shaped op so the FLOPs land on the 128x128 MXU systolic array.
All functions here are jit-friendly: static shapes, no Python branching on
traced values.

Layout convention: queries ``q`` are [B, d], corpus ``x`` is [N, d], the
result is [B, N] of float32 distances (lower = closer), regardless of the
storage dtype (bf16 storage accumulates in f32 via preferred_element_type).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

DISTANCE_METRICS = ("l2-squared", "dot", "cosine", "cosine-dot", "hamming", "manhattan")

# Distance value used to mask out dead/unfilled corpus slots so they can
# never win a top-k. Finite (not +inf) so sorts and NaN-propagation stay sane.
# Plain Python float: a jnp constant here would initialize the JAX backend
# at import time.
MASKED_DISTANCE = float(np.float32(3.0e38))


def normalize(v: jnp.ndarray, eps: float = 1e-30) -> jnp.ndarray:
    """L2-normalize along the last axis (reference: distancer/normalize.go:16).

    Zero vectors are passed through unchanged rather than producing NaN.
    """
    norm = jnp.linalg.norm(v, axis=-1, keepdims=True)
    return v / jnp.where(norm > eps, norm, 1.0)


def normalize_np(v, eps: float = 1e-30):
    """Host-side twin of ``normalize`` for numpy operands that STAY on
    the host (IVF's f32 mirror, PQ rescore queries): same zero-vector
    semantics, no device round-trip — ``np.asarray(normalize(
    jnp.asarray(v)))`` costs two transfers and a dispatch just to divide
    by a norm (graftlint G1 catches exactly that pattern)."""
    v = np.asarray(v, dtype=np.float32)
    norm = np.linalg.norm(v, axis=-1, keepdims=True)
    return v / np.where(norm > eps, norm, np.float32(1.0))


def _dot_matrix(q: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """[B,d]x[N,d] -> [B,N] inner products, f32 accumulation on the MXU.

    Precision: when both operands are f32 we request HIGHEST so XLA does the
    multi-pass f32-accurate matmul — parity with the reference's exact f32
    SIMD kernels (SURVEY §7 hard part #5: recall drift). When the store holds
    bf16 (the fast path), the single-pass MXU matmul is used as-is.
    """
    f32_exact = q.dtype == jnp.float32 and x.dtype == jnp.float32
    return jax.lax.dot_general(
        q,
        x,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST if f32_exact else jax.lax.Precision.DEFAULT,
    )


def _sq_norms(x: jnp.ndarray) -> jnp.ndarray:
    x32 = x.astype(jnp.float32)
    return jnp.sum(x32 * x32, axis=-1)


@functools.partial(jax.jit, static_argnames=("metric",))
def pairwise_distance(
    q: jnp.ndarray,
    x: jnp.ndarray,
    metric: str = "l2-squared",
    x_sq_norms: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Distances between every query in ``q`` [B,d] and every row of ``x`` [N,d].

    Returns [B, N] float32; lower = closer for every metric (dot is negated,
    matching the reference's convention so one top-k works for all metrics).

    ``x_sq_norms`` is an optional precomputed [N] array of squared row norms
    of ``x`` — the corpus-side term of the l2 expansion. The vector store
    maintains it incrementally so a query only computes the [B]-sized query
    norms + one matmul.
    """
    if metric not in DISTANCE_METRICS:
        raise ValueError(f"unknown distance metric {metric!r}; expected one of {DISTANCE_METRICS}")

    if metric == "l2-squared":
        # ||q-x||^2 = ||q||^2 - 2 q.x + ||x||^2 : one MXU matmul + rank-1 terms,
        # instead of the O(N*d) subtract-square-reduce the reference asm does
        # per pair. Clamp at 0 to hide cancellation error for near-identical rows.
        dots = _dot_matrix(q, x)
        qn = _sq_norms(q)[:, None]
        xn = (_sq_norms(x) if x_sq_norms is None else x_sq_norms.astype(jnp.float32))[None, :]
        return jnp.maximum(qn - 2.0 * dots + xn, 0.0)

    if metric == "dot":
        return -_dot_matrix(q, x)

    if metric in ("cosine", "cosine-dot"):
        # Vectors are pre-normalized at insert time (reference normalizes in
        # the store path); queries are normalized here for safety.
        return 1.0 - _dot_matrix(normalize(q.astype(jnp.float32)), x)

    if metric == "hamming":
        # Elementwise compare + popcount-style reduce. VPU op; no MXU use.
        # Compare in the *storage* dtype: with a bf16 store, an f32 query
        # would never equal its own bf16-rounded row after promotion.
        neq = (q.astype(x.dtype)[:, None, :] != x[None, :, :]).astype(jnp.float32)
        return jnp.sum(neq, axis=-1)

    # manhattan
    diff = jnp.abs(q[:, None, :].astype(jnp.float32) - x[None, :, :].astype(jnp.float32))
    return jnp.sum(diff, axis=-1)


@functools.partial(jax.jit, static_argnames=("metric",))
def single_distance(a: jnp.ndarray, b: jnp.ndarray, metric: str = "l2-squared") -> jnp.ndarray:
    """Distance between two single vectors [d],[d] -> scalar f32.

    Parity with the reference's ``SingleDist`` (distancer/provider.go) used in
    tests and PQ training.
    """
    return pairwise_distance(a[None, :], b[None, :], metric=metric)[0, 0]
