"""Product quantization (PQ) on TPU.

Reference: adapters/repos/db/vector/compressionhelpers/product_quantization.go
(ProductQuantizer: Fit :372, Encode :420, per-query DistanceLookUpTable
:33-151 with LUT ``Distance`` :440) trained by kmeans.go / tile_encoder.go.

TPU re-design: the reference's per-query lookup table + per-pair code gather
is a scalar-gather workload that would starve the MXU. Because PQ segments
are orthogonal, the asymmetric distance

    sum_m LUT[m, code[n, m]]     (reference product_quantization.go:440)

is *exactly* ``dist(q, x_hat_n)`` where ``x_hat_n`` is the vector
reconstructed from centroids. So compressed search becomes:

    per chunk: gather codes -> reconstruct [chunk, d] -> one distance matmul

The reconstruction gather is per-*chunk* (amortized over the whole query
batch), and the distance is the same MXU matmul as the uncompressed path,
reading 16-64x fewer HBM bytes (codes are m uint8s instead of d floats).
Identical results to LUT-ADC, radically better TPU utilization.

k-means fit runs as batched Lloyd iterations over all segments at once
(einsum over [N, m, ds]), chunk-scanned so HBM never holds [N, m, k].
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class PQCodebook(NamedTuple):
    """centroids [m, k, ds] f32 — m segments, k centroids each, ds = d/m."""

    centroids: jnp.ndarray

    @property
    def m(self) -> int:
        return self.centroids.shape[0]

    @property
    def k(self) -> int:
        return self.centroids.shape[1]

    @property
    def ds(self) -> int:
        return self.centroids.shape[2]

    @property
    def dim(self) -> int:
        return self.m * self.ds


def default_pq_segments(dim: int, pq_centroids: int = 16) -> int:
    """Segment-count policy shared by every PQ surface: 4-bit codes target
    1 bit/dim (m = d/4), 8-bit codes 1 byte per 8 dims; m must divide d
    for the orthogonal-segment ADC."""
    target = max(1, dim // (4 if pq_centroids <= 16 else 8))
    while dim % target:
        target -= 1
    return target


def _seg_view(vectors: jnp.ndarray, m: int) -> jnp.ndarray:
    n, d = vectors.shape
    assert d % m == 0, f"dim {d} not divisible by {m} segments"
    return vectors.reshape(n, m, d // m)


@functools.partial(jax.jit, static_argnames=("m",))
def _assign(vectors, centroids, m: int):
    """Nearest centroid per segment: [N, m] int32."""
    vs = _seg_view(vectors.astype(jnp.float32), m)  # [N, m, ds]
    # ||v - c||^2 = ||v||^2 - 2 v.c + ||c||^2 ; argmin over k drops ||v||^2
    dots = jnp.einsum(
        "nms,mks->nmk", vs, centroids, preferred_element_type=jnp.float32
    )
    cn = jnp.sum(centroids * centroids, axis=-1)  # [m, k]
    return jnp.argmin(cn[None, :, :] - 2.0 * dots, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("m", "k"))
def _lloyd_step(vectors, centroids, m: int, k: int):
    """One Lloyd iteration over every segment at once."""
    vs = _seg_view(vectors.astype(jnp.float32), m)
    assign = _assign(vectors, centroids, m)  # [N, m]
    one_hot = jax.nn.one_hot(assign, k, dtype=jnp.float32)  # [N, m, k]
    sums = jnp.einsum(
        "nmk,nms->mks", one_hot, vs, preferred_element_type=jnp.float32
    )
    counts = jnp.sum(one_hot, axis=0)  # [m, k]
    fresh = sums / jnp.maximum(counts, 1.0)[:, :, None]
    # keep the old centroid for empty clusters
    return jnp.where((counts > 0)[:, :, None], fresh, centroids)


def pq_fit(
    vectors: np.ndarray,
    m: int,
    k: int = 256,
    iters: int = 8,
    sample: int = 65536,
    seed: int = 0,
) -> PQCodebook:
    """Train a PQ codebook (reference Fit, product_quantization.go:372).

    Trains on a random sample (the reference also caps its training set);
    all ``m`` segments train in parallel on device.
    """
    vectors = np.asarray(vectors, dtype=np.float32)
    n, d = vectors.shape
    if n < k:
        raise ValueError(f"need >= {k} vectors to train k={k} PQ, have {n}")
    rng = np.random.default_rng(seed)
    if n > sample:
        vectors = vectors[rng.choice(n, sample, replace=False)]
        n = sample
    # init: k distinct data points per segment
    init_idx = rng.choice(n, k, replace=False)
    centroids = jnp.asarray(
        _seg_view(jnp.asarray(vectors), m)[init_idx].transpose(1, 0, 2)
    )  # [m, k, ds]
    x = jnp.asarray(vectors)
    for _ in range(iters):
        centroids = _lloyd_step(x, centroids, m, k)
    # the codebook stays a device array (pq_encode reads it on device) —
    # blocking here only serialized training against the host for no
    # reader; any deferred device error surfaces at first encode
    return PQCodebook(centroids=centroids)


def pq_encode(codebook: PQCodebook, vectors: np.ndarray, batch: int = 65536) -> np.ndarray:
    """Encode vectors -> codes [N, m] uint8 (reference Encode :420)."""
    from weaviate_tpu.runtime import tracing  # lazy: ops must not pull runtime at import

    vectors = np.asarray(vectors, dtype=np.float32)
    out = np.empty((len(vectors), codebook.m), dtype=np.uint8)
    for s in range(0, len(vectors), batch):
        chunk = jnp.asarray(vectors[s : s + batch])
        (codes,) = tracing.d2h(_assign(chunk, codebook.centroids, codebook.m))
        out[s : s + batch] = codes.astype(np.uint8)
    return out


@functools.partial(jax.jit, static_argnames=("m",))
def pq_reconstruct(codes: jnp.ndarray, centroids: jnp.ndarray, m: int):
    """codes [N, m] uint8 -> x_hat [N, d] f32 via per-segment centroid gather.

    This is the decompression half of the gather-matmul: the gather indexes
    tiny [k, ds] tables and is amortized over the whole query batch.
    """
    idx = codes.astype(jnp.int32)  # [N, m]
    # vmap the per-segment table lookup over segments
    gathered = jax.vmap(
        lambda table, ix: jnp.take(table, ix, axis=0), in_axes=(0, 1), out_axes=1
    )(centroids, idx)  # [N, m, ds]
    n = codes.shape[0]
    return gathered.reshape(n, m * centroids.shape[2])


@functools.partial(
    jax.jit, static_argnames=("k", "chunk_size", "metric", "m")
)
def pq_topk(
    q: jnp.ndarray,
    codes: jnp.ndarray,
    centroids: jnp.ndarray,
    k: int,
    chunk_size: int,
    metric: str = "l2-squared",
    valid: jnp.ndarray | None = None,
    id_offset: jnp.ndarray | int = 0,
    m: int | None = None,
    allow_bits: jnp.ndarray | None = None,
):
    """Compressed brute-force top-k: scan codes in chunks, reconstruct, score.

    Matches LUT-ADC results exactly for l2-squared/dot/cosine (orthogonal
    segments). Returns (dists [B,k], ids [B,k]) like chunked_topk.
    ``allow_bits`` adds a per-query packed allow bitmask, unpacked once
    and folded per chunk like the shared ``valid``.
    """
    from weaviate_tpu.ops.distances import MASKED_DISTANCE, pairwise_distance
    from weaviate_tpu.ops.topk import approx_topk_smallest, topk_smallest

    m = m or centroids.shape[0]
    n = codes.shape[0]
    assert n % chunk_size == 0, f"codes rows {n} not a multiple of {chunk_size}"
    num_chunks = n // chunk_size
    b = q.shape[0]

    code_chunks = codes.reshape(num_chunks, chunk_size, m)
    valid_chunks = None if valid is None else valid.reshape(num_chunks, chunk_size)
    allow_chunks = None
    if allow_bits is not None:
        from weaviate_tpu.ops.pallas_kernels import unpack_allow_bitmask

        allow_chunks = jnp.moveaxis(
            unpack_allow_bitmask(allow_bits, n).reshape(
                b, num_chunks, chunk_size), 1, 0)

    init_d = jnp.full((b, k), MASKED_DISTANCE, dtype=jnp.float32)
    init_i = jnp.full((b, k), -1, dtype=jnp.int32)

    def body(carry, inp):
        best_d, best_i = carry
        chunk_idx, cc, vc, ac = inp
        x_hat = pq_reconstruct(cc, centroids, m)
        d = pairwise_distance(q, x_hat, metric=metric)
        if vc is not None:
            d = jnp.where(vc[None, :], d, MASKED_DISTANCE)
        if ac is not None:
            d = jnp.where(ac, d, MASKED_DISTANCE)
        ids = (
            chunk_idx * chunk_size
            + id_offset
            + jax.lax.broadcasted_iota(jnp.int32, (1, chunk_size), 1)
        )
        ids = jnp.broadcast_to(ids, (b, chunk_size))
        # two-stage: approx-select within THIS chunk only (one 0.95-recall
        # invocation per candidate), then EXACT merge of the tiny carried
        # set — carried winners can never be dropped by the approx op
        ck_d, ck_i = approx_topk_smallest(d, ids, min(k, chunk_size))
        ck_d = ck_d.astype(jnp.float32)  # bf16 kernel output -> f32 merge
        new_d, new_i = topk_smallest(
            jnp.concatenate([best_d, ck_d], axis=1),
            jnp.concatenate([best_i, ck_i], axis=1),
            k,
        )
        return (new_d, new_i), None

    chunk_ids = jnp.arange(num_chunks, dtype=jnp.int32)
    if num_chunks == 1:
        (fd, fi), _ = body(
            (init_d, init_i),
            (chunk_ids[0], code_chunks[0],
             None if valid_chunks is None else valid_chunks[0],
             None if allow_chunks is None else allow_chunks[0]),
        )
    else:
        (fd, fi), _ = jax.lax.scan(
            body, (init_d, init_i),
            (chunk_ids, code_chunks, valid_chunks, allow_chunks)
        )
    return fd, fi


# -- 4-bit PQ (k<=16): ADC as one MXU matmul per tile ------------------------
#
# The TPU-first operating point: 16 centroids let the per-query lookup
# table ride the MXU (ops/pallas_kernels.pq4_lut_block builds a one-hot in
# VMEM and contracts it against the LUT — mk = 4d FLOPs/row at m = d/4)
# while codes stay 8-32x smaller than bf16 rows in HBM. Exactly the
# reference's DistanceLookUpTable semantics (product_quantization.go:
# 33-151, Distance :440) with the scalar gather turned into a matmul.


def quantize_lut_int8(lut: jnp.ndarray):
    """Per-query int8 quantization of ADC tables, code-major flattened.

    lut [B, m, kc] f32 -> (lut8 [B, kc*m] int8 with lane order c*m + s —
    the order pltpu.repeat / jnp.tile copy-major one-hots produce —
    scale [B] f32). Rank-preserving within each query (one shared scale);
    inverse: adc = dots / scale. Shared by the pq4 scan kernel and the
    IVF probe so the clamp/flatten conventions cannot drift apart.
    """
    b, m, kc = lut.shape
    scale = 127.0 / jnp.maximum(
        jnp.max(jnp.abs(lut.reshape(b, -1)), axis=1), 1e-20)
    lut8 = jnp.clip(jnp.round(lut * scale[:, None, None]), -127, 127)
    lut8 = jnp.transpose(lut8, (0, 2, 1)).reshape(b, kc * m)
    return lut8.astype(jnp.int8), scale


@functools.partial(jax.jit, static_argnames=("metric", "m"))
def pq_lut(q: jnp.ndarray, centroids: jnp.ndarray, metric: str, m: int):
    """Per-query ADC lookup tables: [B, m, k] f32.

    l2-squared: LUT[b,s,c] = ||q_seg[b,s] - centroids[s,c]||^2  (exact ADC)
    dot:        LUT[b,s,c] = -q_seg . c
    cosine:     1 - q.x_hat with the +1 folded into segment 0 (constant
                shift per code value keeps the sum exact)
    """
    qs = _seg_view(q.astype(jnp.float32), m)  # [B, m, ds]
    dots = jnp.einsum("bms,mks->bmk", qs, centroids,
                      preferred_element_type=jnp.float32)
    if metric == "l2-squared":
        qn = jnp.sum(qs * qs, axis=-1)  # [B, m]
        cn = jnp.sum(centroids * centroids, axis=-1)  # [m, k]
        return qn[:, :, None] - 2.0 * dots + cn[None, :, :]
    if metric == "dot":
        return -dots
    # cosine / cosine-dot: operands normalized by the caller
    lut = -dots
    return lut.at[:, 0, :].add(1.0)


@functools.partial(jax.jit, static_argnames=("k", "refine", "metric", "m",
                                             "use_pallas",
                                             "chunk_budget_bytes",
                                             "selection"))
def pq_topk_twostage(
    q: jnp.ndarray,
    q_prefix_words: jnp.ndarray,
    codes: jnp.ndarray,
    centroids: jnp.ndarray,
    prefix_t: jnp.ndarray,
    k: int,
    refine: int = 8,
    metric: str = "l2-squared",
    valid: jnp.ndarray | None = None,
    id_offset: jnp.ndarray | int = 0,
    m: int | None = None,
    use_pallas: bool = True,
    chunk_budget_bytes: int = 128 << 20,
    selection: str = "approx",
    allow_bits: jnp.ndarray | None = None,
):
    """Two-stage PQ scan (the r4 verdict's "extend the prefix idea to PQ").

    An exhaustive ADC scan pays 2*B*N*d MXU FLOPs no matter how small the
    codes (BASELINE r4 roofline note) — pruning is the only way under it.
    Stage 1 scans a 128/256-bit transposed BQ SIGN prefix (built from the
    raw vectors at insert, ops/bq semantics; int8-MXU hamming via
    bq_scan_reduce) and keeps refine*k candidates; stage 2 gathers those
    candidates' PQ codes, reconstructs them with a one-hot MXU matmul
    against the shared codebook (per-query LUT gathers and tiny-table
    takes are the measured TPU anti-patterns — 80x/7x slower), and
    scores the reconstructions directly. On TPU the codebook rides the
    matmul in bf16, so stage-2 distances carry ~2^-8 relative rounding —
    ordering noise absorbed by the oversampled candidate set and the
    caller's exact rescore (QuantizedVectorStore.search); the CPU path
    is f32. The full code array is only touched at R = refine*k rows
    per query.
    """
    from weaviate_tpu.ops import bq as bq_ops
    from weaviate_tpu.ops.distances import MASKED_DISTANCE
    from weaviate_tpu.ops.topk import topk_smallest

    n = codes.shape[0]
    m = m or centroids.shape[0]

    if use_pallas:
        from weaviate_tpu.ops.pallas_kernels import bq_scan_reduce

        # per-query mask prunes in stage 1; stage 2 only sees allowed rows
        vals1, ids1 = bq_scan_reduce(
            q_prefix_words, prefix_t, valid=valid,
            reduce_l=bq_ops._auto_reduce_l(n), transposed=True,
            allow_bits=allow_bits)
        r = min(refine * k, vals1.shape[1])
        if selection == "fused" and r <= 256:
            # exact stage-1 refine via the in-kernel running-carry fold
            from weaviate_tpu.ops.pallas_kernels import fused_topk_pairs

            cand_d1, cand = fused_topk_pairs(vals1, ids1, k=r)
            cand = jnp.where(cand < 0, 0, cand)  # unfilled: masked below
        else:
            negd, pos = jax.lax.approx_max_k(-vals1, r, recall_target=0.95)
            cand_d1 = -negd
            cand = jnp.take_along_axis(ids1, pos, axis=1)  # [B, R] rows
    else:
        cand_d1, ids1 = bq_ops.bq_topk(
            q_prefix_words, prefix_t.T, k=min(refine * k, n), valid=valid,
            use_pallas=False, allow_bits=allow_bits)
        cand = jnp.where(ids1 < 0, 0, ids1)
        r = cand.shape[1]

    b = q.shape[0]
    cg = codes[jnp.clip(cand, 0, n - 1)]  # [B, R, m]
    kc = centroids.shape[1]
    # the CPU backend lacks the bf16 x bf16 -> f32 dot; TPU takes bf16
    dt = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    cent_dt = centroids.astype(dt)
    qn = jnp.sum(q * q, -1)[:, None]

    def score_chunk(cg_c):  # [B, Rc, m] -> [B, Rc]
        rc_ = cg_c.shape[1]
        oh = jax.nn.one_hot(cg_c.reshape(b * rc_, m).astype(jnp.int32),
                            kc, dtype=dt)
        x_hat = jnp.einsum(
            "rmk,mks->rms", oh, cent_dt,
            preferred_element_type=jnp.float32).reshape(b, rc_, -1)
        if metric == "l2-squared":
            return (qn - 2.0 * jnp.einsum(
                "bd,brd->br", q, x_hat,
                preferred_element_type=jnp.float32)
                + jnp.sum(x_hat * x_hat, -1))
        if metric == "dot":
            return -jnp.einsum("bd,brd->br", q, x_hat,
                               preferred_element_type=jnp.float32)
        # cosine / cosine-dot: operands normalized by the caller
        return 1.0 - jnp.einsum("bd,brd->br", q, x_hat,
                                preferred_element_type=jnp.float32)

    # bound the one-hot transient ([B*Rc, m, kc]) — at 8-bit PQ (kc=256)
    # and large B the unchunked tensor reaches gigabytes
    rc = max(1, min(r, chunk_budget_bytes // max(1, b * m * kc * 2)))
    if rc >= r:
        d2 = score_chunk(cg)
    else:
        n_chunks = (r + rc - 1) // rc
        pad = n_chunks * rc - r
        cg_p = jnp.pad(cg, ((0, 0), (0, pad), (0, 0)))
        parts = jnp.transpose(
            cg_p.reshape(b, n_chunks, rc, m), (1, 0, 2, 3))
        d2 = jax.lax.map(score_chunk, parts)  # [n_chunks, B, rc]
        d2 = jnp.transpose(d2, (1, 0, 2)).reshape(b, -1)[:, :r]
    d2 = jnp.where(cand_d1 >= MASKED_DISTANCE * 0.5, MASKED_DISTANCE, d2)
    kk = min(k, r)
    fd, fi = topk_smallest(d2, cand, kk)
    if kk < k:
        fd = jnp.pad(fd, ((0, 0), (0, k - kk)),
                     constant_values=MASKED_DISTANCE)
        fi = jnp.pad(fi, ((0, 0), (0, k - kk)), constant_values=-1)
    fi = jnp.where(fd >= MASKED_DISTANCE * 0.5, -1, fi + id_offset)
    return fd, fi


@functools.partial(jax.jit, static_argnames=("k", "chunk_size", "metric", "m",
                                             "reduce_l", "selection"))
def pq4_topk(
    q: jnp.ndarray,
    codes: jnp.ndarray,
    centroids: jnp.ndarray,
    k: int,
    chunk_size: int = 0,
    metric: str = "l2-squared",
    valid: jnp.ndarray | None = None,
    id_offset: jnp.ndarray | int = 0,
    m: int | None = None,
    reduce_l: int | None = None,
    selection: str = "approx",
    allow_bits: jnp.ndarray | None = None,
):
    """Compressed brute-force top-k over 4-bit codes via the fused ADC scan
    kernel (pallas_kernels.pq4_scan_reduce: per-query int8 LUT, one-hot
    int8 matmul, in-kernel strided block-argmin), then a survivor
    selection over the ~N/L candidates and an exact final top-k.
    ``selection="approx"`` (default) runs one approx_max_k over the
    survivors; ``"fused"`` folds them through the exact in-kernel
    running-carry top-k (pallas_kernels.fused_topk_pairs) instead. Same
    contract as pq_topk; ``chunk_size`` is accepted for API
    compatibility."""
    from weaviate_tpu.ops.bq import _auto_reduce_l
    from weaviate_tpu.ops.pallas_kernels import pq4_scan_reduce

    m = m or centroids.shape[0]
    n = codes.shape[0]
    lut = pq_lut(q, centroids, metric, m)  # [B, m, k]
    rl = reduce_l if reduce_l is not None else _auto_reduce_l(n)
    vals, ids = pq4_scan_reduce(lut, codes, valid=valid, reduce_l=rl,
                                allow_bits=allow_bits)
    from weaviate_tpu.ops.topk import select_survivors

    return select_survivors(vals, ids, k, selection, id_offset)
