"""Pallas TPU kernels for the distance hot path.

The reference's only native code is per-pair SIMD assembly for vector
distances (adapters/repos/db/vector/hnsw/distancer/asm/*.s — AVX2/AVX512/
NEON/SVE dot, l2, hamming; runtime dispatch in distancer/l2_amd64.go:19-25).
These kernels are the TPU equivalent, transposed to the hardware's shape:
instead of one query×one vector at a time, a whole query block is scored
against a corpus tile in one fused kernel so the FLOPs land on the 128x128
MXU and the mask/bias epilogue rides along in VMEM without an extra HBM
round-trip.

Kernels:

- ``distance_block``    fused [B,d]x[TILE,d] -> [B,TILE] distance + validity
                        mask epilogue (l2-squared / dot / cosine). One MXU
                        matmul per tile; the (1-valid)*MASKED epilogue fuses
                        into the same VMEM residency.
- ``bq_hamming_block``  packed binary-quantized hamming: uint32 XOR +
                        popcount + reduce (reference: BQ hamming over uint64
                        words, compressionhelpers/binary_quantization.go:22).
                        VPU-bound — kept for conformance; the fast path is:
- ``bq_mxu_block``      hamming VIA THE MXU: packed sign bits unpack to 0/1
                        planes in VMEM (shift+mask, zero extra HBM traffic)
                        and hamming(q,x) = |q| + |x| - 2*q.x becomes one
                        bf16 matmul. The MXU runs ~2 orders faster than the
                        VPU popcount loop, so "bit tricks" lose to matmuls
                        on TPU; HBM reads stay d/8 bytes per row (16x less
                        than bf16).
- ``pq4_lut_block``     4-bit-PQ ADC scan: per-query LUTs [B, k*m] hit the
                        codes through an in-VMEM one-hot (pltpu.repeat +
                        lane-iota compare) and ONE bf16 matmul — exact
                        LUT-ADC semantics (reference DistanceLookUpTable,
                        product_quantization.go:440) at mk=4d FLOPs/row with
                        m=d/4 codes reading 8-32x fewer HBM bytes per row.

On CPU (tests, dev) the kernels run through the Pallas interpreter —
bit-identical semantics, no Mosaic compile. ``recommended()`` says whether
the compiled path is worth it on the current backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from weaviate_tpu.ops.distances import MASKED_DISTANCE

# Metrics with an MXU-shaped Pallas kernel. hamming-on-floats and manhattan
# stay on the XLA path (elementwise 3D intermediates — VPU-bound either way,
# nothing for a hand kernel to win).
PALLAS_METRICS = ("l2-squared", "dot", "cosine", "cosine-dot")

_LANE = 128  # TPU lane width: last dim of every tile.
_SUBLANE = 8  # f32 sublane count: second-to-last dim multiple.


def recommended() -> bool:
    """True when compiled Pallas kernels should be used (TPU backend)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pad_to(n: int, m: int) -> int:
    return (n + m - 1) // m * m


# -- per-query allow bitmasks -------------------------------------------------
#
# Filtered BATCHED search: each query row carries its own packed allow
# bitmask so B filtered requests share one device program (the reference
# consumes one AllowList per query inside the scan, helpers/allow_list.go).
# A [B, N] f32 mask would multiply the kernel's per-tile input traffic by
# B; packed words cost B*N/8 bytes total and unpack tile-locally in VMEM.
#
# Layout is BLOCK-STRIDED to match the kernels' in-VMEM unpack (the same
# pltpu.repeat + lane-iota-shift idiom the BQ kernels use for bit planes):
# within each MASK_BLOCK-column block, the block's W = MASK_BLOCK/32 words
# hold   bit j of word w  =  allow[block_base + j*W + w],
# so ``pltpu.repeat(words, 32, axis=1)`` (lane l -> word l % W) followed by
# a ``lane_iota // W`` shift lands allow[block_base + l] on lane l exactly
# — no in-kernel gather, no data permutation. Every masked kernel consumes
# whole MASK_BLOCK-column blocks (tiles/subtiles are forced 512-aligned
# when a mask is present), so one fixed layout serves them all.

MASK_BLOCK = 512
_MASK_WORDS = MASK_BLOCK // 32  # 16 words per block


def mask_pad_cols(n: int) -> int:
    """Packed-mask column count covering ``n`` corpus rows."""
    return _pad_to(max(n, 1), MASK_BLOCK)


def pack_allow_bitmask(allow, n_cols: int | None = None):
    """Host-side packer: allow [B, C] (or [C]) bool -> uint32
    [B, n_cols // 32] in block-strided order. Columns past C pack as 0
    (disallowed — they are dead padding either way)."""
    import numpy as np

    allow = np.asarray(allow, dtype=bool)
    if allow.ndim == 1:
        allow = allow[None, :]
    b, c = allow.shape
    if n_cols is None:
        n_cols = mask_pad_cols(c)
    buf = np.zeros((b, n_cols), dtype=bool)
    keep = min(c, n_cols)
    buf[:, :keep] = allow[:, :keep]
    a = buf.reshape(b, n_cols // MASK_BLOCK, 32, _MASK_WORDS)
    shifts = np.arange(32, dtype=np.uint32)[None, None, :, None]
    words = (a.astype(np.uint32) << shifts).sum(axis=2, dtype=np.uint32)
    return words.reshape(b, n_cols // 32)


def pack_allow_bitmask_jnp(allow: jnp.ndarray) -> jnp.ndarray:
    """Traceable twin of ``pack_allow_bitmask`` for on-device packing
    (the sharded path packs each shard's column slice locally)."""
    b, c = allow.shape
    n_cols = mask_pad_cols(c)
    allow = allow.astype(bool)
    if n_cols != c:
        allow = jnp.pad(allow, ((0, 0), (0, n_cols - c)))
    a = allow.reshape(b, n_cols // MASK_BLOCK, 32, _MASK_WORDS)
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, None, :, None]
    words = jnp.sum(a.astype(jnp.uint32) << shifts, axis=2)
    return words.astype(jnp.uint32).reshape(b, n_cols // 32)


def unpack_allow_bitmask(bits: jnp.ndarray, n_cols: int | None = None):
    """Inverse of the packer: [B, W] uint32 -> [B, n_cols] bool. Traceable
    (the XLA fallback scans unpack once and apply a plain where)."""
    b, w_total = bits.shape
    total = w_total * 32
    bits = jnp.asarray(bits)
    a = bits.reshape(b, total // MASK_BLOCK, 1, _MASK_WORDS)
    shifts = jnp.arange(32, dtype=jnp.uint32)[None, None, :, None]
    cols = ((a >> shifts) & jnp.uint32(1)).reshape(b, total)
    out = cols.astype(bool)
    if n_cols is not None and n_cols != total:
        out = (out[:, :n_cols] if n_cols < total else
               jnp.pad(out, ((0, 0), (0, n_cols - total))))
    return out


def allow_bits_for_ids(bits: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    """Per-CANDIDATE allow lookup in the block-strided packed layout.

    ``bits`` [Ba, W] uint32 (``Ba == 1`` broadcasts over the batch),
    ``ids`` [B, C] int32 global column ids -> [B, C] bool. This is the
    candidate plane's fold (ops/candidates.py): instead of unpacking a
    dense [B, capacity] mask, each candidate gathers its ONE word —
    column c lives at word ``(c // MASK_BLOCK) * W_blk + (c % MASK_BLOCK)
    % W_blk``, bit ``(c % MASK_BLOCK) // W_blk`` (the packer's
    block-strided order above). Ids outside [0, 32·W) — including the -1
    empty-slot sentinel — read as disallowed, matching the packer's
    zeros-past-C convention.
    """
    b, c = ids.shape
    n_cols = bits.shape[1] * 32
    safe = jnp.clip(ids, 0, n_cols - 1)
    off = safe % MASK_BLOCK
    word = (safe // MASK_BLOCK) * _MASK_WORDS + (off % _MASK_WORDS)
    bit = (off // _MASK_WORDS).astype(jnp.uint32)
    wb = jnp.broadcast_to(jnp.asarray(bits, dtype=jnp.uint32),
                          (b, bits.shape[1]))
    w = jnp.take_along_axis(wb, word, axis=1)
    ok = ((w >> bit) & jnp.uint32(1)) != 0
    return ok & (ids >= 0) & (ids < n_cols)


def _fit_mask_words(allow_bits, b_pad: int, n_cols: int):
    """Pad/slice packed words to [b_pad, n_cols // 32] int32 (Mosaic wants
    signed lanes; bit extraction is sign-agnostic). Padding rows/columns
    are zeros = disallowed, matching the dead-row masking."""
    wn = n_cols // 32
    ab = jnp.asarray(allow_bits)
    if ab.shape[1] < wn:
        ab = jnp.pad(ab, ((0, 0), (0, wn - ab.shape[1])))
    elif ab.shape[1] > wn:
        ab = ab[:, :wn]
    if ab.shape[0] < b_pad:
        ab = jnp.pad(ab, ((0, b_pad - ab.shape[0]), (0, 0)))
    if ab.dtype == jnp.uint32:
        ab = jax.lax.bitcast_convert_type(ab, jnp.int32)
    return ab.astype(jnp.int32)


def _mask_unpack_block(mw, interpret: bool):
    """One packed block's words [B, W] int32 -> [B, 32W] 0/1 int32 with
    lane l = allow[block_base + l] (see the layout note above)."""
    if interpret:
        rep = jnp.concatenate([mw] * 32, axis=1)
    else:
        rep = pltpu.repeat(mw, 32, axis=1)
    shift = jax.lax.broadcasted_iota(jnp.int32, rep.shape, 1) // mw.shape[1]
    return jax.lax.shift_right_logical(rep, shift) & 1


def _mask_unpack_cols(mw, cols: int, interpret: bool):
    """Unpack ``cols`` columns (a 512-multiple) from words [B, cols//32]:
    per-block repeat+shift, lane-concat across blocks."""
    nb = cols // MASK_BLOCK
    if nb == 1:
        return _mask_unpack_block(mw, interpret)
    parts = [
        _mask_unpack_block(
            mw[:, i * _MASK_WORDS:(i + 1) * _MASK_WORDS], interpret)
        for i in range(nb)
    ]
    return jnp.concatenate(parts, axis=1)


def _distance_kernel(metric: str):
    """Build the tile kernel body for one metric.

    refs: q [B,d] f32/bf16, x [TILE,d], valid [1,TILE] f32, xn [1,TILE] f32,
    out [B,TILE] f32. All VMEM-resident for the tile.
    """

    def kernel(q_ref, x_ref, valid_ref, xn_ref, out_ref):
        q = q_ref[:]
        x = x_ref[:]
        # One MXU contraction: [B,d] x [TILE,d]^T -> [B,TILE], f32 accumulate.
        # f32xf32 requests HIGHEST (multi-pass exact matmul) to match the XLA
        # path's recall-parity guarantee (distances._dot_matrix); bf16 storage
        # takes the single-pass MXU matmul.
        f32_exact = q.dtype == jnp.float32 and x.dtype == jnp.float32
        dots = jax.lax.dot_general(
            q,
            x,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST if f32_exact else jax.lax.Precision.DEFAULT,
        )
        if metric == "l2-squared":
            qn = jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32), axis=1, keepdims=True)
            d = jnp.maximum(qn - 2.0 * dots + xn_ref[:], 0.0)
        elif metric == "dot":
            d = -dots
        else:  # cosine / cosine-dot: operands pre-normalized by the wrapper
            d = 1.0 - dots
        # Masking epilogue fused into the same tile: dead slots can never win.
        out_ref[:] = d + (1.0 - valid_ref[:]) * MASKED_DISTANCE

    return kernel


@functools.partial(
    jax.jit, static_argnames=("metric", "tile_n", "interpret")
)
def _distance_tiled(q, x, valid_f, xn, metric, tile_n, interpret):
    b, d = q.shape
    n = x.shape[0]
    grid = (n // tile_n,)
    return pl.pallas_call(
        _distance_kernel(metric),
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * n * d,
            bytes_accessed=q.size * q.dtype.itemsize + x.size * x.dtype.itemsize + b * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, x, valid_f, xn)


def distance_block(
    q: jnp.ndarray,
    x: jnp.ndarray,
    metric: str = "l2-squared",
    valid: jnp.ndarray | None = None,
    x_sq_norms: jnp.ndarray | None = None,
    tile_n: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Fused masked distances: q [B,d] vs x [N,d] -> [B,N] f32, lower=closer.

    Pads B to the f32 sublane multiple, d to the lane width, N to the tile —
    padded corpus rows are marked invalid so they surface as MASKED_DISTANCE.
    Zero-padding the feature axis is exact for dot/l2/cosine (zeros add
    nothing to the contraction).
    """
    if metric not in PALLAS_METRICS:
        raise ValueError(f"no pallas kernel for metric {metric!r}")
    if interpret is None:
        interpret = not recommended()

    b, d = q.shape
    n = x.shape[0]
    q = q.astype(jnp.float32) if q.dtype not in (jnp.float32, jnp.bfloat16) else q
    if metric in ("cosine", "cosine-dot"):
        from weaviate_tpu.ops.distances import normalize

        q = normalize(q.astype(jnp.float32))

    pb = _pad_to(max(b, 1), _SUBLANE)
    pd = _pad_to(max(d, 1), _LANE)
    tile_n = min(tile_n, _pad_to(max(n, 1), _LANE))
    pn = _pad_to(max(n, 1), tile_n)

    if (pb, pd) != (b, d):
        q = jnp.pad(q, ((0, pb - b), (0, pd - d)))
    if (pn, pd) != (n, d):
        x = jnp.pad(x, ((0, pn - n), (0, pd - d)))

    if valid is None:
        valid_f = (jnp.arange(pn) < n).astype(jnp.float32)
    else:
        valid_f = jnp.pad(valid.astype(jnp.float32), (0, pn - n))
    if x_sq_norms is None:
        x32 = x.astype(jnp.float32)
        xn = jnp.sum(x32 * x32, axis=1)
    else:
        xn = jnp.pad(x_sq_norms.astype(jnp.float32), (0, pn - n))

    out = _distance_tiled(
        q, x, valid_f[None, :], xn[None, :], metric, tile_n, interpret
    )
    return out[:b, :n]


def _bq_kernel(q_ref, x_ref, out_ref):
    """Packed-bits hamming tile: q [B,W] u32, x [TILE,W] u32 -> [B,TILE] f32."""
    q = q_ref[:]
    x = x_ref[:]
    xor = jnp.bitwise_xor(q[:, None, :], x[None, :, :])
    # Mosaic can't reduce unsigned ints — popcount fits in int32 regardless.
    pop = jax.lax.population_count(xor).astype(jnp.int32)
    out_ref[:] = jnp.sum(pop, axis=-1).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def _bq_tiled(q_bits, x_bits, tile_n, interpret):
    b, w = q_bits.shape
    n = x_bits.shape[0]
    return pl.pallas_call(
        _bq_kernel,
        grid=(n // tile_n,),
        in_specs=[
            pl.BlockSpec((b, w), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        interpret=interpret,
    )(q_bits, x_bits)


def _bq_mxu_kernel(q_ref, x_ref, qpop_ref, xpop_ref, valid_ref, out_ref):
    """MXU hamming tile: q01 [B, 32W] bf16 (bit-plane order), x [TILE, W]
    int32 packed. Unpack x to 0/1 planes in VMEM, one matmul, fused
    hamming + mask epilogue."""
    x = x_ref[:]
    # bit-plane unpack: lane block j holds bit j of every word -> the
    # unpacked feature order is d' = j*W + w (queries pre-permuted to match)
    planes = [((x >> j) & 1) for j in range(32)]
    bits = jnp.concatenate(planes, axis=1).astype(jnp.bfloat16)  # [TILE, 32W]
    dots = jax.lax.dot_general(
        q_ref[:], bits,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, TILE]
    d = qpop_ref[:] + xpop_ref[:] - 2.0 * dots
    # candidates are exactly rescored downstream — bf16 output halves the
    # dominant HBM cost (the [B, chunk] distance intermediate)
    out_ref[:] = (d + (1.0 - valid_ref[:]) * MASKED_DISTANCE
                  ).astype(jnp.bfloat16)


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def _bq_mxu_tiled(q01, x_packed, qpop, xpop, valid_f, tile_n, interpret):
    b = q01.shape[0]
    n, w = x_packed.shape
    return pl.pallas_call(
        _bq_mxu_kernel,
        grid=(n // tile_n,),
        in_specs=[
            pl.BlockSpec((b, 32 * w), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.bfloat16),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * n * 32 * w,
            bytes_accessed=q01.size * 2 + x_packed.size * 4 + b * n * 2,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q01, x_packed, qpop, xpop, valid_f)


def bq_queries_to_planes(q_bits: jnp.ndarray, w: int) -> jnp.ndarray:
    """Unpack packed query words [B, W] uint32 -> bit-plane-ordered 0/1
    bf16 [B, 32W] matching ``_bq_mxu_kernel``'s in-VMEM unpack order
    (d' = j*W + w)."""
    planes = [((q_bits >> jnp.uint32(j)) & jnp.uint32(1)) for j in range(32)]
    return jnp.concatenate(planes, axis=1).astype(jnp.bfloat16)


def bq_mxu_block(
    q_bits: jnp.ndarray,
    x_bits: jnp.ndarray,
    x_pop: jnp.ndarray | None = None,
    valid: jnp.ndarray | None = None,
    tile_n: int = 512,
    interpret: bool | None = None,
    q_planes: jnp.ndarray | None = None,
    q_pop: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Hamming distances via the MXU: q_bits [B,W] uint32, x_bits [N,W]
    uint32 -> [B,N] f32 bit differences, invalid rows masked.

    The corpus stays packed in HBM (d/8 bytes per row); unpacking happens
    in VMEM inside the kernel. ``x_pop`` ([N] f32 popcounts) amortizes the
    |x| term — pass the store's cached copy when scanning repeatedly.
    ``q_planes``/``q_pop`` (from ``bq_queries_to_planes``, already padded
    to the sublane multiple) let a chunked scan hoist the loop-invariant
    query unpack out of the scan body.
    """
    if interpret is None:
        interpret = not recommended()
    b, w = q_bits.shape
    n = x_bits.shape[0]
    pb = _pad_to(max(b, 1), _SUBLANE)
    tile_n = min(tile_n, _pad_to(max(n, 1), _LANE))
    pn = _pad_to(max(n, 1), tile_n)
    if pb != b:
        q_bits = jnp.pad(q_bits, ((0, pb - b), (0, 0)))
    if pn != n:
        x_bits = jnp.pad(x_bits, ((0, pn - n), (0, 0)))
    if q_planes is None:
        q01 = bq_queries_to_planes(q_bits, w)
        qpop = jnp.sum(q01.astype(jnp.float32), axis=1, keepdims=True)
    else:
        q01, qpop = q_planes, q_pop
    if x_pop is None:
        xpop = jnp.sum(
            jax.lax.population_count(x_bits).astype(jnp.int32), axis=1
        ).astype(jnp.float32)
    else:
        xpop = jnp.pad(x_pop.astype(jnp.float32), (0, pn - n))
    # Mosaic has no uint32->bf16 cast; the kernel's bit planes convert
    # from int32 instead (bit extraction is sign-agnostic)
    if x_bits.dtype == jnp.uint32:
        x_bits = jax.lax.bitcast_convert_type(x_bits, jnp.int32)
    if valid is None:
        valid_f = (jnp.arange(pn) < n).astype(jnp.float32)
    else:
        valid_f = jnp.pad(valid.astype(jnp.float32), (0, pn - n))
    out = _bq_mxu_tiled(q01, x_bits, qpop, xpop[None, :], valid_f[None, :],
                        tile_n, interpret)
    return out[:b, :n]


def _pq4_kernel(lut_ref, c_ref, valid_ref, out_ref, *, k, m, interpret):
    """4-bit PQ ADC tile: lut [B, k*m] bf16 CODE-MAJOR (lane c*m+s holds
    LUT[s][c]), codes [TILE, m] uint8. pltpu.repeat tiles the code row k
    times (lane c*m+s = codes[s]), a lane-iota//m compare builds the
    one-hot, one bf16 matmul contracts against the LUT."""
    c = c_ref[:].astype(jnp.int32)  # [TILE, m]
    if interpret:  # tile-concat == pltpu.repeat semantics, interpreter-safe
        rep = jnp.concatenate([c] * k, axis=1)
    else:
        rep = pltpu.repeat(c, k, axis=1)  # [TILE, k*m]
    lane_code = jax.lax.broadcasted_iota(jnp.int32, rep.shape, 1) // m
    oh = (rep == lane_code).astype(jnp.bfloat16)
    d = jax.lax.dot_general(
        lut_ref[:], oh,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, TILE]
    out_ref[:] = (d + (1.0 - valid_ref[:]) * MASKED_DISTANCE
                  ).astype(jnp.bfloat16)


@functools.partial(jax.jit, static_argnames=("k", "m", "tile_n", "interpret"))
def _pq4_tiled(lut_cm, codes, valid_f, k, m, tile_n, interpret):
    b = lut_cm.shape[0]
    n = codes.shape[0]
    return pl.pallas_call(
        functools.partial(_pq4_kernel, k=k, m=m, interpret=interpret),
        grid=(n // tile_n,),
        in_specs=[
            pl.BlockSpec((b, k * m), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, m), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.bfloat16),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * n * k * m,
            bytes_accessed=lut_cm.size * 2 + codes.size + b * n * 2,
            transcendentals=0,
        ),
        interpret=interpret,
    )(lut_cm, codes, valid_f)


def pq4_lut_block(
    lut: jnp.ndarray,
    codes: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    tile_n: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Exact ADC distances for 4-bit PQ codes (reference LUT ``Distance``,
    product_quantization.go:440 — same sum, computed as one MXU matmul).

    lut [B, m, k<=16] f32 (seg-major); codes [N, m] uint8 in [0, k).
    Returns [B, N] f32 = sum_s lut[b, s, codes[n, s]] with invalid rows
    masked.
    """
    if interpret is None:
        interpret = not recommended()
    b, m, k = lut.shape
    if k > 16:
        raise ValueError(f"pq4 kernel requires k <= 16 centroids, got {k}")
    k = 16  # pad the code axis so lane count is m*16 regardless
    n = codes.shape[0]
    pb = _pad_to(max(b, 1), _SUBLANE)
    tile_n = min(tile_n, _pad_to(max(n, 1), _LANE))
    pn = _pad_to(max(n, 1), tile_n)
    if pb != b:
        lut = jnp.pad(lut, ((0, pb - b), (0, 0), (0, 0)))
    if lut.shape[2] < k:
        lut = jnp.pad(lut, ((0, 0), (0, 0), (0, k - lut.shape[2])))
    if pn != n:
        codes = jnp.pad(codes, ((0, pn - n), (0, 0)))
    # CODE-MAJOR flatten: lane c*m + s  (pltpu.repeat produces this order)
    lut_cm = jnp.transpose(lut, (0, 2, 1)).reshape(pb, k * m)
    lut_cm = lut_cm.astype(jnp.bfloat16)
    if valid is None:
        valid_f = (jnp.arange(pn) < n).astype(jnp.float32)
    else:
        valid_f = jnp.pad(valid.astype(jnp.float32), (0, pn - n))
    out = _pq4_tiled(lut_cm, codes, valid_f[None, :], k, m, tile_n, interpret)
    return out[:b, :n]


def _pq4_recon_kernel(q_ref, cflat_ref, c_ref, valid_ref, out_ref,
                      *, k, m, metric, interpret):
    """4-bit PQ scan via RECONSTRUCT-matmul: one-hot [TILE, mk] @
    block-diagonal centroids [mk, d] rebuilds x_hat in VMEM, then the
    normal distance matmul scores it. Per-row FLOPs 2*mk*d + 2*d*B —
    beats the LUT formulation's 2*mk*B once B > mk*d/(mk-d) (~170 at
    d=128), so large serving batches take this path."""
    c = c_ref[:].astype(jnp.int32)  # [TILE, m]
    if interpret:
        rep = jnp.concatenate([c] * k, axis=1)
    else:
        rep = pltpu.repeat(c, k, axis=1)  # [TILE, k*m] code-major
    lane_code = jax.lax.broadcasted_iota(jnp.int32, rep.shape, 1) // m
    oh = (rep == lane_code).astype(jnp.bfloat16)
    x_hat = jax.lax.dot_general(
        oh, cflat_ref[:],
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [TILE, d]
    xn = jnp.sum(x_hat * x_hat, axis=1)  # [TILE] = ||x_hat||^2 (exact:
    # segments are disjoint columns, so the reconstruction is exact)
    dots = jax.lax.dot_general(
        q_ref[:], x_hat.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [B, TILE]
    if metric == "l2-squared":
        q = q_ref[:].astype(jnp.float32)
        qn = jnp.sum(q * q, axis=1, keepdims=True)
        d_ = qn - 2.0 * dots + xn[None, :]
    elif metric == "dot":
        d_ = -dots
    else:  # cosine: stored side normalized upstream; ADC keeps ranking
        d_ = 1.0 - dots
    out_ref[:] = (d_ + (1.0 - valid_ref[:]) * MASKED_DISTANCE
                  ).astype(jnp.bfloat16)


@functools.partial(jax.jit,
                   static_argnames=("k", "m", "metric", "tile_n", "interpret"))
def _pq4_recon_tiled(q, cflat, codes, valid_f, k, m, metric, tile_n,
                     interpret):
    b = q.shape[0]
    n = codes.shape[0]
    d = cflat.shape[1]
    return pl.pallas_call(
        functools.partial(_pq4_recon_kernel, k=k, m=m, metric=metric,
                          interpret=interpret),
        grid=(n // tile_n,),
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k * m, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, m), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.bfloat16),
        cost_estimate=pl.CostEstimate(
            flops=2 * n * k * m * d + 2 * b * n * d,
            bytes_accessed=q.size * 2 + codes.size + b * n * 2,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, cflat, codes, valid_f)


def pq4_recon_block(
    q: jnp.ndarray,
    codes: jnp.ndarray,
    centroids: jnp.ndarray,
    metric: str = "l2-squared",
    valid: jnp.ndarray | None = None,
    tile_n: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """ADC distances for 4-bit PQ via in-VMEM reconstruction (same
    candidate semantics as pq4_lut_block; cheaper for large B).

    q [B, d] f32/bf16 (cosine: pre-normalized by caller), codes [N, m]
    uint8, centroids [m, k<=16, ds].
    """
    if interpret is None:
        interpret = not recommended()
    m, kk, ds = centroids.shape
    if kk > 16:
        raise ValueError(f"pq4 kernel requires k <= 16 centroids, got {kk}")
    k = 16
    b, d = q.shape
    n = codes.shape[0]
    pb = _pad_to(max(b, 1), _SUBLANE)
    tile_n = min(tile_n, _pad_to(max(n, 1), _LANE))
    pn = _pad_to(max(n, 1), tile_n)
    q = q.astype(jnp.bfloat16)
    if pb != b:
        q = jnp.pad(q, ((0, pb - b), (0, 0)))
    if pn != n:
        codes = jnp.pad(codes, ((0, pn - n), (0, 0)))
    cent = centroids.astype(jnp.float32)
    if kk < k:
        cent = jnp.pad(cent, ((0, 0), (0, k - kk), (0, 0)))
    # CODE-MAJOR block-diagonal flatten matching pltpu.repeat's one-hot
    # order: cflat[c*m + s, s*ds:(s+1)*ds] = cent[s, c]
    eye = jnp.eye(m, dtype=jnp.float32)
    cflat = jnp.einsum("st,skd->ktsd", eye, cent)  # [k, t, s, ds]
    cflat = cflat.reshape(k * m, m * ds).astype(jnp.bfloat16)
    if valid is None:
        valid_f = (jnp.arange(pn) < n).astype(jnp.float32)
    else:
        valid_f = jnp.pad(valid.astype(jnp.float32), (0, pn - n))
    out = _pq4_recon_tiled(q, cflat, codes, valid_f[None, :], k, m,
                           metric, tile_n, interpret)
    return out[:b, :n]


# -- fused distance + top-k scan ---------------------------------------------
#
# Round-6 tentpole: selection folded INTO the scan. The chunked serving scan
# used to materialize every [B, chunk] distance tile to HBM and pay a
# lax.top_k / approx_max_k per tile — measured at ~100x the raw matmul FLOP
# time (VERDICT r2/r5: 118 s of the 199 s 1M-row bulk build, ~95% of device
# time at 1M rows). Here each grid step computes its distance tile in VMEM
# and folds it into a per-query running top-k carry held in VMEM scratch
# across grid steps, so the [B, N] distances never leave the chip and the
# per-chunk wide selection pass disappears entirely.
#
# The fold is EXACT top-k (ties break like lax.top_k: earlier row wins) via
# threshold-bounded iterated extraction:
#
# 1. tau = current k-th best per query (carry is kept sorted ascending).
# 2. count survivors d < tau per query; the max count over the batch bounds
#    a dynamic-trip-count fori_loop — after the first few tiles tau is tight
#    and almost every tile folds in O(1) extractions instead of k.
# 3. each extraction takes the tile argmin (first occurrence), masks it, and
#    does a sorted insert into the carry (roll-shift + two selects). Inserts
#    of elements >= the k-th best are no-ops, so a stale tau only costs
#    wasted passes, never correctness.
#
# k <= 128 (one lane tile of carry per query) for the distance scan;
# the survivor-merge variant allows k <= 256 (two lane tiles) because the
# quantized stores oversample to rescore_limit*k candidates before their
# exact rescore. Dead/padded rows are excluded before the fold, so unfilled
# carry slots surface as (MASKED_DISTANCE, -1).

_FUSED_TOPK_MAX_K = 128
_FUSED_PAIRS_MAX_K = 256


def _fold_tile_topk(d, tile_ids, cd, ci, k, interpret):
    """Fold one [B, T] distance tile (with explicit [B, T] int32 ids) into a
    sorted-ascending top-k carry (cd [B, k] f32, ci [B, k] i32). Exact."""
    b, t = d.shape
    col = jax.lax.broadcasted_iota(jnp.int32, (b, t), 1)
    kcol = jax.lax.broadcasted_iota(jnp.int32, (b, k), 1)
    far = jnp.int32(2 ** 30)
    if interpret:
        def roll1(a):
            return jnp.roll(a, 1, axis=1)
    else:
        def roll1(a):
            return pltpu.roll(a, 1, axis=1)
    tau = cd[:, k - 1:k]
    n_it = jnp.minimum(
        jnp.max(jnp.sum((d < tau).astype(jnp.int32), axis=1)), k)

    def body(_j, st):
        work, cd_, ci_ = st
        m = jnp.min(work, axis=1, keepdims=True)
        pos = jnp.min(jnp.where(work == m, col, far), axis=1, keepdims=True)
        hit = col == pos
        e_id = jnp.min(jnp.where(hit, tile_ids, far), axis=1, keepdims=True)
        work = jnp.where(hit, jnp.float32(MASKED_DISTANCE), work)
        # sorted insert at #(cd <= m): after equals (stable — the earlier
        # row keeps its spot, matching lax.top_k's lower-index-first ties);
        # ins == k means m lost to every carried element -> no-op
        ins = jnp.sum((cd_ <= m).astype(jnp.int32), axis=1, keepdims=True)
        cd_ = jnp.where(kcol < ins, cd_, jnp.where(kcol == ins, m, roll1(cd_)))
        ci_ = jnp.where(kcol < ins, ci_,
                        jnp.where(kcol == ins, e_id, roll1(ci_)))
        return work, cd_, ci_

    _, cd, ci = jax.lax.fori_loop(0, n_it, body, (d, cd, ci))
    return cd, ci


def _fused_topk_kernel(metric: str, k: int, interpret: bool,
                       masked: bool = False):
    """Distance tile + in-VMEM top-k fold. refs: q [B,d], x [TILE,d],
    valid [1,TILE] f32, xn [1,TILE] f32, (masked: am [B,TILE/32] i32
    packed per-query allow words), outs [B,k] f32 / [B,k] i32, scratch
    carries cd [B,k] f32 / ci [B,k] i32 (persist across the grid)."""

    def kernel(q_ref, x_ref, valid_ref, xn_ref, *refs):
        if masked:
            am_ref, outd_ref, outi_ref, cd_ref, ci_ref = refs
        else:
            outd_ref, outi_ref, cd_ref, ci_ref = refs
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            cd_ref[:] = jnp.full(cd_ref.shape, MASKED_DISTANCE, jnp.float32)
            ci_ref[:] = jnp.full(ci_ref.shape, -1, jnp.int32)

        q = q_ref[:]
        x = x_ref[:]
        f32_exact = q.dtype == jnp.float32 and x.dtype == jnp.float32
        dots = jax.lax.dot_general(
            q, x,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=(jax.lax.Precision.HIGHEST if f32_exact
                       else jax.lax.Precision.DEFAULT),
        )
        if metric == "l2-squared":
            qf = q.astype(jnp.float32)
            qn = jnp.sum(qf * qf, axis=1, keepdims=True)
            d = jnp.maximum(qn - 2.0 * dots + xn_ref[:], 0.0)
        elif metric == "dot":
            d = -dots
        else:  # cosine / cosine-dot: operands pre-normalized by the wrapper
            d = 1.0 - dots
        # exclude dead/padded rows entirely (they can never enter the carry,
        # so k > live surfaces as (MASKED_DISTANCE, -1) — strictly cleaner
        # than the unfused path's arbitrary dead-row ids)
        b, t = d.shape
        ok = valid_ref[:] > 0.5
        if masked:
            # per-query allow bitmask, unpacked tile-locally in VMEM and
            # folded into the same validity epilogue — disallowed rows can
            # never enter the carry, exactly like dead rows
            bits = _mask_unpack_cols(am_ref[:], t, interpret)
            ok = jnp.logical_and(ok, bits > 0)
        d = jnp.where(ok, d, jnp.float32(MASKED_DISTANCE))
        base = step * t
        tile_ids = base + jax.lax.broadcasted_iota(jnp.int32, (b, t), 1)
        cd, ci = _fold_tile_topk(d, tile_ids, cd_ref[:], ci_ref[:], k,
                                 interpret)
        cd_ref[:] = cd
        ci_ref[:] = ci
        outd_ref[:] = cd
        outi_ref[:] = ci

    return kernel


@functools.partial(
    jax.jit, static_argnames=("metric", "k", "tile_n", "masked", "interpret"))
def _fused_topk_tiled(q, x, valid_f, xn, am, metric, k, tile_n, masked,
                      interpret):
    b, d = q.shape
    n = x.shape[0]
    in_specs = [
        pl.BlockSpec((b, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((tile_n, d), lambda i: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, tile_n), lambda i: (0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, tile_n), lambda i: (0, i),
                     memory_space=pltpu.VMEM),
    ]
    operands = (q, x, valid_f, xn)
    if masked:
        in_specs.append(
            pl.BlockSpec((b, tile_n // 32), lambda i: (0, i),
                         memory_space=pltpu.VMEM))
        operands = operands + (am,)
    return pl.pallas_call(
        _fused_topk_kernel(metric, k, interpret, masked),
        grid=(n // tile_n,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((b, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((b, k), jnp.float32),
            pltpu.VMEM((b, k), jnp.int32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * b * n * d,
            bytes_accessed=q.size * q.dtype.itemsize
            + x.size * x.dtype.itemsize + 2 * b * k * 4
            + (b * n // 8 if masked else 0),
            transcendentals=0,
        ),
        interpret=interpret,
    )(*operands)


def fused_topk_scan(
    q: jnp.ndarray,
    x: jnp.ndarray,
    k: int,
    metric: str = "l2-squared",
    valid: jnp.ndarray | None = None,
    x_sq_norms: jnp.ndarray | None = None,
    tile_n: int = 512,
    interpret: bool | None = None,
    allow_bits: jnp.ndarray | None = None,
    allow_rows: jnp.ndarray | None = None,
):
    """Fused masked distance scan + EXACT top-k: q [B,d] vs x [N,d] ->
    (dists [B,k] f32 ascending, row ids [B,k] i32, -1 where fewer than k
    live rows). The [B, N] distance matrix never exists outside VMEM.

    Same padding rules as ``distance_block``; k <= 128 (the carry is one
    lane tile per query). Dead rows never surface — not even to fill out a
    short result. Query batches above ``max_b`` are processed in
    independent blocks so the resident q + [blk, tile_n] distance tile +
    fold working set stay inside the ~16 MB VMEM budget at any serving
    batch (the same cap hnsw_build applies to its query blocks).

    ``allow_bits`` [B, >=ceil(N_512/32)] uint32 adds a PER-QUERY allow
    bitmask (``pack_allow_bitmask`` layout) unpacked tile-locally in VMEM
    and folded into the validity epilogue; ``allow_rows`` [B, N] bool is
    the unpacked convenience form (packed on device — the sharded path
    uses it after slicing its local columns). Masked scans force
    tile_n = MASK_BLOCK so tiles cover whole packed blocks."""
    if metric not in PALLAS_METRICS:
        raise ValueError(f"no fused top-k kernel for metric {metric!r}")
    if not 1 <= k <= _FUSED_TOPK_MAX_K:
        raise ValueError(f"fused top-k requires 1 <= k <= 128, got {k}")
    if interpret is None:
        interpret = not recommended()
    if allow_bits is None and allow_rows is not None:
        allow_bits = pack_allow_bitmask_jnp(allow_rows)

    max_b = 1024
    if q.shape[0] > max_b:
        parts = [
            fused_topk_scan(q[s:s + max_b], x, k, metric=metric,
                            valid=valid, x_sq_norms=x_sq_norms,
                            tile_n=tile_n, interpret=interpret,
                            allow_bits=(None if allow_bits is None
                                        else allow_bits[s:s + max_b]))
            for s in range(0, q.shape[0], max_b)
        ]
        return (jnp.concatenate([p[0] for p in parts]),
                jnp.concatenate([p[1] for p in parts]))

    b, d = q.shape
    n = x.shape[0]
    q = q.astype(jnp.float32) if q.dtype not in (jnp.float32, jnp.bfloat16) \
        else q
    if metric in ("cosine", "cosine-dot"):
        from weaviate_tpu.ops.distances import normalize

        q = normalize(q.astype(jnp.float32))

    pb = _pad_to(max(b, 1), _SUBLANE)
    pd = _pad_to(max(d, 1), _LANE)
    if allow_bits is not None:
        tile_n = MASK_BLOCK  # tiles must cover whole packed mask blocks
    else:
        tile_n = min(tile_n, _pad_to(max(n, 1), _LANE))
    pn = _pad_to(max(n, 1), tile_n)

    if (pb, pd) != (b, d):
        q = jnp.pad(q, ((0, pb - b), (0, pd - d)))
    if (pn, pd) != (n, d):
        x = jnp.pad(x, ((0, pn - n), (0, pd - d)))

    if valid is None:
        valid_f = (jnp.arange(pn) < n).astype(jnp.float32)
    else:
        valid_f = jnp.pad(valid.astype(jnp.float32), (0, pn - n))
    if x_sq_norms is None:
        x32 = x.astype(jnp.float32)
        xn = jnp.sum(x32 * x32, axis=1)
    else:
        xn = jnp.pad(x_sq_norms.astype(jnp.float32), (0, pn - n))

    am = (None if allow_bits is None
          else _fit_mask_words(allow_bits, pb, pn))
    out_d, out_i = _fused_topk_tiled(
        q, x, valid_f[None, :], xn[None, :], am, metric, k, tile_n,
        allow_bits is not None, interpret)
    return out_d[:b], out_i[:b]


def _fused_pairs_kernel(k: int, interpret: bool):
    """Top-k fold over precomputed (vals, ids) tiles — the merge stage for
    the quantized scan-reduce kernels' [B, ~N/L] survivor arrays."""

    def kernel(v_ref, i_ref, outd_ref, outi_ref, cd_ref, ci_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            cd_ref[:] = jnp.full(cd_ref.shape, MASKED_DISTANCE, jnp.float32)
            ci_ref[:] = jnp.full(ci_ref.shape, -1, jnp.int32)

        cd, ci = _fold_tile_topk(v_ref[:], i_ref[:], cd_ref[:], ci_ref[:],
                                 k, interpret)
        cd_ref[:] = cd
        ci_ref[:] = ci
        outd_ref[:] = cd
        outi_ref[:] = ci

    return kernel


@functools.partial(jax.jit, static_argnames=("k", "tile_m", "interpret"))
def _fused_pairs_tiled(vals, ids, k, tile_m, interpret):
    b, m = vals.shape
    return pl.pallas_call(
        _fused_pairs_kernel(k, interpret),
        grid=(m // tile_m,),
        in_specs=[
            pl.BlockSpec((b, tile_m), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((b, tile_m), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((b, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((b, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ),
        scratch_shapes=[
            pltpu.VMEM((b, k), jnp.float32),
            pltpu.VMEM((b, k), jnp.int32),
        ],
        interpret=interpret,
    )(vals, ids)


def fused_topk_pairs(
    vals: jnp.ndarray,
    ids: jnp.ndarray,
    k: int,
    tile_m: int = 2048,
    interpret: bool | None = None,
):
    """EXACT top-k over explicit (vals [B,M] f32, ids [B,M] i32) candidate
    pairs via the same in-VMEM running-carry fold as ``fused_topk_scan`` —
    replaces the post-scan ``approx_max_k`` pass of the quantized
    scan-reduce consumers. Entries at >= MASKED_DISTANCE never surface."""
    if not 1 <= k <= _FUSED_PAIRS_MAX_K:
        raise ValueError(f"fused pairs top-k requires 1 <= k <= 256, got {k}")
    if interpret is None:
        interpret = not recommended()
    b, m = vals.shape
    pb = _pad_to(max(b, 1), _SUBLANE)
    tile_m = min(tile_m, _pad_to(max(m, 1), _LANE))
    pm = _pad_to(max(m, 1), tile_m)
    vals = vals.astype(jnp.float32)
    if (pb, pm) != (b, m):
        vals = jnp.pad(vals, ((0, pb - b), (0, pm - m)),
                       constant_values=MASKED_DISTANCE)
        ids = jnp.pad(ids.astype(jnp.int32), ((0, pb - b), (0, pm - m)),
                      constant_values=-1)
    out_d, out_i = _fused_pairs_tiled(vals, ids.astype(jnp.int32), k,
                                      tile_m, interpret)
    return out_d[:b], out_i[:b]


_SCAN_ID_BITS = 6  # slice-id field width: reduce_l <= 64 strided slices


def _bq_scan_kernel(qmat_ref, x_ref, bias_ref, *refs,
                    w, subtiles, sub_rows, out_w, row_major, masked,
                    interpret):
    """Fused BQ scan supertile: ±1-int8 matmul hamming + strided block-argmin.

    Round-4 redesign of the BQ hot path. The ideas versus ``_bq_mxu_kernel``:

    1. hamming(q, x) = popcount(q) + (1 - 2q) . x_bits — ONE int8 matmul
       with a ±1 query matrix gives (hamming - qpop) exactly (int32
       accumulate), no |x| popcount input, no bf16 rounding. int8 runs the
       MXU at 2x the bf16 rate (measured 178 vs 85 TOP/s on v5e).
    2. the in-VMEM unpack is pltpu.repeat + one lane-iota shift + mask
       (full-width VPU ops) instead of 32 narrow slice-concats.
    3. the kernel reduces each supertile to supertile/L candidates via a
       STRIDED block-argmin before anything leaves VMEM: the [B, N]
       distance matrix — whose HBM write+readback dominated the old kernel
       at large B — shrinks by L. One candidate per strided block loses
       ~k^2/(2 * N/L) of the top-k (birthday bound) — rescored downstream.
    4. value+id+validity are packed into ONE int32 and the merge costs
       TWO VPU passes per element (+bias, min): the query matrix is
       scaled to ±64 so the MXU emits dots PRE-SHIFTED by 6 bits, the
       driver-precomputed bias row carries the strided slice index in
       the low 6 bits plus a +(2d+2)<<6 offset on dead rows that pushes
       them past every legit value. The winning lane position is implicit
       in the output column, so 6 id bits (reduce_l <= 64) identify the
       row exactly. Requires 64*(3d+2) < 2^31, i.e. d <= 16M.

    qmat [B, 32w] int8 in {-64, +64} (bit-plane order d' = j*w + word),
    x_t [w, ST] int32 packed TRANSPOSED — words ride the sublane axis so
    the VMEM tile is lane-dense (a [ST, w] block with w << 128 wastes
    128/w of VMEM to T(8,128) lane padding — the round-4 OOM), bias
    [1, ST] int32. Emits packed int32 [B, ST/L]; driver unpacks
    vals = packed >> 6 (+qpop) and ids = (packed & 63)*out_w + column.

    With ``masked``, an extra [B, ST/32] int32 ref carries per-query
    packed allow words (pack_allow_bitmask layout); disallowed slots are
    forced to INT32_MAX before the strided min so they can never win.
    """
    if masked:
        am_ref, out_ref = refs
    else:
        (out_ref,) = refs
    qmat = qmat_ref[:]
    slices_per_sub = sub_rows // out_w
    # loop-invariant: plane index of each unpacked row/lane
    rep_axis = 1 if row_major else 0
    shape = (sub_rows, 32 * w) if row_major else (32 * w, sub_rows)
    shift = jax.lax.broadcasted_iota(jnp.int32, shape, rep_axis) // w

    def one_subtile(j, acc):
        if row_major:
            x = x_ref[pl.ds(j * sub_rows, sub_rows), :]  # [sub, w] int32
        else:
            x = x_ref[:, pl.ds(j * sub_rows, sub_rows)]  # [w, sub] int32
        if interpret:
            rep = jnp.concatenate([x] * 32, axis=rep_axis)
        else:
            rep = pltpu.repeat(x, 32, axis=rep_axis)  # 32w copy-major
        bits = (jax.lax.shift_right_logical(rep, shift) & 1).astype(jnp.int8)
        dots = jax.lax.dot_general(
            qmat, bits,
            dimension_numbers=(((1,), (1 if row_major else 0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [B, sub] = (hamming - qpop) << 6
        packed = dots + bias_ref[:, pl.ds(j * sub_rows, sub_rows)]
        if masked:
            mw = am_ref[:, pl.ds(j * (sub_rows // 32), sub_rows // 32)]
            bits = _mask_unpack_cols(mw, sub_rows, interpret)
            packed = jnp.where(bits > 0, packed,
                               jnp.iinfo(jnp.int32).max)
        for s in range(slices_per_sub):
            acc = jnp.minimum(acc, packed[:, s * out_w:(s + 1) * out_w])
        return acc

    init = jnp.full((qmat.shape[0], out_w), jnp.iinfo(jnp.int32).max,
                    jnp.int32)
    if subtiles == 1:
        acc = one_subtile(0, init)
    else:
        acc = jax.lax.fori_loop(0, subtiles, one_subtile, init)
    out_ref[:] = acc


@functools.partial(jax.jit, static_argnames=(
    "supertile", "sub_rows", "out_w", "row_major", "masked", "interpret"))
def _bq_scan_tiled(qmat, x_t, bias, am, supertile, sub_rows, out_w,
                   row_major, masked, interpret):
    b = qmat.shape[0]
    if row_major:
        n, w = x_t.shape
        x_spec = pl.BlockSpec((supertile, w), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    else:
        w, n = x_t.shape
        x_spec = pl.BlockSpec((w, supertile), lambda i: (0, i),
                              memory_space=pltpu.VMEM)
    subtiles = supertile // sub_rows
    reduce_l = supertile // out_w
    in_specs = [
        pl.BlockSpec((b, 32 * w), lambda i: (0, 0), memory_space=pltpu.VMEM),
        x_spec,
        pl.BlockSpec((1, supertile), lambda i: (0, i), memory_space=pltpu.VMEM),
    ]
    operands = (qmat, x_t, bias)
    if masked:
        in_specs.append(
            pl.BlockSpec((b, supertile // 32), lambda i: (0, i),
                         memory_space=pltpu.VMEM))
        operands = operands + (am,)
    return pl.pallas_call(
        functools.partial(_bq_scan_kernel, w=w, subtiles=subtiles,
                          sub_rows=sub_rows, out_w=out_w,
                          row_major=row_major, masked=masked,
                          interpret=interpret),
        grid=(n // supertile,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, out_w), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n // reduce_l), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * n * 32 * w,
            bytes_accessed=qmat.size + x_t.size * 4
            + b * (n // reduce_l) * 4 + (b * n // 8 if masked else 0),
            transcendentals=0,
        ),
        interpret=interpret,
    )(*operands)


def bq_queries_to_pm1(q_bits: jnp.ndarray, w: int,
                      scale: int = 1) -> jnp.ndarray:
    """Packed query words [B, W] uint32 -> ±scale int8 matrix [B, 32W] in
    the kernel's bit-plane order (lane j*W + word): +scale where the bit
    is 0, -scale where it is 1, so qmat . x_bits = scale * sum x_d
    (1 - 2 q_d). ``scale=64`` makes the MXU emit dots pre-shifted by the
    6-bit id field of ``_bq_scan_kernel``'s packed merge."""
    planes = [((q_bits >> jnp.uint32(j)) & jnp.uint32(1)) for j in range(32)]
    q01 = jnp.concatenate(planes, axis=1).astype(jnp.int8)
    return (scale - 2 * scale * q01).astype(jnp.int8)


def bq_scan_reduce(
    q_bits: jnp.ndarray,
    x_bits: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    reduce_l: int = 128,
    interpret: bool | None = None,
    transposed: bool = False,
    sub_rows: int | None = None,
    allow_bits: jnp.ndarray | None = None,
):
    """Full-corpus BQ scan with in-kernel candidate reduction.

    q_bits [B, W] uint32, x_bits [N, W] uint32 — or [W, N] with
    ``transposed=True``, the layout the kernel wants (stores keep the
    code matrix transposed to skip the per-call transpose). W is padded
    to a multiple of 4 so the unpacked lane count is a 128-multiple;
    zero bits in the pad are harmless: their ±1 query weight multiplies
    a 0 bit.

    Returns (vals [B, ceil(N/st)*st/L] f32, ids [B, ...] int32) where vals
    are TRUE hamming distances (qpop added back; dead/padded slots surface
    as huge values) and ids are global row indices; strided blocks keep one
    candidate each (see _bq_scan_kernel). Feed to approx/exact top-k, then
    rescore.

    ``allow_bits`` [B, >=ceil(N_512/32)] uint32 adds a per-query allow
    bitmask (pack_allow_bitmask layout); disallowed rows never surface,
    and supertile/sub_rows are forced MASK_BLOCK-aligned so subtiles
    unpack whole packed blocks.
    """
    if interpret is None:
        interpret = not recommended()
    b, w = q_bits.shape
    d = 32 * w
    pw = _pad_to(max(w, 1), 4)
    pb = _pad_to(max(b, 1), _SUBLANE)
    # orientation: words-on-lanes ("row-major") blocks tile VMEM at
    # [sub, 128-padded] — dense enough at w >= 24 and what the capacity
    # store keeps for cheap stage-2 row gathers. Narrow codes (w < 24)
    # waste >= 5x VMEM to lane padding, so they scan TRANSPOSED [w, N]
    # (words on the sublane axis).
    row_major = w >= 24 if not transposed else False
    if transposed:
        x_t = x_bits
        n = x_t.shape[1]
    elif row_major:
        x_t = x_bits
        n = x_bits.shape[0]
    else:
        x_t = x_bits.T
        n = x_bits.shape[0]
    # subtile rows bound the in-kernel unpack intermediates ([32w, sub] int32
    # repeat + iota + int8 bits ~ 9*sub*32w bytes) and the [B, sub] dots tile
    if sub_rows is None:
        if row_major:
            sub_rows = 256
        else:
            sub_rows = 2048 if pw <= 8 else (1024 if pw <= 24 else 512)
        if pb > 512:
            sub_rows = min(sub_rows, 1024)
    # out width per supertile: one strided-min slot per reduce_l rows.
    # supertile = reduce_l * out_w; reduce_l caps at 64 (the packed id
    # field is 6 bits). Row-major supertiles cap at 8192 rows: the VMEM
    # block pads w up to 128 lanes.
    reduce_l = max(1, min(reduce_l, 64))
    reduce_l = 1 << (reduce_l.bit_length() - 1)  # floor pow2
    st_cap = 8192 if row_major else 16384
    out_w = min(max(128, st_cap // reduce_l), sub_rows)
    supertile = reduce_l * out_w
    sub_rows = min(sub_rows, supertile)
    if allow_bits is not None:
        # masked subtiles unpack whole 512-column packed blocks (all of
        # out_w/sub_rows/supertile are pow2, so alignment = scaling up)
        while supertile % MASK_BLOCK:
            out_w *= 2
            supertile = reduce_l * out_w
        sub_rows = min(max(sub_rows, out_w, MASK_BLOCK), supertile)
    pn = _pad_to(max(n, 1), supertile)
    if pw != w:
        q_bits = jnp.pad(q_bits, ((0, 0), (0, pw - w)))
        x_t = (jnp.pad(x_t, ((0, 0), (0, pw - w))) if row_major
               else jnp.pad(x_t, ((0, pw - w), (0, 0))))
    if pb != b:
        q_bits = jnp.pad(q_bits, ((0, pb - b), (0, 0)))
    if pn != n:
        x_t = (jnp.pad(x_t, ((0, pn - n), (0, 0))) if row_major
               else jnp.pad(x_t, ((0, 0), (0, pn - n))))
    # bias row: strided slice index (row // out_w within the supertile) in
    # the low 6 bits; dead rows get +(2d+2) on the value field, past any
    # legit (hamming - qpop) in [-d, d]
    pos = jnp.arange(pn, dtype=jnp.int32)
    slice_id = pos % supertile // out_w
    if valid is None:
        dead = pos >= n
    else:
        dead = jnp.logical_not(jnp.pad(valid.astype(bool), (0, pn - n),
                                       constant_values=False))
        dead = jnp.logical_or(dead, pos >= n)
    bias = slice_id + jnp.where(dead, (2 * d + 2) << _SCAN_ID_BITS, 0)
    qmat = bq_queries_to_pm1(q_bits, pw, scale=1 << _SCAN_ID_BITS)
    qpop = jnp.sum(
        jax.lax.population_count(
            jax.lax.bitcast_convert_type(q_bits, jnp.int32)
        ).astype(jnp.int32), axis=1).astype(jnp.float32)
    if x_t.dtype == jnp.uint32:
        x_t = jax.lax.bitcast_convert_type(x_t, jnp.int32)
    am = (None if allow_bits is None
          else _fit_mask_words(allow_bits, pb, pn))
    packed = _bq_scan_tiled(qmat, x_t, bias[None, :], am, supertile,
                            sub_rows, out_w, row_major,
                            allow_bits is not None, interpret)
    vals = jax.lax.shift_right_arithmetic(packed, _SCAN_ID_BITS)
    slice_ids = jax.lax.bitwise_and(packed, (1 << _SCAN_ID_BITS) - 1)
    col = jnp.arange(pn // reduce_l, dtype=jnp.int32)
    ids = (slice_ids * out_w                 # winning strided slice
           + (col % out_w)[None, :]          # lane position (implicit)
           + (col // out_w * supertile)[None, :])  # supertile base
    vals = vals[:b].astype(jnp.float32) + qpop[:b, None]
    # dead rows came back at hamming + 2d+2 (> d, the max legit hamming);
    # push them to the sentinel so downstream merges never surface them.
    # This pass runs on the reduced [B, N/L] array — cheap.
    vals = jnp.where(vals > d, MASKED_DISTANCE, vals)
    return vals, ids[:b]


def _pq4_scan_kernel(lut_ref, c_ref, bias_ref, *refs,
                     m, subtiles, sub_rows, out_w, row_major, masked,
                     interpret):
    """Fused 4-bit-PQ ADC scan supertile (the PQ twin of _bq_scan_kernel).

    lut [B, 16m] int8 CODE-MAJOR per-query tables (quantized with a
    per-query scale by the driver), codes [ST, m] uint8 row-major or
    [m, ST] transposed, bias [1, ST] int32 carrying the strided slice id
    (low 6 bits) and a dead-row offset. One int8 matmul against the
    in-VMEM one-hot gives integer ADC sums; merge is shift + add + min.
    ``masked``: extra [B, ST/32] int32 ref of per-query packed allow
    words, applied exactly like _bq_scan_kernel's.
    """
    if masked:
        am_ref, out_ref = refs
    else:
        (out_ref,) = refs
    lut = lut_ref[:]
    slices_per_sub = sub_rows // out_w
    rep_axis = 1 if row_major else 0
    shape = (sub_rows, 16 * m) if row_major else (16 * m, sub_rows)
    code_iota = jax.lax.broadcasted_iota(jnp.int32, shape, rep_axis) // m

    def one_subtile(j, acc):
        if row_major:
            c = c_ref[pl.ds(j * sub_rows, sub_rows), :].astype(jnp.int32)
        else:
            c = c_ref[:, pl.ds(j * sub_rows, sub_rows)].astype(jnp.int32)
        if interpret:
            rep = jnp.concatenate([c] * 16, axis=rep_axis)
        else:
            rep = pltpu.repeat(c, 16, axis=rep_axis)  # 16m copy-major
        oh = (rep == code_iota).astype(jnp.int8)
        dots = jax.lax.dot_general(
            lut, oh,
            dimension_numbers=(((1,), (1 if row_major else 0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [B, sub] integer ADC sums
        packed = (jax.lax.shift_left(dots, _SCAN_ID_BITS)
                  + bias_ref[:, pl.ds(j * sub_rows, sub_rows)])
        if masked:
            mw = am_ref[:, pl.ds(j * (sub_rows // 32), sub_rows // 32)]
            bits = _mask_unpack_cols(mw, sub_rows, interpret)
            packed = jnp.where(bits > 0, packed,
                               jnp.iinfo(jnp.int32).max)
        for s in range(slices_per_sub):
            acc = jnp.minimum(acc, packed[:, s * out_w:(s + 1) * out_w])
        return acc

    init = jnp.full((lut.shape[0], out_w), jnp.iinfo(jnp.int32).max,
                    jnp.int32)
    if subtiles == 1:
        acc = one_subtile(0, init)
    else:
        acc = jax.lax.fori_loop(0, subtiles, one_subtile, init)
    out_ref[:] = acc


@functools.partial(jax.jit, static_argnames=(
    "supertile", "sub_rows", "out_w", "row_major", "masked", "interpret"))
def _pq4_scan_tiled(lut8, codes, bias, am, supertile, sub_rows, out_w,
                    row_major, masked, interpret):
    b = lut8.shape[0]
    if row_major:
        n, m = codes.shape
        c_spec = pl.BlockSpec((supertile, m), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    else:
        m, n = codes.shape
        c_spec = pl.BlockSpec((m, supertile), lambda i: (0, i),
                              memory_space=pltpu.VMEM)
    subtiles = supertile // sub_rows
    reduce_l = supertile // out_w
    in_specs = [
        pl.BlockSpec((b, 16 * m), lambda i: (0, 0),
                     memory_space=pltpu.VMEM),
        c_spec,
        pl.BlockSpec((1, supertile), lambda i: (0, i),
                     memory_space=pltpu.VMEM),
    ]
    operands = (lut8, codes, bias)
    if masked:
        in_specs.append(
            pl.BlockSpec((b, supertile // 32), lambda i: (0, i),
                         memory_space=pltpu.VMEM))
        operands = operands + (am,)
    return pl.pallas_call(
        functools.partial(_pq4_scan_kernel, m=m, subtiles=subtiles,
                          sub_rows=sub_rows, out_w=out_w,
                          row_major=row_major, masked=masked,
                          interpret=interpret),
        grid=(n // supertile,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, out_w), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n // reduce_l), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * n * 16 * m,
            bytes_accessed=lut8.size + codes.size
            + b * (n // reduce_l) * 4 + (b * n // 8 if masked else 0),
            transcendentals=0,
        ),
        interpret=interpret,
    )(*operands)


def pq4_scan_reduce(
    lut: jnp.ndarray,
    codes: jnp.ndarray,
    valid: jnp.ndarray | None = None,
    reduce_l: int = 64,
    interpret: bool | None = None,
    transposed: bool = False,
    sub_rows: int | None = None,
    allow_bits: jnp.ndarray | None = None,
):
    """Full-corpus 4-bit-PQ ADC scan with in-kernel candidate reduction.

    lut [B, m, k<=16] f32 per-query ADC tables (ops/pq.py pq_lut); codes
    [N, m] uint8 row-major (or [m, N] with ``transposed=True``). The LUT
    is quantized to int8 with one scale per QUERY (rank-preserving within
    a query; the ~0.4% distance quantization is far below the downstream
    exact-rescore tolerance), so the scan runs at the int8 MXU rate with
    the same packed (value|slice-id) strided-min merge as the BQ kernel.

    Returns (vals [B, ~N/L] f32 approximate ADC distances with dead rows
    at MASKED_DISTANCE, ids [B, ~N/L] int32 global rows). ``allow_bits``
    adds a per-query packed allow bitmask (same contract as
    ``bq_scan_reduce``).
    """
    if interpret is None:
        interpret = not recommended()
    b, m, kk = lut.shape
    if kk > 16:
        raise ValueError(f"pq4 kernel requires k <= 16 centroids, got {kk}")
    pm = _pad_to(max(m, 1), 8)
    pb = _pad_to(max(b, 1), _SUBLANE)
    row_major = (m >= 24) if not transposed else False
    if transposed:
        n = codes.shape[1]
    else:
        n = codes.shape[0]
        if not row_major:
            codes = codes.T
    if sub_rows is None:
        if row_major:
            sub_rows = 256
        else:
            sub_rows = 2048 if pm <= 8 else (1024 if pm <= 24 else 512)
        if pb > 512:
            sub_rows = min(sub_rows, 1024)
    reduce_l = max(1, min(reduce_l, 64))
    reduce_l = 1 << (reduce_l.bit_length() - 1)
    st_cap = 8192 if row_major else 16384
    out_w = min(max(128, st_cap // reduce_l), sub_rows)
    supertile = reduce_l * out_w
    sub_rows = min(sub_rows, supertile)
    if allow_bits is not None:
        while supertile % MASK_BLOCK:
            out_w *= 2
            supertile = reduce_l * out_w
        sub_rows = min(max(sub_rows, out_w, MASK_BLOCK), supertile)
    pn = _pad_to(max(n, 1), supertile)
    if pm != m:
        lut = jnp.pad(lut, ((0, 0), (0, pm - m), (0, 0)))
        codes = (jnp.pad(codes, ((0, 0), (0, pm - m))) if row_major
                 else jnp.pad(codes, ((0, pm - m), (0, 0))))
    if lut.shape[2] < 16:
        lut = jnp.pad(lut, ((0, 0), (0, 0), (0, 16 - lut.shape[2])))
    if pb != b:
        lut = jnp.pad(lut, ((0, pb - b), (0, 0), (0, 0)))
    if pn != n:
        codes = (jnp.pad(codes, ((0, pn - n), (0, 0))) if row_major
                 else jnp.pad(codes, ((0, 0), (0, pn - n))))
    # per-query int8 quantization, code-major (padded segments carry
    # zero entries) — shared helper keeps this and the IVF probe in sync
    from weaviate_tpu.ops.pq import quantize_lut_int8

    lut8, scale = quantize_lut_int8(lut)
    dead_off = 2 * 127 * pm + 2  # past any legit int8 ADC sum
    pos = jnp.arange(pn, dtype=jnp.int32)
    slice_id = pos % supertile // out_w
    if valid is None:
        dead = pos >= n
    else:
        dead = jnp.logical_not(jnp.pad(valid.astype(bool), (0, pn - n),
                                       constant_values=False))
        dead = jnp.logical_or(dead, pos >= n)
    bias = slice_id + jnp.where(dead, dead_off << _SCAN_ID_BITS, 0)
    am = (None if allow_bits is None
          else _fit_mask_words(allow_bits, pb, pn))
    packed = _pq4_scan_tiled(lut8, codes, bias[None, :], am, supertile,
                             sub_rows, out_w, row_major,
                             allow_bits is not None, interpret)
    raw = jax.lax.shift_right_arithmetic(packed, _SCAN_ID_BITS)
    slice_ids = jax.lax.bitwise_and(packed, (1 << _SCAN_ID_BITS) - 1)
    col = jnp.arange(pn // reduce_l, dtype=jnp.int32)
    ids = (slice_ids * out_w + (col % out_w)[None, :]
           + (col // out_w * supertile)[None, :])
    vals = raw[:b].astype(jnp.float32) / scale[:b, None]
    vals = jnp.where(raw[:b] > 127 * pm, MASKED_DISTANCE, vals)
    return vals, ids[:b]


def bq_hamming_block(
    q_bits: jnp.ndarray,
    x_bits: jnp.ndarray,
    tile_n: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Hamming distance between packed sign-bit codes.

    q_bits [B,W] uint32, x_bits [N,W] uint32 -> [B,N] f32 bit differences
    (reference: binary_quantization.go:22 — XOR + popcount over uint64 words;
    we pack to uint32, the TPU-native integer width).
    """
    if interpret is None:
        interpret = not recommended()
    b, w = q_bits.shape
    n = x_bits.shape[0]
    pb = _pad_to(max(b, 1), _SUBLANE)
    pw = _pad_to(max(w, 1), _LANE)
    tile_n = min(tile_n, _pad_to(max(n, 1), _SUBLANE))
    pn = _pad_to(max(n, 1), tile_n)
    if (pb, pw) != (b, w):
        q_bits = jnp.pad(q_bits, ((0, pb - b), (0, pw - w)))
    if (pn, pw) != (n, w):
        x_bits = jnp.pad(x_bits, ((0, pn - n), (0, pw - w)))
    out = _bq_tiled(q_bits, x_bits, tile_n, interpret)
    return out[:b, :n]


# -- block-sparse BM25F over packed posting candidates (hybridplane) ----------
#
# The candidate axis is the hybridplane's "corpus": the host MaxScore
# planner bounds WHICH docs ship (ops/bm25.py packs them), this kernel
# scores them. Per grid step one query row's candidate tile sits in VMEM
# with its [S, tile] tf / prop-length planes; the per-segment scalars
# (term index, boost, avg-len) and per-term idf/k1/b ride in SMEM like
# the pallas guide's scalar discipline prescribes, and candidate
# liveness arrives as block-strided packed words (the PR 3 MASK_BLOCK
# layout) unpacked tile-locally — the same repeat + lane-iota-shift
# idiom every masked kernel here uses. The unrolled segment/term loops
# preserve the HOST scorer's f32 accumulation order exactly (segments in
# pack order per term, terms in ub order), so the top-k parity oracle
# holds bit-for-bit against text/inverted.py.


def _bm25_kernel(tf_ref, ln_ref, mw_ref, term_ref, boost_ref, avg_ref,
                 idf_ref, sc_ref, o_ref, *, interpret: bool):
    s = tf_ref.shape[1]        # static: block shapes carry S and T
    t = idf_ref.shape[1]
    tf = tf_ref[0]                                     # [S, tile]
    ln = ln_ref[0]
    k1 = sc_ref[0, 0]
    bb = sc_ref[0, 1]
    omb = sc_ref[0, 2]
    contribs = []
    for si in range(s):
        norm = omb + (bb * ln[si:si + 1, :]) / avg_ref[0, si]
        ctb = (boost_ref[0, si] * tf[si:si + 1, :]) \
            / jnp.maximum(norm, jnp.float32(1e-9))
        # adding exact 0.0 for misses keeps f32 parity with the host's
        # skip-the-miss accumulation (and guards padded segments)
        contribs.append(jnp.where(tf[si:si + 1, :] > 0.0, ctb, 0.0))
    score = jnp.zeros_like(contribs[0])                # [1, tile]
    for ti in range(t):
        acc = jnp.zeros_like(score)
        for si in range(s):
            acc = acc + jnp.where(term_ref[0, si] == ti,
                                  contribs[si], 0.0)
        score = score + (idf_ref[0, ti] * acc) / (k1 + acc)
    ok = _mask_unpack_cols(mw_ref[:], score.shape[1], interpret)
    o_ref[:] = jnp.where(ok > 0, -score, MASKED_DISTANCE)


@functools.partial(
    jax.jit, static_argnames=("s", "t", "tile_c", "interpret"))
def _bm25_tiled(tf, ln, mw, term, boost, avg, idf, sc, s, t, tile_c,
                interpret):
    b, _, c = tf.shape
    grid = (b, c // tile_c)
    return pl.pallas_call(
        functools.partial(_bm25_kernel, interpret=interpret),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, s, tile_c), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s, tile_c), lambda i, j: (i, 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_c // 32), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, s), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, s), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, s), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, t), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 4), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, tile_c), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, c), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=b * c * (4 * s + t * (s + 3)),
            bytes_accessed=2 * tf.size * 4 + b * c * 4 + mw.size * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(tf, ln, mw, term, boost, avg, idf, sc)


def bm25_block(seg_tf, seg_len, seg_term, seg_boost, seg_avg, idf,
               k1, b, omb, cand_bits, tile_c: int = 512,
               interpret: bool | None = None):
    """NEGATED BM25F scores over packed candidates.

    ``seg_tf``/``seg_len`` [B, S, C] f32 per-(term, prop) planes over the
    candidate axis; ``seg_term`` [B, S] int32 / ``seg_boost``/``seg_avg``
    [B, S] f32 segment scalars; ``idf`` [B, T] f32; ``k1``/``b``/``omb``
    [B] f32 per-row BM25 params (``omb`` = host-rounded f32 ``1 - b``);
    ``cand_bits`` [B, C // 32] uint32 block-strided candidate liveness
    (``pack_allow_bitmask`` layout). C must be a MASK_BLOCK multiple and
    S/T at least 1 (ops/bm25.py's ``stack_sparse_operands`` guarantees
    both). Returns [B, C] f32: ``-score`` on live candidates,
    MASKED_DISTANCE elsewhere — ready for the candidate-plane top-k.
    """
    if interpret is None:
        interpret = not recommended()
    b_n, s, c = seg_tf.shape
    t = idf.shape[1]
    tile_c = min(tile_c, c)
    mw = _fit_mask_words(cand_bits, b_n, c)
    sc = jnp.stack([jnp.asarray(k1, jnp.float32),
                    jnp.asarray(b, jnp.float32),
                    jnp.asarray(omb, jnp.float32),
                    jnp.zeros_like(jnp.asarray(k1, jnp.float32))], axis=1)
    return _bm25_tiled(seg_tf.astype(jnp.float32),
                       seg_len.astype(jnp.float32), mw,
                       seg_term.astype(jnp.int32),
                       seg_boost.astype(jnp.float32),
                       seg_avg.astype(jnp.float32),
                       idf.astype(jnp.float32), sc, s, t, tile_c,
                       interpret)
