"""Pallas TPU kernels for the distance hot path.

The reference's only native code is per-pair SIMD assembly for vector
distances (adapters/repos/db/vector/hnsw/distancer/asm/*.s — AVX2/AVX512/
NEON/SVE dot, l2, hamming; runtime dispatch in distancer/l2_amd64.go:19-25).
These kernels are the TPU equivalent, transposed to the hardware's shape:
instead of one query×one vector at a time, a whole query block is scored
against a corpus tile in one fused kernel so the FLOPs land on the 128x128
MXU and the mask/bias epilogue rides along in VMEM without an extra HBM
round-trip.

Kernels:

- ``distance_block``    fused [B,d]x[TILE,d] -> [B,TILE] distance + validity
                        mask epilogue (l2-squared / dot / cosine). One MXU
                        matmul per tile; the (1-valid)*MASKED epilogue fuses
                        into the same VMEM residency.
- ``bq_hamming_block``  packed binary-quantized hamming: uint32 XOR +
                        popcount + reduce (reference: BQ hamming over uint64
                        words, compressionhelpers/binary_quantization.go:22).

On CPU (tests, dev) the kernels run through the Pallas interpreter —
bit-identical semantics, no Mosaic compile. ``recommended()`` says whether
the compiled path is worth it on the current backend.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from weaviate_tpu.ops.distances import MASKED_DISTANCE

# Metrics with an MXU-shaped Pallas kernel. hamming-on-floats and manhattan
# stay on the XLA path (elementwise 3D intermediates — VPU-bound either way,
# nothing for a hand kernel to win).
PALLAS_METRICS = ("l2-squared", "dot", "cosine", "cosine-dot")

_LANE = 128  # TPU lane width: last dim of every tile.
_SUBLANE = 8  # f32 sublane count: second-to-last dim multiple.


def recommended() -> bool:
    """True when compiled Pallas kernels should be used (TPU backend)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _pad_to(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def _distance_kernel(metric: str):
    """Build the tile kernel body for one metric.

    refs: q [B,d] f32/bf16, x [TILE,d], valid [1,TILE] f32, xn [1,TILE] f32,
    out [B,TILE] f32. All VMEM-resident for the tile.
    """

    def kernel(q_ref, x_ref, valid_ref, xn_ref, out_ref):
        q = q_ref[:]
        x = x_ref[:]
        # One MXU contraction: [B,d] x [TILE,d]^T -> [B,TILE], f32 accumulate.
        # f32xf32 requests HIGHEST (multi-pass exact matmul) to match the XLA
        # path's recall-parity guarantee (distances._dot_matrix); bf16 storage
        # takes the single-pass MXU matmul.
        f32_exact = q.dtype == jnp.float32 and x.dtype == jnp.float32
        dots = jax.lax.dot_general(
            q,
            x,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST if f32_exact else jax.lax.Precision.DEFAULT,
        )
        if metric == "l2-squared":
            qn = jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32), axis=1, keepdims=True)
            d = jnp.maximum(qn - 2.0 * dots + xn_ref[:], 0.0)
        elif metric == "dot":
            d = -dots
        else:  # cosine / cosine-dot: operands pre-normalized by the wrapper
            d = 1.0 - dots
        # Masking epilogue fused into the same tile: dead slots can never win.
        out_ref[:] = d + (1.0 - valid_ref[:]) * MASKED_DISTANCE

    return kernel


@functools.partial(
    jax.jit, static_argnames=("metric", "tile_n", "interpret")
)
def _distance_tiled(q, x, valid_f, xn, metric, tile_n, interpret):
    b, d = q.shape
    n = x.shape[0]
    grid = (n // tile_n,)
    return pl.pallas_call(
        _distance_kernel(metric),
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * b * n * d,
            bytes_accessed=q.size * q.dtype.itemsize + x.size * x.dtype.itemsize + b * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(q, x, valid_f, xn)


def distance_block(
    q: jnp.ndarray,
    x: jnp.ndarray,
    metric: str = "l2-squared",
    valid: jnp.ndarray | None = None,
    x_sq_norms: jnp.ndarray | None = None,
    tile_n: int = 512,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Fused masked distances: q [B,d] vs x [N,d] -> [B,N] f32, lower=closer.

    Pads B to the f32 sublane multiple, d to the lane width, N to the tile —
    padded corpus rows are marked invalid so they surface as MASKED_DISTANCE.
    Zero-padding the feature axis is exact for dot/l2/cosine (zeros add
    nothing to the contraction).
    """
    if metric not in PALLAS_METRICS:
        raise ValueError(f"no pallas kernel for metric {metric!r}")
    if interpret is None:
        interpret = not recommended()

    b, d = q.shape
    n = x.shape[0]
    q = q.astype(jnp.float32) if q.dtype not in (jnp.float32, jnp.bfloat16) else q
    if metric in ("cosine", "cosine-dot"):
        from weaviate_tpu.ops.distances import normalize

        q = normalize(q.astype(jnp.float32))

    pb = _pad_to(max(b, 1), _SUBLANE)
    pd = _pad_to(max(d, 1), _LANE)
    tile_n = min(tile_n, _pad_to(max(n, 1), _LANE))
    pn = _pad_to(max(n, 1), tile_n)

    if (pb, pd) != (b, d):
        q = jnp.pad(q, ((0, pb - b), (0, pd - d)))
    if (pn, pd) != (n, d):
        x = jnp.pad(x, ((0, pn - n), (0, pd - d)))

    if valid is None:
        valid_f = (jnp.arange(pn) < n).astype(jnp.float32)
    else:
        valid_f = jnp.pad(valid.astype(jnp.float32), (0, pn - n))
    if x_sq_norms is None:
        x32 = x.astype(jnp.float32)
        xn = jnp.sum(x32 * x32, axis=1)
    else:
        xn = jnp.pad(x_sq_norms.astype(jnp.float32), (0, pn - n))

    out = _distance_tiled(
        q, x, valid_f[None, :], xn[None, :], metric, tile_n, interpret
    )
    return out[:b, :n]


def _bq_kernel(q_ref, x_ref, out_ref):
    """Packed-bits hamming tile: q [B,W] u32, x [TILE,W] u32 -> [B,TILE] f32."""
    q = q_ref[:]
    x = x_ref[:]
    xor = jnp.bitwise_xor(q[:, None, :], x[None, :, :])
    # Mosaic can't reduce unsigned ints — popcount fits in int32 regardless.
    pop = jax.lax.population_count(xor).astype(jnp.int32)
    out_ref[:] = jnp.sum(pop, axis=-1).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def _bq_tiled(q_bits, x_bits, tile_n, interpret):
    b, w = q_bits.shape
    n = x_bits.shape[0]
    return pl.pallas_call(
        _bq_kernel,
        grid=(n // tile_n,),
        in_specs=[
            pl.BlockSpec((b, w), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((b, tile_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        interpret=interpret,
    )(q_bits, x_bits)


def bq_hamming_block(
    q_bits: jnp.ndarray,
    x_bits: jnp.ndarray,
    tile_n: int = 256,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Hamming distance between packed sign-bit codes.

    q_bits [B,W] uint32, x_bits [N,W] uint32 -> [B,N] f32 bit differences
    (reference: binary_quantization.go:22 — XOR + popcount over uint64 words;
    we pack to uint32, the TPU-native integer width).
    """
    if interpret is None:
        interpret = not recommended()
    b, w = q_bits.shape
    n = x_bits.shape[0]
    pb = _pad_to(max(b, 1), _SUBLANE)
    pw = _pad_to(max(w, 1), _LANE)
    tile_n = min(tile_n, _pad_to(max(n, 1), _SUBLANE))
    pn = _pad_to(max(n, 1), tile_n)
    if (pb, pw) != (b, w):
        q_bits = jnp.pad(q_bits, ((0, pb - b), (0, pw - w)))
    if (pn, pw) != (n, w):
        x_bits = jnp.pad(x_bits, ((0, pn - n), (0, pw - w)))
    out = _bq_tiled(q_bits, x_bits, tile_n, interpret)
    return out[:b, :n]
