"""Coarse k-means for IVF partitioning.

Reference: adapters/repos/db/vector/compressionhelpers/kmeans.go trains PQ
sub-quantizers per segment; here the same Lloyd's iteration runs over FULL
vectors to learn the IVF coarse partition (the reference has no IVF — its
ANN is an in-RAM graph. IVF/ScaNN-style partitioning is the TPU-idiomatic
replacement, SURVEY §7 step 5).

TPU shape: the assign step is one [chunk, k] distance matmul (MXU), the
update step is a one-hot segment-sum einsum (also MXU). Host only loops
over chunks and carries the running sums.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.ops.distances import pairwise_distance


@functools.partial(jax.jit, static_argnames=("k",))
def _assign_accumulate(chunk, centroids, c_norms, k: int):
    """One chunk's Lloyd contribution: (assign [n], sums [k,d], counts [k])."""
    d = pairwise_distance(chunk, centroids, metric="l2-squared",
                          x_sq_norms=c_norms)
    assign = jnp.argmin(d, axis=1)
    one_hot = jax.nn.one_hot(assign, k, dtype=jnp.float32)  # [n, k]
    sums = jnp.einsum("nk,nd->kd", one_hot, chunk.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    counts = jnp.sum(one_hot, axis=0)
    return assign.astype(jnp.int32), sums, counts


def kmeans_fit(vectors: np.ndarray, k: int, iters: int = 10,
               sample: int = 262_144, batch: int = 16_384,
               seed: int = 0) -> np.ndarray:
    """Train ``k`` full-dim centroids; returns [k, d] f32 (host).

    Trains on a random sample; chunked so HBM holds at most
    [batch, k] distances at a time.
    """
    vectors = np.asarray(vectors, dtype=np.float32)
    n, dim = vectors.shape
    if n < k:
        raise ValueError(f"need >= {k} vectors to train {k} centroids, have {n}")
    rng = np.random.default_rng(seed)
    if n > sample:
        vectors = vectors[rng.choice(n, sample, replace=False)]
        n = sample
    centroids = jnp.asarray(vectors[rng.choice(n, k, replace=False)])
    for _ in range(iters):
        c_norms = jnp.sum(centroids * centroids, axis=1)
        sums = jnp.zeros((k, dim), dtype=jnp.float32)
        counts = jnp.zeros((k,), dtype=jnp.float32)
        for s in range(0, n, batch):
            _, cs, cc = _assign_accumulate(jnp.asarray(vectors[s:s + batch]),
                                           centroids, c_norms, k)
            sums = sums + cs
            counts = counts + cc
        fresh = sums / jnp.maximum(counts, 1.0)[:, None]
        centroids = jnp.where((counts > 0)[:, None], fresh, centroids)
    # np.asarray already materializes (and therefore waits for) the
    # result; the extra block_until_ready was a redundant second sync
    return np.asarray(centroids)  # graftlint: disable=G1 — training-time boundary: callers consume host centroids


def kmeans_assign(vectors: np.ndarray, centroids: np.ndarray,
                  batch: int = 16_384) -> np.ndarray:
    """Nearest-centroid id per vector, [N] int32 (host)."""
    vectors = np.asarray(vectors, dtype=np.float32)
    cent = jnp.asarray(centroids, dtype=jnp.float32)
    c_norms = jnp.sum(cent * cent, axis=1)
    k = cent.shape[0]
    out = np.empty(len(vectors), dtype=np.int32)
    for s in range(0, len(vectors), batch):
        a, _, _ = _assign_accumulate(jnp.asarray(vectors[s:s + batch]),
                                     cent, c_norms, k)
        out[s:s + batch] = np.asarray(a)
    return out
