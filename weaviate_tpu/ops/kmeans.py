"""Coarse k-means for IVF partitioning.

Reference: adapters/repos/db/vector/compressionhelpers/kmeans.go trains PQ
sub-quantizers per segment; here the same Lloyd's iteration runs over FULL
vectors to learn the IVF coarse partition (the reference has no IVF — its
ANN is an in-RAM graph. IVF/ScaNN-style partitioning is the TPU-idiomatic
replacement, SURVEY §7 step 5).

TPU shape: the assign step is one [chunk, k] distance matmul (MXU), the
update step is a one-hot segment-sum einsum (also MXU). Host only loops
over chunks and carries the running sums.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.ops.distances import pairwise_distance


@functools.partial(jax.jit, static_argnames=("k",))
def _assign_accumulate(chunk, centroids, c_norms, k: int):
    """One chunk's Lloyd contribution:
    (assign [n], assigned_dist [n], sums [k,d], counts [k])."""
    d = pairwise_distance(chunk, centroids, metric="l2-squared",
                          x_sq_norms=c_norms)
    assign = jnp.argmin(d, axis=1)
    dmin = jnp.min(d, axis=1)
    one_hot = jax.nn.one_hot(assign, k, dtype=jnp.float32)  # [n, k]
    sums = jnp.einsum("nk,nd->kd", one_hot, chunk.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    counts = jnp.sum(one_hot, axis=0)
    return assign.astype(jnp.int32), dmin, sums, counts


def kmeans_fit(vectors: np.ndarray, k: int, iters: int = 10,
               sample: int = 262_144, batch: int = 16_384,
               seed: int = 0) -> np.ndarray:
    """Train ``k`` full-dim centroids; returns [k, d] f32 (host).

    Trains on a random sample; chunked so HBM holds at most
    [batch, k] distances at a time.
    """
    vectors = np.asarray(vectors, dtype=np.float32)
    n, dim = vectors.shape
    if n < k:
        raise ValueError(f"need >= {k} vectors to train {k} centroids, have {n}")
    rng = np.random.default_rng(seed)
    if n > sample:
        vectors = vectors[rng.choice(n, sample, replace=False)]
        n = sample
    centroids = jnp.asarray(vectors[rng.choice(n, k, replace=False)])
    for _ in range(iters):
        c_norms = jnp.sum(centroids * centroids, axis=1)
        sums = jnp.zeros((k, dim), dtype=jnp.float32)
        counts = jnp.zeros((k,), dtype=jnp.float32)
        for s in range(0, n, batch):
            _, _, cs, cc = _assign_accumulate(
                jnp.asarray(vectors[s:s + batch]), centroids, c_norms, k)
            sums = sums + cs
            counts = counts + cc
        fresh = sums / jnp.maximum(counts, 1.0)[:, None]
        centroids = jnp.where((counts > 0)[:, None], fresh, centroids)
        counts_np = np.asarray(counts)  # graftlint: disable=G1 — training-time boundary
        if (counts_np == 0).any():
            centroids = _reseed_empty(vectors, centroids, counts_np, batch)
    # np.asarray already materializes (and therefore waits for) the
    # result; the extra block_until_ready was a redundant second sync
    return np.asarray(centroids)  # graftlint: disable=G1 — training-time boundary: callers consume host centroids


def _reseed_empty(vectors: np.ndarray, centroids, counts_np: np.ndarray,
                  batch: int):
    """Reseed EMPTY clusters from the farthest-assigned points of the
    fullest cluster (deterministic — ties break toward the lowest point
    index, no RNG, so ``kmeans_fit`` stays reproducible under ``seed``).

    Without this, ``jnp.where(counts > 0, fresh, centroids)`` pins a dead
    centroid at its stale position FOREVER: nothing reassigns to it, so
    it stays empty every remaining iteration and the trained partition
    silently runs with fewer effective lists (ISSUE 16 satellite). An
    extra assignment pass only runs on iterations that actually have
    empties.
    """
    n = len(vectors)
    k = centroids.shape[0]
    empties = np.flatnonzero(counts_np == 0)
    fullest = int(np.argmax(counts_np))
    c_norms = jnp.sum(centroids * centroids, axis=1)
    assign_all = np.empty(n, dtype=np.int32)
    dist_all = np.empty(n, dtype=np.float32)
    for s in range(0, n, batch):
        a, dm, _, _ = _assign_accumulate(
            jnp.asarray(vectors[s:s + batch]), centroids, c_norms, k)
        assign_all[s:s + batch] = np.asarray(a)
        dist_all[s:s + batch] = np.asarray(dm)
    pool = np.flatnonzero(assign_all == fullest)
    # farthest first; lexsort's LAST key is primary, `pool` breaks ties
    order = pool[np.lexsort((pool, -dist_all[pool]))]
    chosen = list(order[: len(empties)])
    if len(chosen) < len(empties):
        # degenerate fullest cluster (fewer members than empty slots):
        # top up with the globally farthest-from-assigned points
        taken = set(chosen)
        for idx in np.argsort(-dist_all, kind="stable"):
            if int(idx) not in taken:
                chosen.append(int(idx))
                taken.add(int(idx))
                if len(chosen) == len(empties):
                    break
    # np.array (not asarray): device arrays materialize as read-only views
    cents = np.array(centroids)  # graftlint: disable=G1 — training-time boundary
    cents[empties] = vectors[np.asarray(chosen, dtype=np.int64)]
    return jnp.asarray(cents)


def kmeans_assign(vectors: np.ndarray, centroids: np.ndarray,
                  batch: int = 16_384) -> np.ndarray:
    """Nearest-centroid id per vector, [N] int32 (host)."""
    vectors = np.asarray(vectors, dtype=np.float32)
    cent = jnp.asarray(centroids, dtype=jnp.float32)
    c_norms = jnp.sum(cent * cent, axis=1)
    k = cent.shape[0]
    out = np.empty(len(vectors), dtype=np.int32)
    for s in range(0, len(vectors), batch):
        a, _, _, _ = _assign_accumulate(jnp.asarray(vectors[s:s + batch]),
                                        cent, c_norms, k)
        out[s:s + batch] = np.asarray(a)
    return out
