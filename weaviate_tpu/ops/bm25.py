"""Device-resident BM25F scoring + sparse/dense hybrid fusion (ISSUE 18).

The host MaxScore scorer (text/inverted.py) stays the PLANNER: it picks
which documents are worth shipping (the candidate universe = the allowed
union of every query term's postings, so device top-k is provably exact)
and computes the per-term idf / per-prop average-length scalars. The
SCORING moves here: candidates pack into padded device operands
(``SparseOperand`` / ``stack_sparse_operands``), a segment-sum BM25F
scorer runs on device (XLA fallback below; the block-sparse Pallas twin
is ``pallas_kernels.bm25_block``), the sparse top-k rides the shared
candidate plane (``ops/candidates.masked_candidate_topk``), and fusion
with the dense leg is a device merge (``fuse_topk``) that mirrors
``text/hybrid.py`` — the host implementations are the parity oracle.

Layout (mirrors the ``pack_allow_bitmask`` MASK_BLOCK discipline):

- candidate axis C pads to a pow2 >= 512 (a whole number of MASK_BLOCK
  column blocks); candidate liveness packs block-strided
  (``pack_allow_bitmask``) so the Pallas kernel unpacks it tile-locally
  in VMEM exactly like the filter kernels do;
- per-(term, prop) posting segments land as dense [S, C] tf / prop-len
  planes over the candidate axis (block-sparse: only candidate columns
  are materialized, never corpus columns);
- per-segment scalars (term index, boost, prop avg-len) and per-term idf
  ride as small operands; b/k1 ship as f32 scalars per row.

Arithmetic parity: the host scorer accumulates in f32 with weakly-cast
Python-float scalars. Every device expression below reproduces the host
op order exactly — segments accumulate in pack order (prop order within
the ub-sorted term order), terms saturate and sum in ub order, and
``1 - b`` is pre-rounded on the host (``one_minus_b``) so the same f32
value flows through both paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from weaviate_tpu.ops.candidates import masked_candidate_topk
from weaviate_tpu.ops.distances import MASKED_DISTANCE
from weaviate_tpu.ops.pallas_kernels import (MASK_BLOCK, bm25_block,
                                             pack_allow_bitmask,
                                             recommended)

#: fusion kinds, matching text/hybrid.py's two reference implementations
FUSION_RANKED = 0
FUSION_RELATIVE = 1

#: reciprocal-rank fusion constant (hybrid_fusion.go:36 via text/hybrid.py)
RRF_K = 60.0


def fusion_kind(name: str) -> int:
    return FUSION_RELATIVE if name == "relativeScore" else FUSION_RANKED


class SparseOperand:
    """One hybrid query's host-packed sparse operands.

    Built by ``text/inverted.py::bm25_pack`` (+ the shard layer's
    doc-id -> store-slot translation); consumed by
    ``stack_sparse_operands`` at dispatch. All arrays are host numpy.
    """

    __slots__ = ("doc_ids", "slots", "seg_tf", "seg_len", "seg_term",
                 "seg_boost", "seg_avg", "idf", "k1", "b", "one_minus_b",
                 "alpha", "fusion", "fetch", "stats")

    def __init__(self, doc_ids, slots, seg_tf, seg_len, seg_term,
                 seg_boost, seg_avg, idf, k1, b, one_minus_b,
                 alpha, fusion, fetch, stats=None):
        self.doc_ids = doc_ids      # [C] int64, ascending
        self.slots = slots          # [C] int32 store slots
        self.seg_tf = seg_tf        # [S, C] f32
        self.seg_len = seg_len      # [S, C] f32
        self.seg_term = seg_term    # [S] int32 (ub-descending term order)
        self.seg_boost = seg_boost  # [S] f32
        self.seg_avg = seg_avg      # [S] f32 (per-prop avg_len)
        self.idf = idf              # [T] f32 (ub-descending term order)
        self.k1 = k1
        self.b = b
        self.one_minus_b = one_minus_b  # host-rounded f32(1.0 - b)
        self.alpha = alpha          # dense weight (host hybrid semantics)
        self.fusion = fusion        # FUSION_RANKED | FUSION_RELATIVE
        self.fetch = fetch          # per-leg depth: max(k * 10, 100)
        self.stats = dict(stats or {})


def _bucket(n: int, lo: int) -> int:
    p = lo
    while p < n:
        p *= 2
    return p


def stack_sparse_operands(ops, b_pad: int) -> dict:
    """Stack per-row operands (entries may be None — pure-vector rows)
    into one padded batch dict of host arrays. Shapes bucket to pow2 so
    the device program compiles per (C, S, T) bucket, not per drain;
    the candidate axis pads to MASK_BLOCK multiples and liveness packs
    block-strided for the Pallas kernel's tile-local unpack."""
    live = [op for op in ops if op is not None]
    c_pad = _bucket(max((len(op.slots) for op in live), default=1),
                    MASK_BLOCK)
    s_pad = _bucket(max((op.seg_tf.shape[0] for op in live), default=1), 8)
    t_pad = _bucket(max((len(op.idf) for op in live), default=1), 8)
    b_pad = max(b_pad, len(ops))

    slots = np.full((b_pad, c_pad), -1, np.int32)
    seg_tf = np.zeros((b_pad, s_pad, c_pad), np.float32)
    seg_len = np.zeros((b_pad, s_pad, c_pad), np.float32)
    seg_term = np.zeros((b_pad, s_pad), np.int32)
    seg_boost = np.zeros((b_pad, s_pad), np.float32)
    seg_avg = np.ones((b_pad, s_pad), np.float32)
    idf = np.zeros((b_pad, t_pad), np.float32)
    k1 = np.ones(b_pad, np.float32)
    b_arr = np.zeros(b_pad, np.float32)
    omb = np.ones(b_pad, np.float32)
    alpha = np.ones(b_pad, np.float32)   # pad rows: dense-only
    kind = np.zeros(b_pad, np.int32)
    fetch = np.ones(b_pad, np.int32)
    is_hybrid = np.zeros(b_pad, bool)
    for row, op in enumerate(ops):
        if op is None:
            continue
        c = len(op.slots)
        s = op.seg_tf.shape[0]
        t = len(op.idf)
        slots[row, :c] = op.slots
        seg_tf[row, :s, :c] = op.seg_tf
        seg_len[row, :s, :c] = op.seg_len
        seg_term[row, :s] = op.seg_term
        seg_boost[row, :s] = op.seg_boost
        seg_avg[row, :s] = op.seg_avg
        idf[row, :t] = op.idf
        k1[row] = op.k1
        b_arr[row] = op.b
        omb[row] = op.one_minus_b
        alpha[row] = op.alpha
        kind[row] = op.fusion
        fetch[row] = op.fetch
        is_hybrid[row] = True
    return {
        "slots": slots, "seg_tf": seg_tf, "seg_len": seg_len,
        "seg_term": seg_term, "seg_boost": seg_boost, "seg_avg": seg_avg,
        "idf": idf, "k1": k1, "b": b_arr, "omb": omb, "alpha": alpha,
        "kind": kind, "fetch": fetch, "is_hybrid": is_hybrid,
        # block-strided candidate liveness (MASK_BLOCK discipline): the
        # Pallas scorer unpacks this tile-locally instead of reading a
        # dense [B, C] validity plane
        "cand_bits": pack_allow_bitmask(slots >= 0, c_pad),
    }


def bm25_neg_scores(seg_tf, seg_len, seg_term, seg_boost, seg_avg, idf,
                    k1, b, omb, slots, cand_bits, use_pallas=None):
    """NEGATED BM25F scores [B, C] f32 over the candidate axis (negated +
    MASKED_DISTANCE padding so the result feeds the shared candidate
    top-k directly). Picks the Pallas block kernel on TPU, the exact XLA
    twin elsewhere."""
    if use_pallas is None:
        use_pallas = recommended()
    if use_pallas:
        return bm25_block(seg_tf, seg_len, seg_term, seg_boost, seg_avg,
                          idf, k1, b, omb, cand_bits)
    return _bm25_neg_scores_xla(seg_tf, seg_len, seg_term, seg_boost,
                                seg_avg, idf, k1, b, omb, slots)


@jax.jit
def _bm25_neg_scores_xla(seg_tf, seg_len, seg_term, seg_boost, seg_avg,
                         idf, k1, b, omb, slots):
    """XLA segment-sum fallback — op-for-op the host scorer's f32
    arithmetic (see the module docstring's parity note): per-segment
    ``contrib = boost*tf / max(1 - b + b*len/avg, 1e-9)``, segments
    accumulate per term IN PACK ORDER, terms saturate
    ``idf * a/(k1 + a)`` and sum in ub order."""
    n_b, n_s, n_c = seg_tf.shape
    n_t = idf.shape[1]
    bb = b[:, None, None]
    norm = omb[:, None, None] + (bb * seg_len) / seg_avg[:, :, None]
    contrib = (seg_boost[:, :, None] * seg_tf) \
        / jnp.maximum(norm, jnp.float32(1e-9))
    contrib = jnp.where(seg_tf > 0.0, contrib, 0.0)        # [B, S, C]
    # ordered segment-sum into the per-term accumulator: adding exact
    # 0.0 for non-matching segments keeps f32 parity with the host's
    # skip-the-miss accumulation
    acc = jnp.zeros((n_b, n_t, n_c), jnp.float32)
    t_iota = jnp.arange(n_t, dtype=jnp.int32)[None, :]
    for s in range(n_s):
        onehot = (seg_term[:, s, None] == t_iota).astype(jnp.float32)
        acc = acc + onehot[:, :, None] * contrib[:, s, None, :]
    score = jnp.zeros((n_b, n_c), jnp.float32)
    for t in range(n_t):
        a = acc[:, t, :]
        score = score + (idf[:, t, None] * a) / (k1[:, None] + a)
    return jnp.where(slots >= 0, -score, MASKED_DISTANCE)


@functools.partial(jax.jit, static_argnames=("k",))
def fuse_topk(sp_neg, sp_ids, dn_d, dn_i, alpha, kind, fetch, k: int):
    """Device twin of ``text/hybrid.py`` fusion, one merged top-k.

    ``sp_neg``/``sp_ids`` [B, Fs]: the sparse leg as negated scores
    (ascending = best first, MASKED_DISTANCE + -1 = dead) over store
    slots; ``dn_d``/``dn_i`` [B, Fd]: the dense leg (distances
    ascending, -1 = dead). ``alpha`` [B] f32 is the dense weight,
    ``kind`` [B] int32 picks RRF vs relative-score per row, ``fetch``
    [B] int32 caps each leg's rank depth at the host's over-fetch so
    padded leg widths never change the fusion inputs.

    Parity with the host reference: leg presence follows the host's
    thread gating (sparse iff alpha < 1, dense iff alpha > 0), RRF adds
    ``w / (60 + rank)`` over 0-based ranks, relative-score min-max
    normalizes over the leg's live entries (``norm = 1`` when a leg is
    constant), and the merged tie-break is the host dict's insertion
    order — sparse entries first, then unmatched dense — via the
    concat + lower-index-wins top-k. Returns (neg_fused [B, k],
    ids [B, k]) ascending by negated fused score.
    """
    n_b, fs = sp_neg.shape
    fd = dn_d.shape[1]
    rank_s = jnp.arange(fs, dtype=jnp.int32)[None, :]
    rank_d = jnp.arange(fd, dtype=jnp.int32)[None, :]
    sparse_on = (alpha < 1.0)[:, None]
    dense_on = (alpha > 0.0)[:, None]
    sp_ok = (sp_ids >= 0) & (sp_neg < MASKED_DISTANCE * 0.5) \
        & (rank_s < fetch[:, None]) & sparse_on
    dn_ok = (dn_i >= 0) & (dn_d < MASKED_DISTANCE * 0.5) \
        & (rank_d < fetch[:, None]) & dense_on
    sp_score = -sp_neg
    dn_score = -dn_d
    w_s = (1.0 - alpha)[:, None]
    w_d = alpha[:, None]

    # -- reciprocal-rank contributions (ranks are leg positions: both
    # legs arrive sorted with dead entries pushed past the live tail)
    rrf_s = w_s / (RRF_K + rank_s.astype(jnp.float32))
    rrf_d = w_d / (RRF_K + rank_d.astype(jnp.float32))

    # -- relative-score contributions: min-max over each leg's LIVE
    # entries; a constant leg normalizes to 1.0 (host: hi > lo gate)
    def _rel(score, ok, w):
        lo = jnp.min(jnp.where(ok, score, jnp.inf), axis=1, keepdims=True)
        hi = jnp.max(jnp.where(ok, score, -jnp.inf), axis=1, keepdims=True)
        span = hi - lo
        norm = jnp.where(hi > lo,
                         (score - lo) / jnp.where(span > 0.0, span, 1.0),
                         1.0)
        return w * norm

    rel_s = _rel(sp_score, sp_ok, w_s)
    rel_d = _rel(dn_score, dn_ok, w_d)

    ranked = (kind == FUSION_RANKED)[:, None]
    c_s = jnp.where(sp_ok, jnp.where(ranked, rrf_s, rel_s), 0.0)
    c_d = jnp.where(dn_ok, jnp.where(ranked, rrf_d, rel_d), 0.0)

    # -- slot-match join: a doc in both legs keeps its SPARSE entry
    # (host dict insertion order) and absorbs the dense contribution
    eq = (sp_ids[:, :, None] == dn_i[:, None, :]) \
        & sp_ok[:, :, None] & dn_ok[:, None, :]        # [B, Fs, Fd]
    sp_tot = c_s + jnp.sum(jnp.where(eq, c_d[:, None, :], 0.0), axis=2)
    matched_d = jnp.any(eq, axis=1)                     # [B, Fd]
    dn_keep = dn_ok & ~matched_d

    vals = jnp.concatenate(
        [jnp.where(sp_ok, -sp_tot, MASKED_DISTANCE),
         jnp.where(dn_keep, -c_d, MASKED_DISTANCE)], axis=1)
    ids = jnp.concatenate([sp_ids, dn_i], axis=1)
    return masked_candidate_topk(vals, ids, min(k, vals.shape[1]))


@functools.partial(jax.jit, static_argnames=("k", "use_pallas"))
def hybrid_topk(dn_d, dn_i, pack: dict, k: int, use_pallas: bool = False):
    """The one batched hybrid program: score the packed sparse
    candidates, take the sparse top-leg through the shared candidate
    plane, fuse against the dense leg, and per-row select fused (hybrid
    rows) vs plain dense (pure-vector rows riding the same drain).

    ``dn_d``/``dn_i`` [B, F] are the dense scan's device-resident
    results over store slots (F >= both k and every row's fetch).
    Returns (dists [B, k], ids [B, k]): hybrid rows carry
    (-fused_score, slot), dense rows carry (distance, slot) — the
    caller's finish step resolves slots to doc ids for both."""
    neg = bm25_neg_scores(
        pack["seg_tf"], pack["seg_len"], pack["seg_term"],
        pack["seg_boost"], pack["seg_avg"], pack["idf"], pack["k1"],
        pack["b"], pack["omb"], pack["slots"], pack["cand_bits"],
        use_pallas=use_pallas)
    fs = min(neg.shape[1], dn_d.shape[1])
    sp_neg, sp_ids = masked_candidate_topk(neg, pack["slots"], fs)
    f_d, f_i = fuse_topk(sp_neg, sp_ids, dn_d, dn_i, pack["alpha"],
                         pack["kind"], pack["fetch"], k)
    hyb = pack["is_hybrid"][:, None]
    out_d = jnp.where(hyb, f_d[:, :k], dn_d[:, :k])
    out_i = jnp.where(hyb, f_i[:, :k], dn_i[:, :k])
    return out_d, out_i
