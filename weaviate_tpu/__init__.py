"""weaviate_tpu — a TPU-native vector database framework.

A from-scratch re-design of the capabilities of the reference vector database
(Weaviate, surveyed in SURVEY.md) for TPU hardware:

- Vectors live in HBM as JAX arrays, sharded over a `jax.sharding.Mesh`.
- Distance kernels (l2-squared / dot / cosine / hamming / manhattan) are
  batched matmul-shaped ops that map onto the MXU, with Pallas kernels for
  the fused scan paths (reference: hand-written SIMD assembly in
  adapters/repos/db/vector/hnsw/distancer/asm/*.s).
- Cross-shard top-k merges ride ICI collectives inside one compiled program
  (reference: HTTP scatter-gather in adapters/repos/db/index.go:1541).
- The serving/control plane (schema, LSM object store, inverted index,
  cluster membership, replication) is host-side Python/C++.
"""

__version__ = "0.1.0"
