# weaviate-tpu server image (reference: Dockerfile + docker-compose
# multi-node bring-up). The TPU runtime expects the host to expose the
# accelerator (gVisor/privileged TPU VM); CPU-only serving works out of
# the box for functional deployments and CI.
FROM python:3.12-slim AS base

# native toolchain for the C++ host library
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY csrc/ csrc/
COPY weaviate_tpu/ weaviate_tpu/
COPY setup.py* pyproject.toml* README.md* ./

# jax pinned CPU by default; TPU deployments install the matching
# libtpu wheel at runtime (JAX_PLATFORMS=tpu)
RUN pip install --no-cache-dir \
        "jax>=0.4.30" numpy msgpack grpcio protobuf && \
    g++ -O3 -shared -fPIC -o weaviate_tpu/native/libweaviate_native.so \
        csrc/weaviate_native.cpp || true

# No JAX_COMPILATION_CACHE_DIR here: an explicit dir bypasses the
# CPU-backend guard in runtime/compile_cache.py, and a /var/lib/weaviate
# volume remounted on a different-ISA host could then load AOT CPU
# executables with foreign feature sets (SIGILL at startup). The runtime
# picks a safe per-host cache location itself.
ENV PYTHONPATH=/app \
    PERSISTENCE_DATA_PATH=/var/lib/weaviate \
    JAX_PLATFORMS=cpu

VOLUME /var/lib/weaviate
EXPOSE 8080 50051 2112

ENTRYPOINT ["python", "-m", "weaviate_tpu"]
