# weaviate-tpu server image (reference: Dockerfile + docker-compose
# multi-node bring-up). The TPU runtime expects the host to expose the
# accelerator (gVisor/privileged TPU VM); CPU-only serving works out of
# the box for functional deployments and CI.
FROM python:3.12-slim AS base

# native toolchain for the C++ host library
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY csrc/ csrc/
COPY weaviate_tpu/ weaviate_tpu/
COPY setup.py* pyproject.toml* README.md* ./

# jax pinned CPU by default; TPU deployments install the matching
# libtpu wheel at runtime (JAX_PLATFORMS=tpu)
RUN pip install --no-cache-dir \
        "jax>=0.4.30" numpy msgpack grpcio protobuf && \
    g++ -O3 -shared -fPIC -o weaviate_tpu/native/libweaviate_native.so \
        csrc/weaviate_native.cpp || true

ENV PYTHONPATH=/app \
    PERSISTENCE_DATA_PATH=/var/lib/weaviate \
    JAX_PLATFORMS=cpu \
    JAX_COMPILATION_CACHE_DIR=/var/lib/weaviate/.jax_cache

VOLUME /var/lib/weaviate
EXPOSE 8080 50051 2112

ENTRYPOINT ["python", "-m", "weaviate_tpu"]
