"""Import every weaviate_tpu module under the virtual-CPU platform.

Import-time regressions (a moved jax symbol, a renamed kwarg, a missing
guard around an optional dep — e.g. the pre-PR-1 shard_map breakage)
previously surfaced as pytest COLLECTION errors, which
--continue-on-collection-errors quietly skips past. This makes them a
loud tier-1 failure naming the exact module.
"""

import importlib
import pkgutil

import weaviate_tpu


def test_import_every_module():
    failures = []
    for mod in pkgutil.walk_packages(weaviate_tpu.__path__,
                                     prefix="weaviate_tpu."):
        name = mod.name
        if name.endswith("__main__"):
            continue  # importing it starts the server
        if name.rsplit(".", 1)[-1].startswith("lib"):
            # ctypes-loaded shared objects (libweaviate_native.so,
            # libwvdataplane.so), not Python extension modules
            continue
        try:
            importlib.import_module(name)
        except Exception as e:  # noqa: BLE001 — collect them all
            failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, "modules failed to import:\n" + "\n".join(failures)
