"""Driver-contract tests: entry() compiles, dryrun_multichip(8) runs."""

import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, "/root/repo")

import __graft_entry__ as ge


def test_entry_jits_and_runs():
    fn, args = ge.entry()
    d, i = jax.jit(fn)(*args)
    jax.block_until_ready((d, i))
    assert d.shape == (8, 10) and i.shape == (8, 10)
    assert (np.asarray(i) >= 0).all()


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_dryrun_multichip():
    ge.dryrun_multichip(8)


def test_dryrun_benchkeeper():
    """The perf-gate machinery self-test is part of the driver contract
    (ISSUE 6): parsing, band math, stale detection, fingerprint refusal
    and exit codes all behave on a synthetic run — no device needed."""
    ge.dryrun_benchkeeper()
