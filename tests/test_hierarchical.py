"""Two-level ICI+DCN merge parity (ISSUE 13 tentpole acceptance).

The 8-device virtual CPU mesh doubles as a 2x4 "two-host pod"
(make_hierarchical_mesh(n_hosts=2)): the ``host`` axis stands in for
DCN, ``ici`` for the in-host interconnect. Every SPMD search path —
flat / BQ / PQ4 / IVF, unfiltered / shared-valid / per-query-bitmask —
must return BIT-IDENTICAL (distances AND ids) results on the
hierarchical mesh vs the legacy 1-D merge: exact top-k is mergeable,
and both merges derive the same candidate tie order (host-major concat,
level-1-sorted within host — sharded_search._two_level_merge_topk
docstring has the argument).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from weaviate_tpu.ops import bq as bq_ops
from weaviate_tpu.parallel.mesh import make_hierarchical_mesh, make_mesh
from weaviate_tpu.parallel.sharded_search import (
    merge_dcn_candidate_bytes,
    replicate_array,
    shard_array,
    sharded_quantized_topk,
    sharded_topk,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def _meshes():
    return make_mesh(8), make_hierarchical_mesh(n_hosts=2)


def _place(mesh, x, valid, q, allow=None):
    out = {
        "x": shard_array(jnp.asarray(x), mesh),
        "valid": shard_array(jnp.asarray(valid), mesh),
        "q": replicate_array(jnp.asarray(q), mesh),
    }
    if allow is not None:
        out["allow"] = shard_array(jnp.asarray(allow), mesh, dim=1)
    return out


def _assert_bit_identical(a, b, what=""):
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]),
                                  err_msg=f"{what}: distances diverge")
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]),
                                  err_msg=f"{what}: ids diverge")


@pytest.mark.parametrize("filtered", ["none", "shared", "per_query"])
def test_flat_two_level_parity(rng, filtered):
    flat, hier = _meshes()
    n, d, b, k = 1024, 32, 4, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((b, d)).astype(np.float32)
    valid = np.ones(n, dtype=bool)
    if filtered == "shared":
        valid[::5] = False
    allow = (rng.random((b, n)) > 0.4) if filtered == "per_query" else None

    outs = []
    for mesh in (flat, hier):
        p = _place(mesh, x, valid, q, allow)
        outs.append(sharded_topk(
            p["q"], p["x"], p["valid"], None, k=k, chunk_size=128,
            metric="l2-squared", mesh=mesh,
            allow_rows=p.get("allow")))
    _assert_bit_identical(outs[0], outs[1], f"flat/{filtered}")


def test_flat_two_level_parity_fused_selection(rng):
    flat, hier = _meshes()
    n, d, b, k = 2048, 32, 4, 10
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((b, d)).astype(np.float32)
    valid = np.ones(n, dtype=bool)
    valid[::7] = False
    outs = []
    for mesh in (flat, hier):
        p = _place(mesh, x, valid, q)
        outs.append(sharded_topk(
            p["q"], p["x"], p["valid"], None, k=k, chunk_size=128,
            metric="l2-squared", mesh=mesh, selection="fused"))
    _assert_bit_identical(outs[0], outs[1], "flat/fused")


def test_flat_two_level_parity_k_exceeds_live(rng):
    """k wider than the live candidate pool: the inf-padded DCN slices
    must never displace a real or masked candidate."""
    flat, hier = _meshes()
    n, d, b, k = 256, 16, 2, 64
    x = rng.standard_normal((n, d)).astype(np.float32)
    q = rng.standard_normal((b, d)).astype(np.float32)
    valid = np.zeros(n, dtype=bool)
    valid[:40] = True  # 40 live rows << b*k asked
    outs = []
    for mesh in (flat, hier):
        p = _place(mesh, x, valid, q)
        outs.append(sharded_topk(
            p["q"], p["x"], p["valid"], None, k=k, chunk_size=32,
            metric="l2-squared", mesh=mesh))
    _assert_bit_identical(outs[0], outs[1], "flat/k>live")


@pytest.mark.parametrize("filtered", ["none", "per_query"])
def test_bq_two_level_parity(rng, filtered):
    flat, hier = _meshes()
    n, dim, b, k = 1024, 128, 4, 16
    xb = rng.standard_normal((n, dim)).astype(np.float32)
    qv = rng.standard_normal((b, dim)).astype(np.float32)
    codes = np.asarray(bq_ops.bq_encode(jnp.asarray(xb)))
    qw = np.asarray(bq_ops.bq_encode(jnp.asarray(qv)))
    valid = np.ones(n, dtype=bool)
    valid[::9] = False
    allow = (rng.random((b, n)) > 0.3) if filtered == "per_query" else None
    outs = []
    for mesh in (flat, hier):
        kw = {}
        if allow is not None:
            kw["allow_rows"] = shard_array(jnp.asarray(allow), mesh,
                                           dim=1)
        outs.append(sharded_quantized_topk(
            replicate_array(jnp.asarray(qv), mesh),
            replicate_array(jnp.asarray(qw), mesh),
            shard_array(jnp.asarray(codes), mesh),
            shard_array(jnp.asarray(valid), mesh),
            None, None, k=k, k_out=k, chunk_size=128, quantization="bq",
            metric="l2-squared", mesh=mesh, **kw))
    _assert_bit_identical(outs[0], outs[1], f"bq/{filtered}")


def test_bq_two_level_parity_with_rescore(rng):
    """BQ + owning-device exact rescore: the rescored (f32) candidates
    ride the same two-level merge."""
    flat, hier = _meshes()
    n, dim, b, k = 1024, 64, 4, 8
    xb = rng.standard_normal((n, dim)).astype(np.float32)
    qv = rng.standard_normal((b, dim)).astype(np.float32)
    codes = np.asarray(bq_ops.bq_encode(jnp.asarray(xb)))
    qw = np.asarray(bq_ops.bq_encode(jnp.asarray(qv)))
    valid = np.ones(n, dtype=bool)
    rescore = xb.astype(np.float32)
    outs = []
    for mesh in (flat, hier):
        outs.append(sharded_quantized_topk(
            replicate_array(jnp.asarray(qv), mesh),
            replicate_array(jnp.asarray(qw), mesh),
            shard_array(jnp.asarray(codes), mesh),
            shard_array(jnp.asarray(valid), mesh),
            shard_array(jnp.asarray(rescore), mesh),
            None, k=32, k_out=k, chunk_size=128, quantization="bq",
            metric="l2-squared", mesh=mesh))
    _assert_bit_identical(outs[0], outs[1], "bq/rescore")


@pytest.mark.parametrize("filtered", ["none", "per_query"])
def test_pq4_two_level_parity(rng, filtered):
    from weaviate_tpu.ops import pq as pq_ops

    flat, hier = _meshes()
    n, dim, b, k = 512, 32, 4, 12
    xb = rng.standard_normal((n, dim)).astype(np.float32)
    qv = rng.standard_normal((b, dim)).astype(np.float32)
    codebook = pq_ops.pq_fit(xb, m=8, k=16)  # 16 centroids = pq4 regime
    codes = np.asarray(pq_ops.pq_encode(codebook, xb))
    cent = np.asarray(codebook.centroids)
    valid = np.ones(n, dtype=bool)
    allow = (rng.random((b, n)) > 0.3) if filtered == "per_query" else None
    outs = []
    for mesh in (flat, hier):
        kw = {}
        if allow is not None:
            kw["allow_rows"] = shard_array(jnp.asarray(allow), mesh,
                                           dim=1)
        outs.append(sharded_quantized_topk(
            replicate_array(jnp.asarray(qv), mesh), None,
            shard_array(jnp.asarray(codes), mesh),
            shard_array(jnp.asarray(valid), mesh),
            None, replicate_array(jnp.asarray(cent), mesh),
            k=k, k_out=k, chunk_size=128, quantization="pq4",
            metric="l2-squared", mesh=mesh, **kw))
    _assert_bit_identical(outs[0], outs[1], f"pq4/{filtered}")


def test_bq_compact_dcn_block_ids_match(rng):
    """WEAVIATE_TPU_DCN_COMPACT wire format (bf16 distance + uint32
    slot): BQ hamming counts at dim<=256 are bf16-exact, so even the
    compacted hop stays bit-identical."""
    flat, hier = _meshes()
    n, dim, b, k = 1024, 128, 4, 16
    xb = rng.standard_normal((n, dim)).astype(np.float32)
    qv = rng.standard_normal((b, dim)).astype(np.float32)
    codes = np.asarray(bq_ops.bq_encode(jnp.asarray(xb)))
    qw = np.asarray(bq_ops.bq_encode(jnp.asarray(qv)))
    valid = np.ones(n, dtype=bool)
    outs = []
    for mesh, compact in ((flat, False), (hier, True)):
        outs.append(sharded_quantized_topk(
            replicate_array(jnp.asarray(qv), mesh),
            replicate_array(jnp.asarray(qw), mesh),
            shard_array(jnp.asarray(codes), mesh),
            shard_array(jnp.asarray(valid), mesh),
            None, None, k=k, k_out=k, chunk_size=128, quantization="bq",
            metric="l2-squared", mesh=mesh, dcn_compact=compact))
    _assert_bit_identical(outs[0], outs[1], "bq/compact")


def test_ivf_two_level_parity(rng):
    from weaviate_tpu.parallel.sharded_search import sharded_ivf_pq_topk

    flat, hier = _meshes()
    nlist, cap, m, d, b, k = 32, 16, 8, 32, 4, 10
    cent = rng.standard_normal((nlist, d)).astype(np.float32)
    codes = rng.integers(0, 255, (nlist, cap, m)).astype(np.uint8)
    valid = rng.random((nlist, cap)) > 0.2
    slots = np.arange(nlist * cap, dtype=np.int32).reshape(nlist, cap)
    tvals = rng.standard_normal((nlist, cap)).astype(np.float32)
    pqc = rng.standard_normal((m, 256, d // m)).astype(np.float32)
    q = rng.standard_normal((b, d)).astype(np.float32)
    outs = []
    for mesh in (flat, hier):
        outs.append(sharded_ivf_pq_topk(
            replicate_array(jnp.asarray(q), mesh),
            shard_array(jnp.asarray(cent), mesh),
            shard_array(jnp.asarray(codes), mesh),
            shard_array(jnp.asarray(valid), mesh),
            shard_array(jnp.asarray(slots), mesh),
            shard_array(jnp.asarray(tvals), mesh),
            replicate_array(jnp.asarray(pqc), mesh),
            k=k, nprobe=4, metric="l2-squared", mesh=mesh))
    _assert_bit_identical(outs[0], outs[1], "ivf")


def test_device_store_on_hierarchical_mesh(rng):
    """End to end: DeviceVectorStore placed on the 2x4 mesh serves the
    same results as on the flat mesh, and the ledger's host rollup sees
    the sharded arrays split across both hosts."""
    from weaviate_tpu.engine.store import DeviceVectorStore
    from weaviate_tpu.runtime.hbm_ledger import ledger

    flat, hier = _meshes()
    vecs = rng.standard_normal((200, 16)).astype(np.float32)
    qs = vecs[[3, 77]]
    res = []
    for mesh in (flat, hier):
        store = DeviceVectorStore(dim=16, capacity=512, chunk_size=32,
                                  mesh=mesh)
        assert store.n_shards == 8
        store.add(vecs)
        dd, ii = store.search(qs, k=5)
        res.append((np.asarray(dd), np.asarray(ii)))
        del store
    _assert_bit_identical(res[0], res[1], "store e2e")
    roll = ledger.host_rollup(2)
    assert sum(roll.values()) == ledger.total_bytes()


def test_dcn_candidate_bytes_scale_with_hosts_not_devices():
    """Acceptance: per-host DCN candidate traffic is O(hosts*k), not
    O(devices*k) — on the 2x4 mesh the two-level merge ships 1/n_local
    of the flat merge's bytes (k chosen ICI-divisible so padding is
    zero)."""
    hier = make_hierarchical_mesh(n_hosts=2)
    k = 32
    flat_bytes = merge_dcn_candidate_bytes(hier, k, level="flat")
    two_bytes = merge_dcn_candidate_bytes(hier, k, level="two_level")
    assert flat_bytes == 4 * k * 8      # n_local * k pairs to 1 peer host
    assert two_bytes == k * 8           # ONE k-candidate block per host
    assert two_bytes * 4 == flat_bytes  # ratio = n_local
    # compact wire format: 6 B/pair
    assert merge_dcn_candidate_bytes(hier, k, level="two_level",
                                     compact=True) == k * 6
    # single-host degenerate: nothing crosses DCN
    assert merge_dcn_candidate_bytes(make_mesh(8), k) == 0
